# Team API server image (reference Dockerfile_k8s + charts/skypilot):
# a shared skytpu API server that many clients point
# SKYTPU_API_SERVER_ENDPOINT at. Cluster SSH keys and cloud
# credentials are mounted, not baked.
#
#   docker build -t skytpu-api-server .
#   docker run -p 46580:46580 \
#     -v ~/.config/gcloud:/root/.config/gcloud:ro \
#     -v skytpu-state:/root/.skytpu skytpu-api-server
FROM python:3.12-slim

RUN apt-get update && \
    apt-get install -y --no-install-recommends \
        openssh-client rsync curl && \
    rm -rf /var/lib/apt/lists/*

RUN pip install --no-cache-dir \
    aiohttp requests filelock click pyyaml jsonschema numpy scipy \
    psutil

WORKDIR /app
COPY skypilot_tpu /app/skypilot_tpu
ENV PYTHONPATH=/app

EXPOSE 46580
CMD ["python", "-m", "skypilot_tpu.server.server", \
     "--host", "0.0.0.0", "--port", "46580"]
