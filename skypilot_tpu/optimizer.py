"""Optimizer — choose (cloud, region, zone, instance) per task.

Re-design of reference ``sky/optimizer.py`` (`optimize` :106,
`_optimize_by_dp` :408, `_optimize_by_ilp` :469,
`_fill_in_launchable_resources` :1252). Same contract:

- Each task's Resources set is concretized into *launchable* candidates
  by asking every enabled cloud for feasible offerings.
- Objective is COST (price x estimated runtime) or TIME; DP over chain
  DAGs with per-edge egress cost, an ILP (scipy.optimize.milp — the
  reference uses PuLP) for general DAGs.
- Failover granularity: candidates are expanded per-region for
  on-demand VMs and per-zone for TPU/spot (zonal capacity), matching
  reference `_make_launchables_for_valid_region_zones` :1140.

TPU-first delta: the candidate space is ranked by $/chip-hour and the
time estimator understands slice scaling (2x chips ~ 2x throughput for
DP/FSDP workloads), so "v5e-32 in us-west4 vs v5e-64 spot in us-east5"
comparisons fall out naturally.
"""
from __future__ import annotations

import collections
import enum
from typing import Dict, List, Optional, Tuple

import numpy as np

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import task as task_lib
from skypilot_tpu.utils import log as sky_logging
from skypilot_tpu.utils import registry

logger = sky_logging.init_logger(__name__)

# Assumed runtime when the user provides no estimate (reference uses 1 hr).
_DEFAULT_RUNTIME_SECONDS = 3600.0
# $/GB egress between different clouds/regions (flat approximation;
# reference keeps per-cloud tables).
_EGRESS_COST_PER_GB = 0.09


class OptimizeTarget(enum.Enum):
    COST = 'cost'
    TIME = 'time'


class Optimizer:

    @staticmethod
    def optimize(dag: dag_lib.Dag,
                 minimize: OptimizeTarget = OptimizeTarget.COST,
                 blocked_resources: Optional[List[
                     resources_lib.Resources]] = None,
                 quiet: bool = False) -> dag_lib.Dag:
        """Pick best_resources for every task in the dag, in place."""
        for t in dag.tasks:
            candidates = _fill_in_launchable_resources(t, blocked_resources)
            if not candidates:
                enabled = ', '.join(str(c) for c in _enabled_clouds())
                raise exceptions.ResourcesUnavailableError(
                    f'No launchable resources satisfy task {t.name!r}: '
                    f'{sorted(t.resources, key=repr)}. Enabled clouds: '
                    f'[{enabled}] — run `skytpu check` after setting up '
                    'credentials, and `skytpu show-tpus` for the catalog.')
            t._optimizer_candidates = candidates  # type: ignore[attr-defined]

        if dag.is_chain():
            best = _optimize_by_dp(dag, minimize)
        else:
            best = _optimize_by_ilp(dag, minimize)

        for t, launchable in best.items():
            t.best_resources = launchable
            if not quiet:
                metric = _estimate(t, launchable, minimize)
                unit = '$' if minimize == OptimizeTarget.COST else 's'
                logger.info('Optimizer: %s -> %r (est. %s%.2f)', t.name
                            or 'task', launchable, unit, metric)
        return dag

    @staticmethod
    def estimate_cost(task: task_lib.Task) -> float:
        assert task.best_resources is not None
        return _estimate(task, task.best_resources, OptimizeTarget.COST)


# Measured per-chip training throughput anchor: this repo's own bench
# (bench.py train: 1B-class Llama, seq 8192, bf16, Pallas flash
# attention, 'kvo' remat) measures 10,729 tokens/s/chip at 58.8% MFU
# on v5e. Other generations are seeded by applying that measured MFU
# to their public bf16 peaks (tpu_utils' per-generation table) until
# bench.py runs on that hardware. This replaces the generation-blind
# linear-chips guess (the reference seeds per-accelerator throughput
# from its catalog instead — sky/optimizer.py:236): TIME optimization
# now knows a v6e chip does ~4.7x a v5e chip's work.
_MEASURED_V5E_TOKENS_PER_SEC_PER_CHIP = 10729.0
_V5E_PEAK_TFLOPS = 197.0


def _tokens_per_sec_per_chip(tpu) -> float:
    """Estimated bench-workload throughput for one chip of this
    generation (measured on v5e; MFU-extrapolated elsewhere)."""
    return (_MEASURED_V5E_TOKENS_PER_SEC_PER_CHIP *
            tpu.bf16_tflops_per_chip / _V5E_PEAK_TFLOPS)


def _runtime_seconds(task: task_lib.Task,
                     launchable: resources_lib.Resources) -> float:
    """Estimated runtime on these resources.

    ``task.estimate_runtime`` is seconds on the reference slice
    (v5e-8). For TPU candidates it rescales by the candidate's
    aggregate measured throughput (chips x per-chip rate), so both
    MORE chips and a FASTER generation shorten the estimate.
    """
    base = task.estimate_runtime or _DEFAULT_RUNTIME_SECONDS
    if launchable.is_tpu and task.estimate_runtime:
        ref_rate = 8.0 * _MEASURED_V5E_TOKENS_PER_SEC_PER_CHIP
        rate = (launchable.tpu.num_chips *
                _tokens_per_sec_per_chip(launchable.tpu))
        return base * ref_rate / max(rate, 1e-6)
    return base


def _estimate(task: task_lib.Task, launchable: resources_lib.Resources,
              minimize: OptimizeTarget) -> float:
    runtime = _runtime_seconds(task, launchable)
    if minimize == OptimizeTarget.TIME:
        return runtime
    return launchable.hourly_price() * runtime / 3600.0 * task.num_nodes


def _egress_cost(src: Optional[resources_lib.Resources],
                 dst: resources_lib.Resources,
                 gigabytes: float) -> float:
    if src is None or gigabytes <= 0:
        return 0.0
    same_cloud = (src.cloud is not None and src.cloud.is_same_cloud(dst.cloud))
    same_region = same_cloud and src.region == dst.region
    if same_region:
        return 0.0
    # Egress is billed by the SOURCE cloud at its own rate (reference
    # sky/clouds/*.py get_egress_cost); fall back to the flat default
    # when the source cloud is unknown.
    if src.cloud is not None:
        return src.cloud.egress_cost(gigabytes)
    return _EGRESS_COST_PER_GB * gigabytes


def _edge_gigabytes(src_task: task_lib.Task) -> float:
    return float(getattr(src_task, 'estimated_output_gigabytes', 0.0) or 0.0)


def _enabled_clouds() -> list:
    from skypilot_tpu import check as check_lib
    return check_lib.get_cached_enabled_clouds()


def _fill_in_launchable_resources(
    task: task_lib.Task,
    blocked_resources: Optional[List[resources_lib.Resources]] = None
) -> List[resources_lib.Resources]:
    """Expand the task's Resources set into concrete candidates."""
    blocked_resources = blocked_resources or []
    candidates: List[resources_lib.Resources] = []
    clouds = _enabled_clouds()
    for spec in task.resources:
        if spec.is_launchable() and spec.region is not None:
            target_clouds = [spec.cloud]
        elif spec.cloud is not None:
            target_clouds = [spec.cloud]
        else:
            target_clouds = clouds
        for cloud in target_clouds:
            for launchable in cloud.get_feasible_launchable_resources(spec):
                for expanded in _expand_region_zones(cloud, launchable):
                    if any(b.less_demanding_than(expanded) and
                           expanded.less_demanding_than(b)
                           for b in blocked_resources):
                        continue
                    candidates.append(expanded)
    # Rank cheapest first; stable order for determinism.
    candidates.sort(key=lambda r: (r.hourly_price(), repr(r)))
    return candidates


def _expand_region_zones(
        cloud, launchable: resources_lib.Resources
) -> List[resources_lib.Resources]:
    """One launchable per region (on-demand) or per zone (TPU/spot).

    This is the failover granularity (reference
    `_make_launchables_for_valid_region_zones` sky/optimizer.py:1140):
    the provisioner retries across zones inside a launchable's region
    before the optimizer's next candidate is tried.
    """
    out = []
    for region in cloud.regions_with_offering(launchable):
        if (launchable.is_tpu or launchable.use_spot) and region.zones:
            # Zoneless regions (e.g. a Kubernetes context) fall through
            # to region-level candidates even for TPUs.
            for zone in region.zones:
                out.append(launchable.copy(region=region.name, zone=zone))
        else:
            out.append(launchable.copy(region=region.name))
    return out


def _optimize_by_dp(
    dag: dag_lib.Dag, minimize: OptimizeTarget
) -> Dict[task_lib.Task, resources_lib.Resources]:
    """DP over a chain: min total (node metric + edge egress)."""
    tasks = dag.get_sorted_tasks()
    # dp[candidate] = (total metric, parent candidate)
    prev_dp: Dict[resources_lib.Resources, Tuple[float, Optional[
        resources_lib.Resources]]] = {None: (0.0, None)}  # type: ignore
    choices: List[Dict] = []
    prev_task: Optional[task_lib.Task] = None
    for t in tasks:
        cur: Dict[resources_lib.Resources, Tuple[
            float, Optional[resources_lib.Resources]]] = {}
        for cand in t._optimizer_candidates:  # type: ignore[attr-defined]
            node_metric = _estimate(t, cand, minimize)
            best_total, best_parent = None, None
            for parent, (parent_total, _) in prev_dp.items():
                edge = 0.0
                if parent is not None and minimize == OptimizeTarget.COST:
                    edge = _egress_cost(parent, cand,
                                        _edge_gigabytes(prev_task))
                total = parent_total + node_metric + edge
                if best_total is None or total < best_total:
                    best_total, best_parent = total, parent
            assert best_total is not None
            cur[cand] = (best_total, best_parent)
        choices.append(cur)
        prev_dp = cur
        prev_task = t
    # Backtrack.
    best: Dict[task_lib.Task, resources_lib.Resources] = {}
    tail = min(prev_dp.items(), key=lambda kv: kv[1][0])
    pick: Optional[resources_lib.Resources] = tail[0]
    for t, table in zip(reversed(tasks), reversed(choices)):
        assert pick is not None
        best[t] = pick
        pick = table[pick][1]
    return best


def _optimize_by_ilp(
    dag: dag_lib.Dag, minimize: OptimizeTarget
) -> Dict[task_lib.Task, resources_lib.Resources]:
    """ILP for general DAGs (reference :469 uses PuLP; we use scipy.milp).

    Variables: x[t,c] in {0,1} — task t uses candidate c; per-task
    simplex constraint sum_c x[t,c] == 1. Edge egress is linearized by
    charging each *destination* candidate the worst-case egress over
    feasible parents (an upper bound; exact products would need
    quadratic terms — acceptable because egress is a small tiebreaker).
    """
    from scipy import optimize as sp_opt
    from scipy import sparse

    tasks = dag.get_sorted_tasks()
    var_index: Dict[Tuple[int, int], int] = {}
    costs: List[float] = []
    for ti, t in enumerate(tasks):
        cands = t._optimizer_candidates  # type: ignore[attr-defined]
        for ci, cand in enumerate(cands):
            var_index[(ti, ci)] = len(costs)
            metric = _estimate(t, cand, minimize)
            if minimize == OptimizeTarget.COST:
                parents = list(dag.graph.predecessors(t))
                if parents:
                    metric += max(
                        (_egress_cost(pc, cand, _edge_gigabytes(p))
                         for p in parents
                         for pc in p._optimizer_candidates),  # type: ignore
                        default=0.0)
            costs.append(metric)

    n = len(costs)
    rows, cols, vals, = [], [], []
    for ti, t in enumerate(tasks):
        cands = t._optimizer_candidates  # type: ignore[attr-defined]
        for ci in range(len(cands)):
            rows.append(ti)
            cols.append(var_index[(ti, ci)])
            vals.append(1.0)
    a_eq = sparse.csr_matrix((vals, (rows, cols)), shape=(len(tasks), n))
    constraints = sp_opt.LinearConstraint(a_eq, lb=1.0, ub=1.0)
    res = sp_opt.milp(c=np.asarray(costs),
                      constraints=[constraints],
                      integrality=np.ones(n),
                      bounds=sp_opt.Bounds(0, 1))
    if not res.success:
        raise exceptions.ResourcesUnavailableError(
            f'ILP optimization failed: {res.message}')
    best: Dict[task_lib.Task, resources_lib.Resources] = {}
    for ti, t in enumerate(tasks):
        cands = t._optimizer_candidates  # type: ignore[attr-defined]
        for ci, cand in enumerate(cands):
            if res.x[var_index[(ti, ci)]] > 0.5:
                best[t] = cand
                break
    return best
