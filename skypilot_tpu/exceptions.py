"""Exception taxonomy.

Re-design of reference ``sky/exceptions.py``. The provisioning failover
machinery (backend + jobs recovery) dispatches on these types, so they
are part of the public API surface.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence


class SkyTpuError(Exception):
    """Base class for all framework errors."""


class InvalidTaskError(SkyTpuError, ValueError):
    """Malformed Task / YAML."""


class InvalidResourcesError(SkyTpuError, ValueError):
    """Malformed or unsatisfiable Resources spec."""


class ResourcesUnavailableError(SkyTpuError):
    """No candidate (cloud, region, zone) could satisfy the request.

    Carries ``failover_history`` so callers (managed jobs) can tell quota
    errors from stockouts (reference sky/exceptions.py ResourcesUnavailableError).
    """

    def __init__(self,
                 message: str,
                 no_failover: bool = False,
                 failover_history: Optional[List[Exception]] = None) -> None:
        super().__init__(message)
        self.no_failover = no_failover
        self.failover_history: List[Exception] = failover_history or []

    def with_failover_history(
            self, history: List[Exception]) -> 'ResourcesUnavailableError':
        self.failover_history = history
        return self


class ResourcesMismatchError(SkyTpuError):
    """Requested resources do not match the existing cluster's."""


class ProvisionError(SkyTpuError):
    """A cloud provisioning call failed.

    ``errors`` is a list of dicts with at least ``code`` and ``message``;
    the failover handler maps codes to blocked-resource granularity
    (zone / region / cloud), mirroring the reference's
    FailoverCloudErrorHandlerV2 (sky/backends/cloud_vm_ray_backend.py:888).
    """

    def __init__(self,
                 message: str,
                 errors: Optional[Sequence[Dict[str, Any]]] = None) -> None:
        super().__init__(message)
        self.errors: List[Dict[str, Any]] = list(errors or [])


class QuotaExceededError(ProvisionError):
    """Out of quota in this region — block the whole region."""


class StockoutError(ProvisionError):
    """Capacity unavailable in this zone — block the zone, try next."""


class ClusterNotUpError(SkyTpuError):
    """Operation requires an UP cluster."""

    def __init__(self, message: str, cluster_status=None, handle=None) -> None:
        super().__init__(message)
        self.cluster_status = cluster_status
        self.handle = handle


class ClusterDoesNotExist(SkyTpuError):
    """Named cluster not found in state."""


class ClusterOwnerIdentityMismatchError(SkyTpuError):
    """Cluster was created under a different cloud identity."""


class NotSupportedError(SkyTpuError):
    """Requested feature unsupported for this cloud/resource combination."""


class CommandError(SkyTpuError):
    """A (remote) command exited nonzero."""

    def __init__(self, returncode: int, command: str, error_msg: str,
                 detailed_reason: Optional[str] = None) -> None:
        self.returncode = returncode
        self.command = command
        self.error_msg = error_msg
        self.detailed_reason = detailed_reason
        if len(command) > 100:
            command = command[:100] + '...'
        super().__init__(
            f'Command {command} failed with return code {returncode}.\n'
            f'{error_msg}')


class JobNotFoundError(SkyTpuError):
    """Job id missing from the cluster job table."""


class ManagedJobReachedMaxRetriesError(SkyTpuError):
    """Managed job exhausted max_restarts_on_errors."""


class StorageError(SkyTpuError):
    """Storage layer failure."""


class StorageSpecError(StorageError, ValueError):
    """Malformed storage spec."""


class ServeUserTerminatedError(SkyTpuError):
    """Service terminated by user mid-operation."""


class RequestCancelled(SkyTpuError):
    """API-server request was cancelled by the client."""


class ApiServerConnectionError(SkyTpuError):
    """Cannot reach the API server."""

    def __init__(self, server_url: str) -> None:
        super().__init__(
            f'Could not connect to API server at {server_url}. '
            'Start one with `skytpu api start`.')
        self.server_url = server_url


class ApiVersionMismatchError(SkyTpuError):
    """Client and API server speak incompatible protocol versions."""
