"""Declarative resource spec with TPU pod slices first-class.

Re-design of reference ``sky/resources.py`` (`Resources` :31,
`_set_accelerators` :563, `get_cost` :1040, `less_demanding_than` :1146,
`from_yaml_config` :1348). Differences, TPU-first:

- ``accelerators='tpu-v5e-16'`` parses into a :class:`TpuSlice` with chip
  / host / topology math done eagerly (utils/tpu_utils.py) instead of the
  reference's string-keyed dict passed opaquely to GCP.
- One Task "node" = one slice; ``num_hosts`` on Resources tells the
  backend the gang fan-out width without a cloud round-trip.
- No GPU catalog: this framework targets TPUs (the cloud plugin seam
  still allows other clouds/accelerators to be registered).
"""
from __future__ import annotations

import textwrap
from typing import Any, Dict, List, Optional, Tuple, Union

from skypilot_tpu import exceptions
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import registry
from skypilot_tpu.utils import tpu_utils

_DEFAULT_DISK_SIZE_GB = 256

_RESOURCES_FIELDS = frozenset({
    'cloud', 'region', 'zone', 'instance_type', 'accelerators',
    'accelerator_args', 'cpus', 'memory', 'use_spot', 'job_recovery',
    'disk_size', 'disk_tier', 'image_id', 'ports', 'labels', 'any_of',
})


class Resources:
    """An (immutable) resource requirement / launchable description.

    A Resources is *launchable* when cloud and either instance_type or a
    TPU accelerator are pinned down; the optimizer turns user Resources
    into launchable ones (one per candidate region/zone).
    """

    def __init__(
        self,
        cloud: Optional[Union[str, 'Any']] = None,
        instance_type: Optional[str] = None,
        accelerators: Optional[Union[str, Dict[str, int]]] = None,
        accelerator_args: Optional[Dict[str, Any]] = None,
        cpus: Optional[Union[int, float, str]] = None,
        memory: Optional[Union[int, float, str]] = None,
        use_spot: Optional[bool] = None,
        job_recovery: Optional[Union[str, Dict[str, Any]]] = None,
        region: Optional[str] = None,
        zone: Optional[str] = None,
        disk_size: Optional[int] = None,
        disk_tier: Optional[str] = None,
        image_id: Optional[str] = None,
        ports: Optional[Union[int, str, List[Union[int, str]]]] = None,
        labels: Optional[Dict[str, str]] = None,
    ) -> None:
        self._cloud = self._resolve_cloud(cloud)
        self._region: Optional[str] = region
        self._zone: Optional[str] = zone
        self._instance_type = instance_type
        self._use_spot_specified = use_spot is not None
        self._use_spot = bool(use_spot) if use_spot is not None else False
        self._job_recovery = self._normalize_job_recovery(job_recovery)
        self._disk_size = (int(disk_size)
                           if disk_size is not None else _DEFAULT_DISK_SIZE_GB)
        self._disk_tier = disk_tier
        self._image_id = image_id
        self._labels = dict(labels) if labels else None

        self._set_accelerators(accelerators, accelerator_args)
        # cpus/memory: '4', '4+', 4 — validated here, matched in catalog.
        self._cpus = str(cpus) if cpus is not None else None
        self._memory = str(memory) if memory is not None else None
        common_utils.parse_cpus_memory(self._cpus)
        common_utils.parse_cpus_memory(self._memory)
        self._ports = self._normalize_ports(ports)
        self._validate()

    # ------------------------------------------------------------------
    @staticmethod
    def _resolve_cloud(cloud):
        if cloud is None or not isinstance(cloud, str):
            return cloud
        import skypilot_tpu.clouds  # noqa: F401 (registers built-ins)
        cls = registry.CLOUD_REGISTRY.from_str(cloud)
        return cls()  # type: ignore[operator]

    def _set_accelerators(self, accelerators, accelerator_args) -> None:
        """Normalize accelerators to {name: count}; parse TPU topology.

        Mirrors reference sky/resources.py:563 `_set_accelerators` (which
        detects `tpu-` names and forces GCP); here the TPU path is the
        main path.
        """
        self._tpu: Optional[tpu_utils.TpuSlice] = None
        self._accelerator_args = (dict(accelerator_args)
                                  if accelerator_args else None)
        if accelerators is None:
            self._accelerators: Optional[Dict[str, int]] = None
            return
        if isinstance(accelerators, str):
            if ':' in accelerators:
                name, count_s = accelerators.split(':', 1)
                try:
                    count = int(count_s)
                except ValueError:
                    raise exceptions.InvalidResourcesError(
                        f'Invalid accelerators {accelerators!r}.') from None
                accelerators = {name: count}
            else:
                accelerators = {accelerators: 1}
        if len(accelerators) != 1:
            raise exceptions.InvalidResourcesError(
                'accelerators must name exactly one accelerator type, '
                f'got {accelerators!r}')
        name, count = next(iter(accelerators.items()))
        if tpu_utils.is_tpu_name(name):
            if count != 1:
                raise exceptions.InvalidResourcesError(
                    f'TPU slices are atomic; use a larger slice (e.g. '
                    f'tpu-v5e-{8 * count}) instead of count={count}.')
            self._tpu = tpu_utils.parse(name)
            name = self._tpu.name
        self._accelerators = {name: int(count)}

    @staticmethod
    def _normalize_ports(ports) -> Optional[List[str]]:
        if ports is None:
            return None
        if isinstance(ports, (int, str)):
            ports = [ports]
        out = [str(p) for p in ports]
        return out or None

    def _validate(self) -> None:
        if self._region is not None or self._zone is not None:
            if self._cloud is not None:
                self._cloud.validate_region_zone(self._region, self._zone)
        if self._tpu is not None and self._instance_type is not None:
            raise exceptions.InvalidResourcesError(
                'Specify either a TPU accelerator or an instance_type, '
                'not both (TPU-VM hosts are implied by the slice).')
        if self._disk_size <= 0:
            raise exceptions.InvalidResourcesError(
                f'disk_size must be positive, got {self._disk_size}')

    # ------------------------------------------------------------------
    # Accessors
    @property
    def cloud(self):
        return self._cloud

    @property
    def region(self) -> Optional[str]:
        return self._region

    @property
    def zone(self) -> Optional[str]:
        return self._zone

    @property
    def instance_type(self) -> Optional[str]:
        return self._instance_type

    @property
    def accelerators(self) -> Optional[Dict[str, int]]:
        return self._accelerators

    @property
    def accelerator_args(self) -> Optional[Dict[str, Any]]:
        return self._accelerator_args

    @property
    def tpu(self) -> Optional[tpu_utils.TpuSlice]:
        return self._tpu

    @property
    def is_tpu(self) -> bool:
        return self._tpu is not None

    @property
    def num_hosts(self) -> int:
        """Hosts behind one logical node (gang fan-out width)."""
        return self._tpu.num_hosts if self._tpu is not None else 1

    @property
    def cpus(self) -> Optional[str]:
        return self._cpus

    @property
    def memory(self) -> Optional[str]:
        return self._memory

    @property
    def use_spot(self) -> bool:
        return self._use_spot

    @property
    def use_spot_specified(self) -> bool:
        return self._use_spot_specified

    @staticmethod
    def _normalize_job_recovery(
            job_recovery: Optional[Union[str, Dict[str, Any]]]
    ) -> Optional[Union[str, Dict[str, Any]]]:
        """A plain strategy name, or a dict with per-job knobs
        (reference job_recovery: {strategy, max_restarts_on_errors})."""
        if not job_recovery:
            return None
        if isinstance(job_recovery, str):
            return job_recovery.lower()
        if not isinstance(job_recovery, dict):
            from skypilot_tpu import exceptions
            raise exceptions.InvalidResourcesError(
                f'job_recovery must be a string or a dict; got '
                f'{job_recovery!r}.')
        unknown = set(job_recovery) - {'strategy', 'max_restarts_on_errors'}
        if unknown:
            from skypilot_tpu import exceptions
            raise exceptions.InvalidResourcesError(
                f'Unknown job_recovery fields: {sorted(unknown)}')
        normalized: Dict[str, Any] = {}
        strategy = job_recovery.get('strategy')
        if strategy:
            normalized['strategy'] = str(strategy).lower()
        max_restarts = job_recovery.get('max_restarts_on_errors')
        if max_restarts is not None:
            try:
                normalized['max_restarts_on_errors'] = int(max_restarts)
            except (TypeError, ValueError):
                from skypilot_tpu import exceptions
                raise exceptions.InvalidResourcesError(
                    f'job_recovery.max_restarts_on_errors must be an '
                    f'integer; got {max_restarts!r}.') from None
        return normalized or None

    @property
    def job_recovery(self) -> Optional[Union[str, Dict[str, Any]]]:
        return self._job_recovery

    @property
    def disk_size(self) -> int:
        return self._disk_size

    @property
    def disk_tier(self) -> Optional[str]:
        return self._disk_tier

    @property
    def image_id(self) -> Optional[str]:
        return self._image_id

    def extract_docker_image(self) -> Optional[str]:
        """Container image when image_id is ``docker:<image>`` —
        the task then runs inside that container on every host
        (reference sky/resources.py extract_docker_image)."""
        from skypilot_tpu.utils import docker_utils
        return docker_utils.extract_image(self._image_id)

    @property
    def ports(self) -> Optional[List[str]]:
        return self._ports

    @property
    def labels(self) -> Optional[Dict[str, str]]:
        return self._labels

    # ------------------------------------------------------------------
    def is_launchable(self) -> bool:
        return self._cloud is not None and (self._instance_type is not None or
                                            self._tpu is not None)

    def assert_launchable(self) -> None:
        if not self.is_launchable():
            raise exceptions.InvalidResourcesError(
                f'Resources not launchable: {self!r}')

    def copy(self, **override) -> 'Resources':
        """New Resources with fields overridden."""
        current = {
            'cloud': override.pop('cloud', self._cloud),
            'instance_type': override.pop('instance_type',
                                          self._instance_type),
            'accelerators': override.pop('accelerators', self._accelerators),
            'accelerator_args': override.pop('accelerator_args',
                                             self._accelerator_args),
            'cpus': override.pop('cpus', self._cpus),
            'memory': override.pop('memory', self._memory),
            'use_spot': override.pop(
                'use_spot',
                self._use_spot if self._use_spot_specified else None),
            'job_recovery': override.pop('job_recovery', self._job_recovery),
            'region': override.pop('region', self._region),
            'zone': override.pop('zone', self._zone),
            'disk_size': override.pop('disk_size', self._disk_size),
            'disk_tier': override.pop('disk_tier', self._disk_tier),
            'image_id': override.pop('image_id', self._image_id),
            'ports': override.pop('ports', self._ports),
            'labels': override.pop('labels', self._labels),
        }
        if override:
            raise ValueError(f'Unknown Resources fields: {list(override)}')
        return Resources(**current)

    # ------------------------------------------------------------------
    def hourly_price(self) -> float:
        """Catalog price for one logical node of this launchable."""
        self.assert_launchable()
        return self._cloud.hourly_price(self)

    def get_cost(self, seconds: float) -> float:
        return self.hourly_price() * seconds / 3600.0

    # ------------------------------------------------------------------
    def less_demanding_than(self, other: 'Resources') -> bool:
        """True if `other` (an existing cluster) can serve `self`.

        Mirrors reference sky/resources.py:1146 — used by `exec` and the
        optimizer to reuse clusters.
        """
        if self._cloud is not None and not self._cloud.is_same_cloud(
                other.cloud):
            return False
        if self._region is not None and self._region != other.region:
            return False
        if self._zone is not None and self._zone != other.zone:
            return False
        if (self._instance_type is not None and
                self._instance_type != other.instance_type):
            return False
        if self._accelerators is not None:
            if other.accelerators is None:
                return False
            for name, count in self._accelerators.items():
                if other.accelerators.get(name, 0) < count:
                    return False
        if self._use_spot_specified and self._use_spot != other.use_spot:
            return False
        return True

    # ------------------------------------------------------------------
    @classmethod
    def from_yaml_config(
            cls, config: Optional[Dict[str, Any]]) -> Union[
                'Resources', List['Resources']]:
        """Build from a `resources:` YAML section.

        Supports `any_of:` (a list of alternative specs) like the
        reference (sky/resources.py:1348).
        """
        if config is None:
            return cls()
        config = dict(config)
        unknown = set(config) - _RESOURCES_FIELDS
        if unknown:
            raise exceptions.InvalidResourcesError(
                f'Unknown resources fields: {sorted(unknown)}. '
                f'Valid: {sorted(_RESOURCES_FIELDS)}')
        any_of = config.pop('any_of', None)
        if any_of is not None:
            out = []
            for alt in any_of:
                merged = {**config, **alt}
                r = cls.from_yaml_config(merged)
                assert isinstance(r, Resources)
                out.append(r)
            return out
        return cls(**config)

    def to_yaml_config(self) -> Dict[str, Any]:
        config: Dict[str, Any] = {}

        def add(key, value):
            if value is not None:
                config[key] = value

        add('cloud', str(self._cloud) if self._cloud else None)
        add('region', self._region)
        add('zone', self._zone)
        add('instance_type', self._instance_type)
        add('accelerators', self._accelerators)
        add('accelerator_args', self._accelerator_args)
        add('cpus', self._cpus)
        add('memory', self._memory)
        if self._use_spot_specified:
            config['use_spot'] = self._use_spot
        add('job_recovery', self._job_recovery)
        if self._disk_size != _DEFAULT_DISK_SIZE_GB:
            config['disk_size'] = self._disk_size
        add('disk_tier', self._disk_tier)
        add('image_id', self._image_id)
        add('ports', self._ports)
        add('labels', self._labels)
        return config

    # ------------------------------------------------------------------
    def __repr__(self) -> str:
        parts = []
        if self._cloud is not None:
            parts.append(str(self._cloud))
        if self._instance_type is not None:
            parts.append(self._instance_type)
        if self._tpu is not None:
            parts.append(f'{self._tpu.name}[{self._tpu.topology}, '
                         f'{self._tpu.num_hosts} host'
                         f'{"s" if self._tpu.num_hosts > 1 else ""}]')
        elif self._accelerators is not None:
            parts.append(str(self._accelerators))
        if self._cpus is not None:
            parts.append(f'cpus={self._cpus}')
        if self._memory is not None:
            parts.append(f'mem={self._memory}')
        if self._use_spot:
            parts.append('[Spot]')
        if self._region is not None:
            parts.append(self._region)
        if self._zone is not None:
            parts.append(self._zone)
        inner = ', '.join(parts) if parts else 'default'
        return f'Resources({inner})'

    def __eq__(self, other) -> bool:
        if not isinstance(other, Resources):
            return NotImplemented
        return self.to_yaml_config() == other.to_yaml_config()

    def __hash__(self) -> int:
        return hash(common_utils.dump_yaml_str(self.to_yaml_config()))

    def pretty(self) -> str:
        return textwrap.indent(
            common_utils.dump_yaml_str(self.to_yaml_config()), '  ')
