"""Request-lifecycle contract shared across the serving stack.

One place for the knobs and wire forms that give every admitted
request a bounded lifetime (docs/request_lifecycle.md):

- **Deadline header** (``X-Request-Deadline``): the *remaining* time
  budget in seconds, re-stamped by every hop. The load balancer
  computes an absolute deadline when the request arrives (from the
  client's header or its own policy), and each proxy attempt stamps
  the budget still left; the replica converts it back to an absolute
  deadline against its own clock. Carrying a relative budget instead
  of an absolute timestamp makes the contract immune to clock skew
  between the controller and replica hosts.
- **Drain budget** (``SKYTPU_DRAIN_TIMEOUT_SECONDS``): how long a
  SIGTERM'd replica lets in-flight requests run before cancelling
  them and exiting.
- **Tick watchdog** (``SKYTPU_TICK_HANG_SECONDS``): an engine tick
  slower than this logs a trace-tagged warning and bumps a counter —
  a wedged device must be visible, not silent.

Import-light on purpose: the load balancer and replica manager import
this without dragging in jax.
"""
from __future__ import annotations

import time
from typing import Any, Optional

from skypilot_tpu.utils import env_registry

# Remaining-budget header (seconds, float as string). Stamped by the
# LB on every proxy attempt; accepted from clients directly too.
DEADLINE_HEADER = 'X-Request-Deadline'

# Default drain budget when SKYTPU_DRAIN_TIMEOUT_SECONDS is unset.
DEFAULT_DRAIN_TIMEOUT_SECONDS = 30.0
# Default tick-hang threshold when SKYTPU_TICK_HANG_SECONDS is unset.
DEFAULT_TICK_HANG_SECONDS = 30.0
# Default spot-preemption notice lead time when
# SKYTPU_PREEMPT_NOTICE_S is unset (docs/spot_serving.md).
DEFAULT_PREEMPT_NOTICE_S = 2.0

# Terminal request states (docs/request_lifecycle.md state diagram).
FINISHED = 'finished'
CANCELLED = 'cancelled'
EXPIRED = 'expired'
TERMINAL_STATES = (FINISHED, CANCELLED, EXPIRED)


def _float_env(name: str, default: float) -> float:
    raw = env_registry.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def drain_timeout_s() -> float:
    """Seconds a draining replica lets in-flight requests run before
    force-cancelling them (<= 0 cancels immediately)."""
    return _float_env(env_registry.SKYTPU_DRAIN_TIMEOUT_SECONDS,
                      DEFAULT_DRAIN_TIMEOUT_SECONDS)


def tick_hang_s() -> float:
    """Engine-tick watchdog threshold in seconds; 0 disables."""
    return _float_env(env_registry.SKYTPU_TICK_HANG_SECONDS,
                      DEFAULT_TICK_HANG_SECONDS)


def preempt_notice_s() -> float:
    """Spot-preemption notice lead time in seconds: the window
    between the cloud-style warning and the SIGKILL, inside which the
    LB migrates the doomed replica's live streams
    (docs/spot_serving.md)."""
    return _float_env(env_registry.SKYTPU_PREEMPT_NOTICE_S,
                      DEFAULT_PREEMPT_NOTICE_S)


def parse_budget(value: Any) -> Optional[float]:
    """A remaining-seconds budget from a header/body field; None when
    absent or unusable (a malformed budget must degrade to 'no
    deadline', never to a 500 on the serving path)."""
    if value is None:
        return None
    try:
        budget = float(value)
    except (TypeError, ValueError):
        return None
    if budget != budget or budget in (float('inf'), float('-inf')):
        return None
    return budget


def deadline_from_headers(headers: Any,
                          now: Optional[float] = None) -> Optional[float]:
    """Absolute local deadline from a request's remaining-budget
    header (``X-Request-Deadline``), or None when not set."""
    getter = getattr(headers, 'get', None)
    if getter is None:
        return None
    budget = parse_budget(getter(DEADLINE_HEADER))
    if budget is None:
        return None
    return (time.time() if now is None else now) + budget


def remaining(deadline: Optional[float],
              now: Optional[float] = None) -> Optional[float]:
    """Seconds left before ``deadline`` (negative = already past);
    None when there is no deadline."""
    if deadline is None:
        return None
    return deadline - (time.time() if now is None else now)


def budget_headers(deadline: Optional[float],
                   now: Optional[float] = None) -> dict:
    """The remaining-budget header for the next hop ({} without a
    deadline). Clamped at 0 so a just-expired request still carries
    an explicit empty budget rather than a negative one."""
    left = remaining(deadline, now)
    if left is None:
        return {}
    return {DEADLINE_HEADER: f'{max(0.0, left):.3f}'}
