"""Status enums shared across layers.

Mirrors the state machines of the reference (cluster status
``sky/utils/status_lib.py``, job status ``sky/skylet/job_lib.py:121``,
managed-job status ``sky/jobs/state.py:54``) with TPU-pod semantics:
a pod slice is provisioned and fails as a unit, so there is no
per-node partial-UP state.
"""
from __future__ import annotations

import enum


class ClusterStatus(enum.Enum):
    """Cluster lifecycle: INIT -> UP -> STOPPED -> (terminated: row
    removed). DEGRADED = some (not all) hosts gone — on a TPU slice
    the job is dead, but billable instances remain, so the record must
    survive until teardown."""
    INIT = 'INIT'
    UP = 'UP'
    STOPPED = 'STOPPED'
    DEGRADED = 'DEGRADED'

    def colored_str(self) -> str:
        color = {'INIT': 'yellow', 'UP': 'green', 'STOPPED': 'cyan',
                 'DEGRADED': 'red'}[self.value]
        return f'[{color}]{self.value}[/{color}]'


class JobStatus(enum.Enum):
    """Per-cluster job lifecycle (agent job table).

    INIT -> PENDING -> SETTING_UP -> RUNNING -> terminal.
    """
    INIT = 'INIT'
    PENDING = 'PENDING'
    SETTING_UP = 'SETTING_UP'
    RUNNING = 'RUNNING'
    SUCCEEDED = 'SUCCEEDED'
    FAILED = 'FAILED'
    FAILED_SETUP = 'FAILED_SETUP'
    CANCELLED = 'CANCELLED'

    def is_terminal(self) -> bool:
        return self in _TERMINAL_JOB_STATUSES

    @classmethod
    def nonterminal_statuses(cls):
        return [s for s in cls if not s.is_terminal()]


_TERMINAL_JOB_STATUSES = frozenset({
    JobStatus.SUCCEEDED,
    JobStatus.FAILED,
    JobStatus.FAILED_SETUP,
    JobStatus.CANCELLED,
})


class ManagedJobStatus(enum.Enum):
    """Managed (auto-recovering) job lifecycle, controller-side.

    Mirrors reference sky/jobs/state.py:54 & sky/jobs/README.md:30-60:
    PENDING -> SUBMITTED -> STARTING -> RUNNING -> {SUCCEEDED, ...};
    RUNNING -> RECOVERING -> RUNNING on preemption.
    """
    PENDING = 'PENDING'
    SUBMITTED = 'SUBMITTED'
    STARTING = 'STARTING'
    RUNNING = 'RUNNING'
    RECOVERING = 'RECOVERING'
    CANCELLING = 'CANCELLING'
    SUCCEEDED = 'SUCCEEDED'
    CANCELLED = 'CANCELLED'
    FAILED = 'FAILED'
    FAILED_SETUP = 'FAILED_SETUP'
    FAILED_PRECHECKS = 'FAILED_PRECHECKS'
    FAILED_NO_RESOURCE = 'FAILED_NO_RESOURCE'
    FAILED_CONTROLLER = 'FAILED_CONTROLLER'

    def is_terminal(self) -> bool:
        return self in _TERMINAL_MANAGED_STATUSES

    def is_failed(self) -> bool:
        return self in {
            ManagedJobStatus.FAILED,
            ManagedJobStatus.FAILED_SETUP,
            ManagedJobStatus.FAILED_PRECHECKS,
            ManagedJobStatus.FAILED_NO_RESOURCE,
            ManagedJobStatus.FAILED_CONTROLLER,
        }

    @classmethod
    def terminal_statuses(cls):
        return list(_TERMINAL_MANAGED_STATUSES)


_TERMINAL_MANAGED_STATUSES = frozenset({
    ManagedJobStatus.SUCCEEDED,
    ManagedJobStatus.CANCELLED,
    ManagedJobStatus.FAILED,
    ManagedJobStatus.FAILED_SETUP,
    ManagedJobStatus.FAILED_PRECHECKS,
    ManagedJobStatus.FAILED_NO_RESOURCE,
    ManagedJobStatus.FAILED_CONTROLLER,
})


class ReplicaStatus(enum.Enum):
    """Serve replica lifecycle (reference sky/serve/serve_state.py)."""
    PENDING = 'PENDING'
    PROVISIONING = 'PROVISIONING'
    STARTING = 'STARTING'
    READY = 'READY'
    NOT_READY = 'NOT_READY'
    SHUTTING_DOWN = 'SHUTTING_DOWN'
    SHUTDOWN = 'SHUTDOWN'
    FAILED = 'FAILED'
    FAILED_INITIAL_DELAY = 'FAILED_INITIAL_DELAY'
    FAILED_PROBING = 'FAILED_PROBING'
    FAILED_PROVISION = 'FAILED_PROVISION'
    PREEMPTED = 'PREEMPTED'

    def is_terminal(self) -> bool:
        return self in (ReplicaStatus.FAILED, ReplicaStatus.SHUTDOWN)

    def is_failed(self) -> bool:
        return self.value.startswith('FAILED')

    @classmethod
    def terminal_statuses(cls):
        return [s for s in cls if s.is_failed() or s is cls.SHUTTING_DOWN]


class ServiceStatus(enum.Enum):
    """Serve service lifecycle."""
    CONTROLLER_INIT = 'CONTROLLER_INIT'
    REPLICA_INIT = 'REPLICA_INIT'
    CONTROLLER_FAILED = 'CONTROLLER_FAILED'
    READY = 'READY'
    SHUTTING_DOWN = 'SHUTTING_DOWN'
    FAILED = 'FAILED'
    NO_REPLICA = 'NO_REPLICA'
