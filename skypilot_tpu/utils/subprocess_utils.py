"""Subprocess helpers: parallel fan-out, process-tree kill, streaming run.

Re-design of the reference's ``sky/utils/subprocess_utils.py`` and parts of
``sky/skylet/log_lib.py:138`` — a single place for: running a command with
its output teed to a log file, killing a process tree (needed when
cancelling a gang job so every rank's process group dies), and running a
function over many hosts in parallel (the SSH fan-out used for TPU pod
slices, where one logical node has many worker hosts).
"""
from __future__ import annotations

import os
import shlex
import signal
import subprocess
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, IO, List, Optional, Sequence, Tuple, Union

import psutil

from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)


def get_parallel_threads(num_tasks: int) -> int:
    cpus = os.cpu_count() or 4
    return max(1, min(num_tasks, cpus * 4))


def run_in_parallel(fn: Callable[..., Any],
                    args_list: Sequence[Any],
                    num_threads: Optional[int] = None) -> List[Any]:
    """Run fn over args_list in a thread pool; preserves order.

    Exceptions propagate (first one raised). Used for per-host operations
    on a pod slice: rsync, setup, gang start.
    """
    if not args_list:
        return []
    if len(args_list) == 1:
        return [fn(args_list[0])]
    n = num_threads or get_parallel_threads(len(args_list))
    with ThreadPoolExecutor(max_workers=n) as pool:
        return list(pool.map(fn, args_list))


def process_alive(
        pid: Optional[int],
        cmdline_tokens: Optional[Sequence[str]] = None) -> bool:
    """True iff ``pid`` is a live (non-zombie) process and, when
    ``cmdline_tokens`` is given, every token appears as an exact argv
    element of its command line.

    The tokens guard against PID recycling: after a reboot or PID
    wraparound a recorded pid may name an unrelated process — possibly
    another user's, where ``kill(pid, 0)`` raises EPERM. Exact argv
    matching (not substring) lets callers pin the specific invocation,
    e.g. ``('skypilot_tpu.jobs.controller', '123')`` distinguishes job
    123's controller from job 12's. ``cmdline`` is world-readable on
    Linux, so the check works across users; when the process cannot be
    inspected at all and tokens were given, it cannot be one we spawned
    as this user, so it counts as dead.
    """
    if not pid:
        return False
    try:
        proc = psutil.Process(pid)
        if proc.status() == psutil.STATUS_ZOMBIE:
            return False
        if cmdline_tokens is None:
            return True
        argv = proc.cmdline()
        return all(tok in argv for tok in cmdline_tokens)
    except psutil.NoSuchProcess:
        return False
    except psutil.AccessDenied:
        if cmdline_tokens is not None:
            return False
        # Exists but unreadable and no tokens to compare: report alive
        # (conservative — never tear down someone else's live process).
        return True


def kill_process_tree(pid: int, include_parent: bool = True) -> None:
    """SIGTERM then SIGKILL a whole process tree rooted at pid.

    Tolerates the tree racing us to the grave: any member (including
    the root, after the initial lookup) may exit between enumeration
    and signalling — during teardown that is the NORMAL case, not an
    error, so every psutil call is guarded.
    """
    try:
        root = psutil.Process(pid)
    except psutil.Error:
        return  # already gone (or unreachable: nothing we can do)
    try:
        procs = root.children(recursive=True)
    except psutil.NoSuchProcess:
        return
    except psutil.Error as e:
        # Zombie/access races while walking children: we cannot kill
        # what we cannot enumerate — still kill the root (its psutil
        # identity is create-time-checked, so no pid-recycle risk),
        # but say so: a surviving child tree is a leak worth a log.
        logger.warning('kill_process_tree(%d): cannot enumerate '
                       'children (%r); killing root only.', pid, e)
        procs = []
    if include_parent:
        procs.append(root)
    for p in procs:
        try:
            p.terminate()
        except psutil.Error:
            pass
    try:
        _, alive = psutil.wait_procs(procs, timeout=3)
    except psutil.Error:
        alive = procs
    for p in alive:
        try:
            p.kill()
        except psutil.Error:
            pass


def kill_children_processes() -> None:
    kill_process_tree(os.getpid(), include_parent=False)


def run(cmd: Union[str, List[str]],
        *,
        shell: Optional[bool] = None,
        check: bool = True,
        capture: bool = True,
        env: Optional[dict] = None,
        cwd: Optional[str] = None,
        timeout: Optional[float] = None) -> subprocess.CompletedProcess:
    """Thin wrapper over subprocess.run with sane defaults."""
    if shell is None:
        shell = isinstance(cmd, str)
    return subprocess.run(
        cmd,
        shell=shell,
        check=check,
        capture_output=capture,
        text=True,
        env=env,
        cwd=cwd,
        timeout=timeout,
    )


def run_with_log(cmd: Union[str, List[str]],
                 log_path: str,
                 *,
                 stream_logs: bool = False,
                 env: Optional[dict] = None,
                 cwd: Optional[str] = None,
                 shell: Optional[bool] = None,
                 line_processor: Optional[Callable[[str], None]] = None,
                 start_new_session: bool = True) -> int:
    """Run cmd, teeing combined stdout/stderr to log_path.

    Equivalent of reference sky/skylet/log_lib.py:138 `run_with_log`.
    Returns the exit code. The child is started in its own session so a
    cancel can kill the entire process group.
    """
    log_path = os.path.expanduser(log_path)
    os.makedirs(os.path.dirname(log_path) or '.', exist_ok=True)
    if shell is None:
        shell = isinstance(cmd, str)
    with open(log_path, 'a', encoding='utf-8') as log_file:
        proc = subprocess.Popen(
            cmd,
            shell=shell,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            bufsize=1,
            env=env,
            cwd=cwd,
            start_new_session=start_new_session,
        )
        assert proc.stdout is not None
        try:
            for line in proc.stdout:
                log_file.write(line)
                log_file.flush()
                if stream_logs:
                    print(line, end='', flush=True)
                if line_processor is not None:
                    line_processor(line)
        finally:
            proc.stdout.close()
        return proc.wait()


def command_with_rc_and_output(cmd: str) -> Tuple[int, str, str]:
    proc = subprocess.run(cmd,
                          shell=True,
                          capture_output=True,
                          text=True,
                          check=False)
    return proc.returncode, proc.stdout, proc.stderr


def quote(s: str) -> str:
    return shlex.quote(s)


def daemonize(argv: List[str],
              log_path: str,
              env: Optional[dict] = None,
              cwd: Optional[str] = None) -> int:
    """Start argv fully detached (own session, output to log file).

    Used for the per-cluster agent daemon and detached job drivers —
    the equivalent of the reference's `nohup python -m sky.skylet.skylet`
    (sky/provision/instance_setup.py:467).
    Returns the child PID.
    """
    log_path = os.path.expanduser(log_path)
    os.makedirs(os.path.dirname(log_path) or '.', exist_ok=True)
    with open(log_path, 'a', encoding='utf-8') as log_file:
        proc = subprocess.Popen(
            argv,
            stdout=log_file,
            stderr=subprocess.STDOUT,
            stdin=subprocess.DEVNULL,
            start_new_session=True,
            env=env,
            cwd=cwd,
        )
    return proc.pid


def wait_for(predicate: Callable[[], bool],
             timeout: float,
             interval: float = 0.2,
             desc: str = 'condition') -> None:
    """Poll predicate until true or raise TimeoutError."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return
        time.sleep(interval)
    raise TimeoutError(f'Timed out after {timeout}s waiting for {desc}')
