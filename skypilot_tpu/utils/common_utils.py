"""Small shared helpers: ids, name validation, yaml, sizes, retries.

Re-design of reference ``sky/utils/common_utils.py`` (subset we need).
"""
from __future__ import annotations

import difflib
import functools
import getpass
import hashlib
import os
import re
import uuid
from typing import Any, Callable, Dict, List, Optional, Union

import yaml

CLUSTER_NAME_VALID_REGEX = re.compile(r'^[a-zA-Z]([-_.a-zA-Z0-9]*[a-zA-Z0-9])?$')
_USER_HASH_FILE = os.path.expanduser('~/.skytpu/user_hash')
USER_HASH_LENGTH = 8


def generate_user_hash() -> str:
    hash_str = hashlib.md5(
        (getpass.getuser() + str(uuid.getnode())).encode()).hexdigest()
    return hash_str[:USER_HASH_LENGTH]


@functools.lru_cache(maxsize=1)
def get_user_hash() -> str:
    """Stable per-user hash; persisted so cluster names are stable."""
    env = os.environ.get('SKYTPU_USER_HASH')
    if env:
        return env[:USER_HASH_LENGTH]
    if os.path.exists(_USER_HASH_FILE):
        with open(_USER_HASH_FILE, encoding='utf-8') as f:
            cached = f.read().strip()
        if cached:
            return cached[:USER_HASH_LENGTH]
    user_hash = generate_user_hash()
    os.makedirs(os.path.dirname(_USER_HASH_FILE), exist_ok=True)
    with open(_USER_HASH_FILE, 'w', encoding='utf-8') as f:
        f.write(user_hash)
    return user_hash


def get_user_name() -> str:
    return os.environ.get('SKYTPU_USER', None) or getpass.getuser()


def check_cluster_name_is_valid(name: Optional[str]) -> None:
    if name is None:
        return
    if not CLUSTER_NAME_VALID_REGEX.match(name):
        from skypilot_tpu import exceptions
        raise exceptions.InvalidTaskError(
            f'Cluster name {name!r} is invalid: must start with a letter, '
            'contain only letters, digits, -, _, . and end alphanumeric.')


def make_cluster_name_on_cloud(display_name: str, max_length: int = 35) -> str:
    """Append user hash; truncate+hash long names for cloud resource limits."""
    suffix = f'-{get_user_hash()}'
    base = display_name.lower().replace('_', '-').replace('.', '-')
    if len(base) + len(suffix) > max_length:
        digest = hashlib.md5(base.encode()).hexdigest()[:4]
        base = base[:max_length - len(suffix) - 5] + '-' + digest
    return base + suffix


def get_global_job_id(run_timestamp: str, cluster_name: str,
                      job_id: Union[int, str]) -> str:
    return f'{run_timestamp}_{cluster_name}_{job_id}'


def base36(n: int) -> str:
    chars = '0123456789abcdefghijklmnopqrstuvwxyz'
    out = ''
    while True:
        n, r = divmod(n, 36)
        out = chars[r] + out
        if n == 0:
            return out


def generate_run_id(length: int = 8) -> str:
    return uuid.uuid4().hex[:length]


def read_yaml(path: str) -> Dict[str, Any]:
    with open(os.path.expanduser(path), encoding='utf-8') as f:
        return yaml.safe_load(f)


def read_yaml_all(path: str) -> List[Dict[str, Any]]:
    with open(os.path.expanduser(path), encoding='utf-8') as f:
        configs = list(yaml.safe_load_all(f))
    return [c for c in configs if c is not None] or [{}]


def dump_yaml(path: str, config: Union[Dict, List[Dict]]) -> None:
    with open(os.path.expanduser(path), 'w', encoding='utf-8') as f:
        f.write(dump_yaml_str(config))


def dump_yaml_str(config: Union[Dict, List[Dict]]) -> str:

    class LineBreakDumper(yaml.SafeDumper):

        def write_line_break(self, data=None):
            super().write_line_break(data)
            if len(self.indents) == 1:
                super().write_line_break()

    if isinstance(config, list):
        dump_func = yaml.dump_all
    else:
        dump_func = yaml.dump
    return dump_func(config,
                     Dumper=LineBreakDumper,
                     sort_keys=False,
                     default_flow_style=False)


def parse_cpus_memory(value: Optional[Union[int, float, str]]
                      ) -> Optional[tuple]:
    """Parse '4', '4+', 4 → (4.0, is_plus). None → None."""
    if value is None:
        return None
    s = str(value).strip()
    plus = s.endswith('+')
    if plus:
        s = s[:-1]
    try:
        num = float(s)
    except ValueError:
        from skypilot_tpu import exceptions
        raise exceptions.InvalidResourcesError(
            f'Invalid cpus/memory spec {value!r}; expected e.g. "4" or "4+".'
        ) from None
    return num, plus


def format_float(x: Union[int, float], precision: int = 2) -> str:
    if isinstance(x, int) or x == int(x):
        return str(int(x))
    return f'{x:.{precision}f}'


def close_matches(word: str, candidates: List[str]) -> List[str]:
    return difflib.get_close_matches(word, candidates, n=3, cutoff=0.7)


def retry(fn: Optional[Callable] = None,
          *,
          max_retries: int = 3,
          initial_backoff: float = 1.0,
          exceptions_to_retry=(Exception,)) -> Callable:
    """Retry decorator — thin sugar over the one shared RetryPolicy
    implementation (utils/retry.py)."""
    if fn is None:
        return functools.partial(retry,
                                 max_retries=max_retries,
                                 initial_backoff=initial_backoff,
                                 exceptions_to_retry=exceptions_to_retry)

    from skypilot_tpu.utils import retry as retry_lib
    policy = retry_lib.RetryPolicy(
        max_attempts=max_retries,
        initial_backoff=initial_backoff,
        jitter='none',
        retryable=exceptions_to_retry,
        site=f'common_utils.{getattr(fn, "__name__", "fn")}')

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return policy.call(fn, *args, **kwargs)

    return wrapper


def format_exception(e: BaseException, use_bracket: bool = False) -> str:
    name = type(e).__name__
    if use_bracket:
        return f'[{name}] {e}'
    return f'{name}: {e}'


def truncate_long_string(s: str, max_length: int = 35) -> str:
    if len(s) <= max_length:
        return s
    return s[:max_length - 3] + '...'


def expand_path(path: str) -> str:
    return os.path.abspath(os.path.expanduser(path))
