"""jax.profiler hooks for pod workers — span-emitting wrappers.

Host-side timing in this repo has exactly one primitive: the span
tracer (:mod:`skypilot_tpu.trace`, docs/tracing.md). What remains
here is the DEVICE-level capture that spans cannot express — XLA/HLO
traces via jax.profiler — wrapped so each capture also emits a span
(``jax.profiler.capture``): the merged trace shows *when* in the run
the TensorBoard capture happened, and the capture dir rides on the
span for correlation.

Two knobs, both env-driven so recipes need no code changes:

- ``SKYTPU_PROFILER_PORT``: start jax.profiler's gRPC server on every
  worker at init (``initialize_from_env`` calls
  ``maybe_start_profiler_server``); attach TensorBoard's profile
  capture to ``<worker_ip>:<port>`` for on-demand traces of a live
  job.
- ``SKYTPU_PROFILE_DIR``: bounded automatic capture — ``maybe_trace``
  wraps a region (e.g. one train step) in ``jax.profiler.trace``
  writing a TensorBoard-loadable trace there, once.
"""
from __future__ import annotations

import contextlib
import os
from typing import Iterator, Optional

from skypilot_tpu import trace as trace_lib
from skypilot_tpu.utils import env_registry
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

PROFILER_PORT_ENV = env_registry.SKYTPU_PROFILER_PORT
PROFILE_DIR_ENV = env_registry.SKYTPU_PROFILE_DIR

_server_started = False
_traced_once = False


def maybe_start_profiler_server() -> Optional[int]:
    """Start jax.profiler's server if SKYTPU_PROFILER_PORT is set."""
    global _server_started
    port = os.environ.get(PROFILER_PORT_ENV)
    if not port or _server_started:
        return None
    import jax
    jax.profiler.start_server(int(port))
    _server_started = True
    logger.info('jax.profiler server listening on :%s.', port)
    return int(port)


@contextlib.contextmanager
def maybe_trace(step: Optional[int] = None,
                capture_step: int = 2) -> Iterator[None]:
    """Trace this region to $SKYTPU_PROFILE_DIR (once, at
    ``capture_step`` so compilation noise from step 0/1 is skipped).
    The capture region is also a span, so merged distributed traces
    mark where the device profile sits in the run."""
    global _traced_once
    log_dir = os.environ.get(PROFILE_DIR_ENV)
    should = (log_dir and not _traced_once and
              (step is None or step == capture_step))
    if not should:
        yield
        return
    import jax
    _traced_once = True
    os.makedirs(os.path.expanduser(log_dir), exist_ok=True)
    logger.info('Capturing jax.profiler trace to %s.', log_dir)
    with trace_lib.span('jax.profiler.capture', log_dir=log_dir,
                        step=step):
        with jax.profiler.trace(os.path.expanduser(log_dir)):
            yield
