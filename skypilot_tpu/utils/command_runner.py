"""Command runners — how the framework reaches cluster hosts.

Re-design of reference ``sky/utils/command_runner.py:435,711``. Two
implementations:

- :class:`SSHCommandRunner` — ssh/rsync with ControlMaster multiplexing,
  used for real TPU-VM hosts (each worker of a pod slice gets one).
- :class:`LocalProcessRunner` — executes directly via subprocess with a
  per-host sandbox directory standing in for the remote home. This is
  the hermetic runner behind the Local cloud: `~/x` paths are rewritten
  into the host dir, so N simulated hosts stay isolated on one machine.

The backend is runner-agnostic: gang exec, setup, rsync and codegen all
go through this interface, which is what makes the whole control plane
testable without SSH (SURVEY.md §4 "fake pod slice" lesson).
"""
from __future__ import annotations

import os
import shlex
import shutil
import subprocess
from typing import Dict, List, Optional, Tuple, Union

from skypilot_tpu import exceptions
from skypilot_tpu.utils import fault_injection
from skypilot_tpu.utils import log as sky_logging
from skypilot_tpu.utils import retry as retry_lib
from skypilot_tpu.utils import subprocess_utils

logger = sky_logging.init_logger(__name__)

SSH_OPTIONS = [
    '-o', 'StrictHostKeyChecking=no',
    '-o', 'UserKnownHostsFile=/dev/null',
    '-o', 'IdentitiesOnly=yes',
    '-o', 'ConnectTimeout=30',
    '-o', 'ServerAliveInterval=20',
    '-o', 'ServerAliveCountMax=3',
    '-o', 'LogLevel=ERROR',
    '-o', 'ControlMaster=auto',
    '-o', 'ControlPersist=300s',
]


def _as_script(cmd: Union[str, List[str]]) -> str:
    if isinstance(cmd, list):
        return ' '.join(shlex.quote(c) for c in cmd)
    return cmd


def shell_path(path: str) -> str:
    """Quote a path for a remote shell, preserving ~ expansion.

    ``shlex.quote('~/x')`` would ship a literal tilde; render it as
    ``"$HOME"/...`` instead so remote and local agree on the location.
    """
    if path == '~' or path.startswith('~/'):
        rest = path[2:]
        return '"$HOME"' + (f'/{shlex.quote(rest)}' if rest else '')
    return shlex.quote(path)


class CommandRunner:
    """Abstract host handle."""

    def __init__(self, host_id: str, ip: str) -> None:
        self.host_id = host_id
        self.ip = ip

    def run(self,
            cmd: Union[str, List[str]],
            *,
            env: Optional[Dict[str, str]] = None,
            log_path: str = '/dev/null',
            stream_logs: bool = False,
            require_outputs: bool = False,
            cwd: Optional[str] = None,
            check: bool = False,
            line_processor=None) -> Union[int, Tuple[int, str, str]]:
        raise NotImplementedError

    def rsync(self, source: str, target: str, *, up: bool,
              log_path: str = '/dev/null') -> None:
        raise NotImplementedError

    def check_connection(self) -> bool:
        rc = self.run('true')
        return rc == 0

    def _maybe_raise(self, check: bool, rc: int, cmd_str: str,
                     stderr: str = '') -> None:
        if check and rc != 0:
            raise exceptions.CommandError(rc, cmd_str, stderr)

    def _injected_run_fault(
            self, check: bool, require_outputs: bool,
            cmd_str: str) -> Optional[Union[int, Tuple[int, str, str]]]:
        """Chaos site `command_runner.run`: a fired ssh_failure plays a
        dead transport — exit code 255 exactly like a real ssh client,
        so check= semantics and callers behave identically."""
        fault = fault_injection.poll('command_runner.run',
                                     host_id=self.host_id, ip=self.ip)
        if fault is None:
            return None
        stderr = f'[fault-injection] {fault.kind.value} on {self.host_id}'
        self._maybe_raise(check, 255, cmd_str, stderr)
        return (255, '', stderr) if require_outputs else 255


class LocalProcessRunner(CommandRunner):
    """Runs commands locally inside a per-host sandbox dir.

    ``~`` and ``$HOME`` in commands resolve to the sandbox via the HOME
    env var, so the same scripts the SSH runner would execute remotely
    work unchanged against simulated hosts.
    """

    def __init__(self, host_id: str, host_dir: str) -> None:
        super().__init__(host_id, '127.0.0.1')
        self.host_dir = os.path.abspath(os.path.expanduser(host_dir))
        os.makedirs(self.host_dir, exist_ok=True)

    def translate(self, path: str) -> str:
        """Map a remote-style path into the sandbox.

        Both ``~/...`` and absolute paths resolve under the host dir —
        a simulated host must never write to the real filesystem root
        (e.g. ``file_mounts: {/data: ./x}``).
        """
        if path.startswith('~'):
            return os.path.join(self.host_dir, path.lstrip('~/'))
        if os.path.isabs(path):
            return os.path.join(self.host_dir, path.lstrip('/'))
        return path

    def run(self,
            cmd: Union[str, List[str]],
            *,
            env: Optional[Dict[str, str]] = None,
            log_path: str = '/dev/null',
            stream_logs: bool = False,
            require_outputs: bool = False,
            cwd: Optional[str] = None,
            check: bool = False,
            line_processor=None) -> Union[int, Tuple[int, str, str]]:
        script = _as_script(cmd)
        injected = self._injected_run_fault(check, require_outputs, script)
        if injected is not None:
            return injected
        full_env = dict(os.environ)
        full_env['HOME'] = self.host_dir
        # Keep the framework importable inside the sandbox.
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        existing = full_env.get('PYTHONPATH', '')
        if repo_root not in existing.split(os.pathsep):
            full_env['PYTHONPATH'] = (repo_root + (os.pathsep + existing
                                                   if existing else ''))
        if env:
            full_env.update(env)
        cwd = cwd or self.host_dir
        if require_outputs:
            proc = subprocess.run(['bash', '-c', script],
                                  capture_output=True,
                                  text=True,
                                  env=full_env,
                                  cwd=cwd,
                                  check=False)
            with open(os.path.expanduser(log_path), 'a',
                      encoding='utf-8') as f:
                f.write(proc.stdout)
                f.write(proc.stderr)
            self._maybe_raise(check, proc.returncode, script, proc.stderr)
            return proc.returncode, proc.stdout, proc.stderr
        rc = subprocess_utils.run_with_log(['bash', '-c', script],
                                           log_path,
                                           stream_logs=stream_logs,
                                           env=full_env,
                                           cwd=cwd,
                                           shell=False,
                                           line_processor=line_processor)
        self._maybe_raise(check, rc, script)
        return rc

    def rsync(self, source: str, target: str, *, up: bool,
              log_path: str = '/dev/null') -> None:
        if up:
            src = os.path.expanduser(source)
            dst = self.translate(target)
        else:
            src = self.translate(source)
            dst = os.path.expanduser(target)
        if not os.path.exists(src.rstrip('/')):
            raise exceptions.CommandError(
                1, f'rsync {source} -> {target}', f'{src} does not exist')
        os.makedirs(os.path.dirname(dst.rstrip('/')) or '/', exist_ok=True)
        if os.path.isdir(src.rstrip('/')):
            # rsync semantics: 'src/' copies contents into dst; 'src'
            # copies the directory itself under dst. The SSH runner gets
            # this from real rsync; match it here so local tests see
            # identical layouts.
            if not source.endswith('/'):
                dst = os.path.join(dst, os.path.basename(src.rstrip('/')))
            shutil.copytree(src.rstrip('/'), dst, dirs_exist_ok=True,
                            ignore=shutil.ignore_patterns('.git'))
        else:
            os.makedirs(os.path.dirname(dst) or '/', exist_ok=True)
            shutil.copy2(src, dst)


def runner_from_host_entry(entry: Dict,
                           in_container: bool = True) -> CommandRunner:
    """Build a runner from a hosts.json entry (written at provision
    time; see backend). kind 'local' -> sandboxed local execution,
    'ssh' -> real remote host.

    Kubernetes entries default to the kubectl-exec runner; entries
    with ``mode: port-forward`` (clusters whose admission policy
    blocks ``exec``) get SSH through a kubectl port-forward tunnel
    instead — the pod must run sshd (reference ssh-jump/port-forward
    modes, sky/utils/command_runner.py:711).

    An entry carrying a ``docker`` config wraps the host runner in
    :class:`DockerCommandRunner` so job setup/run commands execute
    inside the task container. Control-plane callers (runtime install,
    agent start, log sync) pass ``in_container=False`` to reach the
    host itself.
    """
    kind = entry.get('kind', 'ssh')
    if kind == 'local':
        runner: CommandRunner = LocalProcessRunner(entry['host_id'],
                                                   entry['host_dir'])
    elif kind == 'k8s' and entry.get('mode') == 'port-forward':
        runner = KubernetesPortForwardRunner(
            namespace=entry['namespace'],
            pod=entry['pod'],
            ssh_user=entry.get('user', 'root'),
            ssh_private_key=entry.get('key', '~/.ssh/id_rsa'),
            context=entry.get('context'),
        )
    elif kind == 'k8s':
        runner = KubernetesCommandRunner(
            namespace=entry['namespace'],
            pod=entry['pod'],
            context=entry.get('context'),
        )
    else:
        runner = SSHCommandRunner(
            ip=entry['ip'],
            ssh_user=entry['user'],
            ssh_private_key=entry['key'],
            port=entry.get('port', 22),
            ssh_proxy_command=entry.get('proxy_command'),
        )
    if in_container and entry.get('docker'):
        return DockerCommandRunner(runner, entry['docker'])
    return runner


class DockerCommandRunner(CommandRunner):
    """Executes commands inside a task container on a host.

    Wraps any host runner (reference sky/utils/command_runner.py:435
    runs docker through a modified SSH runner instead; wrapping keeps
    one docker implementation for SSH, local and future host kinds).
    ``run`` wraps the script in ``docker exec``; env exports and cwd
    are folded INTO the wrapped script so they take effect inside the
    container, not in the docker client's environment. ``rsync``
    delegates to the host runner unchanged — the container bind-mounts
    the host home (docker_utils.bootstrap_command), so host-side syncs
    are already visible inside.
    """

    def __init__(self, inner: CommandRunner,
                 docker_config: Dict) -> None:
        super().__init__(inner.host_id, inner.ip)
        self.inner = inner
        self.docker_config = docker_config

    def run(self,
            cmd: Union[str, List[str]],
            *,
            env: Optional[Dict[str, str]] = None,
            log_path: str = '/dev/null',
            stream_logs: bool = False,
            require_outputs: bool = False,
            cwd: Optional[str] = None,
            check: bool = False,
            line_processor=None) -> Union[int, Tuple[int, str, str]]:
        from skypilot_tpu.utils import docker_utils
        script = _as_script(cmd)
        if env:
            exports = '; '.join(
                f'export {k}={shlex.quote(v)}' for k, v in env.items())
            script = f'{exports}; {script}'
        if cwd:
            script = f'cd {shell_path(cwd)} && {script}'
        wrapped = docker_utils.exec_command(self.docker_config, script)
        return self.inner.run(wrapped,
                              log_path=log_path,
                              stream_logs=stream_logs,
                              require_outputs=require_outputs,
                              check=check,
                              line_processor=line_processor)

    def rsync(self, source: str, target: str, *, up: bool,
              log_path: str = '/dev/null') -> None:
        self.inner.rsync(source, target, up=up, log_path=log_path)

    def check_connection(self) -> bool:
        # Probes the container, not just the host: a crashed container
        # reads as a dead worker, which the driver converts into a job
        # failure the jobs controller can recover from.
        try:
            return self.run('true') == 0
        except Exception:  # pylint: disable=broad-except
            return False

    def bootstrap(self, log_path: str = '/dev/null') -> None:
        """Bring up the task container on this host (idempotent)."""
        import os
        import tempfile

        from skypilot_tpu.utils import docker_utils
        login = self.docker_config.get('login')
        if login and login.get('password'):
            # Ship the registry password as a 0600 file via rsync so
            # it never appears on a remote command line (`ps`) or in
            # docker_setup-*.log; bootstrap_command reads it with
            # --password-stdin and removes it.
            fd, local = tempfile.mkstemp(prefix='skytpu-docker-cred-')
            try:
                os.fchmod(fd, 0o600)
                with os.fdopen(fd, 'w') as f:
                    f.write(login['password'])
                self.inner.rsync(local, f'~/{docker_utils.CRED_FILE}',
                                 up=True, log_path=log_path)
            finally:
                os.unlink(local)
        self.inner.run(docker_utils.bootstrap_command(self.docker_config),
                       log_path=log_path, check=True)

    def kill_workload(self, log_path: str = '/dev/null') -> None:
        """Kill all processes inside the container (restart it)."""
        from skypilot_tpu.utils import docker_utils
        self.inner.run(
            docker_utils.kill_workload_command(self.docker_config),
            log_path=log_path)


def kill_docker_workloads(runners: List[CommandRunner],
                          timeout: float = 10.0) -> None:
    """Best-effort, bounded-parallel restart of every docker runner's
    container. Used when tearing down a containered job (cancel,
    worker death): docker-exec'd processes survive their exec client,
    so killing the client tree alone leaves the workload holding TPU
    devices. One wedged host's SSH must not block the others or the
    caller — each restart runs in a daemon thread joined at
    ``timeout``.
    """
    import threading
    docker_runners = [r for r in runners
                      if isinstance(r, DockerCommandRunner)]
    threads = [
        threading.Thread(target=r.kill_workload, daemon=True)
        for r in docker_runners
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)


class SSHCommandRunner(CommandRunner):
    """ssh/rsync against a real host (a TPU-VM worker)."""

    def __init__(self,
                 ip: str,
                 ssh_user: str,
                 ssh_private_key: str,
                 port: int = 22,
                 ssh_proxy_command: Optional[str] = None) -> None:
        super().__init__(f'{ssh_user}@{ip}:{port}', ip)
        self.ssh_user = ssh_user
        self.ssh_private_key = ssh_private_key
        self.port = port
        self.ssh_proxy_command = ssh_proxy_command
        self._control_path = os.path.expanduser(
            f'~/.skytpu/ssh_control/{ip}-{port}')
        os.makedirs(os.path.dirname(self._control_path), exist_ok=True)

    def _ssh_base(self) -> List[str]:
        args = ['ssh'] + SSH_OPTIONS + [
            '-o', f'ControlPath={self._control_path}',
            '-i', os.path.expanduser(self.ssh_private_key),
            '-p', str(self.port),
        ]
        if self.ssh_proxy_command:
            args += ['-o', f'ProxyCommand={self.ssh_proxy_command}']
        return args + [f'{self.ssh_user}@{self.ip}']

    def run(self,
            cmd: Union[str, List[str]],
            *,
            env: Optional[Dict[str, str]] = None,
            log_path: str = '/dev/null',
            stream_logs: bool = False,
            require_outputs: bool = False,
            cwd: Optional[str] = None,
            check: bool = False,
            line_processor=None) -> Union[int, Tuple[int, str, str]]:
        script = _as_script(cmd)
        injected = self._injected_run_fault(check, require_outputs, script)
        if injected is not None:
            return injected
        if env:
            exports = '; '.join(
                f'export {k}={shlex.quote(v)}' for k, v in env.items())
            script = f'{exports}; {script}'
        if cwd:
            script = f'cd {shell_path(cwd)} && {script}'
        full_cmd = self._ssh_base() + [
            'bash', '--login', '-c',
            shlex.quote(script)
        ]
        if require_outputs:
            proc = subprocess.run(full_cmd,
                                  capture_output=True,
                                  text=True,
                                  check=False)
            with open(os.path.expanduser(log_path), 'a',
                      encoding='utf-8') as f:
                f.write(proc.stdout)
                f.write(proc.stderr)
            self._maybe_raise(check, proc.returncode, script, proc.stderr)
            return proc.returncode, proc.stdout, proc.stderr
        rc = subprocess_utils.run_with_log(full_cmd,
                                           log_path,
                                           stream_logs=stream_logs,
                                           shell=False,
                                           line_processor=line_processor)
        self._maybe_raise(check, rc, script)
        return rc

    def rsync(self, source: str, target: str, *, up: bool,
              log_path: str = '/dev/null') -> None:
        ssh_cmd = ' '.join(
            ['ssh'] + SSH_OPTIONS +
            ['-o', f'ControlPath={self._control_path}',
             '-i', self.ssh_private_key, '-p', str(self.port)])
        # No --delete: merge semantics (matching LocalProcessRunner's
        # copytree) so re-syncing a workdir never destroys artifacts a
        # job already wrote on the remote side.
        rsync_cmd = [
            'rsync', '-avz', '--exclude', '.git',
            '-e', ssh_cmd,
        ]
        if up:
            rsync_cmd += [source, f'{self.ssh_user}@{self.ip}:{target}']
        else:
            rsync_cmd += [f'{self.ssh_user}@{self.ip}:{source}', target]
        rc = subprocess_utils.run_with_log(rsync_cmd, log_path, shell=False)
        if rc != 0:
            raise exceptions.CommandError(
                rc, ' '.join(rsync_cmd), f'rsync failed; see {log_path}')


class KubernetesCommandRunner(CommandRunner):
    """kubectl-exec against a pod (reference
    sky/utils/command_runner.py:711 KubernetesCommandRunner): pods run
    no sshd, so commands go through the API server's exec channel and
    file sync through a tar pipe."""

    def __init__(self, namespace: str, pod: str,
                 context: Optional[str] = None,
                 container: str = 'skytpu') -> None:
        super().__init__(f'{namespace}/{pod}', pod)
        self.namespace = namespace
        self.pod = pod
        self.context = context
        self.container = container

    def _kubectl(self, *args: str, stdin_flag: bool = False) -> List[str]:
        cmd = ['kubectl']
        if self.context:
            cmd += ['--context', self.context]
        cmd += ['-n', self.namespace, 'exec']
        if stdin_flag:
            cmd += ['-i']
        cmd += [self.pod, '-c', self.container, '--']
        cmd += list(args)
        return cmd

    def run(self,
            cmd: Union[str, List[str]],
            *,
            env: Optional[Dict[str, str]] = None,
            log_path: str = '/dev/null',
            stream_logs: bool = False,
            require_outputs: bool = False,
            cwd: Optional[str] = None,
            check: bool = False,
            line_processor=None) -> Union[int, Tuple[int, str, str]]:
        script = _as_script(cmd)
        injected = self._injected_run_fault(check, require_outputs, script)
        if injected is not None:
            return injected
        if env:
            exports = '; '.join(
                f'export {k}={shlex.quote(v)}' for k, v in env.items())
            script = f'{exports}; {script}'
        if cwd:
            script = f'cd {shell_path(cwd)} && {script}'
        full_cmd = self._kubectl('/bin/sh', '-c', script)
        if require_outputs:
            proc = subprocess.run(full_cmd, capture_output=True,
                                  text=True, check=False)
            with open(os.path.expanduser(log_path), 'a',
                      encoding='utf-8') as f:
                f.write(proc.stdout)
                f.write(proc.stderr)
            self._maybe_raise(check, proc.returncode, script, proc.stderr)
            return proc.returncode, proc.stdout, proc.stderr
        rc = subprocess_utils.run_with_log(full_cmd, log_path,
                                           stream_logs=stream_logs,
                                           shell=False,
                                           line_processor=line_processor)
        self._maybe_raise(check, rc, script)
        return rc

    def rsync(self, source: str, target: str, *, up: bool,
              log_path: str = '/dev/null') -> None:
        """tar-over-exec (no rsync binary needed in the image)."""
        if up:
            src_dir = os.path.dirname(os.path.abspath(source)) or '/'
            base = os.path.basename(source.rstrip('/'))
            if source.endswith('/'):
                # contents-into-target semantics
                src_dir, base = os.path.abspath(source), '.'
            pack = subprocess.Popen(
                ['tar', 'cf', '-', '--exclude', '.git', '-C', src_dir,
                 base],
                stdout=subprocess.PIPE)
            unpack = self._kubectl(
                '/bin/sh', '-c',
                f'mkdir -p {shell_path(target)} && '
                f'tar xf - -C {shell_path(target)}',
                stdin_flag=True)
            proc = subprocess.run(unpack, stdin=pack.stdout,
                                  capture_output=True, check=False)
            pack.stdout.close()
            pack.wait()
            rc = proc.returncode or pack.returncode
        else:
            if source.endswith('/'):
                # rsync contents semantics: extract the dir's entries
                # directly under target (matches the SSH runner).
                src_dir, base = source.rstrip('/'), '.'
            else:
                src_dir = os.path.dirname(source.rstrip('/')) or '/'
                base = os.path.basename(source.rstrip('/'))
            pack = self._kubectl(
                '/bin/sh', '-c',
                f'tar cf - -C {shell_path(src_dir)} {shell_path(base)}')
            os.makedirs(os.path.expanduser(target), exist_ok=True)
            p1 = subprocess.Popen(pack, stdout=subprocess.PIPE)
            proc = subprocess.run(
                ['tar', 'xf', '-', '-C', os.path.expanduser(target)],
                stdin=p1.stdout, capture_output=True, check=False)
            p1.stdout.close()
            p1.wait()
            rc = proc.returncode or p1.returncode
        if rc != 0:
            stderr = (proc.stderr or b'').decode(errors='replace')
            with open(os.path.expanduser(log_path), 'a',
                      encoding='utf-8') as f:
                f.write(stderr)
            raise exceptions.CommandError(
                rc, f'k8s rsync {source} -> {target}',
                f'tar-over-exec failed: {stderr[-500:]}')

    def check_connection(self) -> bool:
        try:
            return self.run('true') == 0
        except Exception:  # pylint: disable=broad-except
            return False


class KubernetesPortForwardRunner(SSHCommandRunner):
    """SSH through a ``kubectl port-forward`` tunnel.

    The runner mode for clusters whose admission policy denies
    ``kubectl exec`` (reference sky/utils/command_runner.py:711
    port-forward mode + the ssh-jump machinery in
    sky/provision/kubernetes): the pod runs sshd, the API server
    carries only a TCP tunnel to pod:22, and ssh/rsync then work
    exactly as against a VM — including real rsync, which the exec
    runner must emulate with tar pipes.

    The tunnel is lazy (started on first use) and self-healing (a
    dead tunnel process is restarted on the next call).
    """

    # Overridable clock so tunnel-readiness tests run wall-clock-free.
    _clock = retry_lib.REAL_CLOCK

    def __init__(self, namespace: str, pod: str, ssh_user: str,
                 ssh_private_key: str,
                 context: Optional[str] = None,
                 remote_port: int = 22) -> None:
        self.namespace = namespace
        self.pod = pod
        self.context = context
        self.remote_port = remote_port
        self._tunnel: Optional[subprocess.Popen] = None
        # Local port is assigned when the tunnel starts.
        super().__init__(ip='127.0.0.1', ssh_user=ssh_user,
                         ssh_private_key=ssh_private_key, port=0)
        self.host_id = f'{namespace}/{pod}(port-forward)'

    def _tunnel_cmd(self, local_port: int) -> List[str]:
        cmd = ['kubectl']
        if self.context:
            cmd += ['--context', self.context]
        cmd += ['-n', self.namespace, 'port-forward',
                f'pod/{self.pod}', f'{local_port}:{self.remote_port}']
        return cmd

    @staticmethod
    def _free_port() -> int:
        import socket
        with socket.socket() as s:
            s.bind(('127.0.0.1', 0))
            return s.getsockname()[1]

    def ensure_tunnel(self, timeout: float = 30.0) -> int:
        """Start (or restart) the port-forward; returns the local
        port. Readiness = the local socket accepts a connection.

        The readiness wait runs on the shared RetryPolicy (overall
        deadline, monotonic clock) instead of a hand-rolled
        ``time.time()`` loop, so tests drive it with a FakeClock.
        """
        import socket
        fault_injection.inject('command_runner.ensure_tunnel',
                               host_id=self.host_id)
        if self._tunnel is not None and self._tunnel.poll() is None:
            return self.port
        local_port = self._free_port()
        self._tunnel = subprocess.Popen(
            self._tunnel_cmd(local_port),
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        # Runners are built fresh per operation and most callers never
        # close() them: tie the tunnel's lifetime to this object (and
        # to interpreter exit) so kubectl processes cannot accumulate
        # on a long-lived agent/controller host.
        import weakref
        weakref.finalize(self, _terminate_tunnel, self._tunnel)
        policy = retry_lib.RetryPolicy(max_attempts=None,
                                       initial_backoff=0.2,
                                       multiplier=1.0,
                                       jitter='none',
                                       deadline=timeout,
                                       clock=self._clock,
                                       site='command_runner.'
                                            'ensure_tunnel')
        state = policy.new_state()
        while True:
            if self._tunnel.poll() is not None:
                raise exceptions.CommandError(
                    self._tunnel.returncode or 1,
                    ' '.join(self._tunnel_cmd(local_port)),
                    'kubectl port-forward exited during startup')
            try:
                with socket.create_connection(
                        ('127.0.0.1', local_port), timeout=1):
                    break
            except OSError:
                if not state.should_retry():
                    self.close()
                    raise exceptions.CommandError(
                        1, ' '.join(self._tunnel_cmd(local_port)),
                        f'port-forward tunnel not ready in {timeout}s')
                state.sleep()
        self.port = local_port
        # Control path keys on (ip, port); the port just changed.
        self._control_path = os.path.expanduser(
            f'~/.skytpu/ssh_control/{self.ip}-{self.port}')
        os.makedirs(os.path.dirname(self._control_path), exist_ok=True)
        return local_port

    def close(self) -> None:
        if self._tunnel is not None:
            self._tunnel.terminate()
            try:
                self._tunnel.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._tunnel.kill()
            self._tunnel = None

    def run(self, cmd, **kwargs):
        self.ensure_tunnel()
        return super().run(cmd, **kwargs)

    def rsync(self, source: str, target: str, *, up: bool,
              log_path: str = '/dev/null') -> None:
        self.ensure_tunnel()
        super().rsync(source, target, up=up, log_path=log_path)

    def check_connection(self) -> bool:
        try:
            self.ensure_tunnel()
            return super().run('true') == 0
        except Exception:  # pylint: disable=broad-except
            return False


def _terminate_tunnel(proc: subprocess.Popen) -> None:
    """weakref.finalize target: must not hold the runner itself."""
    if proc.poll() is None:
        proc.terminate()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()
