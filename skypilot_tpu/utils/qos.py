"""Multi-tenant QoS primitives (docs/qos.md).

Pure host-side policy shared by the serving engine, the HTTP front
end and the load balancer: priority classes, the per-tenant token
bucket that rate-limits admission in tick-tokens, and the deficit-
round-robin (DRR) scheduler state that orders admission across
tenants by class weight.

Everything here is deliberately clock-explicit (``now`` is an
argument, never ``time.time()`` read inside) so the unit tests drive
the bucket and the scheduler with a fake clock, and deliberately
import-light (stdlib only) so the HTTP layer and the LB can validate
headers without pulling in the engine.

Class semantics
---------------
``interactive`` > ``standard`` > ``bulk``. Rank 0 is the most
latency-sensitive; shedding and preemption walk the ranks from the
bottom (bulk first), DRR quanta scale with the class weight so
interactive subqueues drain fastest under contention. Requests that
name no class are ``standard`` — single-class traffic therefore
degenerates to the pre-QoS FIFO bitwise (regression-tested).
"""
from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Tuple

from skypilot_tpu.utils import env_registry

# Ordered most- to least-latency-sensitive; index = rank.
PRIORITY_CLASSES: Tuple[str, ...] = ('interactive', 'standard', 'bulk')
DEFAULT_CLASS = 'standard'
CLASS_RANK: Dict[str, int] = {
    c: i for i, c in enumerate(PRIORITY_CLASSES)}

# Request headers (body-key fallback: 'tenant' / 'priority_class').
TENANT_HEADER = 'X-Tenant-ID'
CLASS_HEADER = 'X-Priority-Class'

# Default DRR weights: interactive earns 8 tick-tokens of quantum for
# every 1 bulk earns. Overridden by SKYTPU_QOS_WEIGHTS.
DEFAULT_WEIGHTS: Dict[str, int] = {
    'interactive': 8, 'standard': 4, 'bulk': 1}

# Tenant ids become metric label values and ride in HTTP headers:
# bound the charset and length so a hostile id can neither smuggle
# header syntax nor explode label cardinality by sheer size. (Series
# cardinality itself is bounded separately via max_series.)
_TENANT_RE = re.compile(r'\A[A-Za-z0-9._-]{1,64}\Z')


def validate_tenant(value: Optional[str]) -> Optional[str]:
    """Normalized tenant id, or None for absent. Raises ValueError on
    a malformed id (HTTP maps it to a 400)."""
    if value is None or value == '':
        return None
    if not isinstance(value, str) or not _TENANT_RE.fullmatch(value):
        raise ValueError(
            f'invalid tenant id {value!r}: must match '
            '[A-Za-z0-9._-]{1,64}')
    return value


def validate_class(value: Optional[str]) -> str:
    """Normalized priority class (absent -> DEFAULT_CLASS). Raises
    ValueError on an unknown class (HTTP maps it to a 400)."""
    if value is None or value == '':
        return DEFAULT_CLASS
    if not isinstance(value, str) or \
            value.lower() not in CLASS_RANK:
        raise ValueError(
            f'invalid priority class {value!r}: expected one of '
            f'{PRIORITY_CLASSES}')
    return value.lower()


def class_rank(priority_class: Optional[str]) -> int:
    """Rank for ordering (0 = most latency-sensitive). Unknown or
    absent classes rank as DEFAULT_CLASS — ordering code never
    raises on a request that skipped validation."""
    if priority_class is None:
        return CLASS_RANK[DEFAULT_CLASS]
    return CLASS_RANK.get(priority_class, CLASS_RANK[DEFAULT_CLASS])


def parse_weights(spec: Optional[str] = None) -> Dict[str, int]:
    """DRR weights from a "interactive=8,standard=4,bulk=1" spec
    (SKYTPU_QOS_WEIGHTS when ``spec`` is None). Unknown classes and
    malformed entries raise; missing classes keep their defaults;
    weights clamp to >= 1 (a zero weight would starve the class
    forever — shedding, not weighting, is the starvation tool)."""
    if spec is None:
        spec = env_registry.get(env_registry.SKYTPU_QOS_WEIGHTS)
    weights = dict(DEFAULT_WEIGHTS)
    if not spec:
        return weights
    for part in spec.split(','):
        part = part.strip()
        if not part:
            continue
        if '=' not in part:
            raise ValueError(
                f'malformed QoS weight entry {part!r}: expected '
                'class=weight')
        cls, _, raw = part.partition('=')
        cls = cls.strip().lower()
        if cls not in CLASS_RANK:
            raise ValueError(
                f'unknown priority class {cls!r} in QoS weights: '
                f'expected one of {PRIORITY_CLASSES}')
        weights[cls] = max(1, int(raw.strip()))
    return weights


@dataclasses.dataclass
class TokenBucket:
    """Per-tenant admission budget in tick-tokens.

    ``rate`` tokens/second refill up to ``burst`` capacity; a
    request spends its admission charge (max_new + prefill ticks *
    decode_chunk — the engine's existing cost model) when it is
    actually admitted. ``peek`` answers "could this charge be spent
    NOW" without spending, so the DRR scan can skip a broke tenant
    and admit the next one instead of head-blocking.

    Clock-explicit: callers pass ``now`` (monotonic seconds). Buckets
    start FULL — a fresh tenant gets its burst, which is what makes
    the bucket a rate limiter rather than a slow-start penalty.
    """
    rate: float
    burst: float
    tokens: float = dataclasses.field(default=-1.0)
    updated: float = dataclasses.field(default=0.0)

    def __post_init__(self) -> None:
        if self.tokens < 0:
            self.tokens = self.burst

    def _refill(self, now: float) -> None:
        if now > self.updated:
            self.tokens = min(
                self.burst,
                self.tokens + (now - self.updated) * self.rate)
        self.updated = max(self.updated, now)

    def peek(self, charge: float, now: float) -> bool:
        self._refill(now)
        return self.tokens >= charge

    def spend(self, charge: float, now: float) -> bool:
        self._refill(now)
        if self.tokens < charge:
            return False
        self.tokens -= charge
        return True


class DeficitRoundRobin:
    """Weighted-fair ordering over per-tenant subqueues (DRR,
    Shreedhar & Varghese 1996), priced in tick-tokens.

    Each (tenant, class) stream owns a deficit counter. Each round
    the active streams earn ``quantum * weight[class]`` deficit; a
    stream whose head's charge fits its deficit may admit it (the
    charge is then deducted). The scheduler only ORDERS — the engine
    still runs its capacity check (``_fits``) and the token buckets
    independently, and a stream skipped for capacity keeps its
    deficit for the next tick.

    State is keyed by ``(tenant, class)`` so one tenant submitting
    both interactive and bulk work competes as two streams, each at
    its class's weight. Empty streams forfeit their deficit (classic
    DRR: an idle flow must not bank credit), which `prune` enforces.
    """

    def __init__(self, weights: Optional[Dict[str, int]] = None,
                 quantum: float = 1.0) -> None:
        self.weights = dict(weights or DEFAULT_WEIGHTS)
        self.quantum = float(quantum)
        self._deficit: Dict[Tuple[Optional[str], str], float] = {}
        # Round-robin cursor: streams are visited in a stable rotation
        # so equal-weight tenants alternate instead of one winning
        # every tie.
        self._ring: List[Tuple[Optional[str], str]] = []

    def _weight(self, cls: str) -> int:
        return max(1, self.weights.get(cls,
                                       DEFAULT_WEIGHTS[DEFAULT_CLASS]))

    def earn(self, streams: List[Tuple[Optional[str], str]]) -> None:
        """Start a round: every live stream earns its quantum, dead
        streams (not in ``streams``) forfeit their state."""
        live = set(streams)
        for key in list(self._deficit):
            if key not in live:
                del self._deficit[key]
        self._ring = [k for k in self._ring if k in live]
        for key in streams:
            if key not in self._deficit:
                self._deficit[key] = 0.0
                self._ring.append(key)
            self._deficit[key] += self.quantum * self._weight(key[1])

    def order(self) -> List[Tuple[Optional[str], str]]:
        """Streams in service order for this round: by class rank
        first (interactive before bulk at any deficit), then by the
        rotation cursor within a rank."""
        return sorted(self._ring,
                      key=lambda k: class_rank(k[1]))

    def can_spend(self, key: Tuple[Optional[str], str],
                  charge: float) -> bool:
        return self._deficit.get(key, 0.0) >= charge

    def spend(self, key: Tuple[Optional[str], str],
              charge: float) -> None:
        self._deficit[key] = self._deficit.get(key, 0.0) - charge
        # Move the served stream to the back of its rotation so
        # equal-rank streams take turns across rounds.
        if key in self._ring:
            self._ring.remove(key)
            self._ring.append(key)

    def prune(self) -> None:
        """Forget every stream (end of contention): deficits must not
        survive an idle period as banked credit."""
        self._deficit.clear()
        self._ring.clear()


def qos_config_from_env() -> Dict[str, float]:
    """Engine QoS knobs resolved once at construction (the same
    discipline as the decode-dispatch knobs): rate/burst for the
    per-tenant buckets, the queue-pressure bound, and the preemption
    threshold. All default off."""
    rate = float(env_registry.get(
        env_registry.SKYTPU_QOS_TENANT_RATE, '0') or '0')
    burst_raw = env_registry.get(env_registry.SKYTPU_QOS_TENANT_BURST)
    burst = float(burst_raw) if burst_raw else 4.0 * rate
    return {
        'tenant_rate': rate,
        'tenant_burst': burst,
        'max_queue': int(env_registry.get(
            env_registry.SKYTPU_QOS_MAX_QUEUE, '0') or '0'),
        'preempt_after_s': float(env_registry.get(
            env_registry.SKYTPU_QOS_PREEMPT_AFTER_S, '0') or '0'),
        'disable': env_registry.get(
            env_registry.SKYTPU_QOS_DISABLE, '0') == '1',
    }
