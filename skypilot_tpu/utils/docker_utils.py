"""Docker container runtime for tasks (``image_id: docker:<image>``).

Re-design of the reference's container execution support
(``sky/utils/command_runner.py:435`` docker-exec runner mode,
``sky/provision/docker_utils.py`` container bootstrap and registry
login, ``sky/backends/local_docker_backend.py:33``): a task whose
Resources carry ``image_id: docker:<image>`` gets its setup and run
commands executed inside a long-lived container on every host, while
the framework's control plane (agentd, job queue, log streaming, file
sync) stays on the host.

Design delta vs the reference: the reference runs its entire runtime
(Ray, skylet) *inside* the container and ssh-es into it, which forces
container-image requirements (sshd, rsync) and a docker-ssh proxy
chain. Here the host home is bind-mounted into the container with
slave mount propagation, so workdir syncs, file mounts and
FUSE storage mounts done on the host are visible inside the container
with no docker-cp plumbing, and the container image needs nothing but
bash. TPU device access comes from ``--privileged`` + host networking
(``/dev/accel*`` and the libtpu IPC need both).
"""
from __future__ import annotations

import shlex
from typing import Any, Dict, Optional

# Task env vars holding private-registry credentials (reference
# sky/provision/docker_utils.py DockerLoginConfig.from_env_vars).
DOCKER_USERNAME_ENV = 'SKYTPU_DOCKER_USERNAME'
DOCKER_PASSWORD_ENV = 'SKYTPU_DOCKER_PASSWORD'
DOCKER_SERVER_ENV = 'SKYTPU_DOCKER_SERVER'

_IMAGE_PREFIX = 'docker:'

# Remote path the registry password is shipped to (rsync of a 0600
# local temp file — see DockerCommandRunner.bootstrap). The password
# must never ride a shell command line: remote commands are visible in
# `ps` on the host and are echoed into docker_setup-*.log.
CRED_FILE = '.skytpu_docker_cred'


def extract_image(image_id: Optional[str]) -> Optional[str]:
    """The container image named by ``image_id``, or None.

    ``image_id: docker:ubuntu:22.04`` -> ``ubuntu:22.04``; a bare
    ``image_id`` (a cloud VM image or k8s pod image) returns None.
    """
    if image_id and image_id.startswith(_IMAGE_PREFIX):
        return image_id[len(_IMAGE_PREFIX):]
    return None


def container_name(cluster_name: str) -> str:
    """Stable per-cluster container name (one container per host)."""
    safe = ''.join(c if c.isalnum() or c in '_-' else '-'
                   for c in cluster_name)
    return f'skytpu-{safe}'


def make_docker_config(image: str, task_envs: Dict[str, str],
                       cluster_name: str) -> Dict[str, Any]:
    """The docker entry persisted per host in hosts.json."""
    config: Dict[str, Any] = {
        'image': image,
        'container': container_name(cluster_name),
    }
    if task_envs.get(DOCKER_USERNAME_ENV):
        config['login'] = {
            'username': task_envs[DOCKER_USERNAME_ENV],
            'password': task_envs.get(DOCKER_PASSWORD_ENV, ''),
            'server': task_envs.get(DOCKER_SERVER_ENV, ''),
        }
    return config


def bootstrap_command(config: Dict[str, Any]) -> str:
    """Idempotent shell that brings up the task container on a host.

    Skips everything when the container is already running (cluster
    reuse, exec fast path); otherwise logs into the registry when
    credentials were given, pulls the image, and starts a detached
    container that (a) shares the host network and devices
    (``--net=host --privileged``: TPU access), (b) bind-mounts the
    host home with slave propagation so storage FUSE mounts made on
    the host *after* container start still appear inside, and
    (c) keeps ``$HOME`` pointing at the bind-mounted path so remote
    paths mean the same thing in and out of the container.
    """
    image = config['image']
    cname = config['container']
    login = config.get('login')
    lines = [
        # A non-root user on a fresh VM may not be in the docker group
        # yet; opening the socket is best-effort and a no-op when
        # docker already works.
        'docker info >/dev/null 2>&1 || '
        'sudo chmod 666 /var/run/docker.sock 2>/dev/null || true',
        # Idempotency requires BOTH running state and the requested
        # image: a reused cluster whose task switched image_id must
        # get a fresh container, not silently run in the old image.
        f'if [ "$(docker inspect -f '
        '"{{.State.Running}}|{{.Config.Image}}" '
        f'{shlex.quote(cname)} 2>/dev/null)" = '
        f'{shlex.quote("true|" + image)} ]; then '
        f'echo "container {cname} already running {image}"; else',
    ]
    if login:
        # Empty server = Docker Hub: the argument must be omitted, not
        # passed as '' (docker treats '' as a registry host).
        server = (' ' + shlex.quote(login['server'])
                  if login.get('server') else '')
        # The password comes from CRED_FILE, pre-shipped by
        # DockerCommandRunner.bootstrap() via rsync with 0600 perms —
        # only the (non-secret) username/server appear in the command.
        lines.append(
            f'docker login --username {shlex.quote(login["username"])} '
            f'--password-stdin{server} < "$HOME/{CRED_FILE}" &&')
    # run stays inside the && chain: a failed pull (revoked creds,
    # registry outage) must fail the bootstrap, not silently fall back
    # to a stale cached image.
    lines.extend([
        f'docker pull {shlex.quote(image)} &&',
        f'{{ docker rm -f {shlex.quote(cname)} 2>/dev/null; '
        f'docker run -d --name {shlex.quote(cname)} '
        '--net=host --privileged '
        '-v "$HOME":"$HOME":rslave -e "HOME=$HOME" -w "$HOME" '
        f'{shlex.quote(image)} tail -f /dev/null; }}',
        'fi',
        # The shipped credential must not outlive the bootstrap,
        # whichever branch ran — but the cleanup must not mask the
        # bootstrap's exit status (a failed login/pull has to fail the
        # caller's check=True).
        'rc=$?',
        f'rm -f "$HOME/{CRED_FILE}" 2>/dev/null || true',
        'exit $rc',
    ])
    return '\n'.join(lines)


def exec_command(config: Dict[str, Any], script: str) -> str:
    """Wrap ``script`` to execute inside the task container."""
    cname = shlex.quote(config['container'])
    return f'docker exec {cname} bash -c {shlex.quote(script)}'


def kill_workload_command(config: Dict[str, Any]) -> str:
    """Kill everything inside the container, keeping it running.

    ``docker exec``'d processes are NOT children of the exec client —
    killing the client (or its SSH session) leaves them alive inside
    the container, still holding /dev/accel*. ``docker restart -t 0``
    SIGKILLs the container's whole pid namespace and brings it back up
    (the keepalive is PID 1), so the next job finds a clean container.
    """
    cname = shlex.quote(config['container'])
    return f'docker restart -t 0 {cname}'
