"""TPU pod-slice topology math — the heart of TPU-first Resources.

The reference treats TPUs as a GCP special case bolted onto a GPU-shaped
``accelerators`` dict (sky/resources.py:563 `_set_accelerators`,
sky/clouds/gcp.py:473-497). Here slice topology is a first-class concept:
an accelerator name like ``tpu-v5e-16`` deterministically yields chip
count, host count, chips/host, ICI topology, per-chip HBM and peak
bf16 FLOPs — all of which feed the optimizer (pricing is per chip-hour),
the provisioner (one slice = N hosts gang-provisioned atomically) and the
recipes (mesh shape from topology without querying the cloud).

Public per-generation facts (cloud.google.com/tpu/docs):
  generation  chips/host  cores/chip  HBM GiB/chip  bf16 TFLOP/s/chip
  v2          4           2           8             45
  v3          4           2           16            123
  v4          4           2           32            275
  v5e         8 (<=8) /4  1           16            197
  v5p         4           2           95            459
  v6e         8 (<=8) /4  1           32            918
For v2/v3/v4/v5p the trailing number in the accelerator name counts
TensorCores; for v5e/v6e it counts chips.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Tuple

from skypilot_tpu import exceptions

# generation -> (cores_per_chip, default_chips_per_host, hbm_gib_per_chip,
#                bf16_tflops_per_chip, ici_dims)
_GEN_INFO: Dict[str, Tuple[int, int, float, float, int]] = {
    'v2': (2, 4, 8, 45.0, 2),
    'v3': (2, 4, 16, 123.0, 2),
    'v4': (2, 4, 32, 275.0, 3),
    'v5e': (1, 4, 16, 197.0, 2),
    'v5p': (2, 4, 95, 459.0, 3),
    'v6e': (1, 4, 32, 918.0, 2),
}

# Accelerator-name aliases (reference catalog uses `tpu-v5litepod-N`).
_GEN_ALIASES = {'v5litepod': 'v5e', 'v5lite': 'v5e'}

_NAME_RE = re.compile(r'^tpu-(v\d+[a-z]*)-(\d+)$')

# 2D slice topologies for v2/v3/v5e/v6e by chip count (public shapes).
_TOPO_2D: Dict[int, str] = {
    1: '1x1',
    4: '2x2',
    8: '2x4',
    16: '4x4',
    32: '4x8',
    64: '8x8',
    128: '8x16',
    256: '16x16',
    512: '16x32',
}


@dataclasses.dataclass(frozen=True)
class TpuSlice:
    """Static description of one TPU pod slice."""
    name: str            # canonical accelerator name, e.g. 'tpu-v5e-16'
    generation: str      # 'v5e'
    num_chips: int
    num_hosts: int
    chips_per_host: int
    cores_per_chip: int
    topology: str        # ICI topology, e.g. '4x4' or '2x2x2'
    hbm_gib_per_chip: float
    bf16_tflops_per_chip: float

    @property
    def is_pod(self) -> bool:
        """Multi-host slice (requires gang fan-out)."""
        return self.num_hosts > 1

    @property
    def total_hbm_gib(self) -> float:
        return self.hbm_gib_per_chip * self.num_chips

    @property
    def total_bf16_tflops(self) -> float:
        return self.bf16_tflops_per_chip * self.num_chips

    @property
    def mesh_shape(self) -> Tuple[int, ...]:
        return tuple(int(x) for x in self.topology.split('x'))

    @property
    def runtime_version(self) -> str:
        """Default TPU-VM runtime image for this generation."""
        return {
            'v2': 'tpu-ubuntu2204-base',
            'v3': 'tpu-ubuntu2204-base',
            'v4': 'tpu-ubuntu2204-base',
            'v5e': 'v2-alpha-tpuv5-lite',
            'v5p': 'v2-alpha-tpuv5',
            'v6e': 'v2-alpha-tpuv6e',
        }[self.generation]

    @property
    def gcp_accelerator_type(self) -> str:
        """Name used by tpu.googleapis.com, e.g. 'v5litepod-16'."""
        gen = 'v5litepod' if self.generation == 'v5e' else self.generation
        if self.generation in ('v5e', 'v6e'):
            return f'{gen}-{self.num_chips}'
        return f'{gen}-{self.num_chips * self.cores_per_chip}'


def _topology_3d(num_chips: int) -> str:
    """Smallest-surface 3D torus factorization (v4/v5p slices)."""
    best: Optional[Tuple[int, int, int]] = None
    for x in range(1, num_chips + 1):
        if num_chips % x:
            continue
        rest = num_chips // x
        for y in range(x, rest + 1):
            if rest % y:
                continue
            z = rest // y
            if z < y:
                continue
            dims = (x, y, z)
            if best is None or max(dims) < max(best):
                best = dims
    assert best is not None
    return 'x'.join(str(d) for d in best)


def is_tpu_name(accelerator_name: str) -> bool:
    name = accelerator_name.lower()
    return bool(_NAME_RE.match(name)) or name.startswith('tpu-')


def parse(accelerator_name: str) -> TpuSlice:
    """Parse 'tpu-<gen>-<N>' into a TpuSlice.

    Raises InvalidResourcesError for unknown generations or invalid sizes.
    """
    name = accelerator_name.lower()
    m = _NAME_RE.match(name)
    if m is None:
        raise exceptions.InvalidResourcesError(
            f'Invalid TPU accelerator name {accelerator_name!r}; expected '
            "'tpu-<generation>-<size>', e.g. 'tpu-v5e-16'.")
    gen, size_s = m.group(1), m.group(2)
    gen = _GEN_ALIASES.get(gen, gen)
    if gen not in _GEN_INFO:
        raise exceptions.InvalidResourcesError(
            f'Unknown TPU generation {gen!r} in {accelerator_name!r}. '
            f'Known: {sorted(_GEN_INFO)}')
    size = int(size_s)
    cores_per_chip, chips_per_host, hbm, tflops, ici_dims = _GEN_INFO[gen]

    if gen in ('v5e', 'v6e'):
        num_chips = size
    else:
        if size % cores_per_chip:
            raise exceptions.InvalidResourcesError(
                f'{accelerator_name}: size counts TensorCores for {gen} and '
                f'must be a multiple of {cores_per_chip}.')
        num_chips = size // cores_per_chip

    if gen in ('v5e', 'v6e'):
        # Single-host slices pack up to 8 chips on one host; multi-host
        # slices use 4-chip hosts (GCP ct5lp/ct6e machine shapes).
        if num_chips <= 8:
            num_hosts = 1
            chips_per_host = num_chips
        else:
            chips_per_host = 4
            num_hosts = num_chips // chips_per_host
        if num_chips not in _TOPO_2D:
            raise exceptions.InvalidResourcesError(
                f'{accelerator_name}: unsupported slice size {num_chips}; '
                f'valid chip counts: {sorted(_TOPO_2D)}')
        topology = _TOPO_2D[num_chips]
    elif ici_dims == 2:  # v2/v3
        num_hosts = max(1, num_chips // chips_per_host)
        chips_per_host = min(chips_per_host, num_chips)
        if num_chips not in _TOPO_2D:
            raise exceptions.InvalidResourcesError(
                f'{accelerator_name}: unsupported slice size.')
        topology = _TOPO_2D[num_chips]
    else:  # v4/v5p: 3D torus, 4-chip hosts
        num_hosts = max(1, num_chips // chips_per_host)
        chips_per_host = min(chips_per_host, num_chips)
        topology = _topology_3d(num_chips)

    return TpuSlice(
        name=f'tpu-{gen}-{size}',
        generation=gen,
        num_chips=num_chips,
        num_hosts=num_hosts,
        chips_per_host=chips_per_host,
        cores_per_chip=cores_per_chip,
        topology=topology,
        hbm_gib_per_chip=hbm,
        bf16_tflops_per_chip=tflops,
    )


def try_parse(accelerator_name: str) -> Optional[TpuSlice]:
    try:
        return parse(accelerator_name)
    except exceptions.InvalidResourcesError:
        return None


def list_sizes(generation: str) -> List[str]:
    """All supported accelerator names for a generation (catalog seed)."""
    cores_per_chip = _GEN_INFO[generation][0]
    names = []
    for chips in sorted(_TOPO_2D):
        if generation in ('v5e', 'v6e'):
            names.append(f'tpu-{generation}-{chips}')
        elif generation in ('v2', 'v3'):
            if chips >= 4:
                names.append(f'tpu-{generation}-{chips * cores_per_chip}')
    if generation in ('v4', 'v5p'):
        for chips in (4, 8, 16, 32, 64, 128, 256, 512, 1024):
            names.append(f'tpu-{generation}-{chips * cores_per_chip}')
    return names
