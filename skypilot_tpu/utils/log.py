"""Logging for skypilot_tpu.

TPU-native re-design of the reference's ``sky/sky_logging.py`` (see
/root/reference/sky/sky_logging.py:60-131): env-tunable level, a single
stream handler on the package root logger, and helpers to temporarily
silence or re-route output.
"""
from __future__ import annotations

import contextlib
import logging
import sys
import threading

from skypilot_tpu.utils import env_registry

_FORMAT = ('%(levelname).1s %(asctime)s %(filename)s:%(lineno)d]'
           '%(traceid)s %(message)s')
_DATE_FORMAT = '%m-%d %H:%M:%S'

_setup_lock = threading.Lock()
_initialized = False


def _env_level() -> int:
    if env_registry.is_enabled(env_registry.SKYTPU_DEBUG):
        return logging.DEBUG
    if env_registry.is_enabled(env_registry.SKYTPU_MINIMIZE_LOGGING):
        return logging.WARNING
    return logging.INFO


class NoPrefixFormatter(logging.Formatter):
    """Plain message formatter for user-facing output lines."""

    def format(self, record: logging.LogRecord) -> str:
        return record.getMessage()


class TraceIdFilter(logging.Filter):
    """Stamps ``%(traceid)s``: ``' [trace:<id>]'`` while a span (or
    an inherited ``SKYTPU_TRACE_CONTEXT``) is active and tracing is
    on, else '' — request/launch logs correlate with their trace
    (docs/tracing.md) at zero cost when tracing is disabled. Looks
    the tracer up via sys.modules so logging setup never forces the
    import."""

    def filter(self, record: logging.LogRecord) -> bool:
        tid = None
        mod = sys.modules.get('skypilot_tpu.trace.core')
        if mod is not None:
            tid = mod.current_trace_id()
        record.traceid = f' [trace:{tid}]' if tid else ''
        return True


def _setup() -> None:
    global _initialized
    with _setup_lock:
        if _initialized:
            return
        root = logging.getLogger('skypilot_tpu')
        root.setLevel(logging.DEBUG)
        handler = logging.StreamHandler(sys.stdout)
        handler.setLevel(_env_level())
        handler.setFormatter(logging.Formatter(_FORMAT, datefmt=_DATE_FORMAT))
        handler.addFilter(TraceIdFilter())
        root.addHandler(handler)
        root.propagate = False
        _initialized = True


def init_logger(name: str) -> logging.Logger:
    _setup()
    return logging.getLogger(name)


def logging_enabled(logger: logging.Logger, level: int) -> bool:
    return logger.isEnabledFor(level)


@contextlib.contextmanager
def silent():
    """Suppress INFO-level package output inside the context."""
    _setup()
    root = logging.getLogger('skypilot_tpu')
    previous = [h.level for h in root.handlers]
    try:
        for h in root.handlers:
            h.setLevel(max(h.level, logging.WARNING))
        yield
    finally:
        for h, lvl in zip(root.handlers, previous):
            h.setLevel(lvl)


def get_run_timestamp() -> str:
    import time
    return 'skytpu-' + time.strftime('%Y-%m-%d-%H-%M-%S-%f',
                                     time.localtime())[:len('skytpu-') + 26]
