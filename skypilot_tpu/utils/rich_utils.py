"""Terminal status UX: spinners that degrade to plain logging.

Re-design of reference ``sky/utils/rich_utils.py``: long-running CLI
operations (provisioning, refresh, teardown) show a live spinner with
updatable text when stdout is an interactive terminal and ``rich`` is
importable; in pipes, CI, or minimal images the same code path prints
nothing extra (the operation's own log lines remain the record).
Nested ``client_status`` calls reuse the outer spinner (the reference
does the same so helper functions can annotate progress without
fighting over the terminal); on nested-scope exit the outer message
is restored.
"""
from __future__ import annotations

import contextlib
import sys
import threading
from typing import Iterator, Optional

_active = threading.local()


class _NoopStatus:
    """Fallback handle: update() is a cheap no-op."""

    def update(self, message: str) -> None:
        pass


class _RichStatus:

    def __init__(self, status, message: str) -> None:
        self._status = status
        self.message = message

    def update(self, message: str) -> None:
        self.message = message
        self._status.update(message)


def _rich_console():
    try:
        import rich.console
        return rich.console.Console()
    except ImportError:
        return None


@contextlib.contextmanager
def client_status(message: str) -> Iterator:
    """Spinner context; yields a handle with .update(message).

    TTY + rich -> live spinner. Otherwise a no-op handle. Nested
    calls retext the outer spinner and restore its message on exit,
    so a helper's progress note never outlives the helper.
    """
    outer: Optional[object] = getattr(_active, 'status', None)
    if outer is not None:
        saved = getattr(outer, 'message', None)
        outer.update(message)
        try:
            yield outer
        finally:
            if saved is not None:
                outer.update(saved)
        return
    console = _rich_console()
    if console is None or not sys.stdout.isatty():
        yield _NoopStatus()
        return
    with console.status(message) as status:
        handle = _RichStatus(status, message)
        _active.status = handle
        try:
            yield handle
        finally:
            _active.status = None
