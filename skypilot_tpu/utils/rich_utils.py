"""Terminal status UX: spinners that degrade to plain logging.

Re-design of reference ``sky/utils/rich_utils.py``: long-running CLI
operations (provisioning, refresh, teardown) show a live spinner with
updatable text when stdout is an interactive terminal and ``rich`` is
importable; in pipes, CI, or minimal images the same code path prints
nothing extra (the operation's own log lines remain the record).
Nested ``client_status`` calls reuse the outer spinner (the reference
does the same so helper functions can annotate progress without
fighting over the terminal).
"""
from __future__ import annotations

import contextlib
import sys
import threading
from typing import Iterator, Optional

_active = threading.local()


class _NoopStatus:
    """Fallback and nested-call handle: update() is a cheap no-op."""

    def update(self, message: str) -> None:
        pass


class _RichStatus:

    def __init__(self, status) -> None:
        self._status = status

    def update(self, message: str) -> None:
        self._status.update(message)


def _rich_console():
    try:
        import rich.console
        return rich.console.Console()
    except ImportError:
        return None


def safe_status_enabled() -> bool:
    return sys.stdout.isatty() and _rich_console() is not None


@contextlib.contextmanager
def client_status(message: str) -> Iterator:
    """Spinner context; yields a handle with .update(message).

    TTY + rich -> live spinner. Otherwise, or when nested inside an
    active spinner, a no-op handle (the outer spinner keeps spinning;
    updates from nested scopes retext it).
    """
    outer: Optional[object] = getattr(_active, 'status', None)
    if outer is not None:
        # Nested: retext the outer spinner, hand out a proxy so
        # updates keep landing on it.
        outer.update(message)
        yield outer
        return
    console = _rich_console()
    if console is None or not sys.stdout.isatty():
        yield _NoopStatus()
        return
    with console.status(message) as status:
        handle = _RichStatus(status)
        _active.status = handle
        try:
            yield handle
        finally:
            _active.status = None
