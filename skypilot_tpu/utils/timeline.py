"""Chrome-trace timeline tracing.

Re-design of reference ``sky/utils/timeline.py:22-121``: an
``@timeline.event`` decorator and ``Event`` context manager that append
Chrome trace events (phase B/E) to the file named by
``SKYTPU_TIMELINE_FILE_PATH``. Zero overhead when the env var is unset.
"""
from __future__ import annotations

import atexit
import functools
import json
import os
import threading
import time
from typing import Any, Callable, List, Optional

from skypilot_tpu.utils import env_registry

_ENV = env_registry.SKYTPU_TIMELINE_FILE_PATH
_events: List[dict] = []
_lock = threading.Lock()
_save_registered = False


def enabled() -> bool:
    return bool(os.environ.get(_ENV))


class Event:
    """Context manager emitting a begin/end trace-event pair."""

    def __init__(self, name: str, message: Optional[str] = None) -> None:
        self._name = name
        self._message = message

    def begin(self) -> None:
        if not enabled():
            return
        self._record('B')

    def end(self) -> None:
        if not enabled():
            return
        self._record('E')

    def _record(self, phase: str) -> None:
        global _save_registered
        event = {
            'name': self._name,
            'cat': 'skypilot_tpu',
            'ph': phase,
            'pid': str(os.getpid()),
            'tid': str(threading.get_ident()),
            'ts': f'{time.time() * 10 ** 6: .3f}',
        }
        if self._message is not None:
            event['args'] = {'message': self._message}
        with _lock:
            _events.append(event)
            if not _save_registered:
                atexit.register(save_timeline)
                _save_registered = True

    def __enter__(self) -> 'Event':
        self.begin()
        return self

    def __exit__(self, *args) -> None:
        self.end()


def event(fn: Callable = None, *, name: Optional[str] = None) -> Callable:
    """Decorator tracing a function call as a timeline event."""
    if fn is None:
        return functools.partial(event, name=name)

    @functools.wraps(fn)
    def wrapper(*args: Any, **kwargs: Any):
        event_name = name or getattr(fn, '__qualname__', fn.__name__)
        with Event(name=f'[event] {event_name}'):
            return fn(*args, **kwargs)

    return wrapper


def save_timeline() -> None:
    path = os.environ.get(_ENV)
    if not path or not _events:
        return
    with _lock:
        payload = {
            'traceEvents': list(_events),
            'displayTimeUnit': 'ms',
            'otherData': {'pid': os.getpid()},
        }
        _events.clear()
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, 'w', encoding='utf-8') as f:
        json.dump(payload, f)
