"""Chrome-trace timeline: a thin exporter over the span tracer.

Historically this module was its own timing primitive (an in-memory
Chrome-event buffer behind ``SKYTPU_TIMELINE_FILE_PATH``, the
re-design of reference ``sky/utils/timeline.py:22-121``). The repo's
single timing primitive is now :mod:`skypilot_tpu.trace`; this module
keeps the legacy surface — ``Event``, ``@timeline.event``,
``save_timeline()`` — as span wrappers:

- ``Event``/``@event`` open a real span, so the instrumented
  control-plane paths (locks, backend ops, ``execution.launch``)
  appear in distributed traces whenever ``SKYTPU_TRACE_DIR`` is set;
- when ``SKYTPU_TIMELINE_FILE_PATH`` is set, every finished span —
  from any instrumented site, not just this module's — is ALSO
  rendered into the legacy single-file Chrome trace (balanced B/E
  pairs), written by ``save_timeline()`` at exit.

Zero overhead when both knobs are unset.
"""
from __future__ import annotations

import atexit
import functools
import json
import os
import threading
from typing import Any, Callable, List, Optional

from skypilot_tpu.trace import core as trace_core
from skypilot_tpu.utils import env_registry

_ENV = env_registry.SKYTPU_TIMELINE_FILE_PATH
_events: List[dict] = []
_lock = threading.Lock()
_save_registered = False
# The legacy export is an in-memory buffer flushed at exit; now that
# EVERY span feeds it (per-request serve spans included), a
# long-running server with the knob set would grow without bound.
# Cap it: beyond this many events the earliest-armed capture is
# complete and further spans are counted, not stored.
_MAX_EVENTS = 50_000
_dropped = 0


def enabled() -> bool:
    """Legacy single-file export armed (the span tracer has its own
    ``trace.enabled()``)."""
    return bool(os.environ.get(_ENV))


def record_span(span: 'trace_core.Span') -> None:
    """Render one finished span into the legacy buffer as a balanced
    B/E pair. Called by the tracer for EVERY finished span while
    ``SKYTPU_TIMELINE_FILE_PATH`` is set. Bounded: past
    ``_MAX_EVENTS`` spans are counted as dropped (the spool under
    ``SKYTPU_TRACE_DIR`` is the unbounded sink)."""
    global _save_registered, _dropped
    base = {
        'name': span.name,
        'cat': 'skypilot_tpu',
        'pid': str(os.getpid()),
        'tid': str(threading.get_ident()),
    }
    if span.attrs:
        base['args'] = {k: str(v) for k, v in span.attrs.items()}
    end_us = (span.end_time
              if span.end_time is not None else span.start_time) * 1e6
    begin = dict(base, ph='B', ts=f'{span.start_time * 1e6: .3f}')
    end = dict(base, ph='E', ts=f'{end_us: .3f}')
    with _lock:
        if len(_events) >= _MAX_EVENTS:
            _dropped += 1
            return
        _events.append(begin)
        _events.append(end)
        if not _save_registered:
            atexit.register(save_timeline)
            _save_registered = True


class Event:
    """Legacy begin/end pair — now a span under the hood."""

    def __init__(self, name: str, message: Optional[str] = None) -> None:
        self._name = name
        self._message = message
        self._cm: Optional[trace_core.span] = None

    def begin(self) -> None:
        attrs = ({'message': self._message}
                 if self._message is not None else {})
        # Control-plane events (launch stages, provisioning, lock
        # waits) are minutes-long by nature: exempt from the
        # slow-span warning, which watches the request path.
        self._cm = trace_core.span(self._name, slow_ok=True, **attrs)
        self._cm.__enter__()

    def end(self) -> None:
        if self._cm is not None:
            self._cm.__exit__(None, None, None)
            self._cm = None

    def __enter__(self) -> 'Event':
        self.begin()
        return self

    def __exit__(self, *args) -> None:
        self.end()


def event(fn: Callable = None, *, name: Optional[str] = None) -> Callable:
    """Decorator tracing a function call as a span (legacy API)."""
    if fn is None:
        return functools.partial(event, name=name)

    @functools.wraps(fn)
    def wrapper(*args: Any, **kwargs: Any):
        event_name = name or getattr(fn, '__qualname__', fn.__name__)
        with Event(name=f'[event] {event_name}'):
            return fn(*args, **kwargs)

    return wrapper


def save_timeline() -> None:
    path = os.environ.get(_ENV)
    if not path or not _events:
        return
    global _dropped
    with _lock:
        payload = {
            'traceEvents': list(_events),
            'displayTimeUnit': 'ms',
            'otherData': {'pid': os.getpid()},
        }
        if _dropped:
            payload['otherData']['dropped_spans'] = _dropped
        _events.clear()
        _dropped = 0
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, 'w', encoding='utf-8') as f:
        json.dump(payload, f)
