"""Shared durable-state layer: ONE way to open and mutate control-plane
sqlite databases (docs/crash_recovery.md).

Every control-plane store (managed-jobs state, serve state, the global
cluster DB, the API-server request table, the agent job table, the
benchmark DB) used to roll its own ``sqlite3.connect`` with ad-hoc
pragmas and ad-hoc ``OperationalError`` handling. This module replaces
them (lint rule STL010 keeps it that way) with:

- :func:`connect` — one connection recipe: WAL journal mode (readers
  never block the writer, a torn process never corrupts the file),
  ``busy_timeout`` so concurrent writers queue instead of raising,
  ``synchronous=NORMAL`` (safe with WAL: a power cut may lose the last
  transactions but never corrupts), autocommit isolation so
  transactions are always *explicit*;
- :func:`transaction` — ``BEGIN IMMEDIATE`` … ``COMMIT`` as a context
  manager, with lock-acquisition retries on the shared
  :class:`~skypilot_tpu.utils.retry.RetryPolicy` (per-site attempt/
  giveup metrics) and deterministic crashpoints
  (``statedb.commit.pre`` / ``statedb.commit.post``) bracketing the
  commit so chaos tests can kill a process at the exact instruction
  where atomicity matters;
- an **intent journal** (ARIES-style write-ahead intent records): a
  multi-step operation calls :func:`begin_intent` in the same
  transaction as its first state mutation and :func:`complete_intent`
  in the same transaction as its last. A crash at ANY instruction in
  between leaves an open intent row; recovery-as-startup
  (``reconcile_on_start`` in the jobs and serve controllers) replays
  open intents against cloud/cluster truth — adopt, roll forward, or
  roll back — so the operation is never half-done forever.

Import-light: stdlib + utils.retry + utils.fault_injection only.
"""
from __future__ import annotations

import contextlib
import json
import os
import pathlib
import sqlite3
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu.utils import env_registry
from skypilot_tpu.utils import fault_injection
from skypilot_tpu.utils import retry as retry_lib

# Writers queue behind the WAL write lock for this long before the
# sqlite driver raises SQLITE_BUSY (which transaction() then retries
# through RetryPolicy, so contention also shows up in retry metrics).
BUSY_TIMEOUT_MS = 10_000

_INTENT_DDL = """
    CREATE TABLE IF NOT EXISTS intents (
        intent_id INTEGER PRIMARY KEY AUTOINCREMENT,
        kind TEXT NOT NULL,
        payload TEXT,
        created_at REAL,
        pid INTEGER
    )"""

# One RetryPolicy per site label (jobs.state.write / serve.state.write
# / ...): BEGIN IMMEDIATE contention lands in the shared
# skytpu_retry_attempts_total / _giveups_total series.
_retry_policies: Dict[str, retry_lib.RetryPolicy] = {}
_retry_lock = threading.Lock()


def reconcile_enabled() -> bool:
    """Crash-only startup switch: controllers replay open intents on
    every start unless SKYTPU_RECONCILE_ON_START=0."""
    return os.environ.get(env_registry.SKYTPU_RECONCILE_ON_START,
                          '1') != '0'


def _retry_policy(site: str) -> retry_lib.RetryPolicy:
    with _retry_lock:
        policy = _retry_policies.get(site)
        if policy is None:
            policy = retry_lib.RetryPolicy(
                max_attempts=6,
                initial_backoff=0.05,
                max_backoff=2.0,
                jitter='full',
                retryable=(sqlite3.OperationalError,),
                site=site)
            _retry_policies[site] = policy
        return policy


def connect(path: str, *, row_factory: bool = True) -> sqlite3.Connection:
    """The ONE sqlite connection recipe (see module docstring).

    ``isolation_level=None`` puts the connection in true autocommit:
    single statements commit immediately; multi-statement writes must
    go through :func:`transaction` (lint rule STL010 enforces this
    outside this module).
    """
    pathlib.Path(path).parent.mkdir(parents=True, exist_ok=True)
    conn = sqlite3.connect(path, timeout=BUSY_TIMEOUT_MS / 1000.0,
                           isolation_level=None)
    if row_factory:
        conn.row_factory = sqlite3.Row
    conn.execute('PRAGMA journal_mode=WAL')
    conn.execute(f'PRAGMA busy_timeout={BUSY_TIMEOUT_MS}')
    conn.execute('PRAGMA synchronous=NORMAL')
    return conn


@contextlib.contextmanager
def transaction(conn: sqlite3.Connection, site: str = 'statedb.write'):
    """Explicit write transaction on an existing connection.

    BEGIN IMMEDIATE takes the write lock up front (no deferred-lock
    upgrade deadlocks); SQLITE_BUSY on acquisition is retried through
    the site's RetryPolicy. The body's mutations commit atomically —
    the ``statedb.commit.pre`` / ``.post`` crashpoints let chaos tests
    prove it (a crash at ``pre`` loses the whole transaction, never
    half of it).
    """
    _retry_policy(site).call(conn.execute, 'BEGIN IMMEDIATE')
    try:
        yield conn
    except BaseException:
        _rollback_quiet(conn)
        raise
    fault_injection.crashpoint('statedb.commit.pre', db=site)
    try:
        conn.commit()
    except BaseException:
        # A failed COMMIT (disk full, I/O error) must not strand a
        # cached connection inside the open transaction — every later
        # BEGIN on it would fail with 'cannot start a transaction
        # within a transaction'.
        _rollback_quiet(conn)
        raise
    fault_injection.crashpoint('statedb.commit.post', db=site)


def _rollback_quiet(conn: sqlite3.Connection) -> None:
    try:
        conn.rollback()
    except sqlite3.Error:
        pass  # connection unusable anyway; keep the original error


# ------------------------------------------------------ intent journal


def ensure_intent_table(conn: sqlite3.Connection) -> None:
    conn.execute(_INTENT_DDL)


def begin_intent(conn: sqlite3.Connection, kind: str,
                 payload: Optional[Dict[str, Any]] = None) -> int:
    """Journal the *intention* to perform a multi-step operation.

    Call inside the same :func:`transaction` as the operation's first
    state mutation; keep the returned id and
    :func:`complete_intent` it in the same transaction as the LAST
    mutation. Payload must carry everything recovery needs to decide
    adopt / roll forward / roll back (cluster name, replica id, …).
    """
    cur = conn.execute(
        'INSERT INTO intents (kind, payload, created_at, pid) '
        'VALUES (?,?,?,?)',
        (kind, json.dumps(payload or {}), time.time(), os.getpid()))
    return int(cur.lastrowid)


def complete_intent(conn: sqlite3.Connection, intent_id: int) -> None:
    conn.execute('DELETE FROM intents WHERE intent_id = ?', (intent_id,))


def open_intents(conn: sqlite3.Connection,
                 kind: Optional[str] = None) -> List[Dict[str, Any]]:
    """Open (= not completed) intents, oldest first — exactly the
    operations a dead process left in flight. ``kind`` may end with
    ``*`` to prefix-match (``'jobs.*'``)."""
    query = 'SELECT * FROM intents'
    args: List[Any] = []
    if kind is not None:
        if kind.endswith('*'):
            query += ' WHERE kind LIKE ?'
            args.append(kind[:-1] + '%')
        else:
            query += ' WHERE kind = ?'
            args.append(kind)
    query += ' ORDER BY intent_id'
    out = []
    for row in conn.execute(query, args):
        d = dict(row)
        try:
            d['payload'] = json.loads(d.get('payload') or '{}')
        except ValueError:
            # A torn payload must not wedge recovery of OTHER intents.
            d['payload'] = {}
        out.append(d)
    return out


# ------------------------------------------------------------- StateDB


class StateDB:
    """One control-plane database: path resolution, once-per-path DDL
    (schema creation + in-place migrations), transactions, intents.

    ``path_fn`` re-resolves the path on every connection so tests that
    point the env var at a fresh tmp dir get a fresh DB; the DDL
    ``init_fn(conn)`` runs once per (process, path).
    """

    def __init__(self, path_fn: Callable[[], str],
                 init_fn: Optional[Callable[[sqlite3.Connection],
                                            None]] = None,
                 site: str = 'statedb.write') -> None:
        self._path_fn = path_fn
        self._init_fn = init_fn
        self.site = site
        self._initialized_paths: set = set()
        self._init_lock = threading.Lock()

    def connection(self) -> sqlite3.Connection:
        path = self._path_fn()
        conn = connect(path)
        if path not in self._initialized_paths:
            with self._init_lock:
                if path not in self._initialized_paths:
                    ensure_intent_table(conn)
                    if self._init_fn is not None:
                        self._init_fn(conn)
                    self._initialized_paths.add(path)
        return conn

    @contextlib.contextmanager
    def reader(self):
        """Read-only use; closes the connection on exit."""
        conn = self.connection()
        try:
            yield conn
        finally:
            conn.close()

    @contextlib.contextmanager
    def transaction(self):
        """Fresh connection, one explicit transaction, closed after."""
        conn = self.connection()
        try:
            with transaction(conn, site=self.site) as txn:
                yield txn
        finally:
            conn.close()

    # Convenience single-op intent helpers (own transaction each) for
    # callers that are not already inside one.
    def begin_intent(self, kind: str,
                     payload: Optional[Dict[str, Any]] = None) -> int:
        with self.transaction() as conn:
            return begin_intent(conn, kind, payload)

    def complete_intent(self, intent_id: int) -> None:
        with self.transaction() as conn:
            complete_intent(conn, intent_id)

    def open_intents(self,
                     kind: Optional[str] = None) -> List[Dict[str, Any]]:
        with self.reader() as conn:
            return open_intents(conn, kind)
