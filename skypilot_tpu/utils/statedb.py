"""Shared durable-state layer: ONE way to open and mutate control-plane
sqlite databases (docs/crash_recovery.md).

Every control-plane store (managed-jobs state, serve state, the global
cluster DB, the API-server request table, the agent job table, the
benchmark DB) used to roll its own ``sqlite3.connect`` with ad-hoc
pragmas and ad-hoc ``OperationalError`` handling. This module replaces
them (lint rule STL010 keeps it that way) with:

- :func:`connect` — one connection recipe: WAL journal mode (readers
  never block the writer, a torn process never corrupts the file),
  ``busy_timeout`` so concurrent writers queue instead of raising,
  ``synchronous=NORMAL`` (safe with WAL: a power cut may lose the last
  transactions but never corrupts), autocommit isolation so
  transactions are always *explicit*;
- :func:`transaction` — ``BEGIN IMMEDIATE`` … ``COMMIT`` as a context
  manager, with lock-acquisition retries on the shared
  :class:`~skypilot_tpu.utils.retry.RetryPolicy` (per-site attempt/
  giveup metrics) and deterministic crashpoints
  (``statedb.commit.pre`` / ``statedb.commit.post``) bracketing the
  commit so chaos tests can kill a process at the exact instruction
  where atomicity matters;
- an **intent journal** (ARIES-style write-ahead intent records): a
  multi-step operation calls :func:`begin_intent` in the same
  transaction as its first state mutation and :func:`complete_intent`
  in the same transaction as its last. A crash at ANY instruction in
  between leaves an open intent row; recovery-as-startup
  (``reconcile_on_start`` in the jobs and serve controllers) replays
  open intents against cloud/cluster truth — adopt, roll forward, or
  roll back — so the operation is never half-done forever;
- a **lease table** (docs/control_plane.md): generic expiring
  ownership records with monotonically increasing *fencing tokens*.
  ``lease_try_claim`` is one compare-and-swap transaction (claim
  succeeds only while the row is unowned, expired, or — for restart
  claims — still names the owner the caller observed dead), renewal
  extends the expiry only while the claimant's ``(owner, fence)``
  pair is still current, and :class:`FenceGuard` re-validates the
  pair INSIDE every subsequent :meth:`StateDB.transaction` — in the
  same BEGIN IMMEDIATE as the writes it guards, so a process that
  lost its lease (GC pause, kill, partition) can never clobber the
  successor that claimed over it. This is what lets N controller
  processes (``skypilot_tpu/fleet``) share the jobs/services tables.

Import-light: stdlib + utils.retry + utils.fault_injection +
skypilot_tpu.metrics (already in utils.retry's closure — the lease
layer counts claims/renewals/stale-write rejections).
"""
from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import json
import os
import pathlib
import sqlite3
import threading
import time
from typing import (Any, Callable, Dict, Iterable, List, Optional,
                    Tuple)

from skypilot_tpu import metrics as metrics_lib
from skypilot_tpu.utils import env_registry
from skypilot_tpu.utils import fault_injection
from skypilot_tpu.utils import retry as retry_lib

# Writers queue behind the WAL write lock for this long before the
# sqlite driver raises SQLITE_BUSY (which transaction() then retries
# through RetryPolicy, so contention also shows up in retry metrics).
BUSY_TIMEOUT_MS = 10_000

_INTENT_DDL = """
    CREATE TABLE IF NOT EXISTS intents (
        intent_id INTEGER PRIMARY KEY AUTOINCREMENT,
        kind TEXT NOT NULL,
        payload TEXT,
        created_at REAL,
        pid INTEGER
    )"""

# Lease rows live NEXT TO the state they protect (same sqlite file),
# so the fence check and the guarded writes share one BEGIN IMMEDIATE.
_LEASE_DDL = """
    CREATE TABLE IF NOT EXISTS leases (
        resource TEXT PRIMARY KEY,
        owner TEXT,
        fence INTEGER NOT NULL DEFAULT 0,
        acquired_at REAL,
        expires_at REAL,
        renewals INTEGER NOT NULL DEFAULT 0
    )"""

# One RetryPolicy per site label (jobs.state.write / serve.state.write
# / ...): BEGIN IMMEDIATE contention lands in the shared
# skytpu_retry_attempts_total / _giveups_total series.
_retry_policies: Dict[str, retry_lib.RetryPolicy] = {}
_retry_lock = threading.Lock()


def reconcile_enabled() -> bool:
    """Crash-only startup switch: controllers replay open intents on
    every start unless SKYTPU_RECONCILE_ON_START=0."""
    return os.environ.get(env_registry.SKYTPU_RECONCILE_ON_START,
                          '1') != '0'


# ----------------------------------------------------------- wall clock
# The ONE time source for timestamps written into shared state DBs
# (row timestamps, lease expiries): wall time, because other processes
# compare against it, behind the Clock interface so tests can swap a
# FakeClock in (lint rule STL011 keeps jobs/, serve/ and fleet/ off
# direct ``time.time()``).

_wall_clock: retry_lib.Clock = retry_lib.WALL_CLOCK


def wall_now() -> float:
    """Epoch seconds on the injectable wall clock."""
    return _wall_clock.now()


def set_wall_clock(
        clock: Optional[retry_lib.Clock]) -> retry_lib.Clock:
    """Swap the process wall clock (None = real); returns the previous
    clock so tests can restore it."""
    global _wall_clock
    previous = _wall_clock
    _wall_clock = clock or retry_lib.WALL_CLOCK
    return previous


def wall_clock() -> retry_lib.Clock:
    """The injectable wall clock itself — for components (fleet
    workers, lease tables) that need ``sleep`` as well as ``now`` on
    the SAME timeline the state DBs' timestamps use."""
    return _wall_clock


def _retry_policy(site: str) -> retry_lib.RetryPolicy:
    with _retry_lock:
        policy = _retry_policies.get(site)
        if policy is None:
            policy = retry_lib.RetryPolicy(
                max_attempts=6,
                initial_backoff=0.05,
                max_backoff=2.0,
                jitter='full',
                retryable=(sqlite3.OperationalError,),
                site=site)
            _retry_policies[site] = policy
        return policy


def connect(path: str, *, row_factory: bool = True) -> sqlite3.Connection:
    """The ONE sqlite connection recipe (see module docstring).

    ``isolation_level=None`` puts the connection in true autocommit:
    single statements commit immediately; multi-statement writes must
    go through :func:`transaction` (lint rule STL010 enforces this
    outside this module).
    """
    pathlib.Path(path).parent.mkdir(parents=True, exist_ok=True)
    conn = sqlite3.connect(path, timeout=BUSY_TIMEOUT_MS / 1000.0,
                           isolation_level=None)
    if row_factory:
        conn.row_factory = sqlite3.Row
    # The journal-mode switch needs an exclusive lock, and SQLite
    # skips the busy handler when it suspects a deadlock — so two
    # processes racing to convert a fresh DB to WAL can see
    # SQLITE_BUSY despite the 10s timeout above. Retry through the
    # standard policy instead of surfacing a spurious lock error.
    _retry_policy('statedb.connect').call(conn.execute,
                                          'PRAGMA journal_mode=WAL')
    conn.execute(f'PRAGMA busy_timeout={BUSY_TIMEOUT_MS}')
    conn.execute('PRAGMA synchronous=NORMAL')
    return conn


@contextlib.contextmanager
def transaction(conn: sqlite3.Connection, site: str = 'statedb.write'):
    """Explicit write transaction on an existing connection.

    BEGIN IMMEDIATE takes the write lock up front (no deferred-lock
    upgrade deadlocks); SQLITE_BUSY on acquisition is retried through
    the site's RetryPolicy. The body's mutations commit atomically —
    the ``statedb.commit.pre`` / ``.post`` crashpoints let chaos tests
    prove it (a crash at ``pre`` loses the whole transaction, never
    half of it).
    """
    _retry_policy(site).call(conn.execute, 'BEGIN IMMEDIATE')
    try:
        yield conn
    except BaseException:
        _rollback_quiet(conn)
        raise
    fault_injection.crashpoint('statedb.commit.pre', db=site)
    try:
        conn.commit()
    except BaseException:
        # A failed COMMIT (disk full, I/O error) must not strand a
        # cached connection inside the open transaction — every later
        # BEGIN on it would fail with 'cannot start a transaction
        # within a transaction'.
        _rollback_quiet(conn)
        raise
    fault_injection.crashpoint('statedb.commit.post', db=site)


def _rollback_quiet(conn: sqlite3.Connection) -> None:
    try:
        conn.rollback()
    except sqlite3.Error:
        pass  # connection unusable anyway; keep the original error


# ------------------------------------------------------ intent journal


def ensure_intent_table(conn: sqlite3.Connection) -> None:
    conn.execute(_INTENT_DDL)


def begin_intent(conn: sqlite3.Connection, kind: str,
                 payload: Optional[Dict[str, Any]] = None) -> int:
    """Journal the *intention* to perform a multi-step operation.

    Call inside the same :func:`transaction` as the operation's first
    state mutation; keep the returned id and
    :func:`complete_intent` it in the same transaction as the LAST
    mutation. Payload must carry everything recovery needs to decide
    adopt / roll forward / roll back (cluster name, replica id, …).
    """
    cur = conn.execute(
        'INSERT INTO intents (kind, payload, created_at, pid) '
        'VALUES (?,?,?,?)',
        (kind, json.dumps(payload or {}), time.time(), os.getpid()))
    return int(cur.lastrowid)


def complete_intent(conn: sqlite3.Connection, intent_id: int) -> None:
    conn.execute('DELETE FROM intents WHERE intent_id = ?', (intent_id,))


def open_intents(conn: sqlite3.Connection,
                 kind: Optional[str] = None) -> List[Dict[str, Any]]:
    """Open (= not completed) intents, oldest first — exactly the
    operations a dead process left in flight. ``kind`` may end with
    ``*`` to prefix-match (``'jobs.*'``)."""
    query = 'SELECT * FROM intents'
    args: List[Any] = []
    if kind is not None:
        if kind.endswith('*'):
            query += ' WHERE kind LIKE ?'
            args.append(kind[:-1] + '%')
        else:
            query += ' WHERE kind = ?'
            args.append(kind)
    query += ' ORDER BY intent_id'
    out = []
    for row in conn.execute(query, args):
        d = dict(row)
        try:
            d['payload'] = json.loads(d.get('payload') or '{}')
        except ValueError:
            # A torn payload must not wedge recovery of OTHER intents.
            d['payload'] = {}
        out.append(d)
    return out


# -------------------------------------------------------------- leases
# Generic expiring ownership with fencing tokens (docs/control_plane.md).
# The conn-level functions compose inside an outer transaction()
# (restart claims bundle a budget check with the ownership CAS); the
# LeaseTable class wraps a StateDB for standalone use by fleet workers.

_M_LEASE_CLAIMS = metrics_lib.counter(
    'skytpu_lease_claims_total',
    'Successful lease claims, by kind (fresh = unowned row, takeover '
    '= expired or usurped from a dead owner).',
    labels=('kind',))
_M_LEASE_RENEWALS = metrics_lib.counter(
    'skytpu_lease_renewals_total',
    'Successful lease heartbeat renewals.')
_M_LEASE_RELEASES = metrics_lib.counter(
    'skytpu_lease_releases_total',
    'Leases released voluntarily by their owner.')
_M_LEASE_LOSSES = metrics_lib.counter(
    'skytpu_lease_losses_total',
    'Renewals/releases that found the lease already claimed over '
    '(the caller lost ownership).')
_M_LEASE_STALE_WRITES = metrics_lib.counter(
    'skytpu_lease_stale_writes_total',
    'Guarded state writes rejected because the writer\'s fencing '
    'token was stale (a successor claimed the lease).')


class LeaseLostError(RuntimeError):
    """The caller's lease is no longer current: a successor holds a
    higher fencing token (or the worker was revoked). Any in-flight
    operation must abandon WITHOUT further state writes."""


@dataclasses.dataclass(frozen=True)
class Lease:
    """Immutable claim handle. ``fence`` is the fencing token: it
    increases on every successful claim of the resource, so a write
    guarded by an old fence can never land after a successor's.
    ``takeover`` records whether this claim displaced an expired /
    usurped owner (metrics only, not identity)."""
    resource: str
    owner: str
    fence: int
    expires_at: float
    takeover: bool = False


def record_lease_metric(action: str, *, takeover: bool = False) -> None:
    """Count one lease event. Callers invoke this AFTER their
    transaction commits: counting inside a still-open transaction
    would leave phantom counts behind a rollback, and the counters
    are documented to reconcile with the fencing-token audit."""
    if action == 'claim':
        _M_LEASE_CLAIMS.inc(1, kind='takeover' if takeover
                            else 'fresh')
    elif action == 'renew':
        _M_LEASE_RENEWALS.inc(1)
    elif action == 'release':
        _M_LEASE_RELEASES.inc(1)
    elif action == 'loss':
        _M_LEASE_LOSSES.inc(1)


def ensure_lease_table(conn: sqlite3.Connection) -> None:
    conn.execute(_LEASE_DDL)


def lease_register(conn: sqlite3.Connection, resource: str) -> None:
    """Create the (unowned) lease row if absent — claimable at fence 1."""
    conn.execute(
        'INSERT OR IGNORE INTO leases (resource, owner, fence) '
        'VALUES (?, NULL, 0)', (resource,))


def lease_try_claim(conn: sqlite3.Connection, resource: str,
                    owner: str, ttl: float, now: float,
                    expect_owner: Optional[str] = None
                    ) -> Optional[Lease]:
    """One CAS claim attempt; call inside a transaction().

    Succeeds when the row is unowned, expired at ``now``, or —
    ``expect_owner`` given — still names exactly the owner the caller
    observed to be dead (the restart-claim shape: a changed owner
    means another claimant already took over). Bumps the fencing
    token. Returns the claimed Lease or None (lost).

    A MISSING row is a loss, not an implicit registration: settled
    work's rows are deleted (:func:`lease_delete`), and a claim
    racing that deletion must NOT resurrect the row — it would
    restart the fence sequence and hand out an already-used token
    (:func:`lease_register` / :func:`lease_force_claim` are the
    explicit creation paths).
    """
    row = conn.execute(
        'SELECT owner, fence, expires_at FROM leases '
        'WHERE resource = ?', (resource,)).fetchone()
    if row is None:
        return None
    cur_owner, fence = row['owner'], int(row['fence'])
    expires = row['expires_at']
    unowned = cur_owner is None
    # NULL expiry on an OWNED row means "never expires" (classic
    # one-process controllers own their lease without heartbeating;
    # liveness is proven out-of-band and usurped via expect_owner).
    expired = (not unowned and expires is not None and
               float(expires) <= now)
    usurped = expect_owner is not None and cur_owner == expect_owner
    if not (unowned or expired or usurped):
        return None
    conn.execute(
        'UPDATE leases SET owner = ?, fence = ?, acquired_at = ?, '
        'expires_at = ?, renewals = 0 WHERE resource = ?',
        (owner, fence + 1, now, now + ttl, resource))
    return Lease(resource, owner, fence + 1, now + ttl,
                 takeover=not unowned)


def lease_force_claim(conn: sqlite3.Connection, resource: str,
                      owner: str, now: float,
                      ttl: Optional[float] = None) -> Lease:
    """Unconditional takeover (still bumps the fence): for a process
    whose ownership is proven out-of-band — the controller a
    relauncher just spawned IS the owner, whoever held the row.
    ``ttl=None`` = no expiry (ownership ends only by release or a
    ``expect_owner`` usurp from a caller that observed death)."""
    row = conn.execute(
        'SELECT fence FROM leases WHERE resource = ?',
        (resource,)).fetchone()
    fence = (int(row['fence']) if row is not None else 0) + 1
    expires = None if ttl is None else now + ttl
    conn.execute(
        'INSERT INTO leases (resource, owner, fence, acquired_at, '
        'expires_at, renewals) VALUES (?,?,?,?,?,0) '
        'ON CONFLICT(resource) DO UPDATE SET owner = ?, fence = ?, '
        'acquired_at = ?, expires_at = ?, renewals = 0',
        (resource, owner, fence, now, expires,
         owner, fence, now, expires))
    return Lease(resource, owner, fence,
                 expires if expires is not None else float('inf'),
                 takeover=row is not None)


def lease_renew(conn: sqlite3.Connection, lease: Lease, ttl: float,
                now: float) -> Optional[Lease]:
    """Heartbeat: extend expiry iff (owner, fence) is still current.
    Returns the refreshed Lease, or None — the lease was lost."""
    cur = conn.execute(
        'UPDATE leases SET expires_at = ?, renewals = renewals + 1 '
        'WHERE resource = ? AND owner = ? AND fence = ?',
        (now + ttl, lease.resource, lease.owner, lease.fence))
    if cur.rowcount != 1:
        return None
    return dataclasses.replace(lease, expires_at=now + ttl)


def lease_release(conn: sqlite3.Connection, lease: Lease) -> bool:
    """Voluntary release: the row goes unowned (fence is KEPT — the
    next claim must still fence above this one). False = already lost."""
    cur = conn.execute(
        'UPDATE leases SET owner = NULL, expires_at = NULL '
        'WHERE resource = ? AND owner = ? AND fence = ?',
        (lease.resource, lease.owner, lease.fence))
    return cur.rowcount == 1


def lease_delete(conn: sqlite3.Connection, lease: Lease) -> bool:
    """Retire the row entirely — for work that reached a terminal
    state and will never be claimed again (settled jobs, removed
    services). CAS'd on (owner, fence) like release, so only the
    current owner can retire it; without deletion, every claim scan
    would iterate terminal work's released rows forever."""
    cur = conn.execute(
        'DELETE FROM leases '
        'WHERE resource = ? AND owner = ? AND fence = ?',
        (lease.resource, lease.owner, lease.fence))
    return cur.rowcount == 1


def lease_check(conn: sqlite3.Connection, lease: Lease) -> bool:
    """Is the caller's (owner, fence) pair still the current claim?
    Expiry alone does NOT fail this check: an expired-but-unclaimed
    lease still belongs to its owner (classic fencing) — only a
    successor's claim, which bumps the fence, revokes it."""
    row = conn.execute(
        'SELECT owner, fence FROM leases WHERE resource = ?',
        (lease.resource,)).fetchone()
    return (row is not None and row['owner'] == lease.owner and
            int(row['fence']) == lease.fence)


def lease_get(conn: sqlite3.Connection,
              resource: str) -> Optional[Dict[str, Any]]:
    row = conn.execute('SELECT * FROM leases WHERE resource = ?',
                       (resource,)).fetchone()
    return dict(row) if row is not None else None


def lease_claimable(conn: sqlite3.Connection, prefix: str,
                    now: float) -> List[str]:
    """Resources under ``prefix`` that are unowned or expired at
    ``now`` — the fleet scheduler's scan, oldest expiry first so a
    dead worker's abandoned work is adopted before fresh work."""
    rows = conn.execute(
        'SELECT resource FROM leases WHERE resource LIKE ? AND '
        '(owner IS NULL OR (expires_at IS NOT NULL AND '
        'expires_at <= ?)) '
        'ORDER BY (expires_at IS NULL), expires_at, resource',
        (prefix + '%', now)).fetchall()
    return [r['resource'] for r in rows]


LeaseEvent = Tuple[str, str, str, int, float]  # action, resource, owner, fence, t


class LeaseTable:
    """Lease operations on one StateDB, each in its own transaction.

    ``clock`` is injectable (:class:`~skypilot_tpu.utils.retry.
    FakeClock` drives expiry deterministically in tests); ``on_event``
    receives ``(action, resource, owner, fence, t)`` tuples — the
    scale harness uses it to audit fence monotonicity across workers.
    """

    def __init__(self, db: 'StateDB',
                 clock: Optional[retry_lib.Clock] = None,
                 on_event: Optional[Callable[[LeaseEvent],
                                             None]] = None) -> None:
        self.db = db
        self.clock = clock or _wall_clock
        self.on_event = on_event

    def _emit(self, action: str, resource: str, owner: str,
              fence: int) -> None:
        if self.on_event is not None:
            self.on_event((action, resource, owner, fence,
                           self.clock.now()))

    def register(self, resources: Iterable[str]) -> None:
        resources = list(resources)
        if not resources:
            return
        with self.db.transaction() as conn:
            for resource in resources:
                lease_register(conn, resource)

    def try_claim(self, resource: str, owner: str, ttl: float,
                  expect_owner: Optional[str] = None
                  ) -> Optional[Lease]:
        with self.db.transaction() as conn:
            lease = lease_try_claim(conn, resource, owner, ttl,
                                    self.clock.now(),
                                    expect_owner=expect_owner)
        if lease is not None:
            record_lease_metric('claim', takeover=lease.takeover)
            self._emit('claim', resource, owner, lease.fence)
        return lease

    def renew(self, lease: Lease, ttl: float) -> Optional[Lease]:
        with self.db.transaction() as conn:
            renewed = lease_renew(conn, lease, ttl, self.clock.now())
        record_lease_metric('renew' if renewed is not None else 'loss')
        if renewed is not None:
            self._emit('renew', lease.resource, lease.owner,
                       lease.fence)
        return renewed

    def renew_many(self, leases: List[Lease],
                   ttl: float) -> Dict[str, Optional[Lease]]:
        """Heartbeat a whole held set in ONE transaction: a worker
        holding dozens of leases must not pay (and contend for) one
        write-lock acquisition per lease per sweep — at fleet scale
        that is exactly what makes sweeps outlast the TTL and causes
        spurious expirations."""
        results: Dict[str, Optional[Lease]] = {}
        if not leases:
            return results
        with self.db.transaction() as conn:
            now = self.clock.now()
            for lease in leases:
                results[lease.resource] = lease_renew(conn, lease,
                                                      ttl, now)
        for lease in leases:
            ok = results.get(lease.resource) is not None
            record_lease_metric('renew' if ok else 'loss')
            if ok:
                self._emit('renew', lease.resource, lease.owner,
                           lease.fence)
        return results

    def release(self, lease: Lease) -> bool:
        with self.db.transaction() as conn:
            ok = lease_release(conn, lease)
        record_lease_metric('release' if ok else 'loss')
        if ok:
            self._emit('release', lease.resource, lease.owner,
                       lease.fence)
        return ok

    def delete(self, lease: Lease) -> bool:
        with self.db.transaction() as conn:
            ok = lease_delete(conn, lease)
        record_lease_metric('release' if ok else 'loss')
        if ok:
            self._emit('release', lease.resource, lease.owner,
                       lease.fence)
        return ok

    def check(self, lease: Lease) -> bool:
        with self.db.reader() as conn:
            return lease_check(conn, lease)

    def get(self, resource: str) -> Optional[Dict[str, Any]]:
        with self.db.reader() as conn:
            return lease_get(conn, resource)

    def claimable(self, prefix: str = '') -> List[str]:
        with self.db.reader() as conn:
            return lease_claimable(conn, prefix, self.clock.now())

    def snapshot(self, prefix: str = '') -> List[Dict[str, Any]]:
        with self.db.reader() as conn:
            rows = conn.execute(
                'SELECT * FROM leases WHERE resource LIKE ? '
                'ORDER BY resource', (prefix + '%',)).fetchall()
        return [dict(r) for r in rows]

    def guard(self, lease: Lease,
              extra_check: Optional[Callable[[], None]] = None
              ) -> 'FenceGuard':
        return FenceGuard(self.db, lease, extra_check=extra_check)


# -------------------------------------------------------- fence guards
# While a FenceGuard is installed (contextvar — per thread/task),
# EVERY StateDB.transaction() on the guarded database re-validates the
# lease's (owner, fence) pair inside the same BEGIN IMMEDIATE as the
# caller's writes, and raises LeaseLostError BEFORE any mutation runs
# when the token is stale. This is the fencing invariant: a worker
# that lost its lease mid-operation cannot clobber its successor,
# without threading a lease handle through every state function.

_GUARDS: 'contextvars.ContextVar[tuple]' = contextvars.ContextVar(
    'statedb_fence_guards', default=())


class FenceGuard:
    """One installed lease check. ``extra_check`` runs first on every
    validation (the fleet worker uses it to act out worker death:
    a killed worker's every write raises immediately)."""

    def __init__(self, db: 'StateDB', lease: Lease,
                 extra_check: Optional[Callable[[], None]] = None
                 ) -> None:
        self.db = db
        self.lease = lease
        self.extra_check = extra_check
        self.revoked = False

    def revoke(self) -> None:
        """Mark lost out-of-band (e.g. the renewal heartbeat failed):
        the next guarded write raises without touching the DB."""
        self.revoked = True

    def validate(self, conn: Optional[sqlite3.Connection] = None,
                 path: Optional[str] = None) -> None:
        """Raise LeaseLostError if this guard's lease is stale.

        When ``conn`` is a connection to the guard's own database the
        check runs on it (atomic with the caller's transaction);
        otherwise a fresh reader is used — still a hard fence, just
        checked slightly before the write commits.
        """
        if self.extra_check is not None:
            self.extra_check()
        if self.revoked:
            raise LeaseLostError(
                f'lease {self.lease.resource} (fence '
                f'{self.lease.fence}) was revoked')
        own_path = self.db.path()
        if conn is not None and path == own_path:
            ok = lease_check(conn, self.lease)
        else:
            with self.db.reader() as reader:
                ok = lease_check(reader, self.lease)
        if not ok:
            _M_LEASE_STALE_WRITES.inc(1)
            raise LeaseLostError(
                f'lease {self.lease.resource} (owner '
                f'{self.lease.owner}, fence {self.lease.fence}) is '
                'stale: a successor claimed it')


@contextlib.contextmanager
def guarded(guard: FenceGuard):
    """Install a fence guard for the current thread/task."""
    token = _GUARDS.set(_GUARDS.get() + (guard,))
    try:
        yield guard
    finally:
        _GUARDS.reset(token)


def validate_guards() -> None:
    """Explicit checkpoint for non-statedb side effects (the synthetic
    cloud's launch/terminate call this): raises LeaseLostError when
    any installed guard is stale."""
    for guard in _GUARDS.get():
        guard.validate()


def _apply_guards(conn: sqlite3.Connection, path: str) -> None:
    for guard in _GUARDS.get():
        guard.validate(conn, path)


# ------------------------------------------------------------- StateDB


class StateDB:
    """One control-plane database: path resolution, once-per-path DDL
    (schema creation + in-place migrations), transactions, intents.

    ``path_fn`` re-resolves the path on every connection so tests that
    point the env var at a fresh tmp dir get a fresh DB; the DDL
    ``init_fn(conn)`` runs once per (process, path).
    """

    def __init__(self, path_fn: Callable[[], str],
                 init_fn: Optional[Callable[[sqlite3.Connection],
                                            None]] = None,
                 site: str = 'statedb.write') -> None:
        self._path_fn = path_fn
        self._init_fn = init_fn
        self.site = site
        self._initialized_paths: set = set()
        self._init_lock = threading.Lock()

    def path(self) -> str:
        return self._path_fn()

    def connection(self) -> sqlite3.Connection:
        path = self._path_fn()
        conn = connect(path)
        if path not in self._initialized_paths:
            with self._init_lock:
                if path not in self._initialized_paths:
                    ensure_intent_table(conn)
                    ensure_lease_table(conn)
                    if self._init_fn is not None:
                        self._init_fn(conn)
                    self._initialized_paths.add(path)
        return conn

    @contextlib.contextmanager
    def reader(self):
        """Read-only use; closes the connection on exit."""
        conn = self.connection()
        try:
            yield conn
        finally:
            conn.close()

    @contextlib.contextmanager
    def transaction(self):
        """Fresh connection, one explicit transaction, closed after.

        Installed fence guards (see :func:`guarded`) are validated
        INSIDE the transaction, before the body runs: a stale fencing
        token raises LeaseLostError with zero mutations applied."""
        path = self._path_fn()
        conn = self.connection()
        try:
            with transaction(conn, site=self.site) as txn:
                _apply_guards(txn, path)
                yield txn
        finally:
            conn.close()

    # Convenience single-op intent helpers (own transaction each) for
    # callers that are not already inside one.
    def begin_intent(self, kind: str,
                     payload: Optional[Dict[str, Any]] = None) -> int:
        with self.transaction() as conn:
            return begin_intent(conn, kind, payload)

    def complete_intent(self, intent_id: int) -> None:
        with self.transaction() as conn:
            complete_intent(conn, intent_id)

    def open_intents(self,
                     kind: Optional[str] = None) -> List[Dict[str, Any]]:
        with self.reader() as conn:
            return open_intents(conn, kind)
