"""Central ``SKYTPU_*`` / ``BENCH_*`` environment-variable registry.

Every control-plane / bench tunable is declared here exactly once,
with a help string — the single auditable surface of the env
contract. The *rank* contract names (``SKYTPU_NODE_RANK`` etc.) live
in :mod:`skypilot_tpu.utils.env_contract`; everything else lives
here.

The static analyzer (rule STL005, docs/static_analysis.md) flags any
``SKYTPU_*``/``BENCH_*`` string literal elsewhere in the repo whose
name is not declared in one of these two modules: a name the
registry has never heard of is either a typo (reads silently fall
back to the default) or an undeclared knob. Modules should reference
the constants (``env_registry.SKYTPU_DEBUG``) rather than repeating
the literal, so a rename stays one-line.

Purely stdlib and import-light: this is imported by logging setup.
"""
from __future__ import annotations

import os
import re
from typing import Dict, Mapping, Optional

_NAME_RE = re.compile(r'\A(?:SKYTPU|BENCH)_[A-Z0-9_]+\Z')
_DECLARED: Dict[str, str] = {}


def register(name: str, help: str) -> str:
    """Declare one env var; returns the name (assign it to a module
    constant). Re-declaration and malformed names raise — the
    registry is the one place where duplicates are a bug."""
    if not _NAME_RE.fullmatch(name):
        raise ValueError(f'env var {name!r} must match '
                         '(SKYTPU|BENCH)_[A-Z0-9_]+')
    if not help or not help.strip():
        raise ValueError(f'env var {name!r} needs a help string')
    if name in _DECLARED:
        raise ValueError(f'env var {name!r} declared twice')
    _DECLARED[name] = help
    return name


def declared() -> Mapping[str, str]:
    """name -> help for every registered var (docs/tests enumerate)."""
    return dict(_DECLARED)


def get(name: str, default: Optional[str] = None) -> Optional[str]:
    return os.environ.get(name, default)


def is_enabled(name: str) -> bool:
    """The repo's boolean convention: set to '1' means on."""
    return os.environ.get(name, '0') == '1'


# ------------------------------------------------------------- logging
SKYTPU_DEBUG = register(
    'SKYTPU_DEBUG', 'Set to 1 for DEBUG-level logging.')
SKYTPU_MINIMIZE_LOGGING = register(
    'SKYTPU_MINIMIZE_LOGGING', 'Set to 1 to log WARNING and above only.')

# ----------------------------------------------------- state / config
SKYTPU_CONFIG = register(
    'SKYTPU_CONFIG', 'Path to the user config YAML.')
SKYTPU_STATE_DB = register(
    'SKYTPU_STATE_DB', 'Path of the global cluster-state sqlite DB.')
SKYTPU_DATA_DIR = register(
    'SKYTPU_DATA_DIR', 'Root directory for local artifacts '
    '(cluster dirs, logs, mounts).')
SKYTPU_USER = register(
    'SKYTPU_USER', 'Override the logical user name.')
SKYTPU_USER_HASH = register(
    'SKYTPU_USER_HASH', 'Override the stable per-user hash.')

# -------------------------------------------------------- managed jobs
SKYTPU_JOBS_DB = register(
    'SKYTPU_JOBS_DB', 'Path of the managed-jobs sqlite DB.')
SKYTPU_JOBS_LOG_DIR = register(
    'SKYTPU_JOBS_LOG_DIR', 'Directory for managed-job controller logs.')
SKYTPU_JOBS_LAUNCH_PARALLELISM = register(
    'SKYTPU_JOBS_LAUNCH_PARALLELISM',
    'Max concurrent managed-job launches (jobs/scheduler.py).')
SKYTPU_JOBS_LAUNCH_MAX_ATTEMPTS = register(
    'SKYTPU_JOBS_LAUNCH_MAX_ATTEMPTS',
    'Retry budget for one managed-job launch (RetryPolicy attempts).')
SKYTPU_JOBS_LAUNCH_RETRY_GAP = register(
    'SKYTPU_JOBS_LAUNCH_RETRY_GAP',
    'Initial backoff seconds between managed-job launch attempts.')
SKYTPU_MAX_CONCURRENT_JOBS = register(
    'SKYTPU_MAX_CONCURRENT_JOBS',
    'Cap on simultaneously RUNNING managed jobs.')
SKYTPU_HEARTBEAT_INTERVAL = register(
    'SKYTPU_HEARTBEAT_INTERVAL',
    'Seconds between jobs-controller liveness heartbeats.')

# --------------------------------------------------------------- serve
SKYTPU_SERVE_DB = register(
    'SKYTPU_SERVE_DB', 'Path of the serve-state sqlite DB.')
SKYTPU_SERVE_LOG_DIR = register(
    'SKYTPU_SERVE_LOG_DIR', 'Directory for serve controller/LB logs.')
SKYTPU_SERVE_PORT = register(
    'SKYTPU_SERVE_PORT', 'Serve controller port override.')

# ---------------------------------------------------------- API server
SKYTPU_API_SERVER_ENDPOINT = register(
    'SKYTPU_API_SERVER_ENDPOINT',
    'URL of a remote API server; unset = local execution.')
SKYTPU_REQUESTS_DB = register(
    'SKYTPU_REQUESTS_DB', 'Path of the API-server requests sqlite DB.')
SKYTPU_REQUESTS_LOG_DIR = register(
    'SKYTPU_REQUESTS_LOG_DIR',
    'Directory for per-request API-server logs.')

# --------------------------------------------------------------- agent
SKYTPU_AGENT_EVENT_INTERVAL = register(
    'SKYTPU_AGENT_EVENT_INTERVAL',
    'Seconds between agentd housekeeping events.')
SKYTPU_WORKER_PROBE_INTERVAL = register(
    'SKYTPU_WORKER_PROBE_INTERVAL',
    'Seconds between gang-worker liveness probes (agent/driver.py).')
SKYTPU_WORKER_PROBE_THRESHOLD = register(
    'SKYTPU_WORKER_PROBE_THRESHOLD',
    'Consecutive failed worker probes before a rank is declared lost.')
SKYTPU_SETUP_NODE_RANK = register(
    'SKYTPU_SETUP_NODE_RANK',
    'Rank exposed to per-node setup commands.')

# ----------------------------------------------------------- telemetry
SKYTPU_TRACE_DIR = register(
    'SKYTPU_TRACE_DIR',
    'Span-spool directory for distributed traces (docs/tracing.md); '
    'unset disables tracing entirely.')
SKYTPU_TRACE_CONTEXT = register(
    'SKYTPU_TRACE_CONTEXT',
    'Inherited trace context (traceparent form 00-<trace>-<span>-01) '
    'parenting this process\'s root spans; set for child processes by '
    'trace.child_env().')
SKYTPU_TRACE_SEED = register(
    'SKYTPU_TRACE_SEED',
    'Seed for deterministic trace/span id generation (tests, golden '
    'files); unset = random ids.')
SKYTPU_TRACE_SLOW_SPAN_SECONDS = register(
    'SKYTPU_TRACE_SLOW_SPAN_SECONDS',
    'Log a warning (with the trace id) for any span slower than this '
    'many seconds; 0 disables (default 30).')
SKYTPU_TIMELINE_FILE_PATH = register(
    'SKYTPU_TIMELINE_FILE_PATH',
    'Write a Chrome-trace timeline of control-plane events here '
    '(legacy single-file export; spans are the primary sink).')
SKYTPU_PROFILER_PORT = register(
    'SKYTPU_PROFILER_PORT',
    'Start jax.profiler\'s gRPC server on every worker at this port.')
SKYTPU_PROFILE_DIR = register(
    'SKYTPU_PROFILE_DIR',
    'Capture one jax.profiler trace of a train step into this dir.')
SKYTPU_METRICS_DIR = register(
    'SKYTPU_METRICS_DIR',
    'Spool directory for cross-process metric snapshots '
    '(docs/metrics.md).')
SKYTPU_METRICS_TTL = register(
    'SKYTPU_METRICS_TTL',
    'Seconds before a spooled metrics snapshot ages out of scrapes.')
SKYTPU_USAGE_COLLECTOR_URL = register(
    'SKYTPU_USAGE_COLLECTOR_URL',
    'Usage-report collector endpoint (unset = no reporting).')
SKYTPU_USAGE_FLUSH_INTERVAL = register(
    'SKYTPU_USAGE_FLUSH_INTERVAL',
    'Seconds between usage-report flushes.')
SKYTPU_DISABLE_USAGE = register(
    'SKYTPU_DISABLE_USAGE', 'Set to 1 to disable usage reporting.')

# ----------------------------------------------------------- benchmark
SKYTPU_BENCHMARK_DB = register(
    'SKYTPU_BENCHMARK_DB', 'Path of the benchmark sqlite DB.')
SKYTPU_BENCHMARK_DIR = register(
    'SKYTPU_BENCHMARK_DIR', 'Directory for benchmark artifacts.')

# ------------------------------------------------------ crash recovery
SKYTPU_RECONCILE_ON_START = register(
    'SKYTPU_RECONCILE_ON_START',
    'Crash-only startup for the jobs/serve controllers: replay open '
    'intent records against cloud truth on every start (adopt / roll '
    'forward / roll back; docs/crash_recovery.md). Default on; set 0 '
    'to disable.')
SKYTPU_CONTROLLER_RESTART_LIMIT = register(
    'SKYTPU_CONTROLLER_RESTART_LIMIT',
    'Max automatic relaunches of a managed-job controller process '
    'whose pid died while the job was non-terminal (jobs/scheduler.'
    'py); beyond it the job is marked FAILED_CONTROLLER. Default 3.')

# --------------------------------------------------------------- chaos
SKYTPU_FAULT_PLAN = register(
    'SKYTPU_FAULT_PLAN',
    'Fault-injection plan: inline JSON or a path '
    '(docs/fault_injection.md). Inherited by child processes.')

# ------------------------------------------------------ docker / data
SKYTPU_DOCKER_SERVER = register(
    'SKYTPU_DOCKER_SERVER', 'Private registry server for task images.')
SKYTPU_DOCKER_USERNAME = register(
    'SKYTPU_DOCKER_USERNAME', 'Private registry login user.')
SKYTPU_DOCKER_PASSWORD = register(
    'SKYTPU_DOCKER_PASSWORD', 'Private registry login password.')
SKYTPU_R2_MOUNT_TOOL = register(
    'SKYTPU_R2_MOUNT_TOOL', 'Override the Cloudflare R2 mount binary.')

# ------------------------------------------------------ kernels/models
SKYTPU_FLASH_BLOCK_Q = register(
    'SKYTPU_FLASH_BLOCK_Q', 'Flash-attention Q block size override.')
SKYTPU_FLASH_BLOCK_K = register(
    'SKYTPU_FLASH_BLOCK_K', 'Flash-attention K block size override.')
SKYTPU_DECODE_ATTN = register(
    'SKYTPU_DECODE_ATTN',
    'Decode attention impl: paged | lax (models/inference.py).')
SKYTPU_DECODE_PAGE = register(
    'SKYTPU_DECODE_PAGE', 'Paged decode-attention page size (tokens).')
SKYTPU_PREFILL_CHUNK = register(
    'SKYTPU_PREFILL_CHUNK',
    'Chunked-prefill slice size in prompt tokens (serving engine; '
    'default 128, clamped to max_prompt).')
SKYTPU_PREFILL_BUDGET = register(
    'SKYTPU_PREFILL_BUDGET',
    'Per-tick prompt-token budget across prefilling slots in the '
    'serving engine\'s mixed scheduler (default 256; folds to whole '
    'chunk rows, so the effective budget is '
    'chunk * max(1, budget // chunk)).')
SKYTPU_PREFIX_CACHE = register(
    'SKYTPU_PREFIX_CACHE',
    'Set to 1 to enable automatic prefix caching in the serving '
    'engine (block-hash shared page pool, models/prefix_cache.py; '
    'PERFORMANCE.md "Prefix-reuse KV cache"). Off (default) keeps '
    'engine behavior bit-identical to a build without the cache.')
SKYTPU_PREFIX_POOL_PAGES = register(
    'SKYTPU_PREFIX_POOL_PAGES',
    'Shared prefix-pool capacity in pages (at the engine page size; '
    'default 512). Cold unpinned pages evict LRU beyond it.')
SKYTPU_SPEC_DECODE = register(
    'SKYTPU_SPEC_DECODE',
    'Set to 1 to enable speculative multi-token decoding in the '
    'serving engine (host-side prompt-lookup drafts, batched '
    'draft-and-verify in the fused tick; PERFORMANCE.md '
    '"Speculative decoding"). Off (default) keeps every tick '
    'bit-identical to the pre-speculation engine.')
SKYTPU_SPEC_K = register(
    'SKYTPU_SPEC_K',
    'Max drafted tokens per decode slot per verify tick (default 4; '
    '0 disables speculation outright). Each verify tick feeds k+1 '
    'tokens per slot and consumes k+1 shared cache columns; higher k '
    'buys more tokens/step at the acceptance rate the workload '
    'sustains.')
SKYTPU_SPEC_NGRAM = register(
    'SKYTPU_SPEC_NGRAM',
    'Max n-gram length the prompt-lookup draft proposer matches '
    'against the slot token chain (default 3; longer suffix matches '
    'are tried first, most recent occurrence wins).')
SKYTPU_TP = register(
    'SKYTPU_TP',
    'Default tensor-parallel ways for the HTTP serving replica '
    '(serving_http --tp overrides; default 1). The engine builds a '
    'tp-axis mesh over the first N local chips and every fast path — '
    'paged decode, chunk prefill, verify, prefix cache — runs '
    'sharded on it (PERFORMANCE.md "Multi-chip serving").')
SKYTPU_PREFIX_POOL_SHARD = register(
    'SKYTPU_PREFIX_POOL_SHARD',
    'Default 1: on mesh engines the prefix-cache page pool shards '
    'its kv-head axis over \'tp\' like the live cache, so page '
    'copy-in/out never gathers to one chip. Set 0 to keep the pool '
    'replicated (debugging escape hatch; correctness-neutral).')

# ----------------------------------------------------------------- SLO
SKYTPU_SLO_TTFT_S = register(
    'SKYTPU_SLO_TTFT_S',
    'TTFT SLO threshold in seconds for the serving engine: a first '
    'token slower than this counts a violation '
    '(skytpu_engine_slo_violations_total{kind=ttft}) and pins the '
    'request\'s trace id on the p99 gauge as an exemplar '
    '(docs/load_testing.md). 0 (default) disables violation '
    'accounting; the p99 gauges update regardless.')
SKYTPU_SLO_ITL_S = register(
    'SKYTPU_SLO_ITL_S',
    'Inter-token-latency SLO threshold in seconds (same semantics as '
    'SKYTPU_SLO_TTFT_S, for the streaming stall between token '
    'bursts). 0 (default) disables violation accounting.')
SKYTPU_SLO_WINDOW_S = register(
    'SKYTPU_SLO_WINDOW_S',
    'Sliding-window length in seconds for the skytpu_*_p99 latency '
    'gauges (engine TTFT/ITL, LB request latency; default 60). The '
    'window forgets, unlike the cumulative histograms — it is the '
    'signal the SLO autoscaler scales on.')

# --------------------------------------------------- request lifecycle
SKYTPU_DRAIN_TIMEOUT_SECONDS = register(
    'SKYTPU_DRAIN_TIMEOUT_SECONDS',
    'Graceful-drain budget for a SIGTERM\'d serving replica: seconds '
    'in-flight requests may run to completion before being cancelled '
    'and the process exits (docs/request_lifecycle.md; default 30).')
SKYTPU_TICK_HANG_SECONDS = register(
    'SKYTPU_TICK_HANG_SECONDS',
    'Serving-engine tick watchdog: a device tick slower than this '
    'many seconds logs a trace-tagged warning and bumps '
    'skytpu_engine_tick_hangs_total (0 disables; default 30).')

# ------------------------------------------- replica failover (LB)
SKYTPU_LB_BREAKER_THRESHOLD = register(
    'SKYTPU_LB_BREAKER_THRESHOLD',
    'Consecutive soft proxy failures (timeout, mid-stream death, '
    '5xx) before the LB\'s per-replica circuit breaker trips open '
    '(docs/failover.md; default 3). A hard connect-refused/reset '
    'trips immediately regardless.')
SKYTPU_LB_BREAKER_COOLDOWN_S = register(
    'SKYTPU_LB_BREAKER_COOLDOWN_S',
    'Seconds an open circuit breaker holds a replica out of the '
    'routable set before admitting ONE half-open trial request '
    '(success re-closes, failure re-opens; default 2).')
SKYTPU_LB_HEDGE = register(
    'SKYTPU_LB_HEDGE',
    'TTFT hedging for streaming /generate at the LB: a request that '
    'has streamed ZERO bytes after the hedge delay is raced on a '
    'second replica, the loser cancelled by request id '
    '(docs/failover.md). Default on; set 0 to disable.')
SKYTPU_LB_HEDGE_DELAY_S = register(
    'SKYTPU_LB_HEDGE_DELAY_S',
    'Fallback hedge delay in seconds while the LB\'s sliding TTFT '
    'window has no samples yet (default 2). Once the window fills, '
    'the delay is its p95 TTFT (never below '
    'SKYTPU_LB_HEDGE_MIN_S).')
SKYTPU_LB_HEDGE_MIN_S = register(
    'SKYTPU_LB_HEDGE_MIN_S',
    'Floor on the p95-TTFT-derived hedge delay in seconds (default '
    '0.05): a very fast window must not hedge every request that '
    'hits one slow tick.')
SKYTPU_LB_RESUME = register(
    'SKYTPU_LB_RESUME',
    'Mid-stream resumption for GREEDY streaming /generate at the '
    'LB: when a replica dies mid-stream, the prompt plus the tokens '
    'already streamed are re-submitted to a healthy replica and the '
    'continuation spliced into the client\'s SSE stream '
    '(docs/failover.md). Default on; set 0 to disable.')
SKYTPU_LB_RESUME_MAX = register(
    'SKYTPU_LB_RESUME_MAX',
    'Max resume attempts per client stream before the LB gives up '
    'and ends the (truncated) stream (default 3).')

# ------------------------------------------------- bench.py (BENCH_*)
BENCH_SMOKE = register(
    'BENCH_SMOKE',
    'Set to 1: CPU backend + tiny configs so every bench mode '
    'completes in seconds (CI smoke).')
BENCH_MODE = register('BENCH_MODE', 'Bench mode to run (bench.py).')
BENCH_ALL_MODES = register(
    'BENCH_ALL_MODES', 'Comma-separated mode list for `bench.py all`.')
BENCH_DEVICE_TIMEOUT = register(
    'BENCH_DEVICE_TIMEOUT',
    'Total seconds to wait for TPU devices across all probe attempts.')
BENCH_DEVICE_ATTEMPTS = register(
    'BENCH_DEVICE_ATTEMPTS',
    'Bounded attempts for the bench device probe (utils/retry.'
    'RetryPolicy; the total BENCH_DEVICE_TIMEOUT splits across them).')
BENCH_MODEL = register('BENCH_MODEL', 'Train bench model preset.')
BENCH_SEQ = register('BENCH_SEQ', 'Train bench sequence length.')
BENCH_BATCH = register('BENCH_BATCH', 'Train bench global batch size.')
BENCH_STEPS = register('BENCH_STEPS', 'Train bench step count.')
BENCH_REMAT = register('BENCH_REMAT', 'Train bench remat policy.')
BENCH_PARAM_DTYPE = register(
    'BENCH_PARAM_DTYPE', 'Train bench parameter dtype.')
BENCH_LOSS_CHUNK = register(
    'BENCH_LOSS_CHUNK', 'Train bench chunked-loss vocab chunk size.')
BENCH_CF = register(
    'BENCH_CF', 'MoE capacity factor (MoE presets only).')
BENCH_SERVE_MODEL = register(
    'BENCH_SERVE_MODEL', 'Serve bench model preset.')
BENCH_SERVE_BATCH = register(
    'BENCH_SERVE_BATCH', 'Serve bench engine batch slots.')
BENCH_SERVE_CHUNK = register(
    'BENCH_SERVE_CHUNK', 'Serve bench decode chunk size (steps per '
    'engine tick).')
BENCH_SERVE_PREFILL_CHUNK = register(
    'BENCH_SERVE_PREFILL_CHUNK',
    'Serve bench chunked-prefill slice size (SKYTPU_PREFILL_CHUNK '
    'analog).')
BENCH_SERVE_PREFILL_BUDGET = register(
    'BENCH_SERVE_PREFILL_BUDGET',
    'Serve bench per-tick prefill token budget '
    '(SKYTPU_PREFILL_BUDGET analog).')
BENCH_SERVE_PROMPT = register(
    'BENCH_SERVE_PROMPT', 'Serve bench prompt length.')
BENCH_SERVE_PAGE = register(
    'BENCH_SERVE_PAGE',
    'Serve bench engine page size in tokens (decode paged dispatch '
    'AND prefix-cache block granularity).')
BENCH_SERVE_PREFIX = register(
    'BENCH_SERVE_PREFIX',
    'Set to 1: serve bench generates a shared-prefix workload '
    '(Zipf-distributed reuse over a prefix pool) and enables the '
    'engine prefix cache. Default on under BENCH_SMOKE, off '
    'otherwise.')
BENCH_SERVE_PREFIX_POOL = register(
    'BENCH_SERVE_PREFIX_POOL',
    'Serve bench: number of distinct shared prefixes in the '
    'workload (Zipf-ranked; default 8, 2 under BENCH_SMOKE).')
BENCH_SERVE_PREFIX_LEN = register(
    'BENCH_SERVE_PREFIX_LEN',
    'Serve bench: shared-prefix length in tokens (default 3/4 of '
    'the max prompt).')
BENCH_SERVE_PREFIX_ZIPF = register(
    'BENCH_SERVE_PREFIX_ZIPF',
    'Serve bench: Zipf exponent of the prefix popularity '
    'distribution (default 1.1; higher = more head-heavy reuse).')
BENCH_SERVE_PREFIX_PAGES = register(
    'BENCH_SERVE_PREFIX_PAGES',
    'Serve bench: engine prefix-pool capacity in pages '
    '(SKYTPU_PREFIX_POOL_PAGES analog).')
BENCH_SERVE_TP = register(
    'BENCH_SERVE_TP',
    'serve_tp bench: tensor-parallel ways for the mesh arm (default '
    '2; needs that many visible devices — CPU smoke uses '
    'XLA_FLAGS=--xla_force_host_platform_device_count=8). The mode '
    'reports per-chip tok/s and req/s next to a same-seed tp=1 '
    'baseline and asserts bitwise greedy parity between the arms.')
BENCH_SERVE_MAX_NEW = register(
    'BENCH_SERVE_MAX_NEW', 'Serve bench max new tokens per request.')
BENCH_SERVE_REQUESTS = register(
    'BENCH_SERVE_REQUESTS', 'Serve bench total request count.')
BENCH_SERVE_CONCURRENCY = register(
    'BENCH_SERVE_CONCURRENCY', 'Serve bench client concurrency.')
BENCH_SERVE_QUANT = register(
    'BENCH_SERVE_QUANT', 'Serve bench KV-cache quantization (int8).')
BENCH_SERVE_WQUANT = register(
    'BENCH_SERVE_WQUANT', 'Serve bench weight quantization (int8).')
BENCH_SERVE_A8 = register(
    'BENCH_SERVE_A8', 'Serve bench int8 activation matmuls.')
BENCH_SERVE_MOE_DISPATCH = register(
    'BENCH_SERVE_MOE_DISPATCH', 'Serve bench MoE dispatch impl.')
BENCH_DECODE_MODEL = register(
    'BENCH_DECODE_MODEL', 'Decode bench model preset.')
BENCH_DECODE_BATCH = register(
    'BENCH_DECODE_BATCH', 'Decode bench batch size.')
BENCH_DECODE_CONTEXT = register(
    'BENCH_DECODE_CONTEXT', 'Decode bench context length.')
BENCH_DECODE_STEPS = register(
    'BENCH_DECODE_STEPS', 'Decode bench decode-step count.')
BENCH_DECODE_QUANT = register(
    'BENCH_DECODE_QUANT', 'Decode bench KV quantization (int8).')
BENCH_DECODE_WQUANT = register(
    'BENCH_DECODE_WQUANT', 'Decode bench weight quantization (int8).')
BENCH_DECODE_ATTN = register(
    'BENCH_DECODE_ATTN', 'Decode bench attention impl: paged | lax.')
BENCH_DECODE_PAGED = register(
    'BENCH_DECODE_PAGED', 'Decode bench: force paged attention on/off.')
BENCH_DECODE_PAGE = register(
    'BENCH_DECODE_PAGE', 'Decode bench page size (tokens).')
BENCH_DECODE_HEADROOM = register(
    'BENCH_DECODE_HEADROOM', 'Decode bench extra page headroom.')
BENCH_LOAD_SEED = register(
    'BENCH_LOAD_SEED',
    'serve_load bench: workload-generator seed (same seed => '
    'byte-identical trace and request schedule; the emitted '
    'trace_sha256 is the receipt).')
BENCH_LOAD_REQUESTS = register(
    'BENCH_LOAD_REQUESTS', 'serve_load bench: total request count.')
BENCH_LOAD_QPS = register(
    'BENCH_LOAD_QPS',
    'serve_load bench: mean offered load in requests/second (the '
    'open-loop schedule follows this clock regardless of server '
    'speed).')
BENCH_LOAD_ARRIVAL = register(
    'BENCH_LOAD_ARRIVAL',
    'serve_load bench arrival model: poisson | bursty (Markov-'
    'modulated, default) | uniform (the legacy back-to-back '
    'control arm).')
BENCH_LOAD_BURST = register(
    'BENCH_LOAD_BURST',
    'serve_load bench: bursty-arrival rate multiplier (HI state = '
    'qps * factor, LO = qps / factor; default 4).')
BENCH_LOAD_PREFIXES = register(
    'BENCH_LOAD_PREFIXES',
    'serve_load bench: number of Zipf-shared prompt prefixes (0 = '
    'unique prompts). > 0 also enables the engine prefix cache, so '
    'the goodput number includes the reuse the cache buys.')
BENCH_LOAD_DEADLINE_S = register(
    'BENCH_LOAD_DEADLINE_S',
    'serve_load bench: per-request deadline budget in seconds '
    '(unset = no deadlines; deadlines feed the engine expiry/shed '
    'machinery and the deadline-attainment score).')
BENCH_LOAD_SLO_TTFT = register(
    'BENCH_LOAD_SLO_TTFT',
    'serve_load bench: TTFT SLO in seconds a request must meet to '
    'count toward goodput.')
BENCH_LOAD_SLO_ITL = register(
    'BENCH_LOAD_SLO_ITL',
    'serve_load bench: per-request ITL p99 SLO in seconds for '
    'goodput.')
BENCH_LOAD_TRACE = register(
    'BENCH_LOAD_TRACE',
    'serve_load bench: also write the generated trace (with its '
    'spec header) to this JSONL path — the replayable round '
    'artifact.')
# ------------------------------------------------------ controller fleet
SKYTPU_FLEET_LEASE_TTL = register(
    'SKYTPU_FLEET_LEASE_TTL',
    'Fleet worker lease TTL in seconds (heartbeat renews at TTL/3; a '
    'dead worker\'s leases expire to survivors after at most TTL).')
SKYTPU_FLEET_SCAN_GAP = register(
    'SKYTPU_FLEET_SCAN_GAP',
    'Seconds between fleet-worker scans for claimable job/service '
    'leases.')
SKYTPU_FLEET_CONCURRENCY = register(
    'SKYTPU_FLEET_CONCURRENCY',
    'Max job/service work items one fleet worker runs concurrently.')
BENCH_FLEET_JOBS = register(
    'BENCH_FLEET_JOBS',
    'fleet bench: managed jobs to drive through launch->recover->'
    'terminate on the synthetic cloud (default 1000; 24 under '
    'BENCH_SMOKE).')
BENCH_FLEET_SERVICES = register(
    'BENCH_FLEET_SERVICES',
    'fleet bench: services to drive through scale-up->READY->teardown '
    '(default 100; 3 under BENCH_SMOKE).')
BENCH_FLEET_REPLICAS = register(
    'BENCH_FLEET_REPLICAS',
    'fleet bench: replicas per service (default 2).')
BENCH_FLEET_WORKERS = register(
    'BENCH_FLEET_WORKERS',
    'fleet bench: fleet worker processes-worth of controller loops '
    '(in-process workers; default 4, min 3 for the scale claim).')
BENCH_FLEET_KILLS = register(
    'BENCH_FLEET_KILLS',
    'fleet bench: fleet workers to kill mid-run (lease takeover is '
    'the measured path; default 1).')
BENCH_FLEET_SEED = register(
    'BENCH_FLEET_SEED',
    'fleet bench: RNG seed for the preemption/kill schedule and the '
    'synthetic cloud (same seed => same schedule).')
BENCH_FLEET_DEADLINE_S = register(
    'BENCH_FLEET_DEADLINE_S',
    'fleet bench: overall settle deadline in seconds before the '
    'round reports a timeout.')
BENCH_CHAOS_REPLICAS = register(
    'BENCH_CHAOS_REPLICAS',
    'serve_chaos bench: replica subprocesses behind the in-process '
    'LB (default 2). Replicas always run on CPU — the measured '
    'article is the failover machinery, not the chip.')
BENCH_CHAOS_KILLS = register(
    'BENCH_CHAOS_KILLS',
    'serve_chaos bench: replicas to SIGKILL mid-run at seeded '
    'trace-relative times (default 1; clamped below the replica '
    'count so at least one survivor remains).')
BENCH_CHAOS_SEED = register(
    'BENCH_CHAOS_SEED',
    'serve_chaos bench: seed for the workload trace AND the kill '
    'schedule (same seed => same trace bytes and same kill '
    'times/targets — the determinism receipt).')
BENCH_CHAOS_MIN_RATIO = register(
    'BENCH_CHAOS_MIN_RATIO',
    'serve_chaos bench: minimum goodput-under-chaos over same-seed '
    'no-chaos baseline for the round to report ok (default 0.9).')
# ------------------------------------------------- spot-native serving
SKYTPU_PREEMPT_NOTICE_S = register(
    'SKYTPU_PREEMPT_NOTICE_S',
    'Spot-preemption notice lead time in seconds: how long before '
    'the SIGKILL the cloud-style warning arrives (docs/'
    'spot_serving.md). Read by the notice delivery harness; the LB '
    'uses the window to proactively migrate live streams off the '
    'doomed replica. Default 2.')
SKYTPU_SPOT_RATE_HALFLIFE_S = register(
    'SKYTPU_SPOT_RATE_HALFLIFE_S',
    'Half-life in seconds of the EWMA spot-preemption-rate estimator '
    '(preemptions per spot-replica-hour, serve/autoscalers.py): '
    'shorter reacts faster to a preemption storm, longer smooths '
    'isolated reclaims (default 1800).')
BENCH_SPOT_REPLICAS = register(
    'BENCH_SPOT_REPLICAS',
    'serve_spot bench: spot replica subprocesses in the mixed pool '
    '(default 2). Replicas always run on CPU — the measured article '
    'is the notice/migration machinery, not the chip.')
BENCH_SPOT_ONDEMAND = register(
    'BENCH_SPOT_ONDEMAND',
    'serve_spot bench: on-demand replica subprocesses in the mixed '
    'pool (default 1; these survive every preemption).')
BENCH_SPOT_KILLS = register(
    'BENCH_SPOT_KILLS',
    'serve_spot bench: spot replicas to preempt (notice then '
    'SIGKILL) mid-run at seeded trace-relative times (default 1; '
    'clamped below the spot count).')
BENCH_SPOT_SEED = register(
    'BENCH_SPOT_SEED',
    'serve_spot bench: seed for the workload trace AND the '
    'notice->kill schedule (same seed => same trace bytes and same '
    'notice/kill times/targets — the determinism receipt).')
BENCH_SPOT_NOTICE_S = register(
    'BENCH_SPOT_NOTICE_S',
    'serve_spot bench: notice lead time in seconds between the '
    'preemption notice and the SIGKILL (SKYTPU_PREEMPT_NOTICE_S '
    'analog; default 2).')
BENCH_SPOT_MIN_RATIO = register(
    'BENCH_SPOT_MIN_RATIO',
    'serve_spot bench: minimum goodput of the preempted mixed-pool '
    'run over the same-seed all-on-demand baseline for the round to '
    'report ok (default 0.9).')
BENCH_SPOT_PRICE_RATIO = register(
    'BENCH_SPOT_PRICE_RATIO',
    'serve_spot bench: spot price as a fraction of on-demand for '
    'the $/Mtok proxy (spot chip-seconds are discounted by this '
    'factor; default 0.3 — the ~70%% spot discount).')
BENCH_SPEC_K = register(
    'BENCH_SPEC_K',
    'Speculative-decoding draft length for the decode/serve benches '
    '(SKYTPU_SPEC_K analog): 0 disables the spec phase. Default 4 '
    'under BENCH_SMOKE, 0 otherwise (the decode_spec / serve_spec '
    'modes of `bench.py all` opt in).')
# ------------------------------------------------- multi-tenant QoS
SKYTPU_QOS_WEIGHTS = register(
    'SKYTPU_QOS_WEIGHTS',
    'Deficit-round-robin weights per priority class for the QoS '
    'admission scheduler (docs/qos.md), as '
    '"interactive=8,standard=4,bulk=1" (the default). A class\'s '
    'weight scales the tick-token quantum its subqueues earn per DRR '
    'round — interactive drains ~8x faster than bulk under '
    'contention.')
SKYTPU_QOS_TENANT_RATE = register(
    'SKYTPU_QOS_TENANT_RATE',
    'Per-tenant token-bucket refill rate in tick-tokens/second '
    '(docs/qos.md; a request costs max_new + '
    'ceil(uncached_suffix/prefill_chunk) * decode_chunk). 0 or unset '
    '= no rate limiting (buckets disabled). Admission holds a '
    'tenant\'s requests while its bucket is empty instead of '
    'rejecting them.')
SKYTPU_QOS_TENANT_BURST = register(
    'SKYTPU_QOS_TENANT_BURST',
    'Per-tenant token-bucket capacity in tick-tokens (the burst a '
    'quiet tenant may spend at once). Default 4x '
    'SKYTPU_QOS_TENANT_RATE.')
SKYTPU_QOS_MAX_QUEUE = register(
    'SKYTPU_QOS_MAX_QUEUE',
    'Queue-pressure shed bound: when the engine queue exceeds this '
    'many requests, the newest lowest-class queued request is shed '
    '(status=cancelled, reason=shed_by_priority) until the bound '
    'holds — bulk sheds before standard before interactive '
    '(docs/qos.md). 0 or unset = no queue-pressure shedding.')
SKYTPU_QOS_PREEMPT_AFTER_S = register(
    'SKYTPU_QOS_PREEMPT_AFTER_S',
    'Sustained-overload preemption threshold in seconds: when the '
    'queue head is a higher-priority request that _fits() has '
    'rejected for this long while a strictly lower class holds a '
    'decode slot, the youngest lowest-class slot is preempt-'
    'cancelled (reason=preempted_by_priority) to free capacity. 0 '
    'or unset = never preempt.')
SKYTPU_QOS_DISABLE = register(
    'SKYTPU_QOS_DISABLE',
    'Kill switch: 1 forces legacy FIFO admission even for tenant-'
    'tagged / classed traffic (tags are still validated and '
    'attributed in metrics, but ordering, buckets, shedding and '
    'preemption are all off). The serve_qos bench\'s control arm; '
    'operationally, the fastest way to take QoS out of the blast '
    'radius of an incident.')
BENCH_QOS_SEED = register(
    'BENCH_QOS_SEED',
    'serve_qos bench: workload seed for BOTH the baseline and the '
    'misbehaving-tenant runs (same seed => the interactive sub-'
    'stream is byte-identical across A/B — the isolation claim\'s '
    'determinism receipt).')
BENCH_QOS_REQUESTS = register(
    'BENCH_QOS_REQUESTS',
    'serve_qos bench: requests per tenant stream before the burst '
    'is added (default 40; 16 under BENCH_SMOKE).')
BENCH_QOS_QPS = register(
    'BENCH_QOS_QPS',
    'serve_qos bench: offered load per tenant stream in '
    'requests/second.')
BENCH_QOS_BURST = register(
    'BENCH_QOS_BURST',
    'serve_qos bench: rate multiplier of the misbehaving bulk '
    'tenant\'s burst arm (default 10 — the "10x burst" of the '
    'isolation gate).')
BENCH_QOS_MAX_TTFT_RATIO = register(
    'BENCH_QOS_MAX_TTFT_RATIO',
    'serve_qos bench gate: max interactive-class p99 TTFT of the '
    'QoS-on burst run over the same-seed burst-free baseline '
    '(default 1.2).')
BENCH_QOS_MIN_GOODPUT_RATIO = register(
    'BENCH_QOS_MIN_GOODPUT_RATIO',
    'serve_qos bench gate: min interactive-class goodput of the '
    'QoS-on burst run over the same-seed burst-free baseline '
    '(default 0.9).')
# --------------------------------------- disaggregated prefill/decode
SKYTPU_KV_FETCH_MAX_BYTES = register(
    'SKYTPU_KV_FETCH_MAX_BYTES',
    'Byte budget of one POST /kv/fetch response (docs/'
    'disaggregation.md): the replica packs whole prefix-cache pages '
    'until the budget is spent; requested pages that do not fit are '
    'simply absent (the requester re-prefills them). Default 64 MiB.')
SKYTPU_KV_FETCH_TIMEOUT_S = register(
    'SKYTPU_KV_FETCH_TIMEOUT_S',
    'Client-side timeout in seconds for one KV page fetch against a '
    'peer replica (serve/kv_transfer.py). On expiry the fetch raises '
    'and the caller falls back to interleaved re-prefill. Default '
    '10.')
SKYTPU_DISAGG = register(
    'SKYTPU_DISAGG',
    'Kill switch for the LB\'s disaggregated prefill->decode router '
    '(docs/disaggregation.md): 0 disables the handoff even when a '
    'prefill pool is configured — every request runs interleaved on '
    'the decode/mixed pool. Default on (any other value).')
SKYTPU_LB_RESUME_KV = register(
    'SKYTPU_LB_RESUME_KV',
    'KV-assisted resume (docs/disaggregation.md): 1 (default) lets '
    'the LB\'s mid-stream resume/migration attempts name the dying '
    'replica as a kv_source, so the survivor fetches its published '
    'prompt pages instead of re-prefilling prompt+emitted from '
    'token 0. 0 restores the pure re-prefill resume path.')
BENCH_DISAGG_REQUESTS = register(
    'BENCH_DISAGG_REQUESTS',
    'serve_disagg bench: requests in the long-prompt Zipf trace '
    '(default 12 under BENCH_SMOKE, 32 otherwise).')
BENCH_DISAGG_QPS = register(
    'BENCH_DISAGG_QPS',
    'serve_disagg bench: offered load in requests/second.')
BENCH_DISAGG_SEED = register(
    'BENCH_DISAGG_SEED',
    'serve_disagg bench: seed for the workload trace AND the '
    'mid-handoff prefill-replica kill (same seed => same trace '
    'bytes and same kill time — the determinism receipt).')
BENCH_DISAGG_MIN_RATIO = register(
    'BENCH_DISAGG_MIN_RATIO',
    'serve_disagg bench gate: minimum disagg-arm goodput over the '
    'same-seed equal-chip interleaved baseline for the round to '
    'report ok (default 0.9).')
# ------------------------- cache-aware routing + peer cache warming
SKYTPU_AFFINITY = register(
    'SKYTPU_AFFINITY',
    'Kill switch for prefix-affinity scoring inside the '
    'prefix_affinity LB policy (docs/affinity_routing.md): 0 makes '
    'the policy behave exactly like least_load (the bitwise-parity '
    'baseline arm). Default on (any other value).')
SKYTPU_AFFINITY_SUMMARY_PAGES = register(
    'SKYTPU_AFFINITY_SUMMARY_PAGES',
    'Bound on the recency-ordered hash list a replica\'s /health '
    'prefix digest advertises (models/prefix_cache.py '
    'prefix_summary). Digests past the bound set truncated=true so '
    'the LB scores them conservatively instead of reading absence '
    'as a miss. Default 128 (~4 KB of probe-cadence JSON).')
SKYTPU_AFFINITY_TTL_S = register(
    'SKYTPU_AFFINITY_TTL_S',
    'Staleness bound in seconds on a replica\'s advertised prefix '
    'digest (docs/affinity_routing.md): past the TTL the LB stops '
    'scoring the replica by affinity (it still serves via the '
    'least-load fallback) until the next probe refreshes the '
    'digest. Default 60 (6 probe cycles).')
SKYTPU_AFFINITY_MAX_SKEW = register(
    'SKYTPU_AFFINITY_MAX_SKEW',
    'Imbalance guard of the prefix_affinity policy (docs/'
    'affinity_routing.md): an affinity pick is overridden to '
    'least-load when the target\'s inflight gauge would exceed '
    'max(mean_inflight * MAX_SKEW, MAX_SKEW) across ready '
    'replicas — affinity can never create a hotspot deeper than '
    'this factor. Default 2.0.')
SKYTPU_WARM_MAX_PAGES = register(
    'SKYTPU_WARM_MAX_PAGES',
    'Peer-warming page budget (docs/affinity_routing.md): max '
    'prefix-pool pages a newly provisioned replica pre-fetches from '
    'its warm donor before being marked READY. 0 disables warming. '
    'Default 64.')
SKYTPU_WARM_TIMEOUT_S = register(
    'SKYTPU_WARM_TIMEOUT_S',
    'Wall-clock bound in seconds on the whole peer-warming attempt '
    '(donor digest read + /kv/warm pull). On expiry or any error '
    'the replica is marked READY cold — warming can delay '
    'readiness by at most this bound, never block it. Default 15.')
BENCH_AFFINITY_REQUESTS = register(
    'BENCH_AFFINITY_REQUESTS',
    'serve_affinity bench: requests in the Zipf shared-prefix trace '
    '(default 16 under BENCH_SMOKE, 48 otherwise).')
BENCH_AFFINITY_QPS = register(
    'BENCH_AFFINITY_QPS',
    'serve_affinity bench: offered load in requests/second.')
BENCH_AFFINITY_SEED = register(
    'BENCH_AFFINITY_SEED',
    'serve_affinity bench: seed for the workload trace AND the '
    'mid-trace scale-up point (same seed => same trace bytes — the '
    'determinism receipt).')
BENCH_AFFINITY_MIN_RATIO = register(
    'BENCH_AFFINITY_MIN_RATIO',
    'serve_affinity bench gate: minimum affinity-arm fleet '
    'prefix-hit-rate AND goodput over the same-seed equal-chip '
    'least-load arm for the round to report ok (default 1.0 — '
    'affinity must not lose; raise to demand a margin).')
