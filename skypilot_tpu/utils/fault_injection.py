"""Deterministic fault-injection harness (chaos testing without a cloud).

The paper's robustness story — zone/region failover with blocked
resource sets, spot-preemption recovery, replica replacement — is
exercised hermetically by injecting typed failures at *named sites*
threaded through the stack:

========================================== =============================
site                                       instrumented in
========================================== =============================
``provision.<cloud>.<op>``                 provision/__init__.py router
                                           (e.g. ``provision.local.
                                           run_instances``)
``provisioner.post_provision_runtime_setup`` provision/provisioner.py
``command_runner.run``                     utils/command_runner.py
``command_runner.ensure_tunnel``           utils/command_runner.py
``agent.worker_probe``                     agent/driver.py
``jobs.controller.heartbeat``              jobs/controller.py
``serve.replica.probe_ready``              serve/replica_managers.py
========================================== =============================

A **fault plan** is JSON (env var ``SKYTPU_FAULT_PLAN``, either inline
or a path to a file — child processes inherit the env var, so the
detached jobs controller, agentd and job drivers all see the same
plan) or a :func:`fault_plan` context manager for in-process tests::

    {"seed": 42, "record": "/tmp/faults.jsonl",
     "faults": [{"site": "jobs.controller.heartbeat",
                 "kind": "preemption", "after": 2, "times": 1,
                 "match": {"cluster_name": "spot-1"}}]}

Per fault spec:

- ``site``: exact name or ``fnmatch`` pattern (``provision.*``).
- ``kind``: one of :class:`FaultKind`.
- ``after``: calls to let PASS at this site before firing (default 0).
- ``times``: max firings; ``null`` = unlimited (default 1).
- ``probability``: fire chance per eligible call, drawn from the
  plan's seeded RNG — same seed, same call sequence => same faults.
  Specs with probability 1.0 never touch the RNG, so count-based
  plans are exactly deterministic regardless of interleaving.
- ``match``: equality filter on the site's context kwargs (a site
  call with ``rank=1`` only matches ``{"match": {"rank": 1}}``).

``record`` appends one JSON line per injected fault (pid, site, kind,
context) — tests assert the exact injected sequence across process
boundaries.

Sites call :func:`poll` (returns the fired spec or None — the site
decides how the failure manifests, e.g. a 255 exit code) or
:func:`inject` (raises the typed exception for the kind). With no
active plan both are a near-free attribute check, so production
behavior and tier-1 runtime are unchanged by default.
"""
from __future__ import annotations

import dataclasses
import enum
import fnmatch
import json
import os
import threading
from typing import Any, Dict, List, Optional, Union

from skypilot_tpu import metrics as metrics_lib
from skypilot_tpu.trace import core as trace_core
from skypilot_tpu.utils import env_registry

FAULT_PLAN_ENV = env_registry.SKYTPU_FAULT_PLAN

# The site registry: every static site name (or fnmatch pattern, for
# the provision router's generated ``provision.<cloud>.<op>`` names)
# threaded through the stack. The static analyzer (rule STL007,
# docs/static_analysis.md) cross-checks every literal poll/inject/
# pending site against this tuple — a typo'd site would otherwise
# make a chaos plan silently inert.
KNOWN_SITES = (
    'provision.*',  # provision/__init__.py router: <cloud>.<op>
    'provisioner.post_provision_runtime_setup',
    'command_runner.run',
    'command_runner.ensure_tunnel',
    'agent.worker_probe',
    'jobs.controller.heartbeat',
    'serve.replica.probe_ready',
    # Request-lifecycle sites (docs/request_lifecycle.md): a wedged
    # device tick, a replica whose in-flight work outlives its drain
    # budget, and a client hanging up mid-stream at the LB.
    'engine.tick.hang',
    'serve.replica.drain',
    'lb.client_disconnect',
    # Replica-failure survivability sites (docs/failover.md): an
    # injected connect failure on a proxy attempt (drives the LB's
    # per-replica circuit breaker without killing a process), and the
    # chaos-replay harness's seeded replica SIGKILL schedule (an armed
    # plan can veto or record individual kills; loadgen/replay.py).
    'lb.replica.connect',
    'serve.replica.kill',
    # Spot-preemption notice (docs/spot_serving.md): the cloud-style
    # warning delivered SKYTPU_PREEMPT_NOTICE_S seconds before a spot
    # replica's SIGKILL. The notice→kill replay harness polls it per
    # scheduled notice (an armed plan can veto or record individual
    # notices, same semantics as serve.replica.kill).
    'serve.replica.preempt_notice',
    # Crashpoints (docs/crash_recovery.md): named instructions inside
    # the controllers' multi-step operations where a `crash` fault
    # os._exit()s the process — the chaos analogue of `kill -9` at
    # that exact line. Recovery-as-startup must survive every one.
    'jobs.controller.launch.pre_provision',
    'jobs.controller.launch.post_provision',
    'jobs.controller.recover.mid',
    'serve.scale_up.post_launch',
    'serve.scale_down.pre_terminate',
    'serve.scale_down.post_drain',
    'statedb.commit.pre',
    'statedb.commit.post',
    # Controller-fleet sites (docs/control_plane.md): the synthetic
    # cloud's provision step, and crashpoints inside the fleet
    # worker's lease lifecycle (just after a claim; mid-renewal in
    # the heartbeat thread — the worst instruction to die at, since
    # the lease looks healthy for almost a full TTL afterwards).
    'fleet.synth.launch',
    'fleet.worker.claim.post',
    'fleet.worker.renew.mid',
    # Multi-tenant QoS (docs/qos.md): a fault-plan-driven synthetic
    # burst from a named tenant — the engine's tick loop polls this
    # and, when a spec fires, submits params-described requests
    # (tenant, n, prompt_len, max_new, priority_class, seed) directly
    # into its own queue. Deterministic chaos isolation tests without
    # a load generator in the loop.
    'engine.tenant.burst',
    # Disaggregated prefill/decode (docs/disaggregation.md): polled
    # by the KV page fetch client (serve/kv_transfer.py) before each
    # peer fetch — connect_failure severs the prefill->decode handoff
    # (the caller falls back to interleaved re-prefill), hang stalls
    # it params['seconds'].
    'serve.kv.fetch',
)

# Default exit code for `crash` faults: distinctive in wait statuses,
# so a chaos test can tell an injected crash from an organic failure.
CRASH_EXIT_CODE = 13

# Chaos observability (docs/metrics.md): every injected fault counts
# here, so chaos tests (and dashboards during a game day) can assert
# the fault volume per site without parsing the record file.
_M_FAULTS = metrics_lib.counter(
    'skytpu_faults_injected_total',
    'Faults injected by the chaos harness, by site and kind.',
    labels=('site', 'kind'))


class FaultKind(str, enum.Enum):
    PREEMPTION = 'preemption'
    PARTIAL_GANG_LOSS = 'partial_gang_loss'
    QUOTA_EXCEEDED = 'quota_exceeded'
    STOCKOUT = 'stockout'
    PROVISION_FAILURE = 'provision_failure'
    SSH_FAILURE = 'ssh_failure'
    TUNNEL_FAILURE = 'tunnel_failure'
    PROBE_TIMEOUT = 'probe_timeout'
    # Lifecycle kinds: a stall at the site (the site sleeps for
    # params['seconds']) and a client that hangs up mid-response.
    HANG = 'hang'
    CLIENT_DISCONNECT = 'client_disconnect'
    # A TCP connect that is refused/reset before the request is ever
    # received (lb.replica.connect): the caller KNOWS the peer never
    # saw the request, so retry/breaker logic may act immediately.
    CONNECT_FAILURE = 'connect_failure'
    # Crash-only-software kind: the process os._exit()s at the site —
    # no excepts run, no finallys, no atexit — indistinguishable from
    # `kill -9` at that instruction (docs/crash_recovery.md).
    CRASH = 'crash'
    # The cloud's advance warning that a spot instance will be
    # reclaimed shortly (docs/spot_serving.md): the site delivers the
    # notice to the replica/LB rather than failing anything itself.
    PREEMPT_NOTICE = 'preempt_notice'
    # A misbehaving tenant's synthetic request burst (docs/qos.md):
    # the engine polls engine.tenant.burst each tick and a fired spec
    # makes it submit the params-described requests to itself.
    TENANT_BURST = 'tenant_burst'


@dataclasses.dataclass
class FaultSpec:
    site: str
    kind: FaultKind
    after: int = 0
    times: Optional[int] = 1
    probability: float = 1.0
    match: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # Site-interpreted parameters (e.g. {"host_index": 1} for
    # partial_gang_loss at the controller heartbeat). NOT used for
    # matching — match keys must be context kwargs the site passes.
    params: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # Mutable counters (guarded by the plan lock).
    seen: int = 0
    fired: int = 0

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> 'FaultSpec':
        known = {'site', 'kind', 'after', 'times', 'probability',
                 'match', 'params'}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f'Unknown fault-spec fields: {sorted(unknown)}')
        return cls(site=d['site'],
                   kind=FaultKind(d['kind']),
                   after=int(d.get('after', 0)),
                   times=(None if d.get('times', 1) is None else
                          int(d.get('times', 1))),
                   probability=float(d.get('probability', 1.0)),
                   match=dict(d.get('match') or {}),
                   params=dict(d.get('params') or {}))


class FaultPlan:
    """A seeded, counting schedule of typed failures."""

    def __init__(self,
                 faults: List[Union[FaultSpec, Dict[str, Any]]],
                 seed: int = 0,
                 record_path: Optional[str] = None) -> None:
        import random
        self.specs = [
            f if isinstance(f, FaultSpec) else FaultSpec.from_dict(f)
            for f in faults
        ]
        self.seed = seed
        self.record_path = record_path
        self.log: List[Dict[str, Any]] = []
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    @classmethod
    def from_json(cls, raw: Union[str, Dict[str, Any]]) -> 'FaultPlan':
        if isinstance(raw, str):
            raw = json.loads(raw)
        return cls(faults=raw.get('faults') or [],
                   seed=int(raw.get('seed', 0)),
                   record_path=raw.get('record'))

    def to_json(self) -> str:
        return json.dumps({
            'seed': self.seed,
            'record': self.record_path,
            'faults': [{
                'site': s.site,
                'kind': s.kind.value,
                'after': s.after,
                'times': s.times,
                'probability': s.probability,
                'match': s.match,
                'params': s.params,
            } for s in self.specs],
        })

    def _matches(self, spec: FaultSpec, site: str,
                 context: Dict[str, Any]) -> bool:
        if not (spec.site == site or fnmatch.fnmatch(site, spec.site)):
            return False
        return all(context.get(k) == v for k, v in spec.match.items())

    def pending(self, site: str,
                kinds: Optional[tuple] = None) -> bool:
        """True if some spec could still fire at this site (budget
        left; `after`/match not considered). A cheap gate for sites
        whose pre-fault work is expensive — no counters are touched."""
        with self._lock:
            return any(
                (spec.site == site or fnmatch.fnmatch(site, spec.site))
                and (kinds is None or spec.kind in kinds)
                and (spec.times is None or spec.fired < spec.times)
                for spec in self.specs)

    def poll(self, site: str, *, kinds: Optional[tuple] = None,
             **context: Any) -> Optional[FaultSpec]:
        """One site call: returns the spec that fired, or None.

        ``kinds`` restricts which fault kinds this site consumes:
        specs of other kinds are left untouched (not seen-counted,
        not fired), so a site never burns the budget of — or records
        — a fault it cannot act on.
        """
        with self._lock:
            for spec in self.specs:
                if kinds is not None and spec.kind not in kinds:
                    continue
                if not self._matches(spec, site, context):
                    continue
                spec.seen += 1
                if spec.seen <= spec.after:
                    continue
                if spec.times is not None and spec.fired >= spec.times:
                    continue
                # probability==1.0 skips the RNG: pure-count plans stay
                # deterministic no matter how threads interleave sites.
                if spec.probability < 1.0 and (self._rng.random() >
                                               spec.probability):
                    continue
                spec.fired += 1
                self._record(spec, site, context)
                return spec
        return None

    def _record(self, spec: FaultSpec, site: str,
                context: Dict[str, Any]) -> None:
        _M_FAULTS.inc(1, site=site, kind=spec.kind.value)
        entry = {
            'pid': os.getpid(),
            'site': site,
            'kind': spec.kind.value,
            'fired': spec.fired,
            'context': {k: repr(v) for k, v in context.items()},
            # Chaos <-> trace correlation (docs/tracing.md): the fault
            # record names the trace it fired inside, so a game-day
            # injected failure links straight to the launch/request
            # span tree it perturbed. None when tracing is off.
            'trace': trace_core.current_trace_id(),
        }
        self.log.append(entry)
        if self.record_path:
            try:
                # One small write per line: atomic enough on POSIX for
                # concurrent appends from several processes.
                with open(self.record_path, 'a', encoding='utf-8') as f:
                    f.write(json.dumps(entry) + '\n')
            except OSError:
                pass


# ----------------------------------------------------------------------
# Active-plan resolution: explicit (context manager) beats env var.
_active: Optional[FaultPlan] = None
_env_cache: Optional[tuple] = None  # (raw env value, parsed plan)
_env_lock = threading.Lock()


def _plan_from_env() -> Optional[FaultPlan]:
    global _env_cache
    raw = os.environ.get(FAULT_PLAN_ENV)
    if not raw:
        return None
    # The lock makes the parse once-per-process: concurrent first
    # polls (one worker-probe thread per rank) must share ONE plan —
    # separate plans mean separate counters, and a times:1 fault
    # would fire once per thread.
    with _env_lock:
        if _env_cache is not None and _env_cache[0] == raw:
            return _env_cache[1]
        text = raw
        path = raw[1:] if raw.startswith('@') else raw
        if not raw.lstrip().startswith('{') and os.path.exists(path):
            with open(path, encoding='utf-8') as f:
                text = f.read()
        try:
            plan = FaultPlan.from_json(text)
        except (ValueError, KeyError) as e:
            # Fail loudly AND clearly: this surfaces deep inside
            # production sites, so name the env var (a bare
            # JSONDecodeError from a typo'd path reads as a
            # provisioning crash).
            raise ValueError(
                f'Invalid {FAULT_PLAN_ENV} fault plan '
                f'({raw[:120]!r}): {e}') from e
        _env_cache = (raw, plan)
        return plan


def active_plan() -> Optional[FaultPlan]:
    if _active is not None:
        return _active
    if FAULT_PLAN_ENV not in os.environ:
        return None
    return _plan_from_env()


def poll(site: str, *, kinds: Optional[tuple] = None,
         **context: Any) -> Optional[FaultSpec]:
    """Fast no-op without a plan; otherwise one plan poll."""
    plan = active_plan()
    if plan is None:
        return None
    return plan.poll(site, kinds=kinds, **context)


def inject(site: str, **context: Any) -> None:
    """Poll the site and raise the typed exception for a fired fault."""
    spec = poll(site, **context)
    if spec is not None:
        raise make_exception(spec, site)


def crashpoint(site: str, **context: Any) -> None:
    """A named crash site: if a ``crash`` fault is armed here, the
    process dies NOW via ``os._exit`` — no exception propagation, no
    cleanup handlers — exactly the at-any-instruction `kill -9` the
    crash-only recovery design must survive. Only CRASH-kind specs are
    consumed; other kinds armed at overlapping patterns keep their
    budgets. The fault record (and its metrics line) is written by
    poll() before the exit, so the record file proves WHERE the
    process died."""
    spec = poll(site, kinds=(FaultKind.CRASH,), **context)
    if spec is not None:
        os._exit(int(spec.params.get('exit_code', CRASH_EXIT_CODE)))


def make_exception(spec: FaultSpec, site: str) -> Exception:
    """The exception a fired fault manifests as (typed: the failover
    machinery dispatches on these classes)."""
    from skypilot_tpu import exceptions
    msg = f'[fault-injection] {spec.kind.value} at {site}'
    if spec.kind is FaultKind.QUOTA_EXCEEDED:
        return exceptions.QuotaExceededError(msg)
    if spec.kind is FaultKind.STOCKOUT:
        return exceptions.StockoutError(msg)
    if spec.kind in (FaultKind.PROVISION_FAILURE, FaultKind.PREEMPTION,
                     FaultKind.PARTIAL_GANG_LOSS):
        return exceptions.ProvisionError(msg)
    if spec.kind in (FaultKind.SSH_FAILURE, FaultKind.TUNNEL_FAILURE):
        return exceptions.CommandError(255, f'<{site}>', msg)
    if spec.kind in (FaultKind.PROBE_TIMEOUT, FaultKind.HANG):
        return TimeoutError(msg)
    if spec.kind is FaultKind.CLIENT_DISCONNECT:
        return ConnectionResetError(msg)
    if spec.kind is FaultKind.CONNECT_FAILURE:
        return ConnectionRefusedError(msg)
    if spec.kind is FaultKind.CRASH:
        # CRASH is meant for crashpoint() (which never raises); via
        # inject() it manifests as the exit it would have been.
        return SystemExit(CRASH_EXIT_CODE)
    return AssertionError(f'unmapped fault kind {spec.kind}')


class fault_plan:
    """Context manager activating a plan in-process AND via the env
    var, so processes spawned inside the block (jobs controller,
    agentd, drivers) inherit it::

        with fault_injection.fault_plan(
                faults=[{'site': 'serve.replica.probe_ready',
                         'kind': 'probe_timeout', 'times': None}],
                record=str(tmp / 'faults.jsonl')):
            ...
    """

    def __init__(self,
                 faults: Optional[List[Dict[str, Any]]] = None,
                 *,
                 plan: Optional[FaultPlan] = None,
                 seed: int = 0,
                 record: Optional[str] = None) -> None:
        if plan is None:
            plan = FaultPlan(faults or [], seed=seed, record_path=record)
        self.plan = plan
        self._saved_active: Optional[FaultPlan] = None
        self._saved_env: Optional[str] = None

    def __enter__(self) -> FaultPlan:
        global _active, _env_cache
        self._saved_active = _active
        self._saved_env = os.environ.get(FAULT_PLAN_ENV)
        _active = self.plan
        os.environ[FAULT_PLAN_ENV] = self.plan.to_json()
        # Drop any cached env plan: its consumed counters must not
        # leak into (or out of) this activation.
        with _env_lock:
            _env_cache = None
        return self.plan

    def __exit__(self, *exc_info: Any) -> None:
        global _active, _env_cache
        _active = self._saved_active
        if self._saved_env is None:
            os.environ.pop(FAULT_PLAN_ENV, None)
        else:
            os.environ[FAULT_PLAN_ENV] = self._saved_env
        with _env_lock:
            _env_cache = None
