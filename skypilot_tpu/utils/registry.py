"""Name → class registries.

Re-design of the reference's ``sky/utils/registry.py:16`` — a tiny
case-insensitive registry used for clouds, backends, and jobs-recovery
strategies, so new implementations plug in with a decorator.
"""
from __future__ import annotations

from typing import Callable, Dict, Generic, List, Optional, Type, TypeVar

T = TypeVar('T')


class Registry(Generic[T]):

    def __init__(self, registry_name: str) -> None:
        self._name = registry_name
        self._entries: Dict[str, T] = {}
        self._aliases: Dict[str, str] = {}
        self._default: Optional[str] = None

    def register(self,
                 name: Optional[str] = None,
                 aliases: Optional[List[str]] = None,
                 default: bool = False) -> Callable[[Type], Type]:

        def decorator(cls: Type) -> Type:
            key = (name or cls.__name__).lower()
            if key in self._entries:
                raise ValueError(
                    f'{self._name} registry: duplicate entry {key!r}')
            self._entries[key] = cls
            for alias in aliases or []:
                self._aliases[alias.lower()] = key
            if default:
                self._default = key
            return cls

        return decorator

    def from_str(self, name: Optional[str]) -> Optional[T]:
        if name is None:
            return None
        key = name.lower()
        key = self._aliases.get(key, key)
        if key not in self._entries:
            raise ValueError(
                f'{self._name} {name!r} is not registered. '
                f'Registered: {sorted(self._entries)}')
        return self._entries[key]

    def get_default(self) -> T:
        assert self._default is not None, f'{self._name}: no default set'
        return self._entries[self._default]

    def keys(self) -> List[str]:
        return sorted(self._entries)

    def values(self) -> List[T]:
        return [self._entries[k] for k in sorted(self._entries)]

    def __contains__(self, name: str) -> bool:
        key = name.lower()
        return key in self._entries or key in self._aliases


# Instantiated registries (populated by decorators at import time).
CLOUD_REGISTRY: Registry = Registry('Cloud')
BACKEND_REGISTRY: Registry = Registry('Backend')
JOBS_RECOVERY_STRATEGY_REGISTRY: Registry = Registry('JobsRecoveryStrategy')
