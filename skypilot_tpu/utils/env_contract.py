"""The rank/IP/topology environment-variable contract.

The reference injects ``SKYPILOT_NODE_RANK/NODE_IPS/NUM_NODES/
NUM_GPUS_PER_NODE`` into every rank (sky/skylet/constants.py:325-328) and
lets the user command feed them to torchrun/deepspeed. Our TPU-native
contract instead targets ``jax.distributed.initialize()``: each TPU *host*
of a pod slice is a rank, the coordinator is rank 0's IP, and the slice
topology is exposed so recipes can build their device mesh without
querying the cloud.

One logical "node" in a Task maps to one TPU pod slice; a slice of H
hosts contributes H ranks (the reference's `num_ips_per_node` fan-out,
sky/backends/cloud_vm_ray_backend.py:2531-2538,5052).
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

# Names visible inside the user's `run` command.
NODE_RANK = 'SKYTPU_NODE_RANK'
NODE_IPS = 'SKYTPU_NODE_IPS'
NUM_NODES = 'SKYTPU_NUM_NODES'
NUM_CHIPS_PER_NODE = 'SKYTPU_NUM_CHIPS_PER_NODE'
COORDINATOR_ADDR = 'SKYTPU_COORDINATOR_ADDR'
COORDINATOR_PORT_DEFAULT = 8476
TPU_TOPOLOGY = 'SKYTPU_TPU_TOPOLOGY'
ACCELERATOR_TYPE = 'SKYTPU_ACCELERATOR_TYPE'
TASK_ID = 'SKYTPU_TASK_ID'
CLUSTER_NAME = 'SKYTPU_CLUSTER_NAME'
JOB_ID = 'SKYTPU_JOB_ID'
# Compatibility aliases so recipes written against the reference's
# contract keep working (same semantics, per-host ranks).
_COMPAT_ALIASES = {
    NODE_RANK: 'SKYPILOT_NODE_RANK',
    NODE_IPS: 'SKYPILOT_NODE_IPS',
    NUM_NODES: 'SKYPILOT_NUM_NODES',
    TASK_ID: 'SKYPILOT_TASK_ID',
}


def make_rank_env(rank: int,
                  ips: List[str],
                  *,
                  num_chips_per_node: int = 0,
                  topology: str = '',
                  accelerator_type: str = '',
                  task_id: str = '',
                  cluster_name: str = '',
                  job_id: Optional[int] = None,
                  coordinator_port: int = COORDINATOR_PORT_DEFAULT
                  ) -> Dict[str, str]:
    """Env dict for one rank of a gang job.

    Rank = index of this host's IP in the stable sorted host list
    (reference rank assignment: cloud_vm_ray_backend.py:536-541).
    """
    assert 0 <= rank < len(ips), (rank, ips)
    env = {
        NODE_RANK: str(rank),
        NODE_IPS: '\n'.join(ips),
        NUM_NODES: str(len(ips)),
        NUM_CHIPS_PER_NODE: str(num_chips_per_node),
        COORDINATOR_ADDR: f'{ips[0]}:{coordinator_port}',
        TPU_TOPOLOGY: topology,
        ACCELERATOR_TYPE: accelerator_type,
        TASK_ID: task_id,
        CLUSTER_NAME: cluster_name,
    }
    if job_id is not None:
        env[JOB_ID] = str(job_id)
    for ours, theirs in _COMPAT_ALIASES.items():
        env[theirs] = env[ours]
    return env


def export_statements(env: Dict[str, str]) -> str:
    """Render env as shell `export` lines (IP list newline-safe)."""
    lines = []
    for k, v in env.items():
        escaped = v.replace('"', '\\"').replace('\n', '\\n')
        lines.append(f'export {k}=$(echo -e "{escaped}")'
                     if '\\n' in escaped else f'export {k}="{escaped}"')
    return '\n'.join(lines)


def jax_distributed_kwargs(env: Optional[Dict[str, str]] = None) -> Dict:
    """Map the contract to jax.distributed.initialize() kwargs.

    Recipes call::

        import jax
        from skypilot_tpu.utils import env_contract
        kw = env_contract.jax_distributed_kwargs()
        if kw['num_processes'] > 1:
            jax.distributed.initialize(**kw)
    """
    e = os.environ if env is None else env
    num = int(e.get(NUM_NODES, '1'))
    return {
        'coordinator_address': e.get(COORDINATOR_ADDR,
                                     f'127.0.0.1:{COORDINATOR_PORT_DEFAULT}'),
        'num_processes': num,
        'process_id': int(e.get(NODE_RANK, '0')),
    }
