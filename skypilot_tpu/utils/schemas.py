"""JSON-schema validation for task YAML and config files.

Re-design of reference ``sky/utils/schemas.py`` (985 LoC) trimmed to the
fields this framework implements. Validation errors surface as
InvalidTaskError with the offending path.
"""
from __future__ import annotations

from typing import Any, Dict

from skypilot_tpu import exceptions

_RESOURCES_SCHEMA = {
    'type': 'object',
    'additionalProperties': False,
    'properties': {
        'cloud': {'type': 'string'},
        'region': {'type': 'string'},
        'zone': {'type': 'string'},
        'instance_type': {'type': 'string'},
        'accelerators': {
            'anyOf': [
                {'type': 'string'},
                {'type': 'object', 'additionalProperties': {'type': 'integer'}},
            ]
        },
        'accelerator_args': {'type': 'object'},
        'cpus': {'anyOf': [{'type': 'string'}, {'type': 'number'}]},
        'memory': {'anyOf': [{'type': 'string'}, {'type': 'number'}]},
        'use_spot': {'type': 'boolean'},
        'job_recovery': {
            'anyOf': [
                {'type': 'string'},
                {'type': 'object',
                 'additionalProperties': False,
                 'properties': {
                     'strategy': {'type': 'string'},
                     'max_restarts_on_errors': {'type': 'integer'},
                 }},
            ]
        },
        'disk_size': {'type': 'integer'},
        'disk_tier': {'type': 'string'},
        'image_id': {'type': 'string'},
        'ports': {
            'anyOf': [
                {'type': 'integer'},
                {'type': 'string'},
                {'type': 'array',
                 'items': {'anyOf': [{'type': 'integer'},
                                     {'type': 'string'}]}},
            ]
        },
        'labels': {'type': 'object',
                   'additionalProperties': {'type': 'string'}},
        'any_of': {'type': 'array', 'items': {'type': 'object'}},
    },
}

_SERVICE_SCHEMA = {
    'type': 'object',
    'additionalProperties': False,
    'properties': {
        'readiness_probe': {
            'anyOf': [
                {'type': 'string'},
                {
                    'type': 'object',
                    'additionalProperties': False,
                    'required': ['path'],
                    'properties': {
                        'path': {'type': 'string'},
                        'initial_delay_seconds': {'type': 'number',
                                                  'minimum': 0},
                        'timeout_seconds': {'type': 'number',
                                            'exclusiveMinimum': 0},
                        'post_data': {},
                    },
                },
            ]
        },
        'replica_policy': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {
                'min_replicas': {'type': 'integer', 'minimum': 0},
                'max_replicas': {'type': 'integer', 'minimum': 0},
                'target_qps_per_replica': {'type': 'number',
                                           'exclusiveMinimum': 0},
                'upscale_delay_seconds': {'type': 'number',
                                          'minimum': 0},
                'downscale_delay_seconds': {'type': 'number',
                                            'minimum': 0},
                'base_ondemand_fallback_replicas': {'type': 'integer',
                                                    'minimum': 0},
                'dynamic_ondemand_fallback': {'type': 'boolean'},
                'use_spot': {'type': 'boolean'},
                'spot_placer': {'type': 'string'},
            },
        },
        'replicas': {'type': 'integer', 'minimum': 0},
        'replica_port': {'type': 'integer', 'minimum': 1,
                         'maximum': 65535},
        'load_balancing_policy': {
            'enum': ['round_robin', 'least_load']},
    },
}

# storage_mounts: <mount path> -> storage spec (data/storage.py
# Storage.from_yaml_config's surface).
_STORAGE_SCHEMA = {
    'type': 'object',
    'additionalProperties': False,
    'properties': {
        'name': {'type': 'string'},
        'source': {'type': 'string'},
        'store': {'enum': ['gcs', 's3', 'r2', 'azure', 'ibm', 'oci',
                           'local']},
        'mode': {'enum': ['COPY', 'MOUNT', 'copy', 'mount']},
        'persistent': {'type': 'boolean'},
    },
}

TASK_SCHEMA = {
    'type': 'object',
    'additionalProperties': False,
    'properties': {
        'name': {'type': 'string'},
        'workdir': {'type': 'string'},
        'setup': {'type': 'string'},
        'run': {'type': 'string'},
        'envs': {
            'type': 'object',
            'additionalProperties': {
                'anyOf': [{'type': 'string'}, {'type': 'number'},
                          {'type': 'null'}]
            },
        },
        'num_nodes': {'type': 'integer', 'minimum': 1},
        'estimate_runtime': {'type': 'number', 'exclusiveMinimum': 0},
        'resources': _RESOURCES_SCHEMA,
        # dst path -> local path or bucket URL (gs://, s3://, r2://,
        # https://<account>.blob...).
        'file_mounts': {'type': 'object',
                        'additionalProperties': {'type': 'string'}},
        'storage_mounts': {'type': 'object',
                           'additionalProperties': _STORAGE_SCHEMA},
        'service': _SERVICE_SCHEMA,
    },
}

CONFIG_SCHEMA = {
    'type': 'object',
    'additionalProperties': True,
    'properties': {
        'jobs': {
            'type': 'object',
            'properties': {
                'controller': {
                    'type': 'object',
                    'properties': {
                        'resources': _RESOURCES_SCHEMA,
                        'max_parallel_launches': {'type': 'integer',
                                                  'minimum': 1},
                    },
                },
            },
        },
        'gcp': {
            'type': 'object',
            'properties': {
                'project_id': {'type': 'string'},
            },
        },
        'api_server': {
            'type': 'object',
            'properties': {
                'endpoint': {'type': 'string'},
            },
        },
        'usage': {
            'type': 'object',
            'properties': {
                'collector_url': {'type': 'string'},
            },
        },
        'allowed_clouds': {'type': 'array', 'items': {'type': 'string'}},
    },
}


def validate(config: Dict[str, Any], schema: Dict[str, Any],
             what: str = 'task') -> None:
    # Deferred: jsonschema's format registry costs >1s to import, which
    # would tax every agent subprocess spawn.
    import jsonschema
    try:
        jsonschema.validate(instance=config, schema=schema)
    except jsonschema.ValidationError as e:
        path = '.'.join(str(p) for p in e.absolute_path) or '<root>'
        raise exceptions.InvalidTaskError(
            f'Invalid {what} YAML at {path}: {e.message}') from None


def validate_task(config: Dict[str, Any]) -> None:
    validate(config, TASK_SCHEMA, 'task')


def validate_config(config: Dict[str, Any]) -> None:
    validate(config, CONFIG_SCHEMA, 'config')
