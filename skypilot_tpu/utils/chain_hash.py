"""Chain hashing of prompt token blocks — the SINGLE definition.

Both sides of the fleet's prefix economy key KV pages by the same
function: the engine's prefix pool (``models/prefix_cache.py``)
publishes pages under these digests, and the serve load balancer's
PrefixAffinityPolicy (``serve/load_balancer.py``) recomputes them per
request to score replicas by longest cached prefix. Factoring the
hash here is what makes "LB and engine can never diverge" a property
of the import graph instead of a code-review promise: there is one
byte layout, one digest size, one chaining rule.

The LB runs in the controller process, which must never pay a jax
import for routing — this module depends on numpy + hashlib only.

Digest semantics: digest ``i`` commits to ``tokens[0:(i+1)*page]``
(hash(page_i) folds in hash(page_{i-1})), so equal hashes mean equal
WHOLE prefixes — a lookup can never alias two prompts that share a
block but diverge earlier. 16-byte blake2b keeps the per-page key
small enough to ship thousands in a /health summary.
"""
from __future__ import annotations

import hashlib
from typing import List, Sequence

import numpy as np

DIGEST_SIZE = 16

# Schema version of the /health prefix digest built over these hashes
# (prefix_cache.prefix_summary / the LB's PrefixAffinityPolicy). Bump
# when the digest dict's shape changes; the LB ignores digests it
# does not understand rather than mis-scoring them.
SUMMARY_SCHEMA_VERSION = 1


def page_hashes(tokens: Sequence[int], page: int) -> List[bytes]:
    """Chain hash per FULL page of ``tokens``: digest i commits to
    tokens[0 : (i+1)*page]. Host-side only — never inside a jit."""
    out: List[bytes] = []
    prev = b''
    n_full = len(tokens) // page
    if not n_full:
        return out
    # One fixed-width int32 buffer for the whole hashable region:
    # ~10x cheaper than per-token str() encoding on the driver's hot
    # admission path (and on the LB's per-request scoring path).
    buf = np.asarray(tokens[:n_full * page], np.int32).tobytes()
    stride = 4 * page
    for i in range(n_full):
        d = hashlib.blake2b(prev, digest_size=DIGEST_SIZE)
        d.update(buf[i * stride:(i + 1) * stride])
        prev = d.digest()
        out.append(prev)
    return out


def match_len(hashes_hex: Sequence[str], advertised: frozenset) -> int:
    """Longest prefix (in pages) of ``hashes_hex`` present in
    ``advertised``. Chain hashing makes a prefix scan sound: page i
    can only be cached usefully if pages 0..i-1 match too, so stop at
    the first miss instead of set-intersecting the whole chain."""
    n = 0
    for h in hashes_hex:
        if h not in advertised:
            break
        n += 1
    return n
