"""Unified retry/backoff policy — the ONE retry implementation.

Every layer of the stack used to roll its own sleep loop
(provisioning failover backoff, managed-jobs launch gap, replica
termination retries, tunnel-establishment deadline polling). This
module replaces them with a single :class:`RetryPolicy`:

- exponential backoff with a cap,
- full jitter (seedable, so chaos tests replay identical schedules),
- an optional overall deadline on top of the attempt cap,
- a typed retryable-error predicate (exception classes or callable),
- a monotonic :class:`Clock` abstraction so tests run wall-clock-free
  (:class:`FakeClock` advances virtual time instead of sleeping).

Two usage shapes:

    policy.call(fn, *args)            # run fn with retries

    state = policy.new_state()        # explicit loop control
    while True:
        try:
            return attempt()
        except exceptions.CommandError as e:
            if not policy.is_retryable(e) or not state.should_retry():
                raise
            state.sleep()
"""
from __future__ import annotations

import random
import time
from typing import Any, Callable, Optional, Sequence, Tuple, Union

from skypilot_tpu import metrics as metrics_lib

# Per-site retry pressure (docs/metrics.md): policies constructed
# with a ``site`` label report here; site-less policies stay silent.
_M_ATTEMPTS = metrics_lib.counter(
    'skytpu_retry_attempts_total',
    'Retries scheduled (backoffs taken) per call site.',
    labels=('site',))
_M_GIVEUPS = metrics_lib.counter(
    'skytpu_retry_giveups_total',
    'Retry loops that exhausted their budget (attempts or deadline) '
    'per call site.',
    labels=('site',))


class Clock:
    """Monotonic clock + sleep — the only time source retries use."""

    def now(self) -> float:
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            time.sleep(seconds)


REAL_CLOCK = Clock()


class WallClock(Clock):
    """Wall-clock variant: ``now()`` is epoch seconds (``time.time``).

    Monotonic time is process-local, so anything that WRITES
    timestamps other processes compare against — lease expiries, row
    timestamps in the shared state DBs — must use wall time. Kept
    behind the same Clock interface so a :class:`FakeClock` can stand
    in for it in tests (lease expiry is then driven by virtual time).
    """

    def now(self) -> float:
        return time.time()


WALL_CLOCK = WallClock()


class FakeClock(Clock):
    """Virtual clock for tests: sleeping advances time instantly."""

    def __init__(self, start: float = 0.0) -> None:
        self._now = start
        self.sleeps: list = []

    def now(self) -> float:
        return self._now

    def sleep(self, seconds: float) -> None:
        self.sleeps.append(seconds)
        self._now += max(0.0, seconds)

    def advance(self, seconds: float) -> None:
        self._now += seconds


Retryable = Union[Tuple[type, ...], Sequence[type],
                  Callable[[BaseException], bool]]


class RetryState:
    """Per-call-site mutable state: attempt counter, elapsed time, RNG."""

    def __init__(self, policy: 'RetryPolicy') -> None:
        self.policy = policy
        self.attempt = 0  # completed (failed) attempts so far
        self._backoff = policy.initial_backoff
        self._rng = random.Random(policy.seed)
        self._started = policy.clock.now()

    def elapsed(self) -> float:
        return self.policy.clock.now() - self._started

    def should_retry(self, exc: Optional[BaseException] = None) -> bool:
        """May another attempt be made (after the one that just failed)?"""
        if exc is not None and not self.policy.is_retryable(exc):
            # Non-retryable errors are not budget exhaustion — no
            # giveup count (that series means "ran out of retries").
            return False
        p = self.policy
        if p.max_attempts is not None and self.attempt + 1 >= p.max_attempts:
            if p.site:
                _M_GIVEUPS.inc(1, site=p.site)
            return False
        if p.deadline is not None and self.elapsed() >= p.deadline:
            if p.site:
                _M_GIVEUPS.inc(1, site=p.site)
            return False
        return True

    def next_backoff(self) -> float:
        """Backoff for the attempt that just failed; advances the state."""
        self.attempt += 1
        if self.policy.site:
            _M_ATTEMPTS.inc(1, site=self.policy.site)
        base = self._backoff
        self._backoff = min(self._backoff * self.policy.multiplier,
                            self.policy.max_backoff)
        if self.policy.jitter == 'full':
            backoff = self._rng.uniform(0.0, base)
        else:
            backoff = base
        if self.policy.deadline is not None:
            remaining = self.policy.deadline - self.elapsed()
            backoff = max(0.0, min(backoff, remaining))
        return backoff

    def sleep(self) -> float:
        """Sleep the next backoff on the policy clock; returns seconds."""
        backoff = self.next_backoff()
        self.policy.clock.sleep(backoff)
        return backoff


class RetryPolicy:
    """Immutable retry schedule; produces :class:`RetryState` per call.

    max_attempts=None means unlimited (bounded only by ``deadline``,
    if any). ``retryable`` is a tuple of exception classes or a
    predicate ``exc -> bool``. ``seed`` pins the jitter RNG so a chaos
    test replays the exact same schedule. ``site`` labels the
    skytpu_retry_* counters (None = unmetered).
    """

    def __init__(self,
                 *,
                 max_attempts: Optional[int] = 3,
                 initial_backoff: float = 1.0,
                 max_backoff: float = 300.0,
                 multiplier: float = 2.0,
                 jitter: str = 'full',
                 deadline: Optional[float] = None,
                 retryable: Retryable = (Exception,),
                 seed: Optional[int] = None,
                 clock: Optional[Clock] = None,
                 site: Optional[str] = None) -> None:
        assert jitter in ('full', 'none'), jitter
        self.site = site
        self.max_attempts = max_attempts
        self.initial_backoff = initial_backoff
        self.max_backoff = max_backoff
        self.multiplier = multiplier
        self.jitter = jitter
        self.deadline = deadline
        # A bare exception class is a class, and classes are callable:
        # normalize it to a tuple up front so it is matched with
        # isinstance, never mistaken for a predicate.
        if isinstance(retryable, type) and issubclass(retryable,
                                                      BaseException):
            retryable = (retryable,)
        self._retryable = retryable
        self.seed = seed
        self.clock = clock or REAL_CLOCK

    def is_retryable(self, exc: BaseException) -> bool:
        if callable(self._retryable):
            return bool(self._retryable(exc))
        return isinstance(exc, tuple(self._retryable))

    def new_state(self) -> RetryState:
        return RetryState(self)

    def call(self, fn: Callable[..., Any], *args: Any,
             **kwargs: Any) -> Any:
        """Run fn; retry per the policy; re-raise the last error."""
        state = self.new_state()
        while True:
            try:
                return fn(*args, **kwargs)
            except Exception as e:  # pylint: disable=broad-except
                if not state.should_retry(e):
                    raise
                state.sleep()
