"""In-training callback emitting per-step timing for `skytpu bench`.

Re-design of the reference's ``sky-callback`` package
(``sky/callbacks/sky_callback/base.py:21``): training code calls
``step()`` (or wraps its loop in ``step_iterator``), and a
``summary.json`` lands in ``$SKYTPU_BENCHMARK_DIR`` after every step;
the benchmark harness syncs these summaries down and ranks candidate
TPU types by $/step and time/step.

Usage::

    from skypilot_tpu import callbacks
    cb = callbacks.BenchmarkCallback(total_steps=1000)
    for batch in data:
        ...train...
        cb.step()
"""
from __future__ import annotations

import json
import os
import time
from typing import Iterable, Iterator, Optional

ENV_DIR = 'SKYTPU_BENCHMARK_DIR'
SUMMARY = 'summary.json'


class BenchmarkCallback:

    def __init__(self, log_dir: Optional[str] = None,
                 total_steps: Optional[int] = None) -> None:
        self.log_dir = os.path.expanduser(
            log_dir or os.environ.get(ENV_DIR, '~/skytpu_bench'))
        os.makedirs(self.log_dir, exist_ok=True)
        self.total_steps = total_steps
        self.created = time.time()
        self.num_steps = 0
        self.first_step: Optional[float] = None
        self.last_step: Optional[float] = None

    def step(self) -> None:
        now = time.time()
        self.num_steps += 1
        if self.first_step is None:
            self.first_step = now
        self.last_step = now
        self._write()

    # Alias matching the reference's callback API surface.
    on_step_end = step

    def _write(self) -> None:
        path = os.path.join(self.log_dir, SUMMARY)
        payload = {
            'created': self.created,
            'num_steps': self.num_steps,
            'first_step': self.first_step,
            'last_step': self.last_step,
            'total_steps': self.total_steps,
        }
        tmp = path + '.tmp'
        with open(tmp, 'w', encoding='utf-8') as f:
            json.dump(payload, f)
        os.replace(tmp, path)


def step_iterator(iterable: Iterable,
                  total_steps: Optional[int] = None) -> Iterator:
    """Wrap a training loop: ``for batch in step_iterator(data): ...``"""
    cb = BenchmarkCallback(total_steps=total_steps)
    for item in iterable:
        yield item
        cb.step()
