"""Managed jobs: spot/preemption auto-recovery.

Re-design of reference ``sky/jobs/`` (SURVEY.md §2.6): a controller
process per job monitors cluster + job health, distinguishes
preemption from user failure, and recovers by re-launching through the
normal launch path with failover state. TPU twist: preemption of any
host kills the whole pod slice, so recovery is always slice-granular
relaunch (reference jobs/controller.py:119-300).

Delta vs reference: the controller runs as a detached process on the
*client* machine by default (`python -m skypilot_tpu.jobs.controller`)
instead of on a dedicated controller VM — same process model, no
bootstrap cluster needed. A remote controller cluster can host the
same module unchanged.
"""
from skypilot_tpu.jobs.core import (cancel, launch, queue, tail_logs)
from skypilot_tpu.jobs.state import ManagedJobStatus

__all__ = ['launch', 'queue', 'cancel', 'tail_logs', 'ManagedJobStatus']
