"""Managed-jobs SQLite state.

Re-design of reference ``sky/jobs/state.py:54,114`` (`spot` +
`job_info` tables): one row per managed job task, with the
RECOVERING-aware status machine documented in the reference's
``sky/jobs/README.md:30-60``.

Durability goes through :mod:`skypilot_tpu.utils.statedb` (WAL, busy
timeout, explicit transactions, intent journal): every multi-step
controller operation brackets its state mutations with
``begin_intent``/``complete_intent`` so a crashed controller can be
restarted and reconciled (docs/crash_recovery.md).
"""
from __future__ import annotations

import json
import os
import sqlite3
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import statedb
from skypilot_tpu.utils.status_lib import ManagedJobStatus

_DB_PATH_ENV = 'SKYTPU_JOBS_DB'
_DEFAULT_DB = '~/.skytpu/managed_jobs.db'

# The controller's module path. Load-bearing twice: it is how the
# controller is spawned (`python -m <module> <job_id>`) AND the cmdline
# marker liveness checks use to tell a live controller from an
# unrelated process that recycled its recorded pid.
CONTROLLER_MODULE = 'skypilot_tpu.jobs.controller'


def _db_path() -> str:
    return os.path.expanduser(os.environ.get(_DB_PATH_ENV, _DEFAULT_DB))


def _init(conn: sqlite3.Connection) -> None:
    conn.execute("""
        CREATE TABLE IF NOT EXISTS jobs (
            job_id INTEGER PRIMARY KEY AUTOINCREMENT,
            name TEXT,
            task_yaml TEXT,
            cluster_name TEXT,
            status TEXT,
            submitted_at REAL,
            started_at REAL,
            ended_at REAL,
            recovery_count INTEGER DEFAULT 0,
            failure_reason TEXT,
            controller_pid INTEGER,
            cancel_requested INTEGER DEFAULT 0,
            log_path TEXT,
            dag_json TEXT,
            schedule_state TEXT DEFAULT 'INACTIVE',
            controller_job_id INTEGER,
            cluster_job_id INTEGER,
            task_index INTEGER DEFAULT 0,
            controller_restarts INTEGER DEFAULT 0,
            check_gap REAL
        )""")
    # Migrate pre-schema DBs (CREATE TABLE IF NOT EXISTS is a no-op on
    # an old schema); statedb runs this once per process+path.
    for decl in ("schedule_state TEXT DEFAULT 'INACTIVE'",
                 'controller_job_id INTEGER',
                 'cluster_job_id INTEGER',
                 'task_index INTEGER DEFAULT 0',
                 'controller_restarts INTEGER DEFAULT 0',
                 'check_gap REAL'):
        try:
            conn.execute(f'ALTER TABLE jobs ADD COLUMN {decl}')
        except sqlite3.OperationalError:
            pass  # already present


_DB = statedb.StateDB(_db_path, init_fn=_init, site='jobs.state.write')


def db() -> statedb.StateDB:
    """The jobs StateDB — the fleet layer builds its LeaseTable on it
    so lease rows and job rows share one sqlite file (fence checks
    and guarded writes commit in the same transaction)."""
    return _DB


def controller_resource(job_id: int) -> str:
    """Lease resource name for ownership of one managed job's
    controller loop (docs/control_plane.md)."""
    return f'jobs.controller:{job_id}'


def register_controller_leases(job_ids: List[int]) -> None:
    """Create (unowned) controller-lease rows for these jobs — but
    only while the job is still non-terminal, checked in the SAME
    transaction. A plain register from a stale scan snapshot could
    otherwise resurrect a just-deleted settled job's row at fence 0
    and re-hand already-used fencing tokens."""
    with _DB.transaction() as conn:
        for job_id in job_ids:
            row = conn.execute(
                'SELECT status FROM jobs WHERE job_id = ?',
                (job_id,)).fetchone()
            if row is None or ManagedJobStatus(
                    row['status']).is_terminal():
                continue
            statedb.lease_register(conn, controller_resource(job_id))


def add_job(name: Optional[str], task_yaml: str, cluster_name: str,
            log_path: str, dag_json: str) -> int:
    with _DB.transaction() as conn:
        cur = conn.execute(
            'INSERT INTO jobs (name, task_yaml, cluster_name, status, '
            'submitted_at, log_path, dag_json) VALUES (?,?,?,?,?,?,?)',
            (name, task_yaml, cluster_name,
             ManagedJobStatus.PENDING.value, statedb.wall_now(),
             log_path, dag_json))
        return cur.lastrowid


def set_status(job_id: int, status: ManagedJobStatus,
               failure_reason: Optional[str] = None,
               complete_intent: Optional[int] = None) -> None:
    """Status write; when ``complete_intent`` is given the bracketing
    intent record is completed in the SAME transaction — the
    crash-atomicity contract of docs/crash_recovery.md."""
    sets = ['status = ?']
    args: List[Any] = [status.value]
    if status == ManagedJobStatus.RUNNING:
        sets.append('started_at = COALESCE(started_at, ?)')
        args.append(statedb.wall_now())
    if status.is_terminal():
        sets.append('ended_at = ?')
        args.append(statedb.wall_now())
    if failure_reason is not None:
        sets.append('failure_reason = ?')
        args.append(failure_reason)
    args.append(job_id)
    with _DB.transaction() as conn:
        conn.execute(f'UPDATE jobs SET {", ".join(sets)} WHERE job_id = ?',
                     args)
        if complete_intent is not None:
            statedb.complete_intent(conn, complete_intent)


def set_schedule_state(job_id: int, schedule_state: str) -> None:
    with _DB.transaction() as conn:
        conn.execute(
            'UPDATE jobs SET schedule_state = ? WHERE job_id = ?',
            (schedule_state, job_id))


def try_acquire_launch_slot(job_id: int, limit: int) -> bool:
    """Atomically move this job to LAUNCHING iff fewer than ``limit``
    jobs are launching (the scheduler's one transactional primitive —
    reference sky/jobs/scheduler.py:80 does the equivalent count under
    a file lock)."""
    with _DB.transaction() as conn:
        row = conn.execute(
            "SELECT COUNT(*) AS n FROM jobs "
            "WHERE schedule_state = 'LAUNCHING'").fetchone()
        if row['n'] >= limit:
            return False
        conn.execute(
            "UPDATE jobs SET schedule_state = 'LAUNCHING' "
            'WHERE job_id = ?', (job_id,))
        return True


def count_schedule_state(schedule_state: str) -> int:
    with _DB.reader() as conn:
        row = conn.execute(
            'SELECT COUNT(*) AS n FROM jobs WHERE schedule_state = ?',
            (schedule_state,)).fetchone()
        return int(row['n'])


def set_log_path(job_id: int, log_path: str) -> None:
    with _DB.transaction() as conn:
        conn.execute('UPDATE jobs SET log_path = ? WHERE job_id = ?',
                     (log_path, job_id))


def set_controller_job(job_id: int,
                       cluster_job_id: Optional[int]) -> None:
    """Agent-job id of the controller on the controller cluster
    (controller-cluster placement only)."""
    with _DB.transaction() as conn:
        conn.execute(
            'UPDATE jobs SET controller_job_id = ? WHERE job_id = ?',
            (cluster_job_id, job_id))


def set_controller_pid(job_id: int, pid: int) -> None:
    """Record the controller process AND take the controller lease in
    one transaction. The spawned process is by definition the current
    owner (its spawner held the restart claim), so this is a force
    claim — it bumps the fencing token over whatever relauncher or
    dead predecessor held the row. No expiry: a classic one-process
    controller does not heartbeat; death is observed via pid liveness
    and usurped through :func:`try_claim_controller_restart`."""
    with _DB.transaction() as conn:
        conn.execute('UPDATE jobs SET controller_pid = ? WHERE job_id = ?',
                     (pid, job_id))
        lease = statedb.lease_force_claim(conn,
                                          controller_resource(job_id),
                                          f'pid:{pid}',
                                          statedb.wall_now())
    statedb.record_lease_metric('claim', takeover=lease.takeover)


def set_cluster_job_id(job_id: int,
                       cluster_job_id: Optional[int]) -> None:
    """On-cluster (agent) job id of the CURRENT attempt: the handle a
    restarted controller needs to adopt a still-running launch instead
    of double-launching."""
    with _DB.transaction() as conn:
        conn.execute(
            'UPDATE jobs SET cluster_job_id = ? WHERE job_id = ?',
            (cluster_job_id, job_id))


def set_check_gap(job_id: int, check_gap: Optional[float]) -> None:
    """Monitor-tick gap the controller was asked to run with, kept in
    the row so an automatic controller RELAUNCH (jobs/scheduler.py)
    preserves the submitter's cadence."""
    with _DB.transaction() as conn:
        conn.execute('UPDATE jobs SET check_gap = ? WHERE job_id = ?',
                     (check_gap, job_id))


def set_task_index(job_id: int, task_index: int,
                   complete_intent: Optional[int] = None) -> None:
    """Pipeline cursor: which task of the chain dag is in flight, so a
    restarted controller resumes at the right stage. With
    ``complete_intent``, the advance and the intent's retirement are
    one transaction — a mid-pipeline task can never be re-run after a
    crash that already retired its terminate intent."""
    with _DB.transaction() as conn:
        conn.execute('UPDATE jobs SET task_index = ? WHERE job_id = ?',
                     (task_index, job_id))
        if complete_intent is not None:
            statedb.complete_intent(conn, complete_intent)


# Relauncher claims expire: a relauncher that dies between claiming
# and spawning must not wedge the job forever — after the TTL the
# lease is claimable again (the restart budget was still consumed).
_RELAUNCH_CLAIM_TTL_SECONDS = 120.0


def try_claim_controller_restart(job_id: int, dead_pid: Optional[int],
                                 limit: int):
    """Claim one controller relaunch through the generic lease CAS
    (:func:`statedb.lease_try_claim` with ``expect_owner``).

    One transaction: the claim succeeds only while the controller
    lease still names the dead pid the caller observed (a successor —
    relauncher or respawned controller — bumps the fencing token, so
    a racer loses even inside the claim→spawn window) and the restart
    budget has room. Returns ``('claimed', n)``, ``('lost', n)``
    (someone else owns the relaunch) or ``('exhausted', n)``.
    """
    observed = f'pid:{dead_pid}'
    with _DB.transaction() as conn:
        row = conn.execute(
            'SELECT controller_pid, controller_restarts FROM jobs '
            'WHERE job_id = ?', (job_id,)).fetchone()
        if row is None:
            return ('lost', 0)
        restarts = int(row['controller_restarts'] or 0)
        lease_row = statedb.lease_get(conn,
                                      controller_resource(job_id))
        if lease_row is None:
            # Pre-lease DB (the controller never ran under this code):
            # fall back to the recorded pid, then seed the lease row so
            # the CAS below owns the race from here on.
            if row['controller_pid'] != dead_pid:
                return ('lost', restarts)
            statedb.lease_register(conn, controller_resource(job_id))
        elif lease_row['owner'] is not None and \
                lease_row['owner'] != observed:
            expires = lease_row.get('expires_at')
            if expires is None or float(expires) > statedb.wall_now():
                return ('lost', restarts)
            # Expired foreign claim (a relauncher died between claim
            # and spawn): fall through — the CAS below takes it over.
        if restarts >= limit:
            return ('exhausted', restarts)
        lease = statedb.lease_try_claim(
            conn, controller_resource(job_id),
            f'relauncher:{os.getpid()}',
            ttl=_RELAUNCH_CLAIM_TTL_SECONDS, now=statedb.wall_now(),
            expect_owner=observed)
        if lease is None:
            return ('lost', restarts)
        conn.execute(
            'UPDATE jobs SET controller_restarts = ? WHERE job_id = ?',
            (restarts + 1, job_id))
    statedb.record_lease_metric('claim', takeover=lease.takeover)
    return ('claimed', restarts + 1)


def bump_recovery(job_id: int) -> int:
    with _DB.transaction() as conn:
        conn.execute(
            'UPDATE jobs SET recovery_count = recovery_count + 1 '
            'WHERE job_id = ?', (job_id,))
        row = conn.execute(
            'SELECT recovery_count FROM jobs WHERE job_id = ?',
            (job_id,)).fetchone()
        return row['recovery_count']


def request_cancel(job_id: int) -> None:
    with _DB.transaction() as conn:
        conn.execute(
            'UPDATE jobs SET cancel_requested = 1 WHERE job_id = ?',
            (job_id,))


def cancel_requested(job_id: int) -> bool:
    with _DB.reader() as conn:
        row = conn.execute(
            'SELECT cancel_requested FROM jobs WHERE job_id = ?',
            (job_id,)).fetchone()
        return bool(row and row['cancel_requested'])


def get_job(job_id: int) -> Optional[Dict[str, Any]]:
    with _DB.reader() as conn:
        row = conn.execute('SELECT * FROM jobs WHERE job_id = ?',
                           (job_id,)).fetchone()
        return _to_dict(row) if row else None


def job_statuses() -> Dict[int, ManagedJobStatus]:
    """Lean ``job_id -> status`` map (no dag parsing): the fleet
    worker scans this every claim pass, so it must stay cheap at
    thousands of rows."""
    with _DB.reader() as conn:
        return {
            int(r['job_id']): ManagedJobStatus(r['status'])
            for r in conn.execute('SELECT job_id, status FROM jobs')
        }


def job_status(job_id: int) -> Optional[ManagedJobStatus]:
    """Single-row status read (no dag parsing) — O(1) freshness
    checks in the fleet worker's stale-row retirement."""
    with _DB.reader() as conn:
        row = conn.execute('SELECT status FROM jobs WHERE job_id = ?',
                           (job_id,)).fetchone()
        return ManagedJobStatus(row['status']) if row else None


def sum_recoveries() -> int:
    """Aggregate recovery count across all jobs in one query (the
    scale harness reports this; per-row get_job would re-parse every
    dag_json)."""
    with _DB.reader() as conn:
        row = conn.execute(
            'SELECT COALESCE(SUM(recovery_count), 0) AS n FROM jobs'
        ).fetchone()
        return int(row['n'])


def get_jobs(
        statuses: Optional[List[ManagedJobStatus]] = None
) -> List[Dict[str, Any]]:
    query = 'SELECT * FROM jobs'
    args: List[Any] = []
    if statuses:
        marks = ','.join('?' for _ in statuses)
        query += f' WHERE status IN ({marks})'
        args = [s.value for s in statuses]
    query += ' ORDER BY job_id'
    with _DB.reader() as conn:
        return [_to_dict(r) for r in conn.execute(query, args)]


def _to_dict(row: sqlite3.Row) -> Dict[str, Any]:
    d = dict(row)
    d['status'] = ManagedJobStatus(d['status'])
    if d.get('dag_json'):
        d['dag'] = json.loads(d['dag_json'])
    return d


# ------------------------------------------------------ intent journal
# Thin wrappers over the statedb intent API on the jobs DB; the
# controller's multi-step operations (launch, recover, terminate)
# bracket their state mutations with these (docs/crash_recovery.md).


def begin_intent(kind: str, payload: Dict[str, Any]) -> int:
    return _DB.begin_intent(kind, payload)


def complete_intent(intent_id: int) -> None:
    _DB.complete_intent(intent_id)


def open_intents(job_id: Optional[int] = None) -> List[Dict[str, Any]]:
    intents = _DB.open_intents('jobs.*')
    if job_id is None:
        return intents
    return [i for i in intents
            if i['payload'].get('job_id') == job_id]


def finish_launch_intent(intent_id: int, job_id: int,
                         cluster_job_id: Optional[int]) -> None:
    """The launch reached its commit point: record the on-cluster job
    id AND retire the intent atomically — after this transaction a
    restarted controller adopts via the row, not the journal."""
    with _DB.transaction() as conn:
        conn.execute(
            'UPDATE jobs SET cluster_job_id = ? WHERE job_id = ?',
            (cluster_job_id, job_id))
        statedb.complete_intent(conn, intent_id)


