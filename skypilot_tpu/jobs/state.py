"""Managed-jobs SQLite state.

Re-design of reference ``sky/jobs/state.py:54,114`` (`spot` +
`job_info` tables): one row per managed job task, with the
RECOVERING-aware status machine documented in the reference's
``sky/jobs/README.md:30-60``.
"""
from __future__ import annotations

import json
import os
import pathlib
import sqlite3
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils.status_lib import ManagedJobStatus

_DB_PATH_ENV = 'SKYTPU_JOBS_DB'
_DEFAULT_DB = '~/.skytpu/managed_jobs.db'

# The controller's module path. Load-bearing twice: it is how the
# controller is spawned (`python -m <module> <job_id>`) AND the cmdline
# marker liveness checks use to tell a live controller from an
# unrelated process that recycled its recorded pid.
CONTROLLER_MODULE = 'skypilot_tpu.jobs.controller'


def _db_path() -> str:
    return os.path.expanduser(os.environ.get(_DB_PATH_ENV, _DEFAULT_DB))


# DB paths already migrated by this process.
_migrated_paths: set = set()


def _conn() -> sqlite3.Connection:
    path = _db_path()
    pathlib.Path(path).parent.mkdir(parents=True, exist_ok=True)
    conn = sqlite3.connect(path, timeout=10)
    conn.row_factory = sqlite3.Row
    conn.execute('PRAGMA journal_mode=WAL')
    conn.execute("""
        CREATE TABLE IF NOT EXISTS jobs (
            job_id INTEGER PRIMARY KEY AUTOINCREMENT,
            name TEXT,
            task_yaml TEXT,
            cluster_name TEXT,
            status TEXT,
            submitted_at REAL,
            started_at REAL,
            ended_at REAL,
            recovery_count INTEGER DEFAULT 0,
            failure_reason TEXT,
            controller_pid INTEGER,
            cancel_requested INTEGER DEFAULT 0,
            log_path TEXT,
            dag_json TEXT,
            schedule_state TEXT DEFAULT 'INACTIVE',
            controller_job_id INTEGER
        )""")
    if path not in _migrated_paths:
        # Migrate pre-schema DBs once per process, not on every
        # connection (the scheduler polls this DB twice a second).
        for decl in ("schedule_state TEXT DEFAULT 'INACTIVE'",
                     'controller_job_id INTEGER'):
            try:
                conn.execute(f'ALTER TABLE jobs ADD COLUMN {decl}')
            except sqlite3.OperationalError:
                pass  # already present
        _migrated_paths.add(path)
    return conn


def add_job(name: Optional[str], task_yaml: str, cluster_name: str,
            log_path: str, dag_json: str) -> int:
    with _conn() as conn:
        cur = conn.execute(
            'INSERT INTO jobs (name, task_yaml, cluster_name, status, '
            'submitted_at, log_path, dag_json) VALUES (?,?,?,?,?,?,?)',
            (name, task_yaml, cluster_name,
             ManagedJobStatus.PENDING.value, time.time(), log_path,
             dag_json))
        return cur.lastrowid


def set_status(job_id: int, status: ManagedJobStatus,
               failure_reason: Optional[str] = None) -> None:
    sets = ['status = ?']
    args: List[Any] = [status.value]
    if status == ManagedJobStatus.RUNNING:
        sets.append('started_at = COALESCE(started_at, ?)')
        args.append(time.time())
    if status.is_terminal():
        sets.append('ended_at = ?')
        args.append(time.time())
    if failure_reason is not None:
        sets.append('failure_reason = ?')
        args.append(failure_reason)
    args.append(job_id)
    with _conn() as conn:
        conn.execute(f'UPDATE jobs SET {", ".join(sets)} WHERE job_id = ?',
                     args)


def set_schedule_state(job_id: int, schedule_state: str) -> None:
    with _conn() as conn:
        conn.execute(
            'UPDATE jobs SET schedule_state = ? WHERE job_id = ?',
            (schedule_state, job_id))


def try_acquire_launch_slot(job_id: int, limit: int) -> bool:
    """Atomically move this job to LAUNCHING iff fewer than ``limit``
    jobs are launching (the scheduler's one transactional primitive —
    reference sky/jobs/scheduler.py:80 does the equivalent count under
    a file lock)."""
    conn = _conn()
    try:
        conn.execute('BEGIN IMMEDIATE')
        row = conn.execute(
            "SELECT COUNT(*) AS n FROM jobs "
            "WHERE schedule_state = 'LAUNCHING'").fetchone()
        if row['n'] >= limit:
            conn.rollback()
            return False
        conn.execute(
            "UPDATE jobs SET schedule_state = 'LAUNCHING' "
            'WHERE job_id = ?', (job_id,))
        conn.commit()
        return True
    finally:
        conn.close()


def count_schedule_state(schedule_state: str) -> int:
    with _conn() as conn:
        row = conn.execute(
            'SELECT COUNT(*) AS n FROM jobs WHERE schedule_state = ?',
            (schedule_state,)).fetchone()
        return int(row['n'])


def set_log_path(job_id: int, log_path: str) -> None:
    with _conn() as conn:
        conn.execute('UPDATE jobs SET log_path = ? WHERE job_id = ?',
                     (log_path, job_id))


def set_controller_job(job_id: int,
                       cluster_job_id: Optional[int]) -> None:
    """Agent-job id of the controller on the controller cluster
    (controller-cluster placement only)."""
    with _conn() as conn:
        conn.execute(
            'UPDATE jobs SET controller_job_id = ? WHERE job_id = ?',
            (cluster_job_id, job_id))


def set_controller_pid(job_id: int, pid: int) -> None:
    with _conn() as conn:
        conn.execute('UPDATE jobs SET controller_pid = ? WHERE job_id = ?',
                     (pid, job_id))


def bump_recovery(job_id: int) -> int:
    with _conn() as conn:
        conn.execute(
            'UPDATE jobs SET recovery_count = recovery_count + 1 '
            'WHERE job_id = ?', (job_id,))
        row = conn.execute(
            'SELECT recovery_count FROM jobs WHERE job_id = ?',
            (job_id,)).fetchone()
        return row['recovery_count']


def request_cancel(job_id: int) -> None:
    with _conn() as conn:
        conn.execute(
            'UPDATE jobs SET cancel_requested = 1 WHERE job_id = ?',
            (job_id,))


def cancel_requested(job_id: int) -> bool:
    with _conn() as conn:
        row = conn.execute(
            'SELECT cancel_requested FROM jobs WHERE job_id = ?',
            (job_id,)).fetchone()
        return bool(row and row['cancel_requested'])


def get_job(job_id: int) -> Optional[Dict[str, Any]]:
    with _conn() as conn:
        row = conn.execute('SELECT * FROM jobs WHERE job_id = ?',
                           (job_id,)).fetchone()
        return _to_dict(row) if row else None


def get_jobs(
        statuses: Optional[List[ManagedJobStatus]] = None
) -> List[Dict[str, Any]]:
    query = 'SELECT * FROM jobs'
    args: List[Any] = []
    if statuses:
        marks = ','.join('?' for _ in statuses)
        query += f' WHERE status IN ({marks})'
        args = [s.value for s in statuses]
    query += ' ORDER BY job_id'
    with _conn() as conn:
        return [_to_dict(r) for r in conn.execute(query, args)]


def _to_dict(row: sqlite3.Row) -> Dict[str, Any]:
    d = dict(row)
    d['status'] = ManagedJobStatus(d['status'])
    if d.get('dag_json'):
        d['dag'] = json.loads(d['dag_json'])
    return d
