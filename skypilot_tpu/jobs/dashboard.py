"""Managed-jobs dashboard: one self-refreshing HTML page + JSON API.

Re-design of reference ``sky/jobs/dashboard/`` (a Flask app templated
over the jobs table) on aiohttp (already a dependency via the API
server) with zero static assets.

Run: ``python -m skypilot_tpu.jobs.dashboard --port 46581``
then open http://localhost:46581.
"""
from __future__ import annotations

import argparse
import html
import json
import time

from aiohttp import web

from skypilot_tpu.jobs import core as jobs_core
from skypilot_tpu.utils import statedb

_PAGE = """<!doctype html>
<html><head><title>skytpu jobs</title>
<meta http-equiv="refresh" content="10">
<style>
 body {{ font-family: system-ui, sans-serif; margin: 2rem; }}
 table {{ border-collapse: collapse; width: 100%; }}
 th, td {{ text-align: left; padding: .4rem .8rem;
           border-bottom: 1px solid #ddd; }}
 th {{ background: #f5f5f5; }}
 .RUNNING {{ color: #0a7d32; font-weight: 600; }}
 .RECOVERING {{ color: #b58900; font-weight: 600; }}
 .SUCCEEDED {{ color: #555; }}
 .FAILED, .FAILED_SETUP, .FAILED_CONTROLLER, .FAILED_NO_RESOURCE
   {{ color: #c0392b; font-weight: 600; }}
</style></head>
<body><h2>Managed jobs</h2>
<p>{now} &middot; {n} job(s) &middot; auto-refreshes every 10s
&middot; <a href="/api/jobs">JSON</a></p>
<table><tr><th>ID</th><th>Name</th><th>Status</th><th>Cluster</th>
<th>Recoveries</th><th>Submitted</th><th>Failure</th></tr>
{rows}</table></body></html>"""


def _fmt_ts(ts) -> str:
    if not ts:
        return '-'
    return time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(ts))


def _rows() -> list:
    return jobs_core.queue(refresh=True)


async def handle_index(request: web.Request) -> web.Response:
    rows = []
    jobs = _rows()
    for j in jobs:
        status = j['status'].value
        rows.append(
            f'<tr><td>{j["job_id"]}</td>'
            f'<td>{html.escape(str(j["name"]))}</td>'
            f'<td class="{status}">{status}</td>'
            f'<td>{html.escape(str(j["cluster_name"]))}</td>'
            f'<td>{j["recovery_count"]}</td>'
            f'<td>{_fmt_ts(j["submitted_at"])}</td>'
            f'<td>{html.escape(str(j.get("failure_reason") or ""))}'
            '</td></tr>')
    page = _PAGE.format(now=_fmt_ts(statedb.wall_now()), n=len(jobs),
                        rows='\n'.join(rows))
    return web.Response(text=page, content_type='text/html')


async def handle_jobs_json(request: web.Request) -> web.Response:
    jobs = []
    for j in _rows():
        j = dict(j)
        j['status'] = j['status'].value
        j.pop('dag', None)
        jobs.append(j)
    return web.json_response(jobs, dumps=lambda o: json.dumps(
        o, default=str))


def make_app() -> web.Application:
    app = web.Application()
    app.router.add_get('/', handle_index)
    app.router.add_get('/api/jobs', handle_jobs_json)
    return app


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--host', default='127.0.0.1')
    parser.add_argument('--port', type=int, default=46581)
    args = parser.parse_args()
    web.run_app(make_app(), host=args.host, port=args.port,
                print=lambda *a: None)


if __name__ == '__main__':
    main()
