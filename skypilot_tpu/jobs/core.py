"""Managed-jobs public API: launch/queue/cancel/tail_logs.

Re-design of reference ``sky/jobs/server/core.py:48``: `launch`
records the job, then spawns a detached controller process
(`python -m skypilot_tpu.jobs.controller <id>`) that owns the whole
lifecycle. The reference provisions a controller VM first; here the
controller runs on the client machine (same module could be shipped to
a controller cluster later — nothing in it assumes locality beyond the
state DB path).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Union

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import task as task_lib
from skypilot_tpu.jobs import state
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

def _log_dir() -> str:
    return os.path.expanduser(
        os.environ.get('SKYTPU_JOBS_LOG_DIR', '~/.skytpu/managed_jobs'))


def _controller_alive(pid: Optional[int]) -> bool:
    if not pid:
        return False
    try:
        os.kill(pid, 0)
    except (OSError, ProcessLookupError):
        return False
    # A zombie (un-reaped child of a long-lived launcher, e.g. the API
    # server) still answers kill(0); check the process state.
    try:
        with open(f'/proc/{pid}/stat', 'r', encoding='utf-8') as f:
            # field 3 (after the parenthesized comm) is the state.
            state_char = f.read().rsplit(')', 1)[1].split()[0]
        return state_char != 'Z'
    except (OSError, IndexError):
        return True


def launch(entrypoint: Union[task_lib.Task, 'dag_lib.Dag'],
           name: Optional[str] = None,
           *,
           detach: bool = True,
           controller_check_gap: Optional[float] = None) -> int:
    """Submit a managed job; returns the managed job id."""
    if isinstance(entrypoint, dag_lib.Dag):
        assert len(entrypoint.tasks) == 1, (
            'Managed jobs currently take a single task.')
        task = entrypoint.tasks[0]
    else:
        task = entrypoint
    job_name = name or task.name or 'managed'
    cluster_name = (f'{job_name}-{common_utils.generate_run_id(4)}')
    log_dir = _log_dir()
    os.makedirs(log_dir, exist_ok=True)

    job_id = state.add_job(
        name=job_name,
        task_yaml='',
        cluster_name=cluster_name,
        log_path='',  # id-dependent; recorded just below
        dag_json=json.dumps(task.to_yaml_config()))
    log_path = os.path.join(log_dir, f'{job_id}-{job_name}.log')
    state.set_log_path(job_id, log_path)
    state.set_status(job_id, state.ManagedJobStatus.SUBMITTED)

    cmd = [
        sys.executable, '-u', '-m', 'skypilot_tpu.jobs.controller',
        str(job_id)
    ]
    if controller_check_gap is not None:
        cmd += ['--check-gap', str(controller_check_gap)]
    env = dict(os.environ)
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    existing = env.get('PYTHONPATH', '')
    if repo_root not in existing.split(os.pathsep):
        env['PYTHONPATH'] = repo_root + (os.pathsep + existing
                                         if existing else '')
    with open(log_path, 'ab') as log_f:
        proc = subprocess.Popen(cmd,
                                stdout=log_f,
                                stderr=subprocess.STDOUT,
                                start_new_session=True,
                                env=env)
    state.set_controller_pid(job_id, proc.pid)
    logger.info('Managed job %d submitted (controller pid %d); logs: %s',
                job_id, proc.pid, log_path)
    if not detach:
        proc.wait()
    return job_id


def queue(refresh: bool = True) -> List[Dict[str, Any]]:
    """All managed jobs; dead controllers are reconciled to failed."""
    jobs = state.get_jobs()
    if refresh:
        for job in jobs:
            if (not job['status'].is_terminal() and
                    job['status'] != state.ManagedJobStatus.PENDING and
                    not _controller_alive(job['controller_pid'])):
                state.set_status(
                    job['job_id'],
                    state.ManagedJobStatus.FAILED_CONTROLLER,
                    failure_reason='controller process died')
                job['status'] = state.ManagedJobStatus.FAILED_CONTROLLER
    return jobs


def cancel(job_ids: Optional[List[int]] = None,
           all_jobs: bool = False) -> List[int]:
    """Request cancellation; the controller tears down the cluster."""
    if all_jobs:
        job_ids = [
            j['job_id'] for j in state.get_jobs()
            if not j['status'].is_terminal()
        ]
    cancelled = []
    for job_id in job_ids or []:
        job = state.get_job(job_id)
        if job is None or job['status'].is_terminal():
            continue
        state.request_cancel(job_id)
        cancelled.append(job_id)
    return cancelled


def tail_logs(job_id: int, follow: bool = True) -> int:
    """Stream the controller's log file (which includes launch logs)."""
    job = state.get_job(job_id)
    if job is None:
        raise exceptions.JobNotFoundError(f'Managed job {job_id}')
    path = job.get('log_path') or os.path.join(
        _log_dir(), f'{job_id}-{job["name"]}.log')
    if not os.path.exists(path):
        logger.info('No logs yet for managed job %d.', job_id)
        return 1
    with open(path, 'r', encoding='utf-8', errors='replace') as f:
        while True:
            line = f.readline()
            if line:
                print(line, end='')
                continue
            job = state.get_job(job_id)
            if not follow or job is None or job['status'].is_terminal():
                return 0
            time.sleep(0.5)
