"""Managed-jobs public API: launch/queue/cancel/tail_logs.

Re-design of reference ``sky/jobs/server/core.py:48``: `launch`
records the job, then starts a controller
(`python -m skypilot_tpu.jobs.controller <id>`) that owns the whole
lifecycle. Two placements:

- default: a detached local process (fast path for a workstation);
- ``on_controller=True`` (or config ``jobs.controller.enabled``):
  the controller runs as a job on a dedicated *controller cluster*
  (reference ``sky/templates/jobs-controller.yaml.j2``), provisioned
  on demand and reused across jobs — the controller survives the
  client machine, and its launches are bounded by the jobs scheduler
  (jobs/scheduler.py).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Union

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import task as task_lib
from skypilot_tpu import trace as trace_lib
from skypilot_tpu.jobs import state
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import env_registry
from skypilot_tpu.utils import log as sky_logging
from skypilot_tpu.utils import subprocess_utils

logger = sky_logging.init_logger(__name__)

def _log_dir() -> str:
    return os.path.expanduser(
        env_registry.get(env_registry.SKYTPU_JOBS_LOG_DIR,
                         '~/.skytpu/managed_jobs'))


def _controller_alive(pid: Optional[int], job_id: int) -> bool:
    # The cmdline tokens guard against pid recycling (see
    # subprocess_utils.process_alive); they also exclude zombies.
    return subprocess_utils.process_alive(
        pid, cmdline_tokens=(state.CONTROLLER_MODULE, str(job_id)))


CONTROLLER_CLUSTER_NAME = 'skytpu-jobs-controller'

# Env vars the controller needs to share the submitting user's state
# (jobs DB, cluster DB, launch-parallelism override). On a local-cloud
# controller cluster these point at the same filesystem; a cloud
# controller VM keeps its own copies rsynced at submission.
_CONTROLLER_ENV_PASSTHROUGH = (
    env_registry.SKYTPU_JOBS_DB,
    env_registry.SKYTPU_STATE_DB,
    env_registry.SKYTPU_DATA_DIR,
    env_registry.SKYTPU_JOBS_LOG_DIR,
    env_registry.SKYTPU_CONFIG,
    env_registry.SKYTPU_USER_HASH,
    env_registry.SKYTPU_JOBS_LAUNCH_PARALLELISM,
    # Chaos plans and their retry-schedule overrides must reach the
    # controller wherever it runs (utils/fault_injection.py).
    env_registry.SKYTPU_FAULT_PLAN,
    env_registry.SKYTPU_JOBS_LAUNCH_MAX_ATTEMPTS,
    env_registry.SKYTPU_JOBS_LAUNCH_RETRY_GAP,
)


def _controller_resources() -> 'task_lib.Task':
    """The controller cluster's own (cheap) task, from config
    ``jobs.controller.resources`` (reference
    jobs-controller.yaml.j2's resources block)."""
    from skypilot_tpu import check as check_lib
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu import skypilot_config
    cfg = dict(
        skypilot_config.get_nested(('jobs', 'controller', 'resources'),
                                   default_value={}) or {})
    if 'cloud' not in cfg:
        cfg['cloud'] = 'local'
    if cfg['cloud'] != 'local':
        # The controller shares the submitting user's jobs/cluster DBs
        # through the filesystem (env passthrough below). On a cloud
        # VM those paths don't exist — a remote controller needs its
        # own state DB plus a remote queue/cancel path (reference
        # jobs-controller.yaml.j2 + JobLibCodeGen), which is not built
        # yet. Fail loudly instead of submitting a controller that
        # dies on startup.
        raise exceptions.NotSupportedError(
            'jobs.controller.resources.cloud must be "local" for now: '
            'cloud-VM controller state sharing is not implemented.')
    holder = task_lib.Task('jobs-controller', run='true')
    holder.set_resources(resources_lib.Resources.from_yaml_config(cfg))
    return holder


def ensure_controller_cluster() -> None:
    """Provision (or reuse) the controller cluster."""
    from skypilot_tpu import execution
    from skypilot_tpu.backend import backend_utils
    from skypilot_tpu.utils import status_lib
    record = backend_utils.refresh_cluster_record(
        CONTROLLER_CLUSTER_NAME)
    if record is not None and record[
            'status'] == status_lib.ClusterStatus.UP:
        return
    logger.info('Provisioning jobs controller cluster %s.',
                CONTROLLER_CLUSTER_NAME)
    execution.launch(_controller_resources(),
                     cluster_name=CONTROLLER_CLUSTER_NAME,
                     stream_logs=False)


def _submit_to_controller_cluster(job_id: int,
                                  check_gap: Optional[float]) -> None:
    from skypilot_tpu import execution
    ensure_controller_cluster()
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    cmd = f'python -u -m {state.CONTROLLER_MODULE} {job_id}'
    if check_gap is not None:
        cmd += f' --check-gap {check_gap}'
    envs = {'PYTHONPATH': repo_root}
    for key in _CONTROLLER_ENV_PASSTHROUGH:
        if os.environ.get(key):
            envs[key] = os.environ[key]
    # Continue the submit trace into the remote controller process
    # (SKYTPU_TRACE_CONTEXT + the trace knobs, docs/tracing.md).
    trace_lib.child_env(envs)
    controller_task = task_lib.Task(f'jobs-ctl-{job_id}', run=cmd,
                                    envs=envs)
    cluster_job_id, _ = execution.exec_(controller_task,
                                        CONTROLLER_CLUSTER_NAME,
                                        detach_run=True)
    state.set_controller_job(job_id, cluster_job_id)
    logger.info(
        'Managed job %d controller submitted to cluster %s (job %s).',
        job_id, CONTROLLER_CLUSTER_NAME, cluster_job_id)


def launch(entrypoint: Union[task_lib.Task, 'dag_lib.Dag'],
           name: Optional[str] = None,
           *,
           detach: bool = True,
           on_controller: Optional[bool] = None,
           controller_check_gap: Optional[float] = None) -> int:
    """Submit a managed job; returns the managed job id."""
    if isinstance(entrypoint, dag_lib.Dag):
        if not entrypoint.is_chain():
            raise exceptions.NotSupportedError(
                'Managed jobs take a single task or a chain pipeline.')
        tasks = entrypoint.get_sorted_tasks()
    else:
        tasks = [entrypoint]
    task = tasks[0]
    job_name = name or task.name or 'managed'
    # One span per submission; the spawned controller inherits its
    # context via SKYTPU_TRACE_CONTEXT (trace.child_env below), so a
    # managed job's whole launch -> provision -> recovery history
    # shares this trace id (docs/tracing.md).
    with trace_lib.span('jobs.submit', slow_ok=True,
                        job_name=job_name) as submit_span:
        cluster_name = (f'{job_name}-{common_utils.generate_run_id(4)}')
        log_dir = _log_dir()
        os.makedirs(log_dir, exist_ok=True)

        from skypilot_tpu import usage
        usage.record_event(
            'jobs.launch',
            use_spot=any(r.use_spot for r in task.resources))
        # dag_json is a LIST of task configs: one task = [config], a
        # chain pipeline = its tasks in topological order, each run on
        # its own cluster by the controller (reference jobs run chain
        # dags the same way, sky/jobs/controller.py:371 iterating
        # dag.tasks).
        job_id = state.add_job(
            name=job_name,
            task_yaml='',
            cluster_name=cluster_name,
            log_path='',  # id-dependent; recorded just below
            dag_json=json.dumps([t.to_yaml_config() for t in tasks]))
        if submit_span is not None:
            submit_span.set_attr(job=job_id)
        log_path = os.path.join(log_dir, f'{job_id}-{job_name}.log')
        state.set_log_path(job_id, log_path)
        state.set_status(job_id, state.ManagedJobStatus.SUBMITTED)

        if on_controller is None:
            from skypilot_tpu import skypilot_config
            on_controller = bool(
                skypilot_config.get_nested(
                    ('jobs', 'controller', 'enabled'),
                    default_value=False))
        if controller_check_gap is not None:
            # Persisted so an automatic controller relaunch
            # (jobs/scheduler.maybe_relaunch_controller) keeps the
            # submitter's monitor cadence.
            state.set_check_gap(job_id, controller_check_gap)
        if on_controller:
            _submit_to_controller_cluster(job_id, controller_check_gap)
            return job_id

        proc = spawn_controller(job_id)
        logger.info(
            'Managed job %d submitted (controller pid %d); logs: %s',
            job_id, proc.pid, log_path)
        if not detach:
            proc.wait()
        return job_id


def spawn_controller(job_id: int) -> 'subprocess.Popen':
    """Start (or restart) the detached controller process for a job.

    Used by launch() and by the scheduler's dead-controller relaunch
    (docs/crash_recovery.md): the controller's own reconcile_on_start
    makes a restart safe at any point of the job's lifecycle.
    """
    job = state.get_job(job_id)
    assert job is not None, job_id
    log_path = job.get('log_path') or os.path.join(
        _log_dir(), f'{job_id}-{job["name"]}.log')
    cmd = [
        sys.executable, '-u', '-m', state.CONTROLLER_MODULE,
        str(job_id)
    ]
    if job.get('check_gap') is not None:
        cmd += ['--check-gap', str(job['check_gap'])]
    env = dict(os.environ)
    # The detached controller continues this trace: its root span
    # parents under jobs.submit via SKYTPU_TRACE_CONTEXT.
    trace_lib.child_env(env)
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    existing = env.get('PYTHONPATH', '')
    if repo_root not in existing.split(os.pathsep):
        env['PYTHONPATH'] = repo_root + (os.pathsep + existing
                                         if existing else '')
    os.makedirs(os.path.dirname(log_path) or '.', exist_ok=True)
    with open(log_path, 'ab') as log_f:
        proc = subprocess.Popen(cmd,
                                stdout=log_f,
                                stderr=subprocess.STDOUT,
                                start_new_session=True,
                                env=env)
    state.set_controller_pid(job_id, proc.pid)
    return proc


def queue(refresh: bool = True) -> List[Dict[str, Any]]:
    """All managed jobs; dead controllers are relaunched (crash-only
    recovery, docs/crash_recovery.md) or — past the restart budget /
    with reconcile disabled — reconciled to failed."""
    from skypilot_tpu.jobs import scheduler
    jobs = state.get_jobs()
    if refresh:
        for job in jobs:
            if job['status'].is_terminal() or (
                    job['status'] == state.ManagedJobStatus.PENDING):
                continue
            if (job.get('controller_job_id') is not None and
                    not job['controller_pid']):
                # Controller-cluster placement, controller pid not
                # recorded yet. Not necessarily alive: ask the agent
                # whether the controller's own job already died (e.g.
                # startup crash before set_controller_pid).
                if _controller_cluster_job_dead(
                        job['controller_job_id']):
                    _mark_controller_dead(job)
                continue
            if not _controller_alive(job['controller_pid'],
                                     job['job_id']):
                # Recovery is the startup path: respawn the controller
                # and let its reconcile_on_start adopt or roll back
                # whatever the dead process left in flight.
                if not scheduler.maybe_relaunch_controller(job):
                    _mark_controller_dead(job)
    return jobs


def _mark_controller_dead(job: Dict[str, Any]) -> None:
    state.set_status(job['job_id'],
                     state.ManagedJobStatus.FAILED_CONTROLLER,
                     failure_reason='controller process died')
    # Release any leaked launch slot so the scheduler can't deadlock
    # on rows whose controller will never call finish_launch.
    state.set_schedule_state(job['job_id'], 'DONE')
    job['status'] = state.ManagedJobStatus.FAILED_CONTROLLER


def _controller_cluster_job_dead(controller_job_id: int) -> bool:
    from skypilot_tpu import core as sky_core
    try:
        statuses = sky_core.job_status(CONTROLLER_CLUSTER_NAME,
                                       [controller_job_id])
        status = statuses.get(controller_job_id)
    except Exception:  # pylint: disable=broad-except
        return False  # can't tell; don't false-positive
    return status is not None and status.is_terminal()


def cancel(job_ids: Optional[List[int]] = None,
           all_jobs: bool = False) -> List[int]:
    """Request cancellation; the controller tears down the cluster."""
    if all_jobs:
        job_ids = [
            j['job_id'] for j in state.get_jobs()
            if not j['status'].is_terminal()
        ]
    cancelled = []
    for job_id in job_ids or []:
        job = state.get_job(job_id)
        if job is None or job['status'].is_terminal():
            continue
        state.request_cancel(job_id)
        cancelled.append(job_id)
    return cancelled


def tail_logs(job_id: int, follow: bool = True) -> int:
    """Stream the controller's log file (which includes launch logs)."""
    job = state.get_job(job_id)
    if job is None:
        raise exceptions.JobNotFoundError(f'Managed job {job_id}')
    path = job.get('log_path') or os.path.join(
        _log_dir(), f'{job_id}-{job["name"]}.log')
    if not os.path.exists(path):
        logger.info('No logs yet for managed job %d.', job_id)
        return 1
    with open(path, 'r', encoding='utf-8', errors='replace') as f:
        while True:
            line = f.readline()
            if line:
                print(line, end='')
                continue
            job = state.get_job(job_id)
            if not follow or job is None or job['status'].is_terminal():
                return 0
            time.sleep(0.5)
