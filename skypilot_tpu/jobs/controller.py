"""Managed-jobs controller: one process per job.

Re-design of reference ``sky/jobs/controller.py:53,119-300``: launch
the task's cluster, then loop — poll the on-cluster job status and the
cloud-truth cluster status, distinguish USER FAILURE (job reached a
terminal failed state while the cluster is healthy) from PREEMPTION
(cluster no longer UP / job vanished), and hand preemptions to the
recovery strategy. On a TPU pod slice, losing any host kills the whole
job, so recovery is always a full slice relaunch.

Crash-only (docs/crash_recovery.md): every multi-step operation
(launch, recover, terminate) journals a write-ahead intent record in
the jobs DB, and every start begins with :meth:`reconcile_on_start`,
which replays open intents against cloud truth — adopt a cluster+job
the dead process already launched, roll a terminate forward, or roll a
half-done launch back. ``kill -9`` at any instruction (exercised by
the registered ``crash`` fault sites) leaves the job recoverable.

Run: ``python -m skypilot_tpu.jobs.controller <managed_job_id>``.
"""
from __future__ import annotations

import argparse
import time
import traceback
from typing import Optional

from skypilot_tpu import core
from skypilot_tpu import exceptions
from skypilot_tpu import metrics as metrics_lib
from skypilot_tpu import task as task_lib
from skypilot_tpu import trace as trace_lib
from skypilot_tpu.agent import job_lib as agent_job_lib
from skypilot_tpu.backend import backend_utils
from skypilot_tpu.jobs import recovery_strategy
from skypilot_tpu.jobs import scheduler
from skypilot_tpu.jobs import state
from skypilot_tpu.utils import fault_injection
from skypilot_tpu.utils import log as sky_logging
from skypilot_tpu.utils import statedb
from skypilot_tpu.utils import status_lib

logger = sky_logging.init_logger(__name__)

JOB_STATUS_CHECK_GAP_SECONDS = 20
_MAX_RECOVERIES = 16

# Ops counters (docs/metrics.md). The controller is a detached
# process, so these reach scrapers via the snapshot spool
# (SKYTPU_METRICS_DIR), dumped once per monitor tick.
_M_RECOVERIES = metrics_lib.counter(
    'skytpu_jobs_recoveries_total',
    'Preemption recoveries (full relaunch) per managed job.',
    labels=('job',))
_M_RESTARTS = metrics_lib.counter(
    'skytpu_jobs_restarts_total',
    'Restarts after user failure on healthy infra per managed job.',
    labels=('job',))
_M_RECONCILED = metrics_lib.counter(
    'skytpu_jobs_reconciled_intents_total',
    'Open intent records replayed at controller startup, by outcome '
    '(adopt / roll_forward / roll_back / orphan).',
    labels=('action',))


class JobsController:

    def __init__(self, managed_job_id: int,
                 check_gap: float = JOB_STATUS_CHECK_GAP_SECONDS) -> None:
        record = state.get_job(managed_job_id)
        assert record is not None, managed_job_id
        self.job_id = managed_job_id
        self.cluster_name = record['cluster_name']
        dag = record['dag']
        # dag_json: historically one task config, now a list (chain
        # pipeline); normalize.
        configs = dag if isinstance(dag, list) else [dag]
        self.tasks = [task_lib.Task.from_yaml_config(c) for c in configs]
        self.task = self.tasks[0]
        self.task_index = 0
        self.strategy = recovery_strategy.StrategyExecutor.make(
            self.cluster_name, self.task)
        self.check_gap = check_gap

    def _task_cluster(self, index: int) -> str:
        return (self.cluster_name if index == 0 else
                f'{self.cluster_name}-t{index}')

    # ------------------------------------------------------------------
    def _cluster_status(self) -> Optional[status_lib.ClusterStatus]:
        try:
            record = backend_utils.refresh_cluster_record(
                self.cluster_name, force_refresh=True)
        except Exception:  # pylint: disable=broad-except
            logger.warning('Status refresh failed:\n%s',
                           traceback.format_exc())
            return None
        return record['status'] if record else None

    def _job_status(self,
                    cluster_job_id: int
                    ) -> Optional[agent_job_lib.JobStatus]:
        try:
            statuses = core.job_status(self.cluster_name,
                                       [cluster_job_id])
            return statuses.get(cluster_job_id)
        except Exception:  # pylint: disable=broad-except
            return None

    # ------------------------------------------------------------------
    # Crash-only startup: intent replay (docs/crash_recovery.md).

    def reconcile_on_start(self) -> Optional[int]:
        """Replay this job's open intents against cloud truth before
        doing ANYTHING else — recovery is the only startup path.

        Returns the on-cluster (agent) job id to adopt when the dead
        process's launch already succeeded (the monitor loop resumes
        against it; no double-launch), else None (a fresh launch — or
        nothing — is needed; the journal has been settled either way).
        """
        if not statedb.reconcile_enabled():
            return None
        record = state.get_job(self.job_id)
        intents = state.open_intents(self.job_id)
        resumable = (record is not None and
                     not record['status'].is_terminal() and
                     record.get('cluster_job_id') is not None and
                     record['status'] in (state.ManagedJobStatus.STARTING,
                                          state.ManagedJobStatus.RUNNING))
        if not intents and not resumable:
            return None
        with trace_lib.span('jobs.reconcile', slow_ok=True,
                            job=str(self.job_id),
                            open_intents=len(intents)):
            return self._reconcile(record, intents)

    def _reconcile(self, record, intents) -> Optional[int]:
        adopted: Optional[int] = None
        if record['status'].is_terminal():
            # The job already concluded; any open intent is leftover
            # journal noise from the dying process.
            for intent in intents:
                state.complete_intent(intent['intent_id'])
                _M_RECONCILED.inc(1, action='orphan')
            return None
        for intent in intents:
            kind = intent['kind']
            payload = intent['payload']
            cluster = payload.get('cluster_name') or self.cluster_name
            if kind == 'jobs.terminate':
                # Past the point of no return: roll FORWARD. The
                # teardown is idempotent, the final status comes from
                # the journal, and both settle atomically.
                logger.info('Reconcile: rolling forward terminate of '
                            '%s.', cluster)
                self._down_quiet(cluster)
                final = payload.get('final_status')
                if final is not None:
                    state.set_status(
                        self.job_id, state.ManagedJobStatus(final),
                        failure_reason=payload.get('failure_reason'),
                        complete_intent=intent['intent_id'])
                elif payload.get('next_task_index') is not None:
                    # Mid-pipeline success whose cursor write was lost
                    # to the crash: advance it with the journal so the
                    # finished task is not re-run.
                    state.set_task_index(
                        self.job_id, int(payload['next_task_index']),
                        complete_intent=intent['intent_id'])
                else:
                    state.complete_intent(intent['intent_id'])
                _M_RECONCILED.inc(1, action='roll_forward')
            elif kind in ('jobs.launch', 'jobs.recover'):
                found = self._find_cluster_job(cluster)
                if found is not None:
                    # The dead process finished provisioning and the
                    # job runs: adopt it instead of double-launching.
                    logger.info(
                        'Reconcile: adopting live cluster %s '
                        '(on-cluster job %d).', cluster, found)
                    state.finish_launch_intent(intent['intent_id'],
                                               self.job_id, found)
                    adopted = found
                    _M_RECONCILED.inc(1, action='adopt')
                else:
                    # Launch never reached its commit point and the
                    # cluster is gone/half-provisioned: roll back
                    # (terminate leftovers, clear the journal); the
                    # normal run path relaunches.
                    logger.info(
                        'Reconcile: rolling back half-done launch of '
                        '%s.', cluster)
                    self._down_quiet(cluster)
                    state.complete_intent(intent['intent_id'])
                    _M_RECONCILED.inc(1, action='roll_back')
            else:
                logger.warning('Reconcile: unknown intent kind %r; '
                               'dropping.', kind)
                state.complete_intent(intent['intent_id'])
                _M_RECONCILED.inc(1, action='orphan')
        if adopted is None and record.get('cluster_job_id') is not None \
                and record['status'] in (state.ManagedJobStatus.STARTING,
                                         state.ManagedJobStatus.RUNNING):
            # No journal entry (the crash hit the monitor phase, after
            # the launch committed): the row itself is the recovery
            # record.
            cluster = self._task_cluster(
                int(record.get('task_index') or 0))
            found = self._find_cluster_job(
                cluster, expect=record['cluster_job_id'])
            if found is not None:
                logger.info(
                    'Reconcile: resuming monitor of cluster %s '
                    '(on-cluster job %d).', cluster, found)
                adopted = found
                _M_RECONCILED.inc(1, action='adopt')
        return adopted

    def _find_cluster_job(self, cluster_name: str,
                          expect: Optional[int] = None) -> Optional[int]:
        """Cloud truth for adoption: is the cluster UP, and which
        on-cluster job did the dead process submit? ``expect`` pins a
        known job id; otherwise the newest job on the cluster is the
        one (the controller is the only submitter)."""
        try:
            record = backend_utils.refresh_cluster_record(
                cluster_name, force_refresh=True)
        except Exception:  # pylint: disable=broad-except
            record = None
        if record is None or record['status'] != \
                status_lib.ClusterStatus.UP:
            return None
        try:
            rows = core.queue(cluster_name)
        except Exception:  # pylint: disable=broad-except
            return None
        job_ids = [int(r['job_id']) for r in rows
                   if r.get('job_id') is not None]
        if expect is not None:
            return expect if expect in job_ids else None
        return max(job_ids) if job_ids else None

    def _down_quiet(self, cluster_name: str) -> None:
        try:
            core.down(cluster_name)
        except exceptions.ClusterDoesNotExist:
            pass
        except Exception:  # pylint: disable=broad-except
            logger.warning('Reconcile teardown of %s failed:\n%s',
                           cluster_name, traceback.format_exc())

    def _terminate_task_cluster(
            self,
            final_status: Optional[state.ManagedJobStatus] = None,
            failure_reason: Optional[str] = None,
            next_task_index: Optional[int] = None) -> None:
        """Teardown bracketed by a ``jobs.terminate`` intent: once the
        journal row exists the operation only rolls FORWARD — a crash
        mid-teardown terminates again on restart and then applies the
        journaled OUTCOME (final status, or the pipeline advance to
        ``next_task_index`` for a mid-pipeline success), atomically
        with the intent's completion. Journaling the outcome is what
        keeps a finished task from re-running when the crash lands
        between the teardown and the status/cursor write."""
        payload = {
            'job_id': self.job_id,
            'cluster_name': self.cluster_name,
            'task_index': self.task_index,
        }
        if final_status is not None:
            payload['final_status'] = final_status.value
            if failure_reason is not None:
                payload['failure_reason'] = failure_reason
        elif next_task_index is not None:
            payload['next_task_index'] = next_task_index
        intent_id = state.begin_intent('jobs.terminate', payload)
        self.strategy.terminate_cluster()
        if final_status is not None:
            state.set_status(self.job_id, final_status,
                             failure_reason=failure_reason,
                             complete_intent=intent_id)
        elif next_task_index is not None:
            state.set_task_index(self.job_id, next_task_index,
                                 complete_intent=intent_id)
        else:
            state.complete_intent(intent_id)

    # ------------------------------------------------------------------
    def _maybe_inject_chaos(self) -> None:
        """Chaos site `jobs.controller.heartbeat`: polled once per
        monitor tick while the job is RUNNING. A fired preemption /
        partial_gang_loss fault is ACTED OUT against cloud truth
        through the provision layer (reclaim the cluster / one host),
        so the normal detection + recovery machinery runs for real."""
        plan = fault_injection.active_plan()
        kinds = fault_injection.FaultKind
        # Only reclaim kinds have an action at this site; the kinds
        # filter keeps other specs' budgets untouched.
        actionable = (kinds.PREEMPTION, kinds.PARTIAL_GANG_LOSS)
        if plan is None or not plan.pending('jobs.controller.heartbeat',
                                            actionable):
            # Fast path: without an armed fault this must stay free —
            # the monitor loop deliberately avoids per-tick cloud
            # queries.
            return
        # Resolve the handle BEFORE polling: poll() consumes the
        # fault's times budget and writes the record line, so firing
        # while unable to act would silently drop a planned fault.
        try:
            record = backend_utils.refresh_cluster_record(
                self.cluster_name)
        except Exception:  # pylint: disable=broad-except
            record = None
        if record is None or record.get('handle') is None:
            return
        fault = fault_injection.poll('jobs.controller.heartbeat',
                                     kinds=actionable,
                                     cluster_name=self.cluster_name)
        if fault is None:
            return
        handle = record['handle']
        logger.warning('[fault-injection] acting %s on cluster %s.',
                       fault.kind.value, self.cluster_name)
        try:
            import importlib
            module = importlib.import_module(
                f'skypilot_tpu.provision.{handle.provider_name}.instance')
            if (fault.kind is kinds.PARTIAL_GANG_LOSS and
                    hasattr(module, 'preempt_host')):
                module.preempt_host(
                    handle.cluster_name_on_cloud,
                    int(fault.params.get('host_index', 0)))
            elif hasattr(module, 'preempt'):
                module.preempt(handle.cluster_name_on_cloud)
            else:
                # Providers without a dedicated reclaim hook: a spot
                # reclaim is indistinguishable from termination.
                module.terminate_instances(handle.cluster_name_on_cloud,
                                           handle.region, handle.zone)
        except Exception:  # pylint: disable=broad-except
            # A failed reclaim must not crash the controller — the
            # monitor loop keeps watching the (still-live) cluster.
            logger.warning('[fault-injection] acting %s failed:\n%s',
                           fault.kind.value, traceback.format_exc())

    def _monitor_until_done(self, cluster_job_id: int) -> state.ManagedJobStatus:
        """Returns the terminal managed status for one launched attempt,
        or RECOVERING if the cluster was preempted."""
        missing_streak = 0
        # Launch -> first-heartbeat span: the tail of the launch
        # timeline a provision trace cannot see (agent boot, job
        # pickup) — finished the first time the on-cluster job is
        # visible at all (docs/tracing.md). The try/finally keeps the
        # span in the trace even when cancellation or preemption
        # strikes before the job is ever seen — exactly the case a
        # recovery timeline needs.
        hb_span = trace_lib.start_span(
            'jobs.controller.first_heartbeat', slow_ok=True,
            job=str(self.job_id))
        try:
            return self._monitor_loop(cluster_job_id, hb_span,
                                      missing_streak)
        finally:
            if hb_span.end_time is None:
                hb_span.finish(status='never_seen')

    def _monitor_loop(self, cluster_job_id: int,
                      hb_span: 'trace_lib.Span',
                      missing_streak: int) -> state.ManagedJobStatus:
        while True:
            time.sleep(self.check_gap)
            metrics_lib.dump_snapshot(f'jobs.controller.{self.job_id}')
            if state.cancel_requested(self.job_id):
                return state.ManagedJobStatus.CANCELLING
            job_status = self._job_status(cluster_job_id)
            if job_status is not None:
                missing_streak = 0
                if hb_span.end_time is None:
                    hb_span.finish(status=job_status.value)
            if job_status == agent_job_lib.JobStatus.RUNNING:
                self._maybe_inject_chaos()
            if job_status == agent_job_lib.JobStatus.SUCCEEDED:
                return state.ManagedJobStatus.SUCCEEDED
            if job_status == agent_job_lib.JobStatus.CANCELLED:
                return state.ManagedJobStatus.CANCELLED
            if job_status in (agent_job_lib.JobStatus.FAILED,
                              agent_job_lib.JobStatus.FAILED_SETUP):
                # Failed job on a healthy cluster = user failure; on a
                # dead/degraded cluster = preemption casualty
                # (reference jobs/controller.py:260-300).
                cluster_status = self._cluster_status()
                if cluster_status == status_lib.ClusterStatus.UP:
                    return (state.ManagedJobStatus.FAILED_SETUP
                            if job_status
                            == agent_job_lib.JobStatus.FAILED_SETUP else
                            state.ManagedJobStatus.FAILED)
                logger.info('Job failed with unhealthy cluster (%s): '
                            'treating as preemption.', cluster_status)
                return state.ManagedJobStatus.RECOVERING
            if job_status is None:
                # Can't see the job at all: cluster gone or agent dead.
                cluster_status = self._cluster_status()
                if cluster_status != status_lib.ClusterStatus.UP:
                    logger.info('Cluster %s is %s: preemption.',
                                self.cluster_name, cluster_status)
                    return state.ManagedJobStatus.RECOVERING
                # Cluster claims UP but the job is invisible (agent
                # dead / job table lost): bounded patience, then treat
                # as preemption — a relaunch restores the agent too.
                missing_streak += 1
                if missing_streak >= 6:
                    logger.warning(
                        'Job invisible for %d checks with cluster UP; '
                        'recovering.', missing_streak)
                    return state.ManagedJobStatus.RECOVERING
            # else: INIT/PENDING/SETTING_UP/RUNNING — keep watching.
            if job_status == agent_job_lib.JobStatus.RUNNING:
                record = state.get_job(self.job_id)
                if (record and record['status']
                        != state.ManagedJobStatus.RUNNING):
                    state.set_status(self.job_id,
                                     state.ManagedJobStatus.RUNNING)

    # ------------------------------------------------------------------
    def run(self) -> state.ManagedJobStatus:
        """Run every task of the (chain) dag in order; the managed job
        succeeds only if all tasks do."""
        adopt_job_id = self.reconcile_on_start()
        record = state.get_job(self.job_id)
        if record['status'].is_terminal():
            # Reconcile rolled a terminate forward (or a previous run
            # concluded): nothing left to execute.
            return record['status']
        start_index = int(record.get('task_index') or 0)
        result = state.ManagedJobStatus.SUCCEEDED
        for index, task in enumerate(self.tasks):
            if index < start_index:
                continue
            self.task = task
            self.task_index = index
            self.strategy = recovery_strategy.StrategyExecutor.make(
                self._task_cluster(index), task)
            self.cluster_name = self.strategy.cluster_name
            state.set_task_index(self.job_id, index)
            if index > 0:
                logger.info('Pipeline task %d/%d: %s.', index + 1,
                            len(self.tasks), task.name)
            result = self._run_task(
                adopt_job_id if index == start_index else None)
            if result != state.ManagedJobStatus.SUCCEEDED:
                return result
        state.set_status(self.job_id, state.ManagedJobStatus.SUCCEEDED)
        return result

    def _run_task(self,
                  adopt_job_id: Optional[int] = None
                  ) -> state.ManagedJobStatus:
        if adopt_job_id is not None:
            # reconcile_on_start adopted a cluster the dead controller
            # already launched: resume monitoring, do NOT relaunch.
            cluster_job_id: Optional[int] = adopt_job_id
        else:
            state.set_status(self.job_id, state.ManagedJobStatus.STARTING)
            # Launches are slot-limited (jobs/scheduler.py): a burst of
            # submissions provisions at most launch_parallelism()
            # clusters at once; the rest queue in WAITING. A cancel
            # raised while queued aborts before any cluster exists.
            if not scheduler.wait_for_launch_slot(self.job_id):
                state.set_status(self.job_id,
                                 state.ManagedJobStatus.CANCELLED)
                return state.ManagedJobStatus.CANCELLED
            # Journal the launch BEFORE any cloud mutation: from here
            # until finish_launch_intent, a crash leaves an open intent
            # that reconcile resolves against cluster truth.
            intent_id = state.begin_intent(
                'jobs.launch', {
                    'job_id': self.job_id,
                    'cluster_name': self.cluster_name,
                    'task_index': self.task_index,
                })
            fault_injection.crashpoint(
                'jobs.controller.launch.pre_provision',
                job_id=self.job_id)
            try:
                with trace_lib.span('jobs.controller.launch',
                                    slow_ok=True, job=str(self.job_id),
                                    cluster=self.cluster_name):
                    cluster_job_id = self.strategy.launch()
            except exceptions.ResourcesUnavailableError as e:
                # Controlled failure in THIS process: the operation is
                # over — settle status and journal atomically.
                state.set_status(self.job_id,
                                 state.ManagedJobStatus.FAILED_NO_RESOURCE,
                                 failure_reason=str(e),
                                 complete_intent=intent_id)
                return state.ManagedJobStatus.FAILED_NO_RESOURCE
            finally:
                scheduler.finish_launch(self.job_id)
            assert cluster_job_id is not None
            fault_injection.crashpoint(
                'jobs.controller.launch.post_provision',
                job_id=self.job_id)
            # Commit point: on-cluster job id recorded + intent retired
            # in one transaction — after this, restarts adopt via the
            # row instead of the journal.
            state.finish_launch_intent(intent_id, self.job_id,
                                       cluster_job_id)

        while True:
            result = self._monitor_until_done(cluster_job_id)
            if result == state.ManagedJobStatus.CANCELLING:
                logger.info('Cancel requested; terminating cluster.')
                self._terminate_task_cluster(
                    state.ManagedJobStatus.CANCELLED)
                return state.ManagedJobStatus.CANCELLED
            is_restart = False
            if result in (state.ManagedJobStatus.FAILED,
                          state.ManagedJobStatus.FAILED_SETUP):
                # User failure on a healthy cluster: restart while the
                # strategy's max_restarts_on_errors budget lasts
                # (reference jobs/controller.py restart-on-errors).
                if self.strategy.should_restart_on_failure():
                    logger.info(
                        'User failure; restarting on errors '
                        '(%d/%d).',
                        self.strategy.restart_count_on_errors,
                        self.strategy.max_restarts_on_errors)
                    result = state.ManagedJobStatus.RECOVERING
                    is_restart = True
                    _M_RESTARTS.inc(1, job=str(self.job_id))
                elif self.strategy.max_restarts_on_errors > 0:
                    self._terminate_task_cluster(
                        result,
                        failure_reason=(
                            'exhausted max_restarts_on_errors='
                            f'{self.strategy.max_restarts_on_errors}'))
                    return result
            if result != state.ManagedJobStatus.RECOVERING:
                if result is state.ManagedJobStatus.SUCCEEDED:
                    # A watcher must never observe a terminal status
                    # mid-pipeline, so only the LAST task journals
                    # SUCCEEDED; earlier tasks journal the pipeline
                    # advance instead — either way the outcome commits
                    # atomically with the teardown intent, so a crash
                    # here can never re-run the finished task.
                    last = self.task_index + 1 >= len(self.tasks)
                    self._terminate_task_cluster(
                        state.ManagedJobStatus.SUCCEEDED if last
                        else None,
                        next_task_index=(None if last
                                         else self.task_index + 1))
                else:
                    self._terminate_task_cluster(result)
                return result
            # Preemption: recover.
            n = state.bump_recovery(self.job_id)
            if not is_restart:
                _M_RECOVERIES.inc(1, job=str(self.job_id))
            state.set_status(self.job_id,
                             state.ManagedJobStatus.RECOVERING)
            if n > _MAX_RECOVERIES:
                state.set_status(
                    self.job_id, state.ManagedJobStatus.FAILED_CONTROLLER,
                    failure_reason=f'exceeded {_MAX_RECOVERIES} '
                    'recoveries')
                return state.ManagedJobStatus.FAILED_CONTROLLER
            logger.info('Recovery #%d for managed job %d.', n,
                        self.job_id)
            # Recovery relaunches a cluster — same slot discipline.
            if not scheduler.wait_for_launch_slot(self.job_id):
                self._terminate_task_cluster(
                    state.ManagedJobStatus.CANCELLED)
                return state.ManagedJobStatus.CANCELLED
            intent_id = state.begin_intent(
                'jobs.recover', {
                    'job_id': self.job_id,
                    'cluster_name': self.cluster_name,
                    'task_index': self.task_index,
                    'attempt': n,
                })
            fault_injection.crashpoint('jobs.controller.recover.mid',
                                       job_id=self.job_id)
            try:
                # A restart follows a USER failure on healthy infra:
                # relaunch without blocking the (healthy) region.
                with trace_lib.span(
                        'jobs.controller.recover', slow_ok=True,
                        job=str(self.job_id), attempt=n,
                        kind='restart' if is_restart else 'preemption'):
                    cluster_job_id = (self.strategy.restart()
                                      if is_restart
                                      else self.strategy.recover())
            except exceptions.ResourcesUnavailableError as e:
                state.set_status(
                    self.job_id,
                    state.ManagedJobStatus.FAILED_NO_RESOURCE,
                    failure_reason=str(e),
                    complete_intent=intent_id)
                return state.ManagedJobStatus.FAILED_NO_RESOURCE
            finally:
                scheduler.finish_launch(self.job_id)
            state.finish_launch_intent(intent_id, self.job_id,
                                       cluster_job_id)
            state.set_status(self.job_id, state.ManagedJobStatus.RUNNING)


def _settle_intents_on_failure(job_id: int) -> None:
    """Conclude a FAILED_CONTROLLER job's open intents: tear down each
    journaled cluster (roll back / finish the teardown in-process),
    then retire the record. An intent is kept open if its teardown
    fails, so a manual relaunch can still reconcile it."""
    for intent in state.open_intents(job_id):
        cluster = intent['payload'].get('cluster_name')
        if cluster:
            try:
                core.down(cluster)
            except exceptions.ClusterDoesNotExist:
                pass
            except Exception:  # pylint: disable=broad-except
                logger.warning(
                    'Could not settle intent %s (cluster %s); leaving '
                    'it journaled:\n%s', intent['intent_id'], cluster,
                    traceback.format_exc())
                continue
        state.complete_intent(intent['intent_id'])


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('job_id', type=int)
    parser.add_argument('--check-gap', type=float,
                        default=JOB_STATUS_CHECK_GAP_SECONDS)
    args = parser.parse_args()
    import os
    trace_lib.set_component(f'jobs.controller.{args.job_id}')
    state.set_controller_pid(args.job_id, os.getpid())
    try:
        # The controller's root span: parents under the submitting
        # process's jobs.submit span via SKYTPU_TRACE_CONTEXT, so one
        # trace id covers submit -> launch -> provision -> recovery.
        with trace_lib.span('jobs.controller', slow_ok=True,
                            job=str(args.job_id)):
            JobsController(args.job_id, check_gap=args.check_gap).run()
    except Exception as e:  # pylint: disable=broad-except
        logger.error('Controller crashed:\n%s', traceback.format_exc())
        state.set_status(args.job_id,
                         state.ManagedJobStatus.FAILED_CONTROLLER,
                         failure_reason=str(e))
        # A controlled failure (exception, not a kill): settle this
        # job's open intents NOW — terminate whatever cluster each one
        # journaled (a half-provisioned launch would otherwise leak
        # forever, since a terminal job is never reconciled again) and
        # only then retire the records.
        _settle_intents_on_failure(args.job_id)
        raise
    finally:
        # Final spool dump: the terminal counter values survive the
        # process (the monitor-tick dump may be a whole gap stale).
        metrics_lib.dump_snapshot(f'jobs.controller.{args.job_id}')
        scheduler.job_done(args.job_id)


if __name__ == '__main__':
    main()
