"""Recovery strategies: how a preempted/failed cluster is relaunched.

Re-design of reference ``sky/jobs/recovery_strategy.py:45,382,466``:
a StrategyExecutor owns launch + recover for one task. FAILOVER first
retries the cluster's current region, then lets the provisioner's
blocked-set failover roam; EAGER_NEXT_REGION (default) blocks the
preempted region immediately — on TPU spot, a preempted zone rarely
has capacity seconds later, so moving on converges faster.
"""
from __future__ import annotations

import time
import typing
from typing import Optional

from skypilot_tpu import exceptions
from skypilot_tpu.utils import log as sky_logging
from skypilot_tpu.utils import registry

if typing.TYPE_CHECKING:
    from skypilot_tpu import task as task_lib

logger = sky_logging.init_logger(__name__)

RECOVERY_STRATEGY_REGISTRY = registry.Registry('recovery strategy')
DEFAULT_RECOVERY_STRATEGY = 'EAGER_NEXT_REGION'

_MAX_LAUNCH_ATTEMPTS = 3
_LAUNCH_RETRY_GAP_SECONDS = 30


class StrategyExecutor:
    """Launch/recover one task's cluster through the normal stack."""

    def __init__(self, cluster_name: str, task: 'task_lib.Task',
                 max_restarts_on_errors: int = 0) -> None:
        self.cluster_name = cluster_name
        self.task = task
        self.max_restarts_on_errors = max_restarts_on_errors

    @classmethod
    def make(cls, cluster_name: str, task: 'task_lib.Task'
             ) -> 'StrategyExecutor':
        name = DEFAULT_RECOVERY_STRATEGY
        recovery = None
        for r in task.resources:
            recovery = r.job_recovery or recovery
        if recovery is not None:
            name = str(recovery)
        strategy_cls = RECOVERY_STRATEGY_REGISTRY.from_str(name)
        return strategy_cls(cluster_name, task)

    # ------------------------------------------------------------------
    def _do_launch(self, *, blocked_regions=None) -> Optional[int]:
        """One sky.launch of the task; returns job_id on the cluster."""
        from skypilot_tpu import execution
        task = self.task
        if blocked_regions:
            task = self._without_regions(task, blocked_regions)
        job_id, _ = execution.launch(task,
                                     cluster_name=self.cluster_name,
                                     detach_run=True,
                                     stream_logs=False)
        return job_id

    def _without_regions(self, task: 'task_lib.Task', regions):
        """Copy of the task whose resources un-pin `regions`."""
        from skypilot_tpu import task as task_lib
        new = task_lib.Task.from_yaml_config(task.to_yaml_config())
        new_resources = set()
        for r in task.resources:
            if r.region in regions:
                new_resources.add(r.copy(region=None))
            else:
                new_resources.add(r)
        new.set_resources(new_resources)
        return new

    def launch(self) -> Optional[int]:
        """Initial launch with bounded retries on transient errors."""
        last_exc: Optional[Exception] = None
        for attempt in range(_MAX_LAUNCH_ATTEMPTS):
            try:
                return self._do_launch()
            except exceptions.ResourcesUnavailableError as e:
                raise  # permanent: no capacity anywhere
            except Exception as e:  # pylint: disable=broad-except
                last_exc = e
                logger.warning('Launch attempt %d failed: %s',
                               attempt + 1, e)
                time.sleep(_LAUNCH_RETRY_GAP_SECONDS)
        raise exceptions.ProvisionError(
            f'Launch failed after {_MAX_LAUNCH_ATTEMPTS} attempts: '
            f'{last_exc}')

    def terminate_cluster(self) -> None:
        from skypilot_tpu import core
        try:
            core.down(self.cluster_name)
        except exceptions.ClusterDoesNotExist:
            pass

    def recover(self) -> Optional[int]:
        raise NotImplementedError


@RECOVERY_STRATEGY_REGISTRY.register(name='FAILOVER')
class FailoverStrategy(StrategyExecutor):
    """Retry the same region first, then roam (reference :382)."""

    def recover(self) -> Optional[int]:
        # 1. Relaunch in place: the handle's region is retried first
        #    because the task resources still pin it.
        self.terminate_cluster()
        try:
            return self._do_launch()
        except exceptions.ResourcesUnavailableError:
            logger.info('Same-region recovery failed; roaming.')
        # 2. Unpin the region and let provisioner failover roam.
        self.terminate_cluster()
        return self._do_launch(
            blocked_regions={r.region for r in self.task.resources
                             if r.region})


@RECOVERY_STRATEGY_REGISTRY.register(name='EAGER_NEXT_REGION',
                                     default=True)
class EagerNextRegionStrategy(StrategyExecutor):
    """Skip the preempted region immediately (reference :466)."""

    def recover(self) -> Optional[int]:
        from skypilot_tpu import global_user_state
        record = global_user_state.get_cluster_from_name(
            self.cluster_name)
        preempted_region = None
        if record is not None and record.get('handle') is not None:
            preempted_region = record['handle'].launched_resources.region
        self.terminate_cluster()
        blocked = {preempted_region} if preempted_region else None
        try:
            return self._do_launch(blocked_regions=blocked)
        except exceptions.ResourcesUnavailableError:
            # Everything else is full: the preempted region is better
            # than nothing — retry unrestricted.
            logger.info('Other regions full; retrying all regions.')
            return self._do_launch()
