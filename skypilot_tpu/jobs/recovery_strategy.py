"""Recovery strategies: how a preempted/failed cluster is relaunched.

Re-design of reference ``sky/jobs/recovery_strategy.py:45,382,466``:
a StrategyExecutor owns launch + recover for one task. FAILOVER first
retries the cluster's current region, then lets the provisioner's
blocked-set failover roam; EAGER_NEXT_REGION (default) blocks the
preempted region immediately — on TPU spot, a preempted zone rarely
has capacity seconds later, so moving on converges faster.
"""
from __future__ import annotations

import os
import typing
from typing import Optional

from skypilot_tpu import exceptions
from skypilot_tpu.utils import log as sky_logging
from skypilot_tpu.utils import registry
from skypilot_tpu.utils import retry as retry_lib
from skypilot_tpu.utils import statedb

if typing.TYPE_CHECKING:
    from skypilot_tpu import task as task_lib

logger = sky_logging.init_logger(__name__)

RECOVERY_STRATEGY_REGISTRY = registry.Registry('recovery strategy')
DEFAULT_RECOVERY_STRATEGY = 'EAGER_NEXT_REGION'

_MAX_LAUNCH_ATTEMPTS = 3
_LAUNCH_RETRY_GAP_SECONDS = 30


def _launch_retry_policy() -> retry_lib.RetryPolicy:
    """Transient launch errors get bounded retries on the shared
    RetryPolicy; ResourcesUnavailableError is permanent (no capacity
    anywhere) and never retried. Env overrides let chaos tests tighten
    the schedule in the detached controller process."""
    return retry_lib.RetryPolicy(
        max_attempts=int(
            os.environ.get('SKYTPU_JOBS_LAUNCH_MAX_ATTEMPTS',
                           _MAX_LAUNCH_ATTEMPTS)),
        initial_backoff=float(
            os.environ.get('SKYTPU_JOBS_LAUNCH_RETRY_GAP',
                           _LAUNCH_RETRY_GAP_SECONDS)),
        max_backoff=300.0,
        multiplier=2.0,
        # No jitter: the gap exists to stop hammering a struggling
        # backend, so SKYTPU_JOBS_LAUNCH_RETRY_GAP must MEAN a gap —
        # full jitter would allow ~0s relaunches.
        jitter='none',
        # LeaseLostError is permanent too: a fleet worker whose lease
        # was claimed over must abandon NOW, not retry the launch
        # into its successor's work (docs/control_plane.md).
        retryable=lambda e: not isinstance(
            e, (exceptions.ResourcesUnavailableError,
                statedb.LeaseLostError)),
        site='jobs.launch')


class StrategyExecutor:
    """Launch/recover one task's cluster through the normal stack."""

    def __init__(self, cluster_name: str, task: 'task_lib.Task',
                 max_restarts_on_errors: int = 0) -> None:
        self.cluster_name = cluster_name
        self.task = task
        # How many times a USER failure (job failed on a healthy
        # cluster) may be answered with a restart before going
        # terminal (reference job_recovery.max_restarts_on_errors).
        self.max_restarts_on_errors = max_restarts_on_errors
        self.restart_count_on_errors = 0
        # Region of the last successful launch — captured here because
        # by the time recover() runs, the cluster record has usually
        # been reaped by status refresh.
        self.last_region: Optional[str] = None

    @classmethod
    def make(cls, cluster_name: str, task: 'task_lib.Task'
             ) -> 'StrategyExecutor':
        name = DEFAULT_RECOVERY_STRATEGY
        recovery = None
        for r in task.resources:
            recovery = r.job_recovery or recovery
        max_restarts = 0
        if isinstance(recovery, dict):
            name = str(recovery.get('strategy') or name)
            max_restarts = int(recovery.get('max_restarts_on_errors', 0))
        elif recovery is not None:
            name = str(recovery)
        strategy_cls = RECOVERY_STRATEGY_REGISTRY.from_str(name)
        return strategy_cls(cluster_name, task,
                            max_restarts_on_errors=max_restarts)

    def should_restart_on_failure(self) -> bool:
        """One user failure happened: is a restart still in budget?
        Bumps the counter when it is.

        Restarts relaunch through recover() and count toward the
        controller's recovery tally, so the effective budget is also
        bounded by the controller's _MAX_RECOVERIES backstop — set
        max_restarts_on_errors well below it."""
        if self.restart_count_on_errors >= self.max_restarts_on_errors:
            return False
        self.restart_count_on_errors += 1
        return True

    # ------------------------------------------------------------------
    def _do_launch(self, *, blocked_regions=None) -> Optional[int]:
        """One sky.launch of the task; returns job_id on the cluster.

        blocked_regions seeds the provisioner's failover blocked-set,
        so those regions are skipped at candidate granularity (a task
        pinned to a blocked region raises ResourcesUnavailableError).
        """
        from skypilot_tpu import execution
        job_id, handle = execution.launch(
            self.task,
            cluster_name=self.cluster_name,
            detach_run=True,
            stream_logs=False,
            blocked_regions=list(blocked_regions or ()))
        if handle is not None:
            self.last_region = handle.launched_resources.region
        return job_id

    def launch(self) -> Optional[int]:
        """Initial launch with bounded retries on transient errors."""
        policy = _launch_retry_policy()
        state = policy.new_state()
        while True:
            try:
                return self._do_launch()
            except (exceptions.ResourcesUnavailableError,
                    statedb.LeaseLostError):
                # Permanent: no capacity anywhere / this worker lost
                # ownership — either way, retrying cannot help.
                raise
            except Exception as e:  # pylint: disable=broad-except
                logger.warning('Launch attempt %d failed: %s',
                               state.attempt + 1, e)
                if not state.should_retry(e):
                    raise exceptions.ProvisionError(
                        f'Launch failed after {state.attempt + 1} '
                        f'attempts: {e}')
                state.sleep()

    def terminate_cluster(self) -> None:
        from skypilot_tpu import core
        try:
            core.down(self.cluster_name)
        except exceptions.ClusterDoesNotExist:
            pass

    def restart(self) -> Optional[int]:
        """Relaunch after a USER failure: the infrastructure was
        provably healthy, so no region is blocked — unlike recover(),
        which assumes the cluster's location just failed."""
        self.terminate_cluster()
        return self._do_launch()

    def recover(self) -> Optional[int]:
        raise NotImplementedError


@RECOVERY_STRATEGY_REGISTRY.register(name='FAILOVER')
class FailoverStrategy(StrategyExecutor):
    """Retry the same region first, then roam (reference :382)."""

    def recover(self) -> Optional[int]:
        # 1. Relaunch in place: the handle's region is retried first
        #    because the task resources still pin it.
        self.terminate_cluster()
        try:
            return self._do_launch()
        except exceptions.ResourcesUnavailableError:
            logger.info('Same-region recovery failed; roaming.')
        # 2. Block the failed region and let provisioner failover roam.
        self.terminate_cluster()
        return self._do_launch(
            blocked_regions={self.last_region} if self.last_region
            else None)


@RECOVERY_STRATEGY_REGISTRY.register(name='EAGER_NEXT_REGION',
                                     default=True)
class EagerNextRegionStrategy(StrategyExecutor):
    """Skip the preempted region immediately (reference :466)."""

    def recover(self) -> Optional[int]:
        # last_region was captured at launch time (the cluster record
        # is usually already reaped by the preemption's status refresh).
        self.terminate_cluster()
        blocked = {self.last_region} if self.last_region else None
        try:
            return self._do_launch(blocked_regions=blocked)
        except exceptions.ResourcesUnavailableError:
            # Everything else is full: the preempted region is better
            # than nothing — retry unrestricted.
            logger.info('Other regions full; retrying all regions.')
            return self._do_launch()
