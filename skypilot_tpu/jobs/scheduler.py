"""Managed-jobs launch scheduler: bound concurrent provisioning.

Re-design of reference ``sky/jobs/scheduler.py:80-277``
(maybe_schedule_next_jobs / submit_job / _get_launch_parallelism):
every controller launch or recovery must hold a *launch slot* before
calling ``execution.launch``. Slots bound how many provisioning
attempts run at once on the controller machine — each one spawns SSH
fan-outs and cloud API polling, so an unbounded burst of submissions
would thrash the controller. Monitoring (the ALIVE phase) is cheap
and unbounded.

The slot ledger is the jobs DB itself (``schedule_state`` column,
claimed with one BEGIN IMMEDIATE transaction), so it works no matter
which process each controller runs in — the same property the
reference gets from its file lock + state table.
"""
from __future__ import annotations

import os
import time
from typing import Optional

from skypilot_tpu.jobs import state
from skypilot_tpu.utils import log as sky_logging
from skypilot_tpu.utils import subprocess_utils

logger = sky_logging.init_logger(__name__)

_PARALLELISM_ENV = 'SKYTPU_JOBS_LAUNCH_PARALLELISM'

# States: INACTIVE -> WAITING -> LAUNCHING -> ALIVE -> DONE.
WAITING = 'WAITING'
LAUNCHING = 'LAUNCHING'
ALIVE = 'ALIVE'
DONE = 'DONE'


def launch_parallelism() -> int:
    """Max concurrent launches (reference _get_launch_parallelism
    :277 uses a CPU heuristic: each in-flight launch budgets ~4 CPUs;
    we floor at 4 so small controllers still make progress)."""
    override = os.environ.get(_PARALLELISM_ENV)
    if override:
        return max(1, int(override))
    return max(4, (os.cpu_count() or 4))


def _sweep_dead_launchers() -> None:
    """Release slots held by controllers that died mid-launch (SIGKILL
    / OOM / reboot skip the releasing ``finally``); without this, dead
    LAUNCHING rows would count against the limit forever and
    eventually deadlock all launches."""
    for job in state.get_jobs():
        if job.get('schedule_state') != LAUNCHING:
            continue
        pid = job.get('controller_pid')
        if not pid:
            continue
        # The cmdline tokens distinguish THIS job's live controller
        # from an unrelated process (or another job's controller) that
        # recycled its pid — e.g. after a reboot, where EPERM from
        # another user's process would otherwise read as either
        # alive-forever or dead depending on taste, both wrong in one
        # direction.
        if not subprocess_utils.process_alive(
                pid,
                cmdline_tokens=(state.CONTROLLER_MODULE,
                                str(job['job_id']))):
            logger.warning(
                'Managed job %d: controller %d died holding a launch '
                'slot; releasing.', job['job_id'], pid)
            state.set_schedule_state(job['job_id'], DONE)


def wait_for_launch_slot(job_id: int,
                         poll_seconds: float = 0.5,
                         timeout: Optional[float] = None) -> bool:
    """Block until this job holds a launch slot.

    Returns False (without a slot) if the job's cancel flag is raised
    while queued — a cancelled job must not go on to provision an
    entire cluster just to tear it down.
    """
    state.set_schedule_state(job_id, WAITING)
    limit = launch_parallelism()
    deadline = None if timeout is None else time.time() + timeout
    while not state.try_acquire_launch_slot(job_id, limit):
        if state.cancel_requested(job_id):
            state.set_schedule_state(job_id, DONE)
            return False
        _sweep_dead_launchers()
        if deadline is not None and time.time() > deadline:
            raise TimeoutError(
                f'Managed job {job_id} waited {timeout}s for a launch '
                f'slot ({limit} parallel launches).')
        time.sleep(poll_seconds)
    return True


def finish_launch(job_id: int) -> None:
    """Launch done (success or failure): release the slot, keep the
    job accounted as ALIVE until the controller exits."""
    state.set_schedule_state(job_id, ALIVE)


def job_done(job_id: int) -> None:
    state.set_schedule_state(job_id, DONE)
