"""Managed-jobs launch scheduler: bound concurrent provisioning.

Re-design of reference ``sky/jobs/scheduler.py:80-277``
(maybe_schedule_next_jobs / submit_job / _get_launch_parallelism):
every controller launch or recovery must hold a *launch slot* before
calling ``execution.launch``. Slots bound how many provisioning
attempts run at once on the controller machine — each one spawns SSH
fan-outs and cloud API polling, so an unbounded burst of submissions
would thrash the controller. Monitoring (the ALIVE phase) is cheap
and unbounded.

The slot ledger is the jobs DB itself (``schedule_state`` column,
claimed with one BEGIN IMMEDIATE transaction), so it works no matter
which process each controller runs in — the same property the
reference gets from its file lock + state table.
"""
from __future__ import annotations

import os
import threading
import traceback
from typing import List, Optional

from skypilot_tpu.jobs import state
from skypilot_tpu.utils import env_registry
from skypilot_tpu.utils import log as sky_logging
from skypilot_tpu.utils import statedb
from skypilot_tpu.utils import subprocess_utils

logger = sky_logging.init_logger(__name__)

_PARALLELISM_ENV = 'SKYTPU_JOBS_LAUNCH_PARALLELISM'
_DEFAULT_RESTART_LIMIT = 3

# States: INACTIVE -> WAITING -> LAUNCHING -> ALIVE -> DONE.
WAITING = 'WAITING'
LAUNCHING = 'LAUNCHING'
ALIVE = 'ALIVE'
DONE = 'DONE'


def launch_parallelism() -> int:
    """Max concurrent launches (reference _get_launch_parallelism
    :277 uses a CPU heuristic: each in-flight launch budgets ~4 CPUs;
    we floor at 4 so small controllers still make progress)."""
    override = os.environ.get(_PARALLELISM_ENV)
    if override:
        return max(1, int(override))
    return max(4, (os.cpu_count() or 4))


def _sweep_dead_launchers() -> None:
    """Release slots held by controllers that died mid-launch (SIGKILL
    / OOM / reboot skip the releasing ``finally``); without this, dead
    LAUNCHING rows would count against the limit forever and
    eventually deadlock all launches."""
    for job in state.get_jobs():
        if job.get('schedule_state') != LAUNCHING:
            continue
        pid = job.get('controller_pid')
        if not pid:
            continue
        # The cmdline tokens distinguish THIS job's live controller
        # from an unrelated process (or another job's controller) that
        # recycled its pid — e.g. after a reboot, where EPERM from
        # another user's process would otherwise read as either
        # alive-forever or dead depending on taste, both wrong in one
        # direction.
        if not subprocess_utils.process_alive(
                pid,
                cmdline_tokens=(state.CONTROLLER_MODULE,
                                str(job['job_id']))):
            logger.warning(
                'Managed job %d: controller %d died holding a launch '
                'slot; releasing.', job['job_id'], pid)
            state.set_schedule_state(job['job_id'], DONE)


def wait_for_launch_slot(job_id: int,
                         poll_seconds: float = 0.5,
                         timeout: Optional[float] = None) -> bool:
    """Block until this job holds a launch slot.

    Returns False (without a slot) if the job's cancel flag is raised
    while queued — a cancelled job must not go on to provision an
    entire cluster just to tear it down.
    """
    state.set_schedule_state(job_id, WAITING)
    limit = launch_parallelism()
    deadline = None if timeout is None else statedb.wall_now() + timeout
    while not state.try_acquire_launch_slot(job_id, limit):
        if state.cancel_requested(job_id):
            state.set_schedule_state(job_id, DONE)
            return False
        _sweep_dead_launchers()
        if deadline is not None and statedb.wall_now() > deadline:
            raise TimeoutError(
                f'Managed job {job_id} waited {timeout}s for a launch '
                f'slot ({limit} parallel launches).')
        # Same injectable clock as the deadline above: under a
        # FakeClock the sleep advances virtual time, so the timeout
        # still fires.
        statedb.wall_clock().sleep(poll_seconds)
    return True


def finish_launch(job_id: int) -> None:
    """Launch done (success or failure): release the slot, keep the
    job accounted as ALIVE until the controller exits."""
    state.set_schedule_state(job_id, ALIVE)


def job_done(job_id: int) -> None:
    state.set_schedule_state(job_id, DONE)


# ---------------------------------------------------------------------
# Crash-only controllers (docs/crash_recovery.md): a controller whose
# pid died while its job is non-terminal is RELAUNCHED — recovery is
# the startup path (reconcile_on_start adopts/rolls back whatever the
# dead process left) — instead of the job being declared lost.


def restart_limit() -> int:
    override = os.environ.get(env_registry.SKYTPU_CONTROLLER_RESTART_LIMIT)
    if override:
        return max(0, int(override))
    return _DEFAULT_RESTART_LIMIT


# Serializes relaunch decisions within this process (the API server's
# thread pool can run several queue() refreshes at once). Cross-process
# exclusion comes from the restart-claim CAS below: the claim names the
# dead pid it observed, and spawn_controller overwrites the pid, so a
# racing relauncher that reads state after a spawn loses its claim. A
# second PROCESS racing inside the claim→spawn window can still
# double-spawn in theory; reconcile_on_start makes that converge (both
# adopt the same cluster; intent completion is idempotent).
_relaunch_lock = threading.Lock()


def maybe_relaunch_controller(job: dict) -> bool:
    """Relaunch this job's controller if its process died while the job
    is non-terminal. Returns True when the relaunch is handled (spawned
    here, or owned by a concurrent relauncher); False when the
    controller is alive, the job is terminal/unstarted, the restart
    budget is exhausted, or reconcile-on-start is disabled (the caller
    then falls back to marking the job failed)."""
    if not statedb.reconcile_enabled():
        return False
    with _relaunch_lock:
        # Re-read under the lock: a concurrent caller may have already
        # respawned (new pid) or concluded the job.
        job = state.get_job(job['job_id']) or job
        if job['status'].is_terminal() or \
                job['status'] == state.ManagedJobStatus.PENDING:
            return False
        pid = job.get('controller_pid')
        if not pid:
            return False  # never spawned locally (controller-cluster)
        if subprocess_utils.process_alive(
                pid, cmdline_tokens=(state.CONTROLLER_MODULE,
                                     str(job['job_id']))):
            return False
        outcome, restarts = state.try_claim_controller_restart(
            job['job_id'], pid, restart_limit())
        if outcome == 'lost':
            return True  # another relauncher owns this restart
        if outcome == 'exhausted':
            logger.warning(
                'Managed job %d: controller died %d times; giving up.',
                job['job_id'], restarts)
            return False
        logger.warning(
            'Managed job %d: controller %s is gone with the job %s; '
            'relaunching (restart %d/%d).', job['job_id'], pid,
            job['status'].value, restarts, restart_limit())
        # Release a leaked launch slot first: the dead process cannot
        # call finish_launch, and the relaunched controller re-acquires.
        if job.get('schedule_state') == LAUNCHING:
            state.set_schedule_state(job['job_id'], WAITING)
        from skypilot_tpu.jobs import core as jobs_core
        try:
            jobs_core.spawn_controller(job['job_id'])
        except Exception:  # pylint: disable=broad-except
            logger.error(
                'Managed job %d: controller relaunch failed:\n%s',
                job['job_id'], traceback.format_exc())
            return False
    return True


def relaunch_dead_controllers() -> List[int]:
    """Sweep every non-terminal job for a dead controller and relaunch
    each (bounded by the per-job restart budget)."""
    relaunched = []
    for job in state.get_jobs():
        if maybe_relaunch_controller(job):
            relaunched.append(job['job_id'])
    return relaunched
