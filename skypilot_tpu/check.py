"""`skytpu check` — credential checks and the enabled-clouds cache.

Re-design of reference ``sky/check.py``: probes each registered cloud's
credentials, stores the enabled list in global user state, and the
optimizer consults the cache. The Local cloud is always enabled so the
hermetic path never depends on cloud credentials.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from skypilot_tpu import global_user_state
from skypilot_tpu import skypilot_config
from skypilot_tpu.utils import log as sky_logging
from skypilot_tpu.utils import registry

logger = sky_logging.init_logger(__name__)

_ENABLED_CLOUDS_KEY = 'enabled_clouds'


def check(quiet: bool = False) -> List[str]:
    """Probe all registered clouds; persist and return the enabled list."""
    import skypilot_tpu.clouds  # noqa: F401  (registers built-in clouds)
    enabled = []
    results: List[Tuple[str, bool, Optional[str]]] = []
    allowed = skypilot_config.get_nested(('allowed_clouds',))
    for name in registry.CLOUD_REGISTRY.keys():
        if allowed is not None and name not in [c.lower() for c in allowed]:
            continue
        cloud = registry.CLOUD_REGISTRY.from_str(name)()
        ok, reason = cloud.check_credentials()
        results.append((name, ok, reason))
        if ok:
            enabled.append(name)
    global_user_state.set_config_value(_ENABLED_CLOUDS_KEY, enabled)
    if not quiet:
        for name, ok, reason in results:
            mark = 'enabled' if ok else f'disabled: {reason}'
            logger.info('  %s: %s', name, mark)
    return enabled


def get_cached_enabled_clouds(refresh_if_empty: bool = True) -> list:
    """Cloud instances from the cache (runs `check` on first use)."""
    import skypilot_tpu.clouds  # noqa: F401
    names = global_user_state.get_config_value(_ENABLED_CLOUDS_KEY)
    if not names and refresh_if_empty:
        names = check(quiet=True)
    names = names or ['local']
    out = []
    for name in names:
        if name in registry.CLOUD_REGISTRY:
            out.append(registry.CLOUD_REGISTRY.from_str(name)())
    return out
