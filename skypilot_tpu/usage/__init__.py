"""Opt-out usage recording (reference ``sky/usage/usage_lib.py:341``).

The reference POSTs schema-scrubbed usage messages to a Loki
endpoint; this build has no telemetry backend (and runs in zero-
egress environments), so events append to a local JSONL ring under
``$SKYTPU_DATA_DIR/usage/`` — same scrubbing contract, same opt-out
(``SKYTPU_DISABLE_USAGE=1``). A deployment that wants a collector
tails/ships that file; an in-process POST sink is deliberately not
built.

Scrubbing: only whitelisted, non-identifying fields are recorded
(operation name, cloud, accelerator type, counts, durations, status).
Never commands, paths, env vars, or resource names.
"""
from skypilot_tpu.usage.usage_lib import (disabled, messages_path,
                                          record_event, timed_event)

__all__ = ['record_event', 'timed_event', 'disabled', 'messages_path']
