"""Usage telemetry: local JSONL sink + optional remote collector.

Every event passes the field whitelist (the schema IS the scrub) and
lands in the local ring file; when a collector is configured
(``SKYTPU_USAGE_COLLECTOR_URL`` or config ``usage.collector_url``)
the same scrubbed records are also POSTed in batches to
``<collector>/usage`` from a daemon thread, and long-lived processes
(the API server) POST a periodic ``<collector>/heartbeat`` — the
fleet-visibility role of reference
``sky/usage/usage_lib.py:341,467``. Opt-out: SKYTPU_DISABLE_USAGE=1
silences both sinks. Telemetry is lossy by design: sends are
best-effort, bounded, and can never break or block the product.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

_DISABLE_ENV = 'SKYTPU_DISABLE_USAGE'
_COLLECTOR_ENV = 'SKYTPU_USAGE_COLLECTOR_URL'
_FLUSH_INTERVAL_S = float(os.environ.get(
    'SKYTPU_USAGE_FLUSH_INTERVAL', '30'))
_MAX_PENDING = 1000

# The whitelist IS the schema: anything not listed never leaves the
# call site (reference scrubs via schemas too,
# sky/usage/usage_lib.py + design_docs/usage_collection.md).
_ALLOWED_FIELDS = frozenset({
    'op', 'cloud', 'accelerator', 'num_chips', 'num_hosts',
    'num_nodes', 'use_spot', 'duration_s', 'status', 'error_type',
    'backend', 'recovery_count', 'candidate_count',
})

_MAX_BYTES = 4 * 1024 * 1024  # ring cap


def disabled() -> bool:
    return os.environ.get(_DISABLE_ENV, '').lower() in ('1', 'true')


def messages_path() -> str:
    base = os.path.expanduser(
        os.environ.get('SKYTPU_DATA_DIR', '~/.skytpu'))
    path = os.path.join(base, 'usage')
    os.makedirs(path, exist_ok=True)
    return os.path.join(path, 'messages.jsonl')


def _scrub(fields: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for key, value in fields.items():
        if key not in _ALLOWED_FIELDS:
            continue
        if isinstance(value, (str, int, float, bool)) or value is None:
            out[key] = value
        else:
            out[key] = str(value)
    return out


def collector_url() -> Optional[str]:
    """Remote collector endpoint, or None (local-only)."""
    url = os.environ.get(_COLLECTOR_ENV)
    if url:
        return url
    try:
        from skypilot_tpu import skypilot_config
        return skypilot_config.get_nested(('usage', 'collector_url'))
    except Exception:  # pylint: disable=broad-except
        return None


_pending: List[dict] = []
_pending_lock = threading.Lock()
_flusher: Optional[threading.Thread] = None


def _enqueue_remote(event: Dict[str, Any]) -> None:
    if collector_url() is None:
        return
    global _flusher
    with _pending_lock:
        if len(_pending) < _MAX_PENDING:   # bounded: drop, not grow
            _pending.append(event)
        if _flusher is None or not _flusher.is_alive():
            _flusher = threading.Thread(target=_flush_loop,
                                        name='usage-flusher',
                                        daemon=True)
            _flusher.start()


def _flush_loop() -> None:
    while True:
        time.sleep(_FLUSH_INTERVAL_S)
        flush_remote()


def flush_remote(timeout: float = 5.0) -> bool:
    """POST pending events to ``<collector>/usage`` in one batch.

    Returns True when there was nothing to send or the send
    succeeded. Failed batches are dropped (telemetry is lossy, never
    a queue that grows into the product's memory)."""
    url = collector_url()
    if url is None or disabled():
        return False
    with _pending_lock:
        batch, _pending[:] = list(_pending), []
    if not batch:
        return True
    try:
        import requests
        requests.post(url.rstrip('/') + '/usage',
                      json={'source': common_utils.get_user_hash(),
                            'events': batch},
                      timeout=timeout)
        return True
    except Exception:  # pylint: disable=broad-except
        return False


def heartbeat(**fields: Any) -> bool:
    """POST one liveness beacon to ``<collector>/heartbeat``.

    Long-lived processes (the API server) call this periodically so a
    team deployment has fleet visibility; payload is whitelisted the
    same way events are, plus a cluster count from local state."""
    url = collector_url()
    if url is None or disabled():
        return False
    try:
        from skypilot_tpu import global_user_state
        n_clusters = len(global_user_state.get_clusters())
    except Exception:  # pylint: disable=broad-except
        n_clusters = None
    try:
        import requests
        requests.post(url.rstrip('/') + '/heartbeat',
                      json={'source': common_utils.get_user_hash(),
                            'ts': time.time(),
                            'n_clusters': n_clusters,
                            **_scrub(fields)},
                      timeout=5.0)
        return True
    except Exception:  # pylint: disable=broad-except
        return False


def record_event(op: str, **fields: Any) -> None:
    """Append one scrubbed event; never raises, never blocks long."""
    if disabled():
        return
    try:
        event = {
            'ts': time.time(),
            'run_id': common_utils.get_user_hash(),
            'op': op,
            **_scrub(fields),
        }
        _enqueue_remote(event)
        path = messages_path()
        # Ring behavior: start over when the file grows too large. The
        # rotate-then-append pair is guarded by a file lock because the
        # jobs controller and CLI write concurrently; without it two
        # writers can both rotate and drop the first rotation's events.
        import filelock
        line = json.dumps(event) + '\n'
        try:
            with filelock.FileLock(path + '.lock', timeout=1):
                if (os.path.exists(path) and
                        os.path.getsize(path) > _MAX_BYTES):
                    os.replace(path, path + '.1')
                with open(path, 'a', encoding='utf-8') as f:
                    f.write(line)
        except Exception:  # pylint: disable=broad-except
            # Lock contended (>1s) or unusable (e.g. unwritable .lock
            # file): append lock-less rather than drop the live event.
            # Worst case a rotation races, losing only rotated history —
            # the pre-lock behavior.
            with open(path, 'a', encoding='utf-8') as f:
                f.write(line)
    except Exception:  # pylint: disable=broad-except
        # skytpu-lint: disable=STL001 — telemetry is strictly
        # best-effort: usage reporting must never break the product.
        pass


@contextlib.contextmanager
def timed_event(op: str, **fields: Any) -> Iterator[None]:
    """Record ``op`` with duration + success/error status."""
    start = time.time()
    status, error_type = 'ok', None
    try:
        yield
    except BaseException as e:
        status, error_type = 'error', type(e).__name__
        raise
    finally:
        record_event(op, duration_s=round(time.time() - start, 3),
                     status=status, error_type=error_type, **fields)
