"""Local JSONL usage sink with schema scrubbing."""
from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Any, Dict, Iterator

from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

_DISABLE_ENV = 'SKYTPU_DISABLE_USAGE'

# The whitelist IS the schema: anything not listed never leaves the
# call site (reference scrubs via schemas too,
# sky/usage/usage_lib.py + design_docs/usage_collection.md).
_ALLOWED_FIELDS = frozenset({
    'op', 'cloud', 'accelerator', 'num_chips', 'num_hosts',
    'num_nodes', 'use_spot', 'duration_s', 'status', 'error_type',
    'backend', 'recovery_count', 'candidate_count',
})

_MAX_BYTES = 4 * 1024 * 1024  # ring cap


def disabled() -> bool:
    return os.environ.get(_DISABLE_ENV, '').lower() in ('1', 'true')


def messages_path() -> str:
    base = os.path.expanduser(
        os.environ.get('SKYTPU_DATA_DIR', '~/.skytpu'))
    path = os.path.join(base, 'usage')
    os.makedirs(path, exist_ok=True)
    return os.path.join(path, 'messages.jsonl')


def _scrub(fields: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for key, value in fields.items():
        if key not in _ALLOWED_FIELDS:
            continue
        if isinstance(value, (str, int, float, bool)) or value is None:
            out[key] = value
        else:
            out[key] = str(value)
    return out


def record_event(op: str, **fields: Any) -> None:
    """Append one scrubbed event; never raises, never blocks long."""
    if disabled():
        return
    try:
        event = {
            'ts': time.time(),
            'run_id': common_utils.get_user_hash(),
            'op': op,
            **_scrub(fields),
        }
        path = messages_path()
        # Ring behavior: start over when the file grows too large. The
        # rotate-then-append pair is guarded by a file lock because the
        # jobs controller and CLI write concurrently; without it two
        # writers can both rotate and drop the first rotation's events.
        import filelock
        line = json.dumps(event) + '\n'
        try:
            with filelock.FileLock(path + '.lock', timeout=1):
                if (os.path.exists(path) and
                        os.path.getsize(path) > _MAX_BYTES):
                    os.replace(path, path + '.1')
                with open(path, 'a', encoding='utf-8') as f:
                    f.write(line)
        except Exception:  # pylint: disable=broad-except
            # Lock contended (>1s) or unusable (e.g. unwritable .lock
            # file): append lock-less rather than drop the live event.
            # Worst case a rotation races, losing only rotated history —
            # the pre-lock behavior.
            with open(path, 'a', encoding='utf-8') as f:
                f.write(line)
    except Exception:  # pylint: disable=broad-except
        pass  # usage must never break the product


@contextlib.contextmanager
def timed_event(op: str, **fields: Any) -> Iterator[None]:
    """Record ``op`` with duration + success/error status."""
    start = time.time()
    status, error_type = 'ok', None
    try:
        yield
    except BaseException as e:
        status, error_type = 'error', type(e).__name__
        raise
    finally:
        record_event(op, duration_s=round(time.time() - start, 3),
                     status=status, error_type=error_type, **fields)
