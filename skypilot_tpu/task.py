"""Task — the unit of work.

Re-design of reference ``sky/task.py`` (`Task` :192, `from_yaml_config`
:432, `set_resources` :717, `to_yaml_config` :1179). A Task declares
*what* to run (setup/run commands, workdir, envs, file mounts, a set of
acceptable Resources); the optimizer+backend decide *where/how*.

TPU-first deltas: ``num_nodes`` counts logical nodes (= pod slices); the
per-host gang fan-out is derived from the chosen Resources' slice
topology, so `num_nodes: 1` with `tpu-v5e-64` still launches a 16-host
gang. Env vars are injected per the contract in utils/env_contract.py.
"""
from __future__ import annotations

import os
import re
from typing import Any, Callable, Dict, List, Optional, Set, Union

from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import schemas

_VALID_NAME_REGEX = re.compile(r'^[a-zA-Z0-9]+[a-zA-Z0-9._-]*$')

CommandOrGen = Union[str, Callable[[int, List[str]], Optional[str]], None]


class Task:
    """A coarse-grained unit of work: setup once, run on every rank."""

    def __init__(
        self,
        name: Optional[str] = None,
        *,
        setup: Optional[str] = None,
        run: CommandOrGen = None,
        envs: Optional[Dict[str, str]] = None,
        workdir: Optional[str] = None,
        num_nodes: Optional[int] = None,
        file_mounts: Optional[Dict[str, str]] = None,
        estimate_runtime: Optional[float] = None,
        storage_mounts: Optional[Dict[str, Any]] = None,
        service: Optional[Any] = None,
    ) -> None:
        self.name = name
        self.setup = setup
        self.run = run
        self.workdir = workdir
        self.num_nodes = num_nodes if num_nodes is not None else 1
        self._envs = dict(envs) if envs else {}
        self.file_mounts: Optional[Dict[str, str]] = (dict(file_mounts)
                                                      if file_mounts else None)
        # Seconds on a reference 8-chip slice; the optimizer's TIME
        # objective scales it by chip count.
        self.estimate_runtime: Optional[float] = (
            float(estimate_runtime) if estimate_runtime else None)
        self.storage_mounts: Dict[str, Any] = dict(storage_mounts or {})
        self.service = service
        self._resources: Set[resources_lib.Resources] = {
            resources_lib.Resources()
        }
        # Best resources chosen by the optimizer (a launchable Resources).
        self.best_resources: Optional[resources_lib.Resources] = None
        # DAG wiring (set by Dag).
        self.dag: Optional[Any] = None
        self._validate()
        # Auto-register with an enclosing `with Dag():` block.
        from skypilot_tpu import dag as dag_lib
        current = dag_lib.get_current_dag()
        if current is not None:
            current.add(self)

    def _validate(self) -> None:
        if self.name is not None and not _VALID_NAME_REGEX.match(self.name):
            raise exceptions.InvalidTaskError(
                f'Invalid task name {self.name!r}.')
        if self.num_nodes < 1:
            raise exceptions.InvalidTaskError(
                f'num_nodes must be >= 1, got {self.num_nodes}')
        if self.run is not None and not (isinstance(self.run, str) or
                                         callable(self.run)):
            raise exceptions.InvalidTaskError(
                'run must be a string command or a callable '
                '(rank, ips) -> Optional[str].')
        if self.workdir is not None:
            full = os.path.abspath(os.path.expanduser(self.workdir))
            if not os.path.isdir(full):
                raise exceptions.InvalidTaskError(
                    f'workdir {self.workdir!r} is not an existing directory.')
        for env_key in self._envs:
            if not re.match(r'^[A-Za-z_][A-Za-z0-9_]*$', env_key):
                raise exceptions.InvalidTaskError(
                    f'Invalid env var name {env_key!r}.')
        if self.file_mounts is not None:
            for dst, src in self.file_mounts.items():
                if not isinstance(dst, str) or not isinstance(src, str):
                    raise exceptions.InvalidTaskError(
                        f'file_mounts entries must be str: str, got '
                        f'{dst!r}: {src!r}')

    # ------------------------------------------------------------------
    @property
    def envs(self) -> Dict[str, str]:
        return dict(self._envs)

    def update_envs(self, envs: Dict[str, Optional[str]]) -> 'Task':
        for k, v in envs.items():
            if v is None:
                self._envs.pop(k, None)
            else:
                self._envs[k] = str(v)
        return self

    @property
    def resources(self) -> Set[resources_lib.Resources]:
        return self._resources

    def set_resources(
        self, resources: Union[resources_lib.Resources,
                               List[resources_lib.Resources],
                               Set[resources_lib.Resources]]
    ) -> 'Task':
        if isinstance(resources, resources_lib.Resources):
            resources = {resources}
        self._resources = set(resources)
        if not self._resources:
            raise exceptions.InvalidTaskError('resources set cannot be empty')
        return self

    def set_file_mounts(self, file_mounts: Optional[Dict[str, str]]) -> 'Task':
        self.file_mounts = dict(file_mounts) if file_mounts else None
        self._validate()
        return self

    def update_file_mounts(self, file_mounts: Dict[str, str]) -> 'Task':
        if self.file_mounts is None:
            self.file_mounts = {}
        self.file_mounts.update(file_mounts)
        self._validate()
        return self

    # ------------------------------------------------------------------
    # Chaining sugar: task_a >> task_b (reference sky/task.py:1263)
    def __rshift__(self, other: 'Task') -> 'Task':
        assert self.dag is not None and other.dag is self.dag, (
            'Both tasks must be added to the same Dag (use `with '
            'sky.Dag() as dag:`).')
        self.dag.add_edge(self, other)
        return other

    # ------------------------------------------------------------------
    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any],
                         env_overrides: Optional[Dict[str, str]] = None
                         ) -> 'Task':
        schemas.validate_task(config)
        config = dict(config)
        envs = {
            k: ('' if v is None else str(v))
            for k, v in (config.get('envs') or {}).items()
        }
        if env_overrides:
            envs.update({k: str(v) for k, v in env_overrides.items()})
        # Any `envs:` key with null value must be provided at launch time.
        missing = [k for k, v in envs.items() if v == '']
        if missing and (config.get('envs') or {}):
            null_keys = [
                k for k in missing if (config.get('envs') or {}).get(k) is None
            ]
            if null_keys:
                raise exceptions.InvalidTaskError(
                    f'Env var(s) {null_keys} require values; pass --env.')
        task = cls(
            name=config.get('name'),
            setup=config.get('setup'),
            run=config.get('run'),
            envs=envs,
            workdir=config.get('workdir'),
            num_nodes=config.get('num_nodes'),
            file_mounts=config.get('file_mounts'),
            storage_mounts=config.get('storage_mounts'),
        )
        if 'service' in config:
            from skypilot_tpu.serve import service_spec
            task.service = service_spec.ServiceSpec.from_yaml_config(
                config['service'])
        if config.get('estimate_runtime') is not None:
            # Seconds on a reference 8-chip slice; the optimizer's
            # TIME objective scales it by chip count.
            task.estimate_runtime = float(config['estimate_runtime'])
        resources_config = config.get('resources')
        parsed = resources_lib.Resources.from_yaml_config(resources_config)
        task.set_resources(parsed if isinstance(parsed, list) else {parsed})
        return task

    @classmethod
    def from_yaml(cls, yaml_path: str,
                  env_overrides: Optional[Dict[str, str]] = None) -> 'Task':
        config = common_utils.read_yaml(yaml_path)
        if not isinstance(config, dict):
            raise exceptions.InvalidTaskError(
                f'{yaml_path} does not contain a task mapping.')
        return cls.from_yaml_config(config, env_overrides)

    def to_yaml_config(self) -> Dict[str, Any]:
        config: Dict[str, Any] = {}

        def add(key, value):
            if value is not None and value != {} and value != []:
                config[key] = value

        add('name', self.name)
        resources_list = [r.to_yaml_config() for r in sorted(
            self._resources, key=repr)]
        if len(resources_list) == 1:
            add('resources', resources_list[0])
        else:
            add('resources', {'any_of': resources_list})
        if self.num_nodes != 1:
            config['num_nodes'] = self.num_nodes
        add('workdir', self.workdir)
        add('setup', self.setup)
        add('run', self.run if isinstance(self.run, str) else None)
        add('envs', self._envs or None)
        add('file_mounts', self.file_mounts)
        add('storage_mounts', self.storage_mounts or None)
        if self.service is not None:
            add('service', self.service.to_yaml_config())
        add('estimate_runtime', self.estimate_runtime)
        return config

    # ------------------------------------------------------------------
    def __repr__(self) -> str:
        label = self.name or '-'
        r = (repr(self.best_resources)
             if self.best_resources is not None else
             ', '.join(repr(x) for x in sorted(self._resources, key=repr)))
        return f'Task({label}, num_nodes={self.num_nodes}, resources={r})'
