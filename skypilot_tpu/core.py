"""Core cluster operations: status/stop/start/down/autostop/queue/...

Re-design of reference ``sky/core.py``. These are the in-process
implementations; the API server (skypilot_tpu/server) exposes each as a
route and the CLI/SDK call through it (or directly in local mode).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu.backend import backend_utils
from skypilot_tpu.backend import gang_backend
from skypilot_tpu.utils import log as sky_logging
from skypilot_tpu.utils import status_lib

logger = sky_logging.init_logger(__name__)


def status(cluster_names: Optional[Union[str, List[str]]] = None,
           refresh: bool = False) -> List[Dict[str, Any]]:
    """Cluster records, optionally reconciled against the cloud."""
    if isinstance(cluster_names, str):
        cluster_names = [cluster_names]
    records = global_user_state.get_clusters()
    if cluster_names is not None:
        records = [r for r in records if r['name'] in cluster_names]
    if refresh:
        refreshed = []
        for r in records:
            try:
                rec = backend_utils.refresh_cluster_record(
                    r['name'], force_refresh=True)
            except exceptions.ClusterOwnerIdentityMismatchError as e:
                # One foreign-identity cluster must not blank the
                # whole listing — show the stale record, tagged.
                logger.warning(str(e))
                r = dict(r)
                r['identity_mismatch'] = True
                rec = r
            if rec is not None:
                refreshed.append(rec)
        records = refreshed
    return records


def _get_handle(cluster_name: str) -> gang_backend.GangResourceHandle:
    record = global_user_state.get_cluster_from_name(cluster_name)
    if record is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    return record['handle']


def stop(cluster_name: str, purge: bool = False) -> None:
    """Stop a cluster's instances (restartable with `start`)."""
    handle = _get_handle(cluster_name)
    from skypilot_tpu.clouds import cloud as cloud_lib
    resources = handle.launched_resources
    resources.cloud.check_features_are_supported(
        resources, {cloud_lib.CloudImplementationFeatures.STOP})
    backend = gang_backend.GangBackend()
    backend.teardown(handle, terminate=False, purge=purge)


def down(cluster_name: str, purge: bool = False) -> None:
    """Terminate a cluster and all its resources."""
    handle = _get_handle(cluster_name)
    backend = gang_backend.GangBackend()
    backend.teardown(handle, terminate=True, purge=purge)


def start(cluster_name: str,
          idle_minutes_to_autostop: Optional[int] = None,
          retry_until_up: bool = False) -> gang_backend.GangResourceHandle:
    """Restart a stopped cluster (same resources/zone)."""
    record = global_user_state.get_cluster_from_name(cluster_name)
    if record is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    from skypilot_tpu import task as task_lib
    task = task_lib.Task()
    task.set_resources(record['handle'].launched_resources)
    task.num_nodes = record['handle'].launched_nodes
    backend = gang_backend.GangBackend()
    handle = backend.provision(task,
                               record['handle'].launched_resources,
                               dryrun=False,
                               stream_logs=True,
                               cluster_name=cluster_name,
                               retry_until_up=retry_until_up)
    assert handle is not None
    if idle_minutes_to_autostop is not None:
        backend.set_autostop(handle, idle_minutes_to_autostop)
    return handle


def autostop(cluster_name: str, idle_minutes: int,
             down: bool = False) -> None:  # pylint: disable=redefined-outer-name
    """Set (or cancel with idle_minutes=-1) the autostop budget."""
    handle = backend_utils.check_cluster_available(cluster_name)
    backend = gang_backend.GangBackend()
    backend.set_autostop(handle, idle_minutes, down=down)


def queue(cluster_name: str) -> List[Dict[str, Any]]:
    """The cluster's job table."""
    handle = backend_utils.check_cluster_available(cluster_name)
    backend = gang_backend.GangBackend()
    return backend.get_job_queue(handle)


def job_status(cluster_name: str,
               job_ids: Optional[List[int]] = None
               ) -> Dict[int, Optional[status_lib.JobStatus]]:
    handle = backend_utils.check_cluster_available(cluster_name)
    backend = gang_backend.GangBackend()
    return backend.get_job_status(handle, job_ids)


def cancel(cluster_name: str,
           job_ids: Optional[List[int]] = None,
           all_jobs: bool = False) -> List[int]:
    """Cancel queued/running jobs (all non-terminal if all_jobs)."""
    handle = backend_utils.check_cluster_available(cluster_name)
    backend = gang_backend.GangBackend()
    if all_jobs:
        job_ids = None
    elif not job_ids:
        raise ValueError('Specify job_ids or all_jobs=True.')
    return backend.cancel_jobs(handle, job_ids)


def tail_logs(cluster_name: str,
              job_id: Optional[int] = None,
              follow: bool = True) -> int:
    """Stream a job's merged rank logs to stdout."""
    handle = backend_utils.check_cluster_available(cluster_name)
    backend = gang_backend.GangBackend()
    return backend.tail_logs(handle, job_id, follow=follow)


def sync_down_logs(cluster_name: str,
                   job_id: Optional[int] = None,
                   local_dir: str = '~/skytpu_logs') -> str:
    """Download a job's log tree from the cluster head to this machine
    (reference sync_down_logs, sky/backends/
    cloud_vm_ray_backend.py:3705). Returns the local directory."""
    handle = backend_utils.check_cluster_available(cluster_name)
    backend = gang_backend.GangBackend()
    return backend.sync_down_logs(handle, job_id, local_dir)


def cost_report() -> List[Dict[str, Any]]:
    """Accumulated cost per cluster from usage intervals (reference
    sky/core.py cost_report)."""
    import time as time_lib
    out = []
    for row in global_user_state.get_cluster_history():
        launched = row['launched_resources']
        duration = row['duration']
        cost = None
        if launched is not None:
            try:
                cost = (launched.hourly_price() * row['num_nodes'] *
                        duration / 3600.0)
            except Exception:  # pylint: disable=broad-except
                cost = None
        out.append({
            'name': row['name'],
            'duration': duration,
            'num_nodes': row['num_nodes'],
            # repr, not the object: results cross the API server as
            # JSON.
            'resources': repr(launched) if launched is not None else None,
            'cost': cost,
            'queried_at': time_lib.time(),
        })
    return out
