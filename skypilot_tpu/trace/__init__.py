"""Distributed span tracing for the skypilot_tpu stack.

Public API (docs/tracing.md)::

    from skypilot_tpu import trace

    with trace.span('lb.proxy', replica=url) as sp:
        ...                       # nested spans parent automatically

    @trace.span('provisioner.bulk_provision')
    def bulk_provision(...): ...

    env = dict(os.environ)
    trace.child_env(env)          # propagate across a process spawn
    headers.update(trace.traceparent_headers())   # ... or over HTTP

Spans spool as JSONL per process under ``SKYTPU_TRACE_DIR`` (unset =
tracing off, near-zero overhead); ``python -m skypilot_tpu.trace``
merges the spool into Chrome/Perfetto JSON or a text tree.
"""
from skypilot_tpu.trace.core import REQUEST_ID_HEADER
from skypilot_tpu.trace.core import SLOW_SPAN_ENV
from skypilot_tpu.trace.core import Span
from skypilot_tpu.trace.core import SpanContext
from skypilot_tpu.trace.core import TRACE_CONTEXT_ENV
from skypilot_tpu.trace.core import TRACE_DIR_ENV
from skypilot_tpu.trace.core import TRACEPARENT_HEADER
from skypilot_tpu.trace.core import activate
from skypilot_tpu.trace.core import child_env
from skypilot_tpu.trace.core import context_from_headers
from skypilot_tpu.trace.core import current_context
from skypilot_tpu.trace.core import current_span
from skypilot_tpu.trace.core import current_trace_id
from skypilot_tpu.trace.core import enabled
from skypilot_tpu.trace.core import format_traceparent
from skypilot_tpu.trace.core import new_request_id
from skypilot_tpu.trace.core import new_span_id
from skypilot_tpu.trace.core import new_trace_id
from skypilot_tpu.trace.core import parse_traceparent
from skypilot_tpu.trace.core import seed_ids
from skypilot_tpu.trace.core import set_clock
from skypilot_tpu.trace.core import set_component
from skypilot_tpu.trace.core import span
from skypilot_tpu.trace.core import spool_path
from skypilot_tpu.trace.core import start_span
from skypilot_tpu.trace.core import traceparent_headers

__all__ = [
    'REQUEST_ID_HEADER', 'SLOW_SPAN_ENV', 'Span', 'SpanContext',
    'TRACE_CONTEXT_ENV', 'TRACE_DIR_ENV', 'TRACEPARENT_HEADER',
    'activate', 'child_env', 'context_from_headers', 'current_context',
    'current_span', 'current_trace_id', 'enabled', 'format_traceparent',
    'new_request_id', 'new_span_id', 'new_trace_id', 'parse_traceparent',
    'seed_ids', 'set_clock', 'set_component', 'span', 'spool_path',
    'start_span', 'traceparent_headers',
]
