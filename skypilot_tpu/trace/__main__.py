"""``python -m skypilot_tpu.trace``: merge a span spool into a
Chrome/Perfetto trace or a text tree.

    python -m skypilot_tpu.trace --format chrome -o trace.json
    python -m skypilot_tpu.trace --format tree --trace <trace_id>

``--dir`` defaults to ``SKYTPU_TRACE_DIR``. Exit 0 with an empty
document when the spool holds no spans (an empty run is not an
error); exit 2 when no spool directory is known at all.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from skypilot_tpu.trace import core, export


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog='python -m skypilot_tpu.trace',
        description='Merge span spool files into a Chrome/Perfetto '
                    'trace or a text tree (docs/tracing.md).')
    parser.add_argument('--dir', default=None,
                        help='Span spool directory (default: '
                        '$SKYTPU_TRACE_DIR).')
    parser.add_argument('--format', choices=('chrome', 'tree'),
                        default='chrome',
                        help='chrome: trace-event JSON (loads in '
                        'chrome://tracing and Perfetto); tree: '
                        'per-trace text tree.')
    parser.add_argument('-o', '--out', default=None,
                        help='Write here instead of stdout.')
    parser.add_argument('--trace', default=None,
                        help='Restrict to one trace id (tree only).')
    args = parser.parse_args(argv)

    trace_dir = args.dir or os.environ.get(core.TRACE_DIR_ENV)
    if not trace_dir:
        print('No spool directory: pass --dir or set '
              f'{core.TRACE_DIR_ENV}.', file=sys.stderr)
        return 2
    spans = export.read_spans(trace_dir)
    if args.format == 'chrome':
        out = json.dumps(export.to_chrome(spans))
    else:
        out = export.to_tree(spans, trace_id=args.trace)
    if args.out:
        with open(args.out, 'w', encoding='utf-8') as f:
            f.write(out)
        print(f'{args.out}: {len(spans)} span(s).', file=sys.stderr)
    else:
        sys.stdout.write(out if out.endswith('\n') or not out
                         else out + '\n')
    return 0


if __name__ == '__main__':
    sys.exit(main())
