"""Trace spool -> Chrome/Perfetto JSON and text tree views.

The spool (``SKYTPU_TRACE_DIR``) holds one append-only
``spans-<component>-<pid>.jsonl`` file per traced process.
:func:`read_spans` merges them; :func:`to_chrome` renders Chrome
trace-event JSON (complete 'X' events — loads directly in
``chrome://tracing`` and https://ui.perfetto.dev); :func:`to_tree`
renders a per-trace text tree with durations, the quick-look form for
"where did this request/launch spend its time?".

Corrupt lines are skipped, never fatal: spool files are concurrent
append targets and a crashed writer may leave a torn tail.
"""
from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, List, Optional

from skypilot_tpu.trace import core


def read_spans(trace_dir: Optional[str] = None) -> List[Dict[str, Any]]:
    """All spans in the spool, sorted by start time."""
    trace_dir = os.path.expanduser(
        trace_dir or os.environ.get(core.TRACE_DIR_ENV) or '.')
    spans: List[Dict[str, Any]] = []
    for path in sorted(glob.glob(os.path.join(trace_dir,
                                              'spans-*.jsonl'))):
        try:
            with open(path, encoding='utf-8') as f:
                lines = f.readlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail from a crashed writer
            if (isinstance(rec, dict) and
                    isinstance(rec.get('name'), str) and
                    isinstance(rec.get('trace_id'), str) and
                    isinstance(rec.get('start'), (int, float)) and
                    isinstance(rec.get('end'), (int, float))):
                spans.append(rec)
    spans.sort(key=lambda r: (r['start'], r.get('end', 0.0)))
    return spans


def to_chrome(spans: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Chrome trace-event JSON ('X' complete events, microseconds).

    pid/tid carry the real process/thread so Perfetto's track view
    shows one lane per process; the trace/span/parent ids ride in
    ``args`` for click-through correlation.
    """
    events = []
    for rec in spans:
        args = dict(rec.get('attrs') or {})
        args['trace_id'] = rec['trace_id']
        args['span_id'] = rec.get('span_id')
        if rec.get('parent_id'):
            args['parent_id'] = rec['parent_id']
        if rec.get('component'):
            args['component'] = rec['component']
        events.append({
            'name': rec['name'],
            'cat': 'skypilot_tpu',
            'ph': 'X',
            'ts': round(rec['start'] * 1e6, 3),
            'dur': round((rec['end'] - rec['start']) * 1e6, 3),
            'pid': rec.get('pid', 0),
            'tid': rec.get('tid', 0),
            'args': args,
        })
    return {'traceEvents': events, 'displayTimeUnit': 'ms'}


def write_chrome(trace_dir: Optional[str] = None,
                 out_path: Optional[str] = None) -> str:
    """Merge the spool into one Chrome-trace file; returns its path
    (default ``<trace_dir>/trace_merged.json``)."""
    trace_dir = os.path.expanduser(
        trace_dir or os.environ.get(core.TRACE_DIR_ENV) or '.')
    out_path = out_path or os.path.join(trace_dir, 'trace_merged.json')
    payload = to_chrome(read_spans(trace_dir))
    with open(out_path, 'w', encoding='utf-8') as f:
        json.dump(payload, f)
    return out_path


def _fmt_dur(seconds: float) -> str:
    if seconds >= 1.0:
        return f'{seconds:.3f}s'
    return f'{seconds * 1e3:.1f}ms'


def to_tree(spans: List[Dict[str, Any]],
            trace_id: Optional[str] = None) -> str:
    """Text tree per trace: indentation = parentage, one line per
    span with duration and attrs. Orphans (parent span never flushed,
    e.g. a process killed mid-span) surface as roots rather than
    disappearing."""
    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    for rec in spans:
        if trace_id is not None and rec['trace_id'] != trace_id:
            continue
        by_trace.setdefault(rec['trace_id'], []).append(rec)
    lines: List[str] = []
    for tid in sorted(by_trace,
                      key=lambda t: by_trace[t][0]['start']):
        group = by_trace[tid]
        ids = {rec.get('span_id') for rec in group}
        children: Dict[Any, List[Dict[str, Any]]] = {}
        roots: List[Dict[str, Any]] = []
        for rec in group:
            parent = rec.get('parent_id')
            if parent in ids and parent is not None:
                children.setdefault(parent, []).append(rec)
            else:
                roots.append(rec)
        lines.append(f'trace {tid}')

        def walk(rec: Dict[str, Any], depth: int) -> None:
            attrs = rec.get('attrs') or {}
            attr_s = (' ' + ' '.join(f'{k}={v}'
                                     for k, v in sorted(attrs.items()))
                      if attrs else '')
            dur = _fmt_dur(rec['end'] - rec['start'])
            where = rec.get('component') or rec.get('pid', '')
            lines.append(f'{"  " * (depth + 1)}{rec["name"]}  {dur}  '
                         f'[{where}]{attr_s}')
            for child in sorted(children.get(rec.get('span_id'), ()),
                                key=lambda r: r['start']):
                walk(child, depth + 1)

        for root in roots:
            walk(root, 0)
    return '\n'.join(lines) + ('\n' if lines else '')
