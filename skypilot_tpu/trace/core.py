"""Span tracer core: contextvar-scoped spans + cross-process context.

The Dapper-style timing substrate of the stack (docs/tracing.md). A
**span** is a named, attributed interval with a ``trace_id`` (shared
by every span of one logical operation, across processes), its own
``span_id`` and an optional ``parent_id``. The active span is tracked
in a :mod:`contextvars` variable, so nesting follows the call stack —
including across ``await`` points within one asyncio task — and
worker threads start clean instead of inheriting an unrelated parent.

Enablement and overhead:

- ``SKYTPU_TRACE_DIR`` set: finished spans append, one JSON line
  each, to ``spans-<component>-<pid>.jsonl`` under that directory
  (the spool ``python -m skypilot_tpu.trace`` merges).
- ``SKYTPU_TIMELINE_FILE_PATH`` set: finished spans are ALSO handed
  to :mod:`skypilot_tpu.utils.timeline`, which renders them into the
  legacy single-file Chrome trace (that module is now a thin exporter
  over this one).
- Neither set: :class:`span` enters and exits on two env lookups —
  no ids, no contextvar writes, no allocation beyond the manager
  itself. Hot loops can additionally gate on :func:`enabled`.

Cross-boundary propagation uses one wire form, the W3C traceparent
string ``00-<32hex trace>-<16hex span>-01``:

- process boundary: :func:`child_env` stamps it into
  ``SKYTPU_TRACE_CONTEXT`` (plus the trace knobs) for a spawned
  process; a span started with no in-process parent adopts it.
- HTTP boundary: :func:`traceparent_headers` /
  :func:`context_from_headers` carry it in the ``traceparent``
  header (serve LB -> replica ``serving_http`` -> engine).

Ids come from ``os.urandom``; with ``SKYTPU_TRACE_SEED`` (or
:func:`seed_ids`) they come from a seeded RNG so tests and golden
files are deterministic. :func:`set_clock` swaps the timestamp source
for the same reason. Dependency-free by design: this module may be
imported by logging setup and must never drag in jax, metrics or
aiohttp.
"""
from __future__ import annotations

import contextlib
import contextvars
import functools
import json
import os
import random
import re
import threading
from typing import Any, Callable, Dict, Iterator, Optional, Union

from skypilot_tpu.utils import env_registry

TRACE_DIR_ENV = env_registry.SKYTPU_TRACE_DIR
TRACE_CONTEXT_ENV = env_registry.SKYTPU_TRACE_CONTEXT
TRACE_SEED_ENV = env_registry.SKYTPU_TRACE_SEED
SLOW_SPAN_ENV = env_registry.SKYTPU_TRACE_SLOW_SPAN_SECONDS
_TIMELINE_ENV = env_registry.SKYTPU_TIMELINE_FILE_PATH

# The wire header (W3C trace-context name, lowercase per spec) and the
# request-correlation header serving_http accepts/echoes. These are
# the repo's constant registry for trace headers — reference them,
# never repeat the literals.
TRACEPARENT_HEADER = 'traceparent'
REQUEST_ID_HEADER = 'X-Request-ID'

_TRACEPARENT_RE = re.compile(
    r'\A00-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}\Z')

_DEFAULT_SLOW_SPAN_SECONDS = 30.0

_current: contextvars.ContextVar[Optional['Span']] = \
    contextvars.ContextVar('skytpu_trace_span', default=None)

_lock = threading.Lock()
_ids_rng: Optional[random.Random] = None
_ids_rng_from_env: Optional[str] = None  # raw env value the rng came from
_component: Optional[str] = None

# Test hook (FakeClock discipline of utils/retry.py): golden exports
# need deterministic timestamps. Wall-clock by default — span times
# must merge across processes, so a monotonic-but-unanchored clock
# would not do.
import time as _time  # noqa: E402  (kept separate for set_clock)

_clock: Callable[[], float] = _time.time


def set_clock(fn: Optional[Callable[[], float]]) -> None:
    """Override the span timestamp source (tests); None restores."""
    global _clock
    _clock = fn if fn is not None else _time.time


def set_component(name: str) -> None:
    """Name this process's spool file (``spans-<name>-<pid>.jsonl``)
    and stamp every record — call once from process mains (jobs
    controller, serve controller, engine server, bench)."""
    global _component
    _component = ''.join(c if c.isalnum() or c in '._-' else '-'
                         for c in name)[:64]


def enabled() -> bool:
    """True when span records are being spooled (SKYTPU_TRACE_DIR)."""
    return bool(os.environ.get(TRACE_DIR_ENV))


def _legacy_enabled() -> bool:
    return bool(os.environ.get(_TIMELINE_ENV))


def _recording() -> bool:
    return bool(os.environ.get(TRACE_DIR_ENV) or
                os.environ.get(_TIMELINE_ENV))


# ------------------------------------------------------------------ ids
def seed_ids(seed: Optional[int]) -> None:
    """Deterministic ids from ``seed``; None restores random ids."""
    global _ids_rng, _ids_rng_from_env
    with _lock:
        _ids_rng = None if seed is None else random.Random(seed)
        # An explicit call pins the generator: env changes no longer
        # override it (None re-arms env resolution).
        _ids_rng_from_env = None if seed is None else '<explicit>'


def _rng() -> Optional[random.Random]:
    global _ids_rng, _ids_rng_from_env
    raw = os.environ.get(TRACE_SEED_ENV)
    with _lock:
        if _ids_rng_from_env == '<explicit>':
            return _ids_rng
        if raw != _ids_rng_from_env:
            _ids_rng_from_env = raw
            _ids_rng = None if raw is None else random.Random(int(raw))
        return _ids_rng


def new_trace_id() -> str:
    rng = _rng()
    if rng is not None:
        with _lock:
            return f'{rng.getrandbits(128):032x}'
    return os.urandom(16).hex()


def new_span_id() -> str:
    rng = _rng()
    if rng is not None:
        with _lock:
            return f'{rng.getrandbits(64):016x}'
    return os.urandom(8).hex()


def new_request_id() -> str:
    """A fresh X-Request-ID value (16 hex chars)."""
    return new_span_id()


# ------------------------------------------------------------- context
class SpanContext:
    """The propagatable identity of a span: (trace_id, span_id)."""

    __slots__ = ('trace_id', 'span_id')

    def __init__(self, trace_id: str, span_id: str) -> None:
        self.trace_id = trace_id
        self.span_id = span_id

    def __eq__(self, other: Any) -> bool:
        return (isinstance(other, SpanContext) and
                other.trace_id == self.trace_id and
                other.span_id == self.span_id)

    def __repr__(self) -> str:
        return f'SpanContext({self.trace_id}, {self.span_id})'


def format_traceparent(ctx: 'SpanContext') -> str:
    return f'00-{ctx.trace_id}-{ctx.span_id}-01'


def parse_traceparent(value: Optional[str]) -> Optional[SpanContext]:
    """Parse a traceparent string; malformed input -> None (a bad
    header from the outside world must degrade to a fresh trace, not
    crash the request path)."""
    if not value or not isinstance(value, str):
        return None
    m = _TRACEPARENT_RE.fullmatch(value.strip().lower())
    if m is None:
        return None
    return SpanContext(m.group(1), m.group(2))


def _env_context() -> Optional[SpanContext]:
    return parse_traceparent(os.environ.get(TRACE_CONTEXT_ENV))


def current_span() -> Optional['Span']:
    return _current.get()


def current_context() -> Optional[SpanContext]:
    """The active span's context, else the inherited env context."""
    sp = _current.get()
    if sp is not None:
        return sp.context
    return _env_context()


def current_trace_id() -> Optional[str]:
    """Trace id for log/record correlation; None when tracing is off
    (log lines must not grow a field nobody can look up)."""
    if not _recording():
        return None
    ctx = current_context()
    return ctx.trace_id if ctx is not None else None


def traceparent_headers() -> Dict[str, str]:
    """Outbound HTTP propagation: ``{traceparent: ...}`` for the
    active context, ``{}`` when tracing is off (so an upstream
    client's own header passes through proxies untouched)."""
    if not _recording():
        return {}
    ctx = current_context()
    if ctx is None:
        return {}
    return {TRACEPARENT_HEADER: format_traceparent(ctx)}


def context_from_headers(headers: Any) -> Optional[SpanContext]:
    """Parse the traceparent header out of a (case-insensitive)
    mapping; aiohttp's CIMultiDict and plain dicts both work."""
    value = None
    try:
        value = headers.get(TRACEPARENT_HEADER)
        if value is None and hasattr(headers, 'items'):
            for k, v in headers.items():
                if str(k).lower() == TRACEPARENT_HEADER:
                    value = v
                    break
    except (AttributeError, TypeError):
        return None
    return parse_traceparent(value)


def child_env(env: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """The env block a spawned process needs to continue this trace:
    ``SKYTPU_TRACE_CONTEXT`` (the active span as traceparent) plus
    the trace knobs. Updates ``env`` in place when given; always
    returns the block."""
    out: Dict[str, str] = {}
    for name in (TRACE_DIR_ENV, TRACE_SEED_ENV, SLOW_SPAN_ENV):
        val = os.environ.get(name)
        if val:
            out[name] = val
    if enabled():
        ctx = current_context()
        if ctx is not None:
            out[TRACE_CONTEXT_ENV] = format_traceparent(ctx)
    if env is not None:
        env.update(out)
    return out


# --------------------------------------------------------------- spans
class Span:
    """One timed, attributed interval. Created via :func:`start_span`
    or the :class:`span` context manager; ``finish()`` writes the
    record (when recording was on at start)."""

    __slots__ = ('name', 'trace_id', 'span_id', 'parent_id', 'attrs',
                 'start_time', 'end_time', '_recorded', '_slow_ok')

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: Optional[str], attrs: Dict[str, Any],
                 recorded: bool, slow_ok: bool = False) -> None:
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = attrs
        self.start_time = _clock()
        self.end_time: Optional[float] = None
        self._recorded = recorded
        self._slow_ok = slow_ok

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    @property
    def recorded(self) -> bool:
        return self._recorded

    @property
    def duration(self) -> float:
        """Seconds from start to end (or to now while open) — the
        single timing source for metrics at instrumented sites."""
        end = self.end_time if self.end_time is not None else _clock()
        return max(0.0, end - self.start_time)

    @property
    def exemplar(self) -> Optional[str]:
        """Trace id for a metrics exemplar, None when not recorded
        (an exemplar nobody can look up is noise)."""
        return self.trace_id if self._recorded else None

    def set_attr(self, **attrs: Any) -> None:
        self.attrs.update(attrs)

    def finish(self, **attrs: Any) -> 'Span':
        if self.end_time is not None:  # idempotent
            return self
        self.end_time = _clock()
        if attrs:
            self.attrs.update(attrs)
        if self._recorded:
            _emit(self)
        return self


def start_span(name: str,
               parent: Union[Span, SpanContext, None] = None,
               slow_ok: bool = False,
               **attrs: Any) -> Span:
    """Start a span WITHOUT activating it (explicit-parent workflows:
    the serving engine tracks per-request spans across driver-loop
    ticks where no call stack connects submit to first token).

    Parent resolution: explicit ``parent`` > active contextvar span >
    ``SKYTPU_TRACE_CONTEXT`` (cross-process). Always returns a Span —
    when tracing is disabled it is a timer-only object (no ids are
    minted, no os.urandom syscalls) whose ``duration`` still serves
    as the metric timing source, but ``finish()`` writes nothing and
    ``exemplar`` is None.

    ``slow_ok=True`` exempts the span from the slow-span warning —
    for spans that are long-lived by construction (controller
    lifetimes, cloud provisioning, bench timed sections), where 30s
    is the happy path, not an anomaly.
    """
    recorded = _recording()
    if not recorded:
        return Span(name, '', '', None, dict(attrs), False,
                    slow_ok=slow_ok)
    if parent is None:
        parent = _current.get()
        if parent is None:
            parent = _env_context()
    if isinstance(parent, (Span, SpanContext)):
        trace_id, parent_id = parent.trace_id, parent.span_id
    else:
        trace_id, parent_id = new_trace_id(), None
    return Span(name, trace_id, new_span_id(), parent_id, dict(attrs),
                recorded, slow_ok=slow_ok)


@contextlib.contextmanager
def activate(sp: Optional[Span]) -> Iterator[Optional[Span]]:
    """Make ``sp`` the ambient parent for the block (child spans and
    outbound traceparent headers pick it up)."""
    if sp is None:
        yield None
        return
    token = _current.set(sp)
    try:
        yield sp
    finally:
        _current.reset(token)


class span:
    """Context manager AND decorator: time a block as a child of the
    ambient span.

        with trace.span('lb.proxy', replica=url) as sp:
            ...

        @trace.span('provisioner.bulk_provision')
        def bulk_provision(...): ...

    Disabled mode (no SKYTPU_TRACE_DIR / timeline file): enter/exit
    are two env lookups and yield None — safe on warm paths.
    """

    __slots__ = ('_name', '_parent', '_attrs', '_slow_ok', '_span',
                 '_token')

    def __init__(self, name: str,
                 parent: Union[Span, SpanContext, None] = None,
                 slow_ok: bool = False,
                 **attrs: Any) -> None:
        self._name = name
        self._parent = parent
        self._slow_ok = slow_ok
        self._attrs = attrs
        self._span: Optional[Span] = None
        self._token = None

    def __enter__(self) -> Optional[Span]:
        if not _recording():
            return None
        self._span = start_span(self._name, parent=self._parent,
                                slow_ok=self._slow_ok, **self._attrs)
        self._token = _current.set(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._span is None:
            return
        _current.reset(self._token)
        if exc_type is not None:
            self._span.set_attr(error=f'{exc_type.__name__}: {exc}')
        self._span.finish()
        self._span = None
        self._token = None

    def __call__(self, fn: Callable) -> Callable:
        name = self._name or getattr(fn, '__qualname__', fn.__name__)
        parent = self._parent
        slow_ok = self._slow_ok
        attrs = self._attrs

        @functools.wraps(fn)
        def wrapper(*args: Any, **kwargs: Any):
            with span(name, parent=parent, slow_ok=slow_ok, **attrs):
                return fn(*args, **kwargs)

        return wrapper


# ------------------------------------------------------------ emission
def _slow_threshold() -> float:
    raw = os.environ.get(SLOW_SPAN_ENV)
    if raw is None:
        return _DEFAULT_SLOW_SPAN_SECONDS
    try:
        return float(raw)
    except ValueError:
        return _DEFAULT_SLOW_SPAN_SECONDS


def spool_path(trace_dir: Optional[str] = None) -> str:
    """This process's spool file under ``trace_dir`` (default: the
    env knob)."""
    trace_dir = trace_dir or os.environ.get(TRACE_DIR_ENV) or '.'
    name = _component or 'proc'
    return os.path.join(os.path.expanduser(trace_dir),
                        f'spans-{name}-{os.getpid()}.jsonl')


def _emit(sp: Span) -> None:
    if enabled():
        record = {
            'name': sp.name,
            'trace_id': sp.trace_id,
            'span_id': sp.span_id,
            'parent_id': sp.parent_id,
            'start': sp.start_time,
            'end': sp.end_time,
            'pid': os.getpid(),
            'tid': threading.get_ident(),
            'component': _component,
            'attrs': {k: _jsonable(v) for k, v in sp.attrs.items()},
        }
        path = spool_path()
        try:
            # Open-append-close per span, like the fault-injection
            # record file: small single writes are atomic enough on
            # POSIX for concurrent processes, and a crash loses at
            # most the open span. Span volume is control-plane /
            # per-request, never per-token.
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, 'a', encoding='utf-8') as f:
                f.write(json.dumps(record) + '\n')
        except OSError as e:
            # Tracing must never take down the traced path; say why
            # the trace will be missing and carry on.
            _logger().warning('trace spool write failed (%s): %s',
                              path, e)
    if _legacy_enabled():
        from skypilot_tpu.utils import timeline
        timeline.record_span(sp)
    if sp._slow_ok:  # noqa: SLF001 — same module
        return
    threshold = _slow_threshold()
    if threshold > 0 and sp.duration >= threshold:
        _logger().warning(
            '[trace] slow span %s took %.2fs (trace=%s span=%s)',
            sp.name, sp.duration, sp.trace_id, sp.span_id)


def _jsonable(v: Any) -> Any:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return repr(v)


_logger_obj = None


def _logger():
    # Lazy: utils.log imports this module's ids for its trace-id
    # stamping filter, so the reverse import must happen at call
    # time, not import time.
    global _logger_obj
    if _logger_obj is None:
        from skypilot_tpu.utils import log as sky_logging
        _logger_obj = sky_logging.init_logger(__name__)
    return _logger_obj
