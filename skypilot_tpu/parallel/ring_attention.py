"""Ring attention: exact attention over a sequence-sharded axis.

Long-context support the reference lacks entirely (SURVEY.md §2.11:
grep for ring/ulysses/context-parallel over the reference returns
nothing). Each device holds a sequence chunk of Q, K, V; K/V chunks
rotate around the ICI ring via ``lax.ppermute`` while each device
accumulates its Q-block's attention with a numerically-stable online
softmax (the flash-attention recurrence). Communication is
neighbor-to-neighbor only, so on a TPU torus it rides ICI at full
bisection bandwidth and overlaps with the per-step matmuls.

Two properties matter at scale and are native here:

- **GQA-native**: K/V stay at ``n_kv_heads`` — query heads fold into
  [B, S, kv, group, D] instead of repeating K/V. For Llama-8B's 8:1
  GQA that is 4x less K/V memory AND 4x less ICI traffic per ring
  hop, exactly where long-context ring attention lives or dies.
- **Arbitrary global positions** (``q_positions``/``kv_positions``):
  the causal mask is computed from per-token global positions, not
  from contiguous chunk offsets. This is what makes zig-zag layouts
  work: with the standard contiguous sharding, causality leaves
  low-rank devices idle for most ring steps (device 0 has 1 unmasked
  block out of n); interleaving each device's tokens as chunks
  (i, 2n-1-i) — ``zigzag_indices`` below — gives every device the
  same causal work per step, recovering ~2x utilization at the cost
  of a one-time input permutation.

Usage (inside shard_map/pjit with a mesh axis 'sp'):

    out = ring_attention(q, k, v, axis_name='sp', causal=True)

Shapes are per-shard [batch, seq/n, heads, head_dim]; K/V may carry
fewer (kv) heads than Q.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_NEG_INF = -1e30


def zigzag_indices(seq_len: int, num_shards: int) -> np.ndarray:
    """Permutation placing chunks (i, 2n-1-i) on shard i.

    ``x[..., zigzag_indices(S, n), ...]`` re-orders a contiguous
    sequence so that contiguous sharding over n devices yields the
    load-balanced zig-zag layout; feed the matching positions
    (the permutation itself) as q_positions/kv_positions.
    """
    assert seq_len % (2 * num_shards) == 0, (seq_len, num_shards)
    chunk = seq_len // (2 * num_shards)
    order = []
    for i in range(num_shards):
        order.extend(range(i * chunk, (i + 1) * chunk))
        j = 2 * num_shards - 1 - i
        order.extend(range(j * chunk, (j + 1) * chunk))
    return np.asarray(order, dtype=np.int32)


def _block_update(q, k, v, o, m, l, q_pos, k_pos, scale, causal):
    """One flash-attention accumulation step of Q-block vs K/V-block.

    q: [B, Sq, Kv, G, D]; k, v: [B, Sk, Kv, D]; o: like q, f32;
    m, l: [B, Sq, Kv, G] f32 running max / normalizer;
    q_pos: [Sq], k_pos: [Sk] global token positions.
    """
    s = jnp.einsum('bqkgd,bskd->bkgqs', q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]        # [Sq, Sk]
        s = jnp.where(mask[None, None, None], s, _NEG_INF)
    m_blk = jnp.max(s, axis=-1)                        # [B,Kv,G,Sq]
    m_blk = m_blk.transpose(0, 3, 1, 2)                # [B,Sq,Kv,G]
    m_new = jnp.maximum(m, m_blk)
    # exp with the new running max; fully-masked rows stay at 0.
    p = jnp.exp(s - m_new.transpose(0, 2, 3, 1)[..., None])
    corr = jnp.exp(m - m_new)                          # [B,Sq,Kv,G]
    l_new = l * corr + jnp.sum(p, axis=-1).transpose(0, 3, 1, 2)
    pv = jnp.einsum('bkgqs,bskd->bqkgd', p, v.astype(jnp.float32),
                    preferred_element_type=jnp.float32)
    o_new = o * corr[..., None] + pv
    return o_new, m_new, l_new


def ring_attention(q: jax.Array,
                   k: jax.Array,
                   v: jax.Array,
                   *,
                   axis_name: str,
                   causal: bool = True,
                   scale: Optional[float] = None,
                   q_positions: Optional[jax.Array] = None,
                   kv_positions: Optional[jax.Array] = None
                   ) -> jax.Array:
    """Exact (flash-equivalent) attention over a ring-sharded sequence.

    Args:
      q: per-shard [batch, local_seq, heads, head_dim].
      k, v: per-shard [batch, local_seq, kv_heads, head_dim] —
        kv_heads may divide heads (GQA); K/V are never repeated.
      axis_name: mesh axis the sequence is sharded over.
      causal: apply a causal mask using global positions.
      scale: softmax scale; default 1/sqrt(head_dim).
      q_positions/kv_positions: per-shard [local_seq] global token
        positions (defaults: contiguous chunks). Pass the zig-zag
        permutation's positions for load-balanced causal rings.

    Returns per-shard [batch, local_seq, heads, head_dim], dtype of q.
    """
    if scale is None:
        scale = q.shape[-1]**-0.5
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    n_kv = k.shape[2]
    assert h % n_kv == 0, (h, n_kv)
    g = h // n_kv
    perm = [(i, (i + 1) % n) for i in range(n)]

    if q_positions is None:
        q_positions = my_idx * s_local + jnp.arange(s_local,
                                                    dtype=jnp.int32)
    if kv_positions is None:
        kv_positions = my_idx * s_local + jnp.arange(s_local,
                                                     dtype=jnp.int32)

    # Fold query heads onto their KV group: [B, Sq, Kv, G, D].
    qg = q.reshape(b, s_local, n_kv, g, d)

    # Derive the initial accumulators from q (not fresh jnp.zeros) so
    # they carry shard_map's varying-manual-axes type for lax.scan.
    qf = qg.astype(jnp.float32)
    o0 = jnp.zeros_like(qf)
    m0 = jnp.full_like(qf[..., 0], _NEG_INF) + 0.0 * qf[..., 0]
    l0 = jnp.zeros_like(qf[..., 0])

    def step(carry, _):
        o, m, l, k_cur, v_cur, kpos_cur = carry
        o, m, l = _block_update(qg, k_cur, v_cur, o, m, l,
                                q_pos=q_positions, k_pos=kpos_cur,
                                scale=scale, causal=causal)
        # Rotate AFTER compute so XLA can overlap the ppermute DMA with
        # the next step's matmuls (double-buffered on ICI).
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        kpos_nxt = lax.ppermute(kpos_cur, axis_name, perm)
        return (o, m, l, k_nxt, v_nxt, kpos_nxt), None

    (o, _, l, _, _, _), _ = lax.scan(
        step, (o0, m0, l0, k, v, kv_positions), None, length=n)
    # Guard against fully-masked rows (cannot happen for causal
    # self-attention, but keeps the non-causal edge cases NaN-free).
    o = o / jnp.maximum(l, 1e-30)[..., None]
    return o.reshape(b, s_local, h, d).astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, *, axis_name: str = 'sp',
                           causal: bool = True, positions=None):
    """Convenience wrapper: shard_map ring_attention over ``mesh``.

    q [batch, seq, heads, head_dim] and k/v [batch, seq, kv_heads,
    head_dim] are global arrays; sequence is sharded over
    ``axis_name``, batch over the data axes. ``positions`` (global
    [seq] int32, optional) enables non-contiguous (zig-zag) layouts.
    """
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    import jax as _jax
    # Inside a partial-manual region (the pp pipeline), shard_map must
    # receive the CONTEXT mesh (some axes already Manual) rather than
    # the concrete all-Auto mesh, or jax rejects the mismatch.
    # Absent on older jax (which also has no set_mesh, so there is
    # never an ambient mesh to honor there).
    ambient = getattr(_jax.sharding, 'get_abstract_mesh',
                      lambda: None)()
    if ambient is not None and len(ambient.shape) > 0:
        mesh = ambient
    spec = P(('dp', 'fsdp'), axis_name, 'tp', None)
    if positions is None:
        fn = shard_map(
            functools.partial(ring_attention, axis_name=axis_name,
                              causal=causal),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
        return fn(q, k, v)
    pos_spec = P(axis_name)

    def inner(q, k, v, pos):
        return ring_attention(q, k, v, axis_name=axis_name,
                              causal=causal, q_positions=pos,
                              kv_positions=pos)

    fn = shard_map(inner, mesh=mesh,
                   in_specs=(spec, spec, spec, pos_spec),
                   out_specs=spec)
    return fn(q, k, v, positions)
