"""Ring attention: exact attention over a sequence-sharded axis.

Long-context support the reference lacks entirely (SURVEY.md §2.11:
grep for ring/ulysses/context-parallel over the reference returns
nothing). Each device holds a contiguous sequence chunk of Q, K, V; K/V
chunks rotate around the ICI ring via ``lax.ppermute`` while each
device accumulates its Q-block's attention with a numerically-stable
online softmax (the flash-attention recurrence). Communication is
neighbor-to-neighbor only, so on a TPU torus it rides ICI at full
bisection bandwidth and overlaps with the per-step matmuls.

Usage (inside shard_map/pjit with a mesh axis 'sp'):

    out = ring_attention(q, k, v, axis_name='sp', causal=True)

Shapes are per-shard [batch, seq/n, heads, head_dim].
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def _block_update(q, k, v, o, m, l, q_offset, kv_offset, scale, causal):
    """One flash-attention accumulation step of Q-block vs K/V-block.

    q: [B, Sq, H, D]; k, v: [B, Sk, H, D]; o: [B, Sq, H, D] f32;
    m, l: [B, Sq, H] f32 running max / normalizer.
    """
    sq = q.shape[1]
    sk = k.shape[1]
    s = jnp.einsum('bqhd,bkhd->bhqk', q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = q_offset + lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_pos = kv_offset + lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
    m_blk = jnp.max(s, axis=-1)                       # [B, H, Sq]
    m_new = jnp.maximum(m, m_blk.transpose(0, 2, 1))  # [B, Sq, H]
    # exp with the new running max; fully-masked rows stay at 0.
    p = jnp.exp(s - m_new.transpose(0, 2, 1)[..., None])  # [B,H,Sq,Sk]
    corr = jnp.exp(m - m_new)                             # [B, Sq, H]
    l_new = l * corr + jnp.sum(p, axis=-1).transpose(0, 2, 1)
    pv = jnp.einsum('bhqk,bkhd->bqhd', p, v.astype(jnp.float32),
                    preferred_element_type=jnp.float32)
    o_new = o * corr[..., None] + pv
    return o_new, m_new, l_new


def ring_attention(q: jax.Array,
                   k: jax.Array,
                   v: jax.Array,
                   *,
                   axis_name: str,
                   causal: bool = True,
                   scale: Optional[float] = None) -> jax.Array:
    """Exact (flash-equivalent) attention over a ring-sharded sequence.

    Args:
      q, k, v: per-shard [batch, local_seq, heads, head_dim]. For GQA,
        repeat K/V heads to match Q before calling.
      axis_name: mesh axis the sequence is sharded over.
      causal: apply a causal mask using *global* positions.
      scale: softmax scale; default 1/sqrt(head_dim).

    Returns per-shard [batch, local_seq, heads, head_dim], dtype of q.
    """
    if scale is None:
        scale = q.shape[-1]**-0.5
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    # Derive the initial accumulators from q (not fresh jnp.zeros) so
    # they carry shard_map's varying-manual-axes type for lax.scan.
    qf = q.astype(jnp.float32)
    o0 = jnp.zeros_like(qf)
    m0 = jnp.full_like(qf[..., 0], _NEG_INF) + 0.0 * qf[..., 0]
    l0 = jnp.zeros_like(qf[..., 0])

    def step(carry, i):
        o, m, l, k_cur, v_cur = carry
        # After i rotations device my_idx holds chunk (my_idx - i) mod n.
        src = (my_idx - i) % n
        o, m, l = _block_update(q, k_cur, v_cur, o, m, l,
                                q_offset=my_idx * s_local,
                                kv_offset=src * s_local,
                                scale=scale, causal=causal)
        # Rotate AFTER compute so XLA can overlap the ppermute DMA with
        # the next step's matmuls (double-buffered on ICI).
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (o, m, l, k_nxt, v_nxt), None

    (o, _, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v),
                                  jnp.arange(n))
    # Guard against fully-masked rows (cannot happen for causal
    # self-attention, but keeps the non-causal edge cases NaN-free).
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, *, axis_name: str = 'sp',
                           causal: bool = True):
    """Convenience wrapper: shard_map ring_attention over ``mesh``.

    q/k/v are global arrays [batch, seq, heads, head_dim]; sequence is
    sharded over ``axis_name``, batch over the data axes.
    """
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    spec = P(('dp', 'fsdp'), axis_name, 'tp', None)
    fn = shard_map(
        functools.partial(ring_attention, axis_name=axis_name,
                          causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
