"""Device-mesh construction for TPU slices.

Axes convention (outer → inner, DCN-slowest → ICI-fastest):

  ``dp``    pure data parallelism (replicated params)
  ``fsdp``  data parallelism with fully-sharded params (ZeRO-3 style)
  ``sp``    sequence/context parallelism (ring attention over ICI)
  ``tp``    tensor (Megatron) parallelism — innermost, so its
            collectives ride the fastest ICI links
  ``ep``    expert parallelism (MoE expert banks shard their E axis
            here; token dispatch crosses it as an all-to-all). Sits
            between tp and pp: its all-to-all is lighter than tp's
            per-matmul all-reduces but heavier than pp's activation
            handoffs
  ``pp``    pipeline parallelism (stages exchange one activation per
            microbatch tick — the lowest-bandwidth traffic in the
            step). Listed last for a partitioner constraint: inside
            the pp-manual pipeline region the OTHER axes become
            manual, and shardy requires manual axes to precede free
            axes within any dimension sharding — which holds exactly
            when pp is the final mesh axis. Physical placement of pp
            onto DCN is a device-order concern handled in make_mesh,
            not by the logical axis order.

The reference has no equivalent (it is an orchestrator; SURVEY.md §2.11)
— this is the TPU-native layer its recipes would otherwise hand-roll.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Mapping, Optional, Sequence, Tuple

AXIS_ORDER = ('dp', 'fsdp', 'sp', 'tp', 'ep', 'pp')


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """A concrete axis-size assignment for a device count."""
    pp: int = 1
    dp: int = 1
    fsdp: int = 1
    sp: int = 1
    tp: int = 1
    ep: int = 1

    @property
    def num_devices(self) -> int:
        return (self.pp * self.dp * self.fsdp * self.sp * self.tp *
                self.ep)

    def axis_sizes(self) -> Tuple[Tuple[str, int], ...]:
        return tuple((a, getattr(self, a)) for a in AXIS_ORDER)


def plan_mesh(num_devices: int,
              *,
              tp: int = 1,
              sp: int = 1,
              dp: int = 1,
              pp: int = 1,
              ep: int = 1,
              fsdp: int = -1) -> MeshPlan:
    """Fill in one -1 axis so the product equals ``num_devices``.

    Default: everything not explicitly assigned goes to fsdp — the
    right default for LLM training on a v5e/v6e 2D torus, where
    fully-sharded params + ICI all-gather is the bandwidth-optimal
    layout (scaling-book recipe).
    """
    sizes = {'pp': pp, 'dp': dp, 'fsdp': fsdp, 'sp': sp, 'tp': tp,
             'ep': ep}
    free = [a for a, s in sizes.items() if s == -1]
    if len(free) > 1:
        raise ValueError(f'At most one axis may be -1, got {free}')
    fixed = math.prod(s for s in sizes.values() if s != -1)
    if free:
        if num_devices % fixed:
            raise ValueError(
                f'{num_devices} devices not divisible by fixed axes '
                f'product {fixed} ({sizes})')
        sizes[free[0]] = num_devices // fixed
    elif fixed != num_devices:
        raise ValueError(
            f'Axis product {fixed} != device count {num_devices}')
    return MeshPlan(**sizes)


def make_mesh(plan: Optional[MeshPlan] = None,
              *,
              devices: Optional[Sequence] = None,
              axis_names: Sequence[str] = AXIS_ORDER,
              **axis_sizes: int):
    """Build a jax.sharding.Mesh from a plan (or kwargs like tp=4).

    Uses ``jax.experimental.mesh_utils.create_device_mesh`` so the
    logical mesh is laid out along the physical ICI torus — adjacent
    mesh coordinates are ICI neighbors, which is what makes ring
    collectives (sp) and tp all-reduces ride ICI instead of DCN.
    """
    import jax
    from jax.experimental import mesh_utils

    if devices is None:
        devices = jax.devices()
    if plan is None:
        plan = plan_mesh(len(devices), **{'fsdp': -1, **axis_sizes})
    if plan.num_devices != len(devices):
        raise ValueError(
            f'Plan wants {plan.num_devices} devices, have {len(devices)}')
    shape = tuple(getattr(plan, a) for a in axis_names)
    try:
        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except (ValueError, AssertionError):
        # Non-torus device sets (CPU test meshes) — plain reshape.
        import numpy as np
        dev_array = np.asarray(devices).reshape(shape)
    return jax.sharding.Mesh(dev_array, axis_names)


def mesh_from_env(**axis_sizes: int):
    """Mesh over all visible devices, sized from the env contract.

    On a gang-launched pod slice every host sees its local chips;
    jax.devices() after initialize_from_env() returns the global
    device list, so the same call works single-host and multi-host.
    """
    import jax
    return make_mesh(devices=jax.devices(), **axis_sizes)
