"""TPU-native parallelism layer.

The reference framework (SkyPilot) implements no parallelism math — it
delegates distributed training to user commands via injected env vars
(SURVEY.md §2.11; sky/skylet/constants.py:325-328). Our TPU-first build
promotes the "recipe" layer to a first-class library: device-mesh
construction over ICI/DCN, named-sharding rules for tp/fsdp/dp/sp,
`jax.distributed` bootstrap from the gang env contract, and ring
attention (sequence/context parallelism) over the ICI torus.
"""
from skypilot_tpu.parallel.distributed import initialize_from_env
from skypilot_tpu.parallel.mesh import (MeshPlan, make_mesh, plan_mesh)
from skypilot_tpu.parallel.pipeline import (pipeline_apply,
                                            pipeline_mesh)
from skypilot_tpu.parallel.ring_attention import (ring_attention,
                                                  zigzag_indices)
from skypilot_tpu.parallel.sharding import (batch_spec, logical_to_spec,
                                            shard_pytree)

__all__ = [
    'initialize_from_env',
    'MeshPlan',
    'make_mesh',
    'plan_mesh',
    'pipeline_apply',
    'pipeline_mesh',
    'ring_attention',
    'zigzag_indices',
    'batch_spec',
    'logical_to_spec',
    'shard_pytree',
]
