"""Pipeline parallelism: GPipe microbatching over a mesh axis.

SURVEY.md §2.11 lists pipeline parallelism as absent from the
reference (its recipes may use it internally; the framework offers
nothing). Here it is a library primitive in the TPU idiom: the layer
stack [L, ...] is sharded over a 'pp' mesh axis (stage s holds layers
s*L/n .. (s+1)*L/n), and inside ``shard_map`` every stage runs the
same traced program — a ``lax.scan`` over M + n - 1 ticks in which
activations hop stage-to-stage via ``lax.ppermute`` (neighbor DMA on
the ICI/DCN link between stages) while each stage processes one
microbatch per tick. Bubble fraction is the standard (n-1)/(M+n-1);
choose num_microbatches >> n_stages.

Backprop works by construction: ppermute is differentiable, so
``jax.grad`` of a loss through ``pipeline_apply`` yields the reverse
pipeline schedule automatically.

Use DCN-adjacent mesh axes for 'pp' (stages exchange only one
activation tensor per tick, the lowest-bandwidth traffic in the
stack) — the scaling-book placement: pp over DCN, fsdp/tp inside the
slice.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def pipeline_mesh(pp: int, devices=None):
    """A 1-axis ('pp',) mesh over the first pp devices."""
    import numpy as np
    if devices is None:
        devices = jax.devices()
    return jax.sharding.Mesh(
        np.asarray(devices[:pp]).reshape(pp), ('pp',))


def _stage_apply(layer_fn: Callable, local_params, x, pos=None):
    """Apply this stage's layers (leading dim = L/n_stages). With
    ``pos``, each layer also receives the microbatch's positions."""

    def body(h, lp):
        if pos is None:
            return layer_fn(lp, h), None
        return layer_fn(lp, h, pos), None

    out, _ = lax.scan(body, x, local_params)
    return out


def pipeline_apply(layer_fn: Callable,
                   stacked_params,
                   x: jax.Array,
                   *,
                   mesh,
                   num_microbatches: int,
                   axis_name: str = 'pp') -> jax.Array:
    """Run ``x`` through a layer stack pipelined over ``axis_name``.

    Args:
      layer_fn: (layer_params, h) -> h for ONE layer.
      stacked_params: pytree with leading layer dim L (L divisible by
        the number of stages).
      x: [batch, ...] activations (batch divisible by
        num_microbatches).
      mesh: a Mesh containing ``axis_name``.
      num_microbatches: GPipe M.

    Returns [batch, ...], same as applying the layers sequentially.
    """
    n_stages = mesh.shape[axis_name]
    b = x.shape[0]
    assert b % num_microbatches == 0, (b, num_microbatches)
    mb = b // num_microbatches
    m = num_microbatches
    xm = x.reshape((m, mb) + x.shape[1:])

    def per_stage(local_params, xm):
        stage = lax.axis_index(axis_name)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        # Seed carries with a device-varying term (0 * stage) so they
        # match the loop body's varying-manual-axes type under
        # shard_map (same trick as ring_attention's accumulators).
        varying_zero = (stage * 0).astype(x.dtype)
        state = jnp.zeros_like(xm[0]) + varying_zero
        outputs = jnp.zeros_like(xm) + varying_zero

        def tick(carry, t):
            state, outputs = carry
            # Stage 0 feeds from the input microbatches; later stages
            # from the activation just received from the left.
            feed_idx = jnp.clip(t, 0, m - 1)
            inp = jnp.where(stage == 0, xm[feed_idx], state)
            out = _stage_apply(layer_fn, local_params, inp)
            # The last stage emits microbatch t - (n-1) at tick t.
            out_idx = t - (n_stages - 1)
            write = ((stage == n_stages - 1) & (out_idx >= 0) &
                     (out_idx < m))
            outputs = lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(write, out, outputs[jnp.clip(out_idx, 0,
                                                       m - 1)]),
                jnp.clip(out_idx, 0, m - 1), 0)
            state = lax.ppermute(out, axis_name, perm)
            return (state, outputs), None

        (_, outputs), _ = lax.scan(
            tick, (state, outputs), jnp.arange(m + n_stages - 1))
        # Only the last stage holds real outputs (earlier stages wrote
        # nothing); psum replicates them everywhere.
        keep = (stage == n_stages - 1).astype(outputs.dtype)
        return lax.psum(outputs * keep, axis_name)

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    param_specs = jax.tree.map(lambda _: P(axis_name), stacked_params)
    fn = shard_map(per_stage, mesh=mesh,
                   in_specs=(param_specs, P()), out_specs=P())
    out = fn(stacked_params, xm)
    return out.reshape((b,) + x.shape[1:])


def pipeline_layers(layer_fn: Callable,
                    stacked_params,
                    x: jax.Array,
                    *,
                    mesh,
                    num_microbatches: int,
                    axis_name: str = 'pp',
                    positions=None) -> jax.Array:
    """GPipe over ``axis_name`` with every OTHER mesh axis automatic.

    The flagship-integration variant of :func:`pipeline_apply`: the
    shard_map is manual over the pipeline axis ONLY
    (``axis_names={axis_name}``), so the tensor/fsdp/sequence sharding
    of the layer math keeps working exactly as in the non-pipelined
    path — XLA still auto-inserts the Megatron all-reduces and ZeRO-3
    all-gathers inside each stage, and sharding constraints on
    dp/fsdp/sp/tp remain valid inside the pipelined body. Activations
    hop stages via ppermute (one [mb, ...] tensor per tick, the
    cheapest traffic in the step — put 'pp' on DCN).

    ``stacked_params`` must be sharded P('pp', ...) on the layer dim
    (see llama.param_specs(pp=True)); layer count divisible by the
    stage count, batch by ``num_microbatches``.

    ``positions``: optional per-token aux input [batch, ...] split
    into microbatches alongside ``x``; when given, ``layer_fn`` is
    called as ``layer_fn(lp, h, pos)`` with the positions of the
    microbatch the stage is processing (stage s at tick t holds
    microbatch t - s, so each stage indexes the replicated
    microbatched array directly — no extra ppermute traffic).
    """
    n_stages = mesh.shape[axis_name]
    b = x.shape[0]
    assert b % num_microbatches == 0, (b, num_microbatches)
    mb = b // num_microbatches
    m = num_microbatches
    xm = x.reshape((m, mb) + x.shape[1:])
    pm = None
    if positions is not None:
        assert positions.shape[0] == b, (positions.shape, b)
        pm = positions.reshape((m, mb) + positions.shape[1:])

    def per_stage(local_params, xm, pm):
        stage = lax.axis_index(axis_name)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        varying_zero = (stage * 0).astype(x.dtype)
        state = jnp.zeros_like(xm[0]) + varying_zero
        outputs = jnp.zeros_like(xm) + varying_zero

        def tick(carry, t):
            state, outputs = carry
            feed_idx = jnp.clip(t, 0, m - 1)
            inp = jnp.where(stage == 0, xm[feed_idx], state)
            # Microbatch index this stage is processing at tick t
            # (clip: out-of-range ticks compute discarded garbage).
            pos = (None if pm is None else
                   pm[jnp.clip(t - stage, 0, m - 1)])
            out = _stage_apply(layer_fn, local_params, inp, pos)
            out_idx = t - (n_stages - 1)
            write = ((stage == n_stages - 1) & (out_idx >= 0) &
                     (out_idx < m))
            outputs = lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(write, out, outputs[jnp.clip(out_idx, 0,
                                                       m - 1)]),
                jnp.clip(out_idx, 0, m - 1), 0)
            state = lax.ppermute(out, axis_name, perm)
            return (state, outputs), None

        (_, outputs), _ = lax.scan(
            tick, (state, outputs), jnp.arange(m + n_stages - 1))
        keep = (stage == n_stages - 1).astype(outputs.dtype)
        return lax.psum(outputs * keep, axis_name)

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    param_specs = jax.tree.map(lambda _: P(axis_name), stacked_params)
    if pm is None:
        fn = shard_map(lambda lp, xm_: per_stage(lp, xm_, None),
                       mesh=mesh,
                       in_specs=(param_specs, P()),
                       out_specs=P(),
                       axis_names={axis_name})
        out = fn(stacked_params, xm)
    else:
        fn = shard_map(per_stage,
                       mesh=mesh,
                       in_specs=(param_specs, P(), P()),
                       out_specs=P(),
                       axis_names={axis_name})
        out = fn(stacked_params, xm, pm)
    return out.reshape((b,) + x.shape[1:])
