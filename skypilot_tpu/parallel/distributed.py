"""Multi-host JAX bootstrap from the gang env contract.

Replaces the reference's torchrun/NCCL rendezvous (SURVEY.md §2.11:
`examples/resnet_distributed_torch.yaml` feeds SKYPILOT_NODE_RANK to
torch DDP). Here every TPU host of a gang-provisioned slice calls
:func:`initialize_from_env` once at process start; the coordinator is
rank 0's IP from the stable sorted host list.
"""
from __future__ import annotations

import os
from typing import Optional

from skypilot_tpu.utils import env_contract

_initialized = False


def initialize_from_env(env: Optional[dict] = None) -> bool:
    """Initialize jax.distributed from SKYTPU_* env vars.

    Returns True if multi-process initialization happened, False for
    single-process (no-op). Idempotent.
    """
    global _initialized
    if _initialized:
        return True
    # Every worker exposes a profiler endpoint when asked — the
    # capture hook of SURVEY.md §5 (TensorBoard attaches to
    # <worker_ip>:$SKYTPU_PROFILER_PORT on a live job).
    from skypilot_tpu.utils import profiling
    profiling.maybe_start_profiler_server()
    kw = env_contract.jax_distributed_kwargs(env)
    if kw['num_processes'] <= 1:
        return False
    import jax  # deferred: control-plane code must not import jax
    jax.distributed.initialize(**kw)
    _initialized = True
    return True


def process_info() -> dict:
    """Rank/world info without requiring jax (for logging/recipes)."""
    e = os.environ
    return {
        'rank': int(e.get(env_contract.NODE_RANK, '0')),
        'world': int(e.get(env_contract.NUM_NODES, '1')),
        'coordinator': e.get(env_contract.COORDINATOR_ADDR, ''),
        'topology': e.get(env_contract.TPU_TOPOLOGY, ''),
        'accelerator': e.get(env_contract.ACCELERATOR_TYPE, ''),
    }
