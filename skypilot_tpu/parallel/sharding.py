"""Logical-axis → mesh-axis sharding rules.

A thin, dependency-free take on flax's logical partitioning: model code
annotates arrays with *logical* axis names ('batch', 'seq', 'embed',
'heads', 'mlp', 'vocab'); a rule table maps those to mesh axes. The
table below is the Megatron+FSDP layout from the scaling-book recipe:
params shard over ('fsdp', 'tp'), activations over (('dp','fsdp'),
'sp') — so the tp all-reduce and the sp ring ride ICI.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

AxisName = Union[str, Tuple[str, ...], None]

# logical axis -> mesh axis (or tuple of mesh axes, or None=replicate)
DEFAULT_RULES = {
    'batch': ('dp', 'fsdp'),   # activations: batch over all data axes
    'seq': 'sp',               # activations: sequence/context parallel
    'embed': 'fsdp',           # params: ZeRO-3 shard of the d_model dim
    'heads': 'tp',             # params+acts: attention heads tensor-par
    'kv_heads': 'tp',
    'mlp': 'tp',               # params: ffn hidden dim tensor-parallel
    'vocab': 'tp',             # params: embedding/lm-head vocab dim
    'head_dim': None,
    'layers': None,
    None: None,
}


def logical_to_spec(logical_axes: Sequence[Optional[str]],
                    rules: Optional[dict] = None):
    """('batch','seq',None) -> PartitionSpec(('dp','fsdp'),'sp',None)."""
    from jax.sharding import PartitionSpec
    rules = DEFAULT_RULES if rules is None else rules
    return PartitionSpec(*(rules.get(ax) for ax in logical_axes))


def batch_spec():
    """PartitionSpec for a [batch, seq, ...] activation."""
    return logical_to_spec(('batch', 'seq'))


def named_sharding(mesh, *logical_axes, rules: Optional[dict] = None):
    from jax.sharding import NamedSharding
    return NamedSharding(mesh, logical_to_spec(logical_axes, rules))


def shard_pytree(tree, spec_tree, mesh):
    """Device-put a pytree of arrays with a matching pytree of specs."""
    import jax
    from jax.sharding import NamedSharding
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree,
        spec_tree)
