"""Hybrid-mesh validation: N processes × M local devices (DCN × ICI).

The pod-slice shape the env contract promises (SURVEY.md §2.11;
reference analog ``examples/nccl_test.yaml:30-40`` validates its NCCL
world the same way): data parallelism over the PROCESS axis — the DCN
boundary on real hardware — with fsdp/tp sharding INSIDE each
process's devices (ICI). The single-process 8-device dryrun cannot
see process-boundary bugs (host-local batch assembly, cross-process
collectives in the optimizer, coordinator wiring); this check can.

Run directly (driver-runnable)::

    python -m skypilot_tpu.parallel.hybrid_check            # 2 × 4
    python -m skypilot_tpu.parallel.hybrid_check --procs 2 --local 2

The parent spawns the N-process world over localhost using the SAME
``SKYTPU_*`` env contract a gang-launched job gets (so
``distributed.initialize_from_env`` is exercised, not bypassed), runs
two sharded train steps of the tiny Llama config, then replays them
single-process on N×M virtual devices and asserts loss parity.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import tempfile


def _with_device_count(flags: str, n: int) -> str:
    """Replace (or add) the host-device-count flag, preserving every
    other XLA flag in the string."""
    flags = re.sub(r'--xla_force_host_platform_device_count=\S+', '',
                   flags).strip()
    return (f'{flags} '
            f'--xla_force_host_platform_device_count={n}').strip()

_STEPS = 2
_BATCH = 8           # global batch rows
_SEQ = 64


def _make_global_batch(vocab_size: int):
    import numpy as np
    rng = np.random.default_rng(0)
    inputs = rng.integers(0, vocab_size, (_BATCH, _SEQ),
                          dtype=np.int32)
    targets = rng.integers(1, vocab_size, (_BATCH, _SEQ),
                           dtype=np.int32)
    return {'inputs': inputs, 'targets': targets}


def _run_steps(mesh, local_rows):
    """Init + _STEPS sharded train steps; returns per-step losses."""
    import jax

    from skypilot_tpu import models

    cfg = models.LlamaConfig.tiny()
    batch_np = _make_global_batch(cfg.vocab_size)
    batch = models.shard_batch(
        {k: v[local_rows] for k, v in batch_np.items()}, mesh)
    state, opt = models.init_train_state(cfg, jax.random.PRNGKey(0),
                                         mesh)
    step = models.make_train_step(cfg, opt, mesh)
    losses = []
    for _ in range(_STEPS):
        state, metrics = step(state, batch)
        losses.append(float(metrics['loss']))
    return losses


def _force_cpu() -> None:
    """Pin jax to the CPU platform even when the image's sitecustomize
    already imported jax with a TPU/axon plugin selected via env."""
    os.environ['JAX_PLATFORMS'] = 'cpu'
    os.environ.pop('PALLAS_AXON_POOL_IPS', None)
    import jax
    try:
        jax.config.update('jax_platforms', 'cpu')
    except RuntimeError:
        pass  # backend already initialized: the env pin above holds


def _child(procs: int, local: int, out_path: str) -> None:
    _force_cpu()
    import jax

    from skypilot_tpu.parallel import distributed
    from skypilot_tpu.parallel import make_mesh, plan_mesh

    assert distributed.initialize_from_env(), 'env contract missing'
    assert jax.process_count() == procs, (jax.process_count(), procs)
    n = procs * local
    assert len(jax.devices()) == n, (len(jax.devices()), n)
    # dp over the process (DCN) axis; tp innermost on the fastest
    # links, the rest of each process's devices to fsdp (ICI).
    tp = 2 if local % 2 == 0 else 1
    mesh = make_mesh(plan_mesh(n, dp=procs, tp=tp, sp=1, fsdp=-1),
                     devices=jax.devices())
    rank = jax.process_index()
    rows = slice(rank * _BATCH // procs, (rank + 1) * _BATCH // procs)
    losses = _run_steps(mesh, rows)
    with open(out_path, 'w', encoding='utf-8') as f:
        json.dump({'rank': rank, 'losses': losses}, f)
    print(f'hybrid_check child rank={rank} losses={losses}')


def _oracle(procs: int, local: int) -> list:
    import jax

    from skypilot_tpu.parallel import make_mesh, plan_mesh
    n = procs * local
    tp = 2 if local % 2 == 0 else 1
    mesh = make_mesh(plan_mesh(n, dp=procs, tp=tp, sp=1, fsdp=-1),
                     devices=jax.devices()[:n])
    return _run_steps(mesh, slice(0, _BATCH))


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument('--procs', type=int, default=2)
    parser.add_argument('--local', type=int, default=4,
                        help='virtual devices per process')
    parser.add_argument('--port', type=int, default=0,
                        help='coordinator port (0 = pick free)')
    args = parser.parse_args()

    if os.environ.get('_SKYTPU_HYBRID_ROLE') == 'child':
        _child(args.procs, args.local,
               os.environ['_SKYTPU_HYBRID_OUT'])
        return 0

    port = args.port
    if port == 0:
        import socket
        with socket.socket() as s:
            s.bind(('127.0.0.1', 0))
            port = s.getsockname()[1]

    from skypilot_tpu.utils import env_contract
    tmpdir = tempfile.mkdtemp(prefix='skytpu-hybrid-')
    ips = ['127.0.0.1'] * args.procs
    children = []
    for rank in range(args.procs):
        env = dict(os.environ)
        env.update(
            env_contract.make_rank_env(rank, ips,
                                       coordinator_port=port))
        env['JAX_PLATFORMS'] = 'cpu'
        env.pop('PALLAS_AXON_POOL_IPS', None)
        env['XLA_FLAGS'] = _with_device_count(
            env.get('XLA_FLAGS', ''), args.local)
        env['_SKYTPU_HYBRID_ROLE'] = 'child'
        env['_SKYTPU_HYBRID_OUT'] = os.path.join(
            tmpdir, f'rank{rank}.json')
        children.append(
            subprocess.Popen([sys.executable, '-m',
                              'skypilot_tpu.parallel.hybrid_check',
                              '--procs', str(args.procs),
                              '--local', str(args.local)],
                             env=env))
    rcs = [p.wait(timeout=600) for p in children]
    if any(rcs):
        print(f'hybrid_check: child rcs={rcs}', file=sys.stderr)
        return 1

    per_rank = []
    for rank in range(args.procs):
        with open(os.path.join(tmpdir, f'rank{rank}.json'),
                  encoding='utf-8') as f:
            per_rank.append(json.load(f)['losses'])
    # Every rank must report the identical (psum-replicated) loss.
    for rank, losses in enumerate(per_rank[1:], 1):
        assert losses == per_rank[0], (rank, losses, per_rank[0])

    # Single-process oracle in THIS process (no jax backend touched
    # until now, so the device count/platform can still be forced).
    n = args.procs * args.local
    os.environ['XLA_FLAGS'] = _with_device_count(
        os.environ.get('XLA_FLAGS', ''), n)
    _force_cpu()
    oracle = _oracle(args.procs, args.local)

    import numpy as np
    ok = np.allclose(per_rank[0], oracle, rtol=1e-4, atol=1e-5)
    print(f'hybrid_check: {args.procs} procs x {args.local} devices '
          f'losses={per_rank[0]} oracle={oracle} parity={ok}')
    if not ok:
        return 1
    print(f'hybrid_check({args.procs}x{args.local}): OK')
    return 0


if __name__ == '__main__':
    sys.exit(main())
