"""On-cluster agent constants.

Counterpart of reference ``sky/skylet/constants.py``: runtime version
gate (client refuses to talk to an older agent), canonical directory
layout on every cluster host, and the env-var contract re-export.
"""
import os

# Bumped whenever the client<->agent codegen protocol changes
# (reference SKYLET_VERSION, sky/skylet/constants.py:92).
AGENT_VERSION = 1

# Directory on the head host holding all agent state for a cluster.
# Local-cloud clusters override via --state-dir so many clusters can
# coexist on one machine.
DEFAULT_STATE_DIR = '~/.skytpu-agent'

# Remote path of the synced workdir (reference SKY_REMOTE_WORKDIR).
REMOTE_WORKDIR = '~/skytpu_workdir'

HOSTS_FILE = 'hosts.json'
JOBS_DB = 'jobs.db'
AUTOSTOP_FILE = 'autostop.json'
LAST_ACTIVITY_FILE = 'last_activity'
AGENT_PID_FILE = 'agentd.pid'
AGENT_LOG = 'agentd.log'

# Seconds between agentd event ticks (reference
# events.EVENT_CHECKING_INTERVAL_SECONDS = 20).
EVENT_INTERVAL_SECONDS = float(os.environ.get(
    'SKYTPU_AGENT_EVENT_INTERVAL', '20'))


def jobs_dir(state_dir: str) -> str:
    return os.path.join(state_dir, 'jobs')


def job_dir(state_dir: str, job_id: int) -> str:
    return os.path.join(jobs_dir(state_dir), str(job_id))
