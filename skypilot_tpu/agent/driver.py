"""Gang job driver — one detached process per job.

TPU-native replacement for the reference's generated Ray driver program
(RayCodeGen, sky/backends/cloud_vm_ray_backend.py:225-672). Where the
reference builds a Ray placement group with STRICT_SPREAD bundles and
wraps each rank in a `ray.remote` bash task, a TPU pod slice is already
gang-provisioned — so the driver simply fans out over every host with a
command runner, injects the rank/IP/topology env contract, streams
per-rank output into rank files plus a merged run.log, and reduces the
exit codes. Setup failure on any host -> FAILED_SETUP; any nonzero run
rc -> FAILED; all zero -> SUCCEEDED.

Runs on the head host (or locally for the Local cloud), spawned by
job_lib.schedule_step.
"""
from __future__ import annotations

import argparse
import json
import os
import threading
import time
from typing import Dict, List

from skypilot_tpu.agent import autostop_lib
from skypilot_tpu.agent import constants
from skypilot_tpu.agent import job_lib
from skypilot_tpu.agent import log_lib
from skypilot_tpu.utils import command_runner as runner_lib
from skypilot_tpu.utils import env_contract
from skypilot_tpu.utils import fault_injection
from skypilot_tpu.utils import subprocess_utils

JobStatus = job_lib.JobStatus

# Worker liveness probing (weak spot of head-only agents: a hung
# worker host used to be visible only as a hung SSH). Overridable for
# tests.
_PROBE_INTERVAL = float(os.environ.get('SKYTPU_WORKER_PROBE_INTERVAL',
                                       '30'))
_PROBE_THRESHOLD = int(os.environ.get('SKYTPU_WORKER_PROBE_THRESHOLD',
                                      '3'))


def monitor_workers(runners: List[runner_lib.CommandRunner],
                    stop_event: threading.Event,
                    on_dead,
                    interval: float = None,
                    threshold: int = None) -> None:
    """Probe every host while ranks run; after ``threshold``
    consecutive failed probes on any host, call ``on_dead(rank)``.

    The reference has no equivalent (its workers are reached only by
    in-flight SSH; a dead worker hangs the job until TCP gives up) —
    here a wedged TPU-VM worker converts into a clean job failure the
    jobs controller can treat as a preemption and recover from.

    One prober thread per host: a single wedged host blocking in its
    SSH probe must not delay detection of (or probes to) the others.
    ``on_dead`` never fires after ``stop_event`` is set, so a probe
    in flight while the job finishes cannot fail a succeeded job.
    """
    interval = _PROBE_INTERVAL if interval is None else interval
    threshold = _PROBE_THRESHOLD if threshold is None else threshold

    death = threading.Event()

    def probe_host(rank: int) -> None:
        runner = runners[rank]
        misses = 0
        while not stop_event.wait(interval):
            if death.is_set():
                return
            try:
                ok = runner.check_connection()
            except Exception:  # pylint: disable=broad-except
                ok = False
            # Chaos site: a fired fault plays a dead worker heartbeat
            # (match {"rank": N} targets one host = partial-gang loss).
            if fault_injection.poll(
                    'agent.worker_probe', rank=rank,
                    host_id=getattr(runner, 'host_id',
                                    None)) is not None:
                ok = False
            misses = 0 if ok else misses + 1
            if misses >= threshold:
                if not stop_event.is_set():
                    on_dead(rank)
                death.set()
                return

    threads = [
        threading.Thread(target=probe_host, args=(rank,), daemon=True)
        for rank in range(len(runners))
    ]
    for t in threads:
        t.start()
    while not (stop_event.is_set() or death.is_set()):
        time.sleep(min(interval, 0.05))


def load_hosts(state_dir: str) -> List[Dict]:
    path = os.path.join(state_dir, constants.HOSTS_FILE)
    with open(path, encoding='utf-8') as f:
        return json.load(f)


class _MergedLog:
    """Thread-safe merged log with rank prefixes."""

    def __init__(self, path: str, multi_rank: bool) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self._f = open(path, 'a', buffering=1, encoding='utf-8')
        self._lock = threading.Lock()
        self._multi = multi_rank

    def writer(self, rank: int):

        def write(line: str) -> None:
            with self._lock:
                if self._multi:
                    self._f.write(f'(rank {rank}) {line}')
                else:
                    self._f.write(line)

        return write

    def close(self) -> None:
        self._f.close()


def _run_setup(state_dir: str, job_id: int, spec: Dict,
               runners: List[runner_lib.CommandRunner]) -> bool:
    setup = spec.get('setup')
    if not setup:
        return True
    rcs = subprocess_utils.run_in_parallel(
        lambda pair: pair[1].run(
            setup,
            env={**spec.get('env', {}), 'SKYTPU_SETUP_NODE_RANK':
                 str(pair[0])},
            log_path=log_lib.setup_log_path(state_dir, job_id, pair[0]),
            cwd=_work_cwd(spec, pair[1])),
        list(enumerate(runners)))
    return all(rc == 0 for rc in rcs)


def _work_cwd(spec: Dict, runner: runner_lib.CommandRunner):
    if not spec.get('has_workdir'):
        return None
    if isinstance(runner, runner_lib.LocalProcessRunner):
        return runner.translate(constants.REMOTE_WORKDIR)
    return constants.REMOTE_WORKDIR


def _run_ranks(state_dir: str, job_id: int, spec: Dict,
               runners: List[runner_lib.CommandRunner]) -> List[int]:
    num_ranks = len(runners)
    ips = spec.get('ips') or [r.ip for r in runners]
    run_commands: List[str] = spec['run_commands']
    merged = _MergedLog(log_lib.run_log_path(state_dir, job_id),
                        multi_rank=num_ranks > 1)
    rcs: List[int] = [0] * num_ranks

    def run_one(rank: int) -> None:
        cmd = run_commands[rank]
        if cmd is None:
            rcs[rank] = 0
            return
        env = dict(spec.get('env', {}))
        env.update(
            env_contract.make_rank_env(
                rank,
                ips,
                num_chips_per_node=spec.get('num_chips_per_host', 0),
                topology=spec.get('topology', ''),
                accelerator_type=spec.get('accelerator_type', ''),
                task_id=spec.get('task_id', ''),
                cluster_name=spec.get('cluster_name', ''),
                job_id=job_id,
            ))
        rcs[rank] = runners[rank].run(
            cmd,
            env=env,
            log_path=log_lib.rank_log_path(state_dir, job_id, rank),
            line_processor=merged.writer(rank),
            cwd=_work_cwd(spec, runners[rank]),
        )

    threads = [
        threading.Thread(target=run_one, args=(rank,), daemon=True)
        for rank in range(num_ranks)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    merged.close()
    return rcs


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--state-dir', required=True)
    parser.add_argument('--job-id', type=int, required=True)
    args = parser.parse_args()
    state_dir = os.path.expanduser(args.state_dir)
    job_id = args.job_id

    job = job_lib.get_job(state_dir, job_id)
    assert job is not None, (state_dir, job_id)
    spec = job['spec']
    hosts = load_hosts(state_dir)
    runners = [runner_lib.runner_from_host_entry(h) for h in hosts]
    autostop_lib.touch_activity(state_dir)

    try:
        job_lib.set_status(state_dir, job_id, JobStatus.SETTING_UP)
        if not _run_setup(state_dir, job_id, spec, runners):
            job_lib.set_status(state_dir, job_id, JobStatus.FAILED_SETUP)
            return
        job_lib.set_status(state_dir, job_id, JobStatus.RUNNING)
        stop_probing = threading.Event()

        def on_dead(rank: int) -> None:
            print(f'Worker {rank} unreachable for '
                  f'{_PROBE_THRESHOLD} consecutive probes; failing '
                  f'job {job_id}.')
            job_lib.set_status(state_dir, job_id, JobStatus.FAILED)
            # Containered jobs first: docker-exec'd processes are not
            # children of the exec client, so killing our subprocess
            # tree alone would leave them alive inside the container
            # holding TPU devices.
            runner_lib.kill_docker_workloads(
                [r for i, r in enumerate(runners) if i != rank])
            # Kill our whole subprocess tree: the SSH clients driving
            # ranks on still-HEALTHY hosts would otherwise be orphaned
            # and keep their remote processes holding TPU devices into
            # the next scheduled job. Then exit hard — rank threads
            # may be wedged inside SSH to the dead host; the status is
            # already terminal, and agentd's next tick resumes
            # scheduling.
            subprocess_utils.kill_process_tree(os.getpid(),
                                               include_parent=False)
            os._exit(1)

        probe = threading.Thread(
            target=monitor_workers,
            args=(runners, stop_probing, on_dead), daemon=True)
        probe.start()
        try:
            rcs = _run_ranks(state_dir, job_id, spec, runners)
        finally:
            stop_probing.set()
        if any(rc != 0 for rc in rcs):
            print(f'Job {job_id} failed: per-rank return codes {rcs}')
            job_lib.set_status(state_dir, job_id, JobStatus.FAILED)
        else:
            job_lib.set_status(state_dir, job_id, JobStatus.SUCCEEDED)
    except Exception as e:  # pylint: disable=broad-except
        print(f'Driver exception for job {job_id}: {e!r}')
        job_lib.set_status(state_dir, job_id, JobStatus.FAILED)
        raise
    finally:
        autostop_lib.touch_activity(state_dir)
        # Wake the scheduler for the next queued job.
        job_lib.schedule_step(state_dir)


if __name__ == '__main__':
    main()
