"""Agent CLI — the codegen target the client invokes on the head host.

Replacement for the reference's `python -c <generated code>` pattern
(JobLibCodeGen, sky/skylet/job_lib.py:930): the client runs
``python -m skypilot_tpu.agent.cli <op> --state-dir ...`` over the
cluster's command runner and parses one JSON document from stdout
(between sentinel markers, so stray prints from login shells don't
corrupt parsing).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any

from skypilot_tpu.agent import autostop_lib
from skypilot_tpu.agent import constants
from skypilot_tpu.agent import job_lib
from skypilot_tpu.agent import log_lib

BEGIN = '<skytpu-agent-output>'
END = '</skytpu-agent-output>'


def emit(payload: Any) -> None:
    print(BEGIN + json.dumps(payload) + END, flush=True)


def parse_output(text: str) -> Any:
    start = text.rfind(BEGIN)
    end = text.rfind(END)
    if start == -1 or end == -1 or end < start:
        raise ValueError(f'No agent output found in: {text[-500:]!r}')
    return json.loads(text[start + len(BEGIN):end])


def main() -> None:
    parser = argparse.ArgumentParser(prog='skytpu-agent')
    parser.add_argument('--state-dir', default=constants.DEFAULT_STATE_DIR)
    sub = parser.add_subparsers(dest='op', required=True)

    p = sub.add_parser('add-job')
    p.add_argument('--name', default=None)
    p.add_argument('--username', required=True)
    p.add_argument('--run-timestamp', required=True)
    p.add_argument('--resources', default='')
    p.add_argument('--spec-json', required=True,
                   help='JobSpec as a JSON string')

    p = sub.add_parser('queue-job')
    p.add_argument('--job-id', type=int, required=True)

    p = sub.add_parser('job-status')
    p.add_argument('--job-ids', type=int, nargs='*', default=None)

    sub.add_parser('queue')

    p = sub.add_parser('cancel')
    p.add_argument('--job-ids', type=int, nargs='*', default=None)

    p = sub.add_parser('tail-logs')
    p.add_argument('--job-id', type=int, default=None)
    p.add_argument('--follow', action='store_true')
    p.add_argument('--tail', type=int, default=0)

    p = sub.add_parser('set-autostop')
    p.add_argument('--idle-minutes', type=int, required=True)
    p.add_argument('--down', action='store_true')
    p.add_argument('--provider-name', required=True)
    p.add_argument('--cluster-name-on-cloud', required=True)
    p.add_argument('--region', required=True)
    p.add_argument('--zone', default=None)

    sub.add_parser('version')

    args = parser.parse_args()
    state_dir = os.path.expanduser(args.state_dir)

    if args.op == 'add-job':
        spec = json.loads(args.spec_json)
        job_id = job_lib.add_job(state_dir, args.name, args.username,
                                 args.run_timestamp, args.resources, spec)
        emit({'job_id': job_id})
    elif args.op == 'queue-job':
        job_lib.queue_job(state_dir, args.job_id)
        emit({'ok': True})
    elif args.op == 'job-status':
        job_lib.update_dead_drivers(state_dir)
        if args.job_ids:
            # Unknown ids map to null (core.job_status's
            # Dict[int, Optional[JobStatus]] contract).
            emit({
                str(jid): (j['status'].value if j is not None else None)
                for jid in args.job_ids
                for j in [job_lib.get_job(state_dir, jid)]
            })
        else:
            jobs = job_lib.get_jobs(state_dir)[:1]
            emit({
                str(j['job_id']): j['status'].value
                for j in jobs if j is not None
            })
    elif args.op == 'queue':
        job_lib.update_dead_drivers(state_dir)
        jobs = job_lib.get_jobs(state_dir)
        emit([{
            'job_id': j['job_id'],
            'name': j['name'],
            'username': j['username'],
            'submitted_at': j['submitted_at'],
            'status': j['status'].value,
            'start_at': j['start_at'],
            'end_at': j['end_at'],
            'resources': j['resources'],
        } for j in jobs])
    elif args.op == 'cancel':
        job_ids = args.job_ids
        if not job_ids:
            running = job_lib.get_jobs(
                state_dir, [job_lib.JobStatus.SETTING_UP,
                            job_lib.JobStatus.RUNNING,
                            job_lib.JobStatus.PENDING])
            job_ids = [j['job_id'] for j in running]
        cancelled = [
            j for j in job_ids if job_lib.cancel_job(state_dir, j)
        ]
        emit({'cancelled': cancelled})
    elif args.op == 'tail-logs':
        # Streams raw lines (not JSON): consumed with stream_logs=True.
        for line in log_lib.tail_logs(state_dir, args.job_id,
                                      follow=args.follow, tail=args.tail):
            sys.stdout.write(line)
            sys.stdout.flush()
    elif args.op == 'set-autostop':
        autostop_lib.set_autostop(state_dir, args.idle_minutes, args.down,
                                  args.provider_name,
                                  args.cluster_name_on_cloud, args.region,
                                  args.zone)
        emit({'ok': True})
    elif args.op == 'version':
        emit({'version': constants.AGENT_VERSION})


if __name__ == '__main__':
    main()
