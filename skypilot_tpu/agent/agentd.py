"""agentd — the per-cluster daemon (skylet equivalent).

Re-design of reference ``sky/skylet/skylet.py:18-36`` +
``sky/skylet/events.py``: an event loop on the head host ticking every
EVENT_INTERVAL_SECONDS. Events:

- JobSchedulerEvent: reconcile dead drivers, start next queued job.
- AutostopEvent: if idle budget exceeded, stop/terminate the cluster
  *from the cluster* through the provision layer (the Local provider
  makes this testable hermetically; on GCP the agent uses the TPU/GCE
  APIs with the cluster's service account).

Run: ``python -m skypilot_tpu.agent.agentd --state-dir <dir>`` —
daemonized by the backend at provision time.
"""
from __future__ import annotations

import argparse
import os
import time

from skypilot_tpu.agent import autostop_lib
from skypilot_tpu.agent import constants
from skypilot_tpu.agent import job_lib
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)


class Event:
    interval_seconds: float = constants.EVENT_INTERVAL_SECONDS

    def __init__(self, state_dir: str) -> None:
        self.state_dir = state_dir
        self._last = 0.0

    def maybe_run(self) -> None:
        now = time.time()
        if now - self._last < self.interval_seconds:
            return
        self._last = now
        try:
            self.run()
        except Exception as e:  # pylint: disable=broad-except
            logger.exception('%s failed: %r', type(self).__name__, e)

    def run(self) -> None:
        raise NotImplementedError


class JobSchedulerEvent(Event):

    def run(self) -> None:
        job_lib.schedule_step(self.state_dir)


class AutostopEvent(Event):

    def run(self) -> None:
        config = autostop_lib.get_autostop(self.state_dir)
        if not config or config['idle_minutes'] < 0:
            return
        # Busy clusters are never stopped.
        active = job_lib.get_jobs(self.state_dir,
                                  job_lib.JobStatus.nonterminal_statuses())
        if active:
            autostop_lib.touch_activity(self.state_dir)
            return
        idle = autostop_lib.idle_seconds(self.state_dir)
        if idle < config['idle_minutes'] * 60:
            return
        logger.info('Autostop: idle %.0fs >= %d min; %s cluster.', idle,
                    config['idle_minutes'],
                    'terminating' if config['down'] else 'stopping')
        from skypilot_tpu import provision
        if config['down']:
            provision.terminate_instances(config['provider_name'],
                                          config['cluster_name_on_cloud'],
                                          config['region'], config['zone'])
        else:
            provision.stop_instances(config['provider_name'],
                                     config['cluster_name_on_cloud'],
                                     config['region'], config['zone'])
        # Our cluster is gone (or stopped); this daemon's work is done.
        # On real clouds the host dies with the instance; on the Local
        # cloud we must exit explicitly. SystemExit bypasses the event
        # loop's broad Exception handler.
        raise SystemExit(0)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--state-dir', default=constants.DEFAULT_STATE_DIR)
    parser.add_argument('--interval', type=float,
                        default=None, help='override event tick seconds')
    args = parser.parse_args()
    state_dir = os.path.expanduser(args.state_dir)
    os.makedirs(state_dir, exist_ok=True)
    with open(os.path.join(state_dir, constants.AGENT_PID_FILE), 'w',
              encoding='utf-8') as f:
        f.write(str(os.getpid()))

    # Restart recovery: reconcile jobs whose driver died while agentd
    # was down (pid-liveness-checked, so drivers that survived an
    # agentd-only restart are left alone).
    job_lib.update_dead_drivers(state_dir)

    events = [JobSchedulerEvent(state_dir), AutostopEvent(state_dir)]
    if args.interval is not None:
        for e in events:
            e.interval_seconds = args.interval
    logger.info('agentd started for %s (tick %.1fs)', state_dir,
                events[0].interval_seconds)
    hosts_path = os.path.join(state_dir, constants.HOSTS_FILE)
    while True:
        # hosts.json is written by the provisioner and never recreated
        # here, so its absence reliably means the cluster was torn down
        # (agentd's own startup may race teardown and re-mkdir the
        # state dir — checking the dir alone is not enough).
        if not os.path.exists(hosts_path):
            logger.info('%s removed; agentd exiting.', hosts_path)
            return
        for event in events:
            event.maybe_run()
        time.sleep(min(e.interval_seconds for e in events) / 4)


if __name__ == '__main__':
    main()
