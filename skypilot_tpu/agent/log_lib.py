"""Job log files and tailing.

Re-design of reference ``sky/skylet/log_lib.py`` (tail_logs /
_follow_job_logs :388,304). Per-job layout under the agent state dir::

    jobs/<id>/driver.log      gang driver output
    jobs/<id>/setup-<k>.log   per-host setup
    jobs/<id>/rank-<k>.log    per-rank run output
    jobs/<id>/run.log         merged, rank-prefixed stream (tail target)
"""
from __future__ import annotations

import os
import time
from typing import Iterator, Optional

from skypilot_tpu.agent import constants
from skypilot_tpu.agent import job_lib

_POLL_SECONDS = 0.2


def run_log_path(state_dir: str, job_id: int) -> str:
    return os.path.join(constants.job_dir(state_dir, job_id), 'run.log')


def rank_log_path(state_dir: str, job_id: int, rank: int) -> str:
    return os.path.join(constants.job_dir(state_dir, job_id),
                        f'rank-{rank}.log')


def setup_log_path(state_dir: str, job_id: int, rank: int) -> str:
    return os.path.join(constants.job_dir(state_dir, job_id),
                        f'setup-{rank}.log')


def tail_logs(state_dir: str,
              job_id: Optional[int],
              follow: bool = True,
              tail: int = 0) -> Iterator[str]:
    """Yield log lines; with follow=True, stream until the job ends.

    Survives the log file not existing yet (job still PENDING).
    """
    if job_id is None:
        job_id = job_lib.get_latest_job_id(state_dir)
        if job_id is None:
            yield 'No jobs submitted to this cluster.\n'
            return
    path = run_log_path(state_dir, job_id)

    # Wait for the job to start producing logs.
    deadline_notice = time.time() + 5
    while follow and not os.path.exists(path):
        job = job_lib.get_job(state_dir, job_id)
        if job is None:
            yield f'Job {job_id} not found.\n'
            return
        if job['status'].is_terminal():
            break
        if time.time() > deadline_notice:
            yield f'Waiting for job {job_id} to start...\n'
            deadline_notice = float('inf')
        time.sleep(_POLL_SECONDS)

    if not os.path.exists(path):
        # Job finished without producing a run log (e.g. failed setup):
        # surface setup/driver logs instead.
        for fallback in (setup_log_path(state_dir, job_id, 0),
                         os.path.join(constants.job_dir(state_dir, job_id),
                                      'driver.log')):
            if os.path.exists(fallback):
                with open(fallback, encoding='utf-8') as f:
                    yield from f
                return
        yield f'Job {job_id} produced no logs.\n'
        return

    with open(path, encoding='utf-8') as f:
        if tail > 0:
            lines = f.readlines()
            yield from lines[-tail:]
        else:
            yield from _read_available(f)
        while follow:
            job = job_lib.get_job(state_dir, job_id)
            line_seen = False
            for line in _read_available(f):
                line_seen = True
                yield line
            if job is None or job['status'].is_terminal():
                # One final drain after the status flips.
                yield from _read_available(f)
                return
            if not line_seen:
                time.sleep(_POLL_SECONDS)


def _read_available(f) -> Iterator[str]:
    while True:
        line = f.readline()
        if not line:
            return
        yield line
