"""Autostop bookkeeping on the cluster.

Re-design of reference ``sky/skylet/autostop_lib.py:55``: the client
stores an idle budget (+ stop-vs-down flag) in the agent state dir; the
agentd AutostopEvent compares it against the last-activity timestamp
(touched by job drivers) and, when exceeded, tears the cluster down
*from the cluster itself* via the cloud API.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

from skypilot_tpu.agent import constants

AUTOSTOP_DISABLED = -1


def _path(state_dir: str) -> str:
    return os.path.join(os.path.expanduser(state_dir),
                        constants.AUTOSTOP_FILE)


def _activity_path(state_dir: str) -> str:
    return os.path.join(os.path.expanduser(state_dir),
                        constants.LAST_ACTIVITY_FILE)


def set_autostop(state_dir: str, idle_minutes: int, down: bool,
                 provider_name: str, cluster_name_on_cloud: str,
                 region: str, zone: Optional[str]) -> None:
    os.makedirs(os.path.expanduser(state_dir), exist_ok=True)
    with open(_path(state_dir), 'w', encoding='utf-8') as f:
        json.dump(
            {
                'idle_minutes': idle_minutes,
                'down': down,
                'provider_name': provider_name,
                'cluster_name_on_cloud': cluster_name_on_cloud,
                'region': region,
                'zone': zone,
                'set_at': time.time(),
            }, f)
    touch_activity(state_dir)


def get_autostop(state_dir: str) -> Optional[Dict[str, Any]]:
    try:
        with open(_path(state_dir), encoding='utf-8') as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None


def touch_activity(state_dir: str) -> None:
    os.makedirs(os.path.expanduser(state_dir), exist_ok=True)
    with open(_activity_path(state_dir), 'w', encoding='utf-8') as f:
        f.write(str(time.time()))


def last_activity(state_dir: str) -> float:
    try:
        with open(_activity_path(state_dir), encoding='utf-8') as f:
            return float(f.read().strip())
    except (FileNotFoundError, ValueError):
        return 0.0


def idle_seconds(state_dir: str) -> float:
    config = get_autostop(state_dir)
    anchor = max(last_activity(state_dir),
                 config['set_at'] if config else 0.0)
    if anchor == 0.0:
        return 0.0
    return time.time() - anchor
