"""Per-cluster job table + FIFO scheduler.

Re-design of reference ``sky/skylet/job_lib.py`` (JobStatus :121,
JobScheduler :204, driver liveness :538). State lives in a SQLite DB in
the cluster's agent state dir. Scheduling is FIFO in submission order
with a resource-class split:

- **TPU jobs** (spec carries an accelerator_type) are slice-exclusive
  — a TPU slice is one atomic resource, so exactly one gang owns it
  at a time (no fractional-accelerator packing to do).
- **CPU jobs** pack concurrently up to ``SKYTPU_MAX_CONCURRENT_JOBS``
  (default: the host's CPU count), the role of the reference's
  resource-counting JobScheduler (:204) on controller-class clusters.

FIFO order is never bypassed: the head of the queue waits for what it
needs rather than being overtaken, so a TPU job can't be starved by a
stream of small CPU jobs.
"""
from __future__ import annotations

import json
import os
import sqlite3
import time
from typing import Any, Dict, List, Optional

import filelock

from skypilot_tpu.agent import constants
from skypilot_tpu.utils import log as sky_logging
from skypilot_tpu.utils import statedb
from skypilot_tpu.utils import status_lib
from skypilot_tpu.utils import subprocess_utils

logger = sky_logging.init_logger(__name__)

JobStatus = status_lib.JobStatus


def _db_path(state_dir: str) -> str:
    return os.path.join(os.path.expanduser(state_dir), constants.JOBS_DB)


_LOCKS: Dict[str, filelock.FileLock] = {}


def _lock(state_dir: str) -> filelock.FileLock:
    """One FileLock object per path — FileLock is only reentrant when
    the same instance is re-acquired, and schedule_step nests over
    set_status."""
    path = _db_path(state_dir) + '.lock'
    if path not in _LOCKS:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        _LOCKS[path] = filelock.FileLock(path)
    return _LOCKS[path]


def _connect(state_dir: str) -> sqlite3.Connection:
    # statedb.connect: shared WAL/busy_timeout/autocommit recipe
    # (docs/crash_recovery.md); cross-process write ordering here is
    # already serialized by the agent's file lock.
    conn = statedb.connect(_db_path(state_dir), row_factory=False)
    conn.execute("""\
        CREATE TABLE IF NOT EXISTS jobs (
            job_id INTEGER PRIMARY KEY AUTOINCREMENT,
            name TEXT,
            username TEXT,
            submitted_at REAL,
            status TEXT,
            run_timestamp TEXT,
            start_at REAL,
            end_at REAL,
            resources TEXT,
            driver_pid INTEGER,
            spec TEXT)""")
    conn.commit()
    return conn


# ----------------------------------------------------------------------
def add_job(state_dir: str,
            name: Optional[str],
            username: str,
            run_timestamp: str,
            resources_str: str,
            spec: Dict[str, Any]) -> int:
    """Insert a job in INIT status; returns job_id."""
    with _lock(state_dir):
        conn = _connect(state_dir)
        cur = conn.execute(
            """INSERT INTO jobs
               (name, username, submitted_at, status, run_timestamp,
                resources, spec)
               VALUES (?,?,?,?,?,?,?)""",
            (name, username, time.time(), JobStatus.INIT.value,
             run_timestamp, resources_str, json.dumps(spec)))
        conn.commit()
        job_id = cur.lastrowid
    os.makedirs(constants.job_dir(state_dir, job_id), exist_ok=True)
    return int(job_id)


def queue_job(state_dir: str, job_id: int) -> None:
    """INIT -> PENDING; then let the scheduler try to start it."""
    set_status(state_dir, job_id, JobStatus.PENDING)
    schedule_step(state_dir)


def set_status(state_dir: str, job_id: int, status: JobStatus) -> None:
    with _lock(state_dir):
        conn = _connect(state_dir)
        updates = {'status': status.value}
        if status == JobStatus.SETTING_UP:
            updates['start_at'] = time.time()
        if status.is_terminal():
            updates['end_at'] = time.time()
        sets = ', '.join(f'{k}=?' for k in updates)
        conn.execute(f'UPDATE jobs SET {sets} WHERE job_id=?',
                     (*updates.values(), job_id))
        conn.commit()


def set_driver_pid(state_dir: str, job_id: int, pid: int) -> None:
    with _lock(state_dir):
        conn = _connect(state_dir)
        conn.execute('UPDATE jobs SET driver_pid=? WHERE job_id=?',
                     (pid, job_id))
        conn.commit()


def get_job(state_dir: str, job_id: int) -> Optional[Dict[str, Any]]:
    rows = _query(state_dir, 'WHERE job_id=?', (job_id,))
    return rows[0] if rows else None


def get_latest_job_id(state_dir: str) -> Optional[int]:
    rows = _query(state_dir, 'ORDER BY job_id DESC LIMIT 1', ())
    return rows[0]['job_id'] if rows else None


def get_jobs(state_dir: str,
             statuses: Optional[List[JobStatus]] = None
             ) -> List[Dict[str, Any]]:
    rows = _query(state_dir, 'ORDER BY job_id DESC', ())
    if statuses is not None:
        wanted = {s for s in statuses}
        rows = [r for r in rows if r['status'] in wanted]
    return rows


def _query(state_dir: str, suffix: str, params: tuple
           ) -> List[Dict[str, Any]]:
    if not os.path.exists(_db_path(state_dir)):
        return []
    conn = _connect(state_dir)
    cur = conn.execute(
        f"""SELECT job_id, name, username, submitted_at, status,
                   run_timestamp, start_at, end_at, resources, driver_pid,
                   spec FROM jobs {suffix}""", params)
    out = []
    for row in cur.fetchall():
        (job_id, name, username, submitted_at, status, run_timestamp,
         start_at, end_at, resources, driver_pid, spec) = row
        out.append({
            'job_id': job_id,
            'name': name,
            'username': username,
            'submitted_at': submitted_at,
            'status': JobStatus(status),
            'run_timestamp': run_timestamp,
            'start_at': start_at,
            'end_at': end_at,
            'resources': resources,
            'driver_pid': driver_pid,
            'spec': json.loads(spec) if spec else None,
        })
    return out


# ----------------------------------------------------------------------
# Scheduler
def _driver_alive(pid: Optional[int]) -> bool:
    return subprocess_utils.process_alive(pid)


def update_dead_drivers(state_dir: str) -> None:
    """Jobs whose driver died without a terminal status -> FAILED.

    The reference does the same liveness reconciliation in
    job_lib.py:538 (`_update_status`).
    """
    for job in get_jobs(state_dir, JobStatus.nonterminal_statuses()):
        if job['status'] in (JobStatus.INIT, JobStatus.PENDING):
            continue
        if not _driver_alive(job['driver_pid']):
            logger.warning('Job %s driver (pid %s) died; marking FAILED.',
                           job['job_id'], job['driver_pid'])
            set_status(state_dir, job['job_id'], JobStatus.FAILED)


def _is_tpu_job(job: Dict[str, Any]) -> bool:
    spec = job.get('spec') or {}
    return bool(spec.get('accelerator_type'))


def _max_concurrent_jobs() -> int:
    try:
        return max(1, int(os.environ['SKYTPU_MAX_CONCURRENT_JOBS']))
    except (KeyError, ValueError):
        return max(1, os.cpu_count() or 1)


def _can_start(job: Dict[str, Any],
               active: List[Dict[str, Any]]) -> bool:
    if not active:
        return True
    # TPU jobs own the slice exclusively, in both directions.
    if _is_tpu_job(job) or any(_is_tpu_job(a) for a in active):
        return False
    return len(active) < _max_concurrent_jobs()


def _start_job(state_dir: str, job: Dict[str, Any]) -> int:
    job_id = job['job_id']
    log_path = os.path.join(constants.job_dir(state_dir, job_id),
                            'driver.log')
    pid = subprocess_utils.daemonize(
        ['python', '-u', '-m', 'skypilot_tpu.agent.driver',
         '--state-dir', state_dir, '--job-id', str(job_id)],
        log_path=log_path)
    set_driver_pid(state_dir, job_id, pid)
    # Driver moves it to SETTING_UP/RUNNING; mark it out of PENDING
    # now so a concurrent schedule_step won't double-start.
    set_status(state_dir, job_id, JobStatus.SETTING_UP)
    return job_id


def schedule_step(state_dir: str) -> Optional[int]:
    """Start every PENDING job the concurrency policy admits, oldest
    first and without queue bypass (the head waits for what it needs;
    nothing overtakes it).

    Returns the first started job_id, or None. Each driver process is
    spawned detached (`python -m skypilot_tpu.agent.driver`), exactly
    one per job, like the reference's generated driver program.
    """
    first: Optional[int] = None
    with _lock(state_dir):
        update_dead_drivers(state_dir)
        while True:
            active = get_jobs(state_dir,
                              [JobStatus.SETTING_UP, JobStatus.RUNNING])
            pending = get_jobs(state_dir, [JobStatus.PENDING])
            if not pending:
                return first
            job = pending[-1]  # oldest (rows are DESC)
            if not _can_start(job, active):
                return first
            started = _start_job(state_dir, job)
            if first is None:
                first = started


def cancel_job(state_dir: str, job_id: int) -> bool:
    """Kill the driver tree and mark CANCELLED. Returns True if it was
    non-terminal."""
    job = get_job(state_dir, job_id)
    if job is None:
        from skypilot_tpu import exceptions
        raise exceptions.JobNotFoundError(f'No job {job_id} on cluster.')
    if job['status'].is_terminal():
        return False
    started = (job['driver_pid'] or
               job['status'] in (JobStatus.SETTING_UP,
                                 JobStatus.RUNNING))
    if job['driver_pid']:
        subprocess_utils.kill_process_tree(job['driver_pid'])
    # Containered jobs: the killed tree holds only docker-exec
    # clients; the workload survives inside the container. Restart
    # each host's container so cancel actually frees the TPU. Gated
    # on the job having STARTED — cancelling a PENDING job must not
    # SIGKILL whatever other job currently owns the containers.
    if started:
        try:
            hosts_path = os.path.join(os.path.expanduser(state_dir),
                                      constants.HOSTS_FILE)
            with open(hosts_path, encoding='utf-8') as f:
                entries = json.load(f)
            from skypilot_tpu.utils import command_runner as runner_lib
            runner_lib.kill_docker_workloads([
                runner_lib.runner_from_host_entry(e) for e in entries
                if e.get('docker')
            ])
        except (OSError, ValueError):
            pass  # hosts.json gone (teardown race): nothing to kill
    set_status(state_dir, job_id, JobStatus.CANCELLED)
    schedule_step(state_dir)
    return True

