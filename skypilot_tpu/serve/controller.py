"""Service controller: autoscaler loop + LB + replica manager.

Re-design of reference ``sky/serve/controller.py:36`` +
``service.py:139``: one process per service
(``python -m skypilot_tpu.serve.controller <name>``) running the load
balancer (aiohttp, in-process) and a control loop that probes
replicas, feeds LB request counts to the autoscaler, and reconciles
replica count. The reference splits controller and LB into two
processes; one asyncio process is equivalent here and halves the
moving parts.
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import traceback

from skypilot_tpu.serve import autoscalers
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve.load_balancer import LoadBalancer
from skypilot_tpu.serve.replica_managers import ReplicaManager
from skypilot_tpu.serve.serve_state import ReplicaStatus, ServiceStatus
from skypilot_tpu.serve.service_spec import ServiceSpec
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

CONTROL_LOOP_GAP_SECONDS = 10.0


class ServeController:

    def __init__(self, service_name: str,
                 loop_gap: float = CONTROL_LOOP_GAP_SECONDS,
                 lb_port: int = 0) -> None:
        record = serve_state.get_service(service_name)
        assert record is not None, service_name
        self.name = service_name
        self.spec = ServiceSpec.from_yaml_config(record['spec'])
        self.autoscaler = autoscalers.make_autoscaler(self.spec)
        self.replica_manager = ReplicaManager(service_name, self.spec,
                                              record['task'])
        self.load_balancer = LoadBalancer(
            lb_port,
            policy=self.spec.load_balancing_policy,
            on_request=self.autoscaler.record_request)
        self.loop_gap = loop_gap
        self._shutdown = asyncio.Event()

    # ------------------------------------------------------------------
    async def _control_loop(self) -> None:
        target = self.spec.min_replicas
        self.replica_manager.reconcile(target)
        serve_state.set_service_status(self.name,
                                       ServiceStatus.REPLICA_INIT)
        while not self._shutdown.is_set():
            try:
                await asyncio.to_thread(self.replica_manager.probe_all)
                replicas = serve_state.get_replicas(self.name)
                live = [
                    r for r in replicas if r['status'] in
                    (ReplicaStatus.PENDING, ReplicaStatus.PROVISIONING,
                     ReplicaStatus.STARTING, ReplicaStatus.READY,
                     ReplicaStatus.NOT_READY)
                ]
                decision = self.autoscaler.evaluate(len(live))
                await asyncio.to_thread(self.replica_manager.reconcile,
                                        decision.target_replicas)
                urls = self.replica_manager.ready_urls()
                self.load_balancer.set_replica_urls(urls)
                serve_state.set_service_status(
                    self.name, ServiceStatus.READY
                    if urls else ServiceStatus.REPLICA_INIT)
            except Exception:  # pylint: disable=broad-except
                logger.error('Control loop error:\n%s',
                             traceback.format_exc())
            try:
                await asyncio.wait_for(self._shutdown.wait(),
                                       timeout=self.loop_gap)
            except asyncio.TimeoutError:
                pass

    async def run(self) -> None:
        await self.load_balancer.start()
        # Publish the actually-bound port (the row holds the preferred
        # port, possibly 0 = auto; `up` polls for the real one).
        serve_state.set_service_lb_port(self.name,
                                        self.load_balancer.bound_port)
        try:
            await self._control_loop()
        finally:
            await self.load_balancer.stop()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('service_name')
    parser.add_argument('--loop-gap', type=float,
                        default=CONTROL_LOOP_GAP_SECONDS)
    parser.add_argument('--lb-port', type=int, default=0,
                        help='Preferred LB port; 0 = OS-assigned. The '
                        'bound port is written back to serve_state.')
    args = parser.parse_args()
    serve_state.set_service_controller_pid(args.service_name,
                                           os.getpid())
    controller = ServeController(args.service_name,
                                 loop_gap=args.loop_gap,
                                 lb_port=args.lb_port)
    try:
        asyncio.run(controller.run())
    except Exception as e:  # pylint: disable=broad-except
        logger.error('Serve controller crashed:\n%s',
                     traceback.format_exc())
        serve_state.set_service_status(args.service_name,
                                       ServiceStatus.FAILED)
        raise


if __name__ == '__main__':
    main()
