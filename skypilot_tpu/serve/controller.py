"""Service controller: autoscaler loop + LB + replica manager.

Re-design of reference ``sky/serve/controller.py:36`` +
``service.py:139``: one process per service
(``python -m skypilot_tpu.serve.controller <name>``) running the load
balancer (aiohttp, in-process) and a control loop that probes
replicas, feeds LB request counts to the autoscaler, and reconciles
replica count. The reference splits controller and LB into two
processes; one asyncio process is equivalent here and halves the
moving parts.
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import traceback
from typing import Optional

from skypilot_tpu import metrics as metrics_lib
from skypilot_tpu import trace as trace_lib
from skypilot_tpu.serve import autoscalers
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve.load_balancer import LoadBalancer
from skypilot_tpu.serve.replica_managers import ReplicaManager
from skypilot_tpu.serve.serve_state import ReplicaStatus, ServiceStatus
from skypilot_tpu.serve.service_spec import ServiceSpec
from skypilot_tpu.utils import log as sky_logging
from skypilot_tpu.utils import statedb

logger = sky_logging.init_logger(__name__)

CONTROL_LOOP_GAP_SECONDS = 10.0


class ServeController:

    def __init__(self, service_name: str,
                 loop_gap: float = CONTROL_LOOP_GAP_SECONDS,
                 lb_port: int = 0) -> None:
        record = serve_state.get_service(service_name)
        assert record is not None, service_name
        self.name = service_name
        self._version = serve_state.get_current_version(service_name)
        self.spec = ServiceSpec.from_yaml_config(record['spec'])
        self.autoscaler = autoscalers.make_autoscaler(
            self.spec, service=service_name)
        # A restarted controller resumes the persisted QPS window +
        # hysteresis clocks instead of starting cold (which would
        # forget demand and downscale a loaded service).
        saved = serve_state.load_autoscaler_state(service_name)
        if saved:
            self.autoscaler.restore(saved)
        self.replica_manager = ReplicaManager(service_name, self.spec,
                                              record['task'],
                                              drain_fn=self._drain_url)
        self.load_balancer = LoadBalancer(
            lb_port,
            policy=self.spec.load_balancing_policy,
            on_request=self.autoscaler.record_request,
            # First-hand unreachability from the data plane demotes
            # the replica NOW instead of after the probe cycle
            # (docs/failover.md); the LB invokes this off its event
            # loop.
            on_replica_down=self.replica_manager.note_unreachable)
        # Spot-native serving (docs/spot_serving.md): each spot
        # preemption feeds the autoscaler's EWMA rate estimator, and
        # a preemption NOTICE proactively migrates the replica's live
        # streams at the LB before the kill lands. Late-bound through
        # self.autoscaler so a rolling update's rebuilt autoscaler
        # keeps receiving events.
        self.replica_manager.on_preemption = self._record_preemption
        self.replica_manager.on_preempt_notice = self._preempt_notice
        self.loop_gap = loop_gap
        self._shutdown = asyncio.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None

    def _drain_url(self, url: str) -> None:
        """Blocking LB drain of a replica URL; called from replica
        teardown threads so in-flight requests finish before the
        cluster goes down (rolling update / downscale)."""
        loop = self._loop
        if loop is None or loop.is_closed():
            return
        try:
            fut = asyncio.run_coroutine_threadsafe(
                self.load_balancer.drain(url), loop)
            fut.result(timeout=90)
        except Exception:  # pylint: disable=broad-except
            logger.warning('Drain of %s did not complete:\n%s', url,
                           traceback.format_exc())

    def _record_preemption(self) -> None:
        record = getattr(self.autoscaler, 'record_preemption', None)
        if record is not None:
            record()

    def _preempt_notice(self, url: str) -> None:
        """Bridge a replica's preemption notice to the LB: stop
        routing to ``url`` and migrate its live streams to survivors
        NOW — blocking the probe thread briefly so the migration is
        in flight before the probe loop (and the cloud's kill clock)
        moves on (docs/spot_serving.md)."""
        loop = self._loop
        if loop is None or loop.is_closed():
            return
        try:
            fut = asyncio.run_coroutine_threadsafe(
                self.load_balancer.mark_preempting(url), loop)
            fut.result(timeout=10)
        except Exception:  # pylint: disable=broad-except
            logger.warning('Preemption migration of %s did not '
                           'complete:\n%s', url,
                           traceback.format_exc())

    def _refresh_version(self) -> None:
        """Pick up a rolling update: when current_version moves, reload
        the spec and rebuild the autoscaler so scaling decisions follow
        the NEW version's policy while old replicas drain."""
        version = serve_state.get_current_version(self.name)
        if version == self._version:
            return
        record = serve_state.get_version_spec(self.name, version)
        if record is None:
            return
        logger.info('Service %s: rolling to version %d.', self.name,
                    version)
        self._version = version
        self.spec = ServiceSpec.from_yaml_config(record['spec'])
        self.replica_manager.spec = self.spec
        self.autoscaler = autoscalers.make_autoscaler(
            self.spec, service=self.name)
        # Demand does not reset because the policy changed: carry the
        # persisted QPS window into the new version's autoscaler.
        saved = serve_state.load_autoscaler_state(self.name)
        if saved:
            self.autoscaler.restore(saved)
        self.load_balancer.on_request = self.autoscaler.record_request

    # ------------------------------------------------------------------
    async def _control_loop(self) -> None:
        # Crash-only startup (docs/crash_recovery.md): settle whatever
        # a dead predecessor left mid-operation — adopt its live
        # replicas, roll its scale-downs forward, roll half-launches
        # back, sweep orphans — BEFORE the first scaling decision, so
        # the autoscaler never counts (or double-launches over) ghost
        # state.
        if statedb.reconcile_enabled():
            with trace_lib.span('serve.reconcile', slow_ok=True,
                                service=self.name):
                await asyncio.to_thread(
                    self.replica_manager.reconcile_on_start)
        # Initial scale-out honors the spot split from the start.
        self.replica_manager.reconcile(self.autoscaler.initial())
        serve_state.set_service_status(self.name,
                                       ServiceStatus.REPLICA_INIT)
        while not self._shutdown.is_set():
            try:
                self._refresh_version()
                await asyncio.to_thread(self.replica_manager.probe_all)
                replicas = serve_state.get_replicas(self.name)
                live = [
                    r for r in replicas if r['status'] in
                    (ReplicaStatus.PENDING, ReplicaStatus.PROVISIONING,
                     ReplicaStatus.STARTING, ReplicaStatus.READY,
                     ReplicaStatus.NOT_READY)
                ]
                latest = [r for r in live
                          if (r.get('version') or 1) == self._version]
                # The autoscaler scales the QPS-serving pool: for a
                # spot service that is the latest-version spot
                # replicas (the on-demand fallback is derived from
                # the same decision), otherwise all latest replicas.
                if self.spec.use_spot:
                    pool = [r for r in latest if r.get('is_spot')]
                else:
                    pool = latest
                num_ready_spot = sum(
                    1 for r in latest if r.get('is_spot') and
                    r['status'] is ReplicaStatus.READY)
                if isinstance(self.autoscaler,
                              autoscalers.SLOAutoscaler):
                    # Feed the latency loop: scrape each ready
                    # replica's p99/est-wait gauges off the event
                    # loop (bounded per-replica timeout) before the
                    # scaling decision reads them.
                    await asyncio.to_thread(
                        self.autoscaler.scrape_replicas,
                        self.replica_manager.ready_urls())
                decision = self.autoscaler.evaluate(
                    len(pool), num_ready_spot=num_ready_spot)
                serve_state.save_autoscaler_state(
                    self.name, self.autoscaler.to_state())
                await asyncio.to_thread(self.replica_manager.reconcile,
                                        decision)
                ready = self.replica_manager.ready_replicas()
                urls = [r['url'] for r in ready]
                # Spot-ness rides along so the LB's tie-break prefers
                # on-demand survivors for new streams, hedges, and
                # resume targets (docs/spot_serving.md).
                self.load_balancer.set_replica_urls(
                    urls,
                    spot_urls=[r['url'] for r in ready
                               if r['is_spot']])
                # Prefix digests ride the same probe cadence
                # (docs/affinity_routing.md): the cache-aware policy
                # scores replicas from what the probes ALREADY
                # fetched — the LB never makes its own HTTP call.
                self.load_balancer.update_prefix_summaries(
                    self.replica_manager.prefix_digests())
                serve_state.set_service_status(
                    self.name, ServiceStatus.READY
                    if urls else ServiceStatus.REPLICA_INIT)
                # Export this controller's counters to the metrics
                # spool (no-op without SKYTPU_METRICS_DIR): any
                # /metrics endpoint on this machine merges them in.
                metrics_lib.dump_snapshot(f'serve.{self.name}')
            except Exception:  # pylint: disable=broad-except
                logger.error('Control loop error:\n%s',
                             traceback.format_exc())
            try:
                await asyncio.wait_for(self._shutdown.wait(),
                                       timeout=self.loop_gap)
            except asyncio.TimeoutError:
                pass

    async def run(self) -> None:
        self._loop = asyncio.get_running_loop()
        await self.load_balancer.start()
        # Publish the actually-bound port (the row holds the preferred
        # port, possibly 0 = auto; `up` polls for the real one).
        serve_state.set_service_lb_port(self.name,
                                        self.load_balancer.bound_port)
        try:
            await self._control_loop()
        finally:
            await self.load_balancer.stop()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('service_name')
    parser.add_argument('--loop-gap', type=float,
                        default=CONTROL_LOOP_GAP_SECONDS)
    parser.add_argument('--lb-port', type=int, default=0,
                        help='Preferred LB port; 0 = OS-assigned. The '
                        'bound port is written back to serve_state.')
    args = parser.parse_args()
    trace_lib.set_component(f'serve.{args.service_name}')
    serve_state.set_service_controller_pid(args.service_name,
                                           os.getpid())
    controller = ServeController(args.service_name,
                                 loop_gap=args.loop_gap,
                                 lb_port=args.lb_port)
    try:
        asyncio.run(controller.run())
    except Exception as e:  # pylint: disable=broad-except
        logger.error('Serve controller crashed:\n%s',
                     traceback.format_exc())
        serve_state.set_service_status(args.service_name,
                                       ServiceStatus.FAILED)
        raise


if __name__ == '__main__':
    main()
