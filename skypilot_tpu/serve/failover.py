"""Per-replica circuit breakers for the serve load balancer.

Replica-failure survivability (docs/failover.md): the probe loop
discovers a dead replica in seconds, but a proxy attempt discovers it
in ONE round trip. The breaker turns that first-hand evidence into
routing: a replica whose proxy attempts fail is ejected from the
pickable set immediately (``closed -> open``), held out for a
cooldown, then re-admitted through a single half-open trial request
(``open -> half_open -> closed``), instead of burning a client
attempt per probe cycle.

State machine::

      +--------+  trip (hard connect failure, or     +------+
      | closed | ----- threshold soft failures) ---> | open |
      +--------+                                     +------+
          ^                                             |
          | trial success                               | cooldown
          | (recovery)                                  v elapsed
          |                 trial failure          +-----------+
          +------------------- re-opens <--------- | half_open |
                                                   +-----------+

A *hard* failure is a connect refused/reset: the replica never
received the request, and a process that will not accept TCP is down,
not slow — one strike opens the breaker. *Soft* failures (timeouts,
mid-stream death, upstream 5xx) count a consecutive streak against
``SKYTPU_LB_BREAKER_THRESHOLD``. Any success resets the streak.

Single-threaded by design: breakers live on the LB's event loop, and
every transition happens synchronously between awaits (``blocked`` /
``acquire`` / ``record_*`` never await). Time is injectable
(``retry.Clock``) so tests drive the cooldown with a FakeClock.
"""
from __future__ import annotations

from typing import Optional

from skypilot_tpu import metrics as metrics_lib
from skypilot_tpu.utils import env_registry
from skypilot_tpu.utils import log as sky_logging
from skypilot_tpu.utils import retry as retry_lib
from skypilot_tpu.utils import statedb

logger = sky_logging.init_logger(__name__)

CLOSED = 'closed'
OPEN = 'open'
HALF_OPEN = 'half_open'

# Gauge encoding of the state (docs/metrics.md): 0 closed (healthy),
# 1 open (ejected), 2 half-open (one trial in flight or allowed).
STATE_VALUES = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}

_M_STATE = metrics_lib.gauge(
    'skytpu_lb_breaker_state',
    'Per-replica circuit-breaker state at the LB: 0 closed '
    '(routable), 1 open (ejected after proxy failures), 2 half-open '
    '(one trial request re-probing the replica). docs/failover.md.',
    labels=('replica',))
_M_TRIPS = metrics_lib.counter(
    'skytpu_lb_breaker_trips_total',
    'Circuit-breaker trips (closed/half-open -> open) per replica: '
    'each is a replica ejected from the routable set on first-hand '
    'proxy evidence instead of waiting out probe cycles.',
    labels=('replica',))
_M_RECOVERIES = metrics_lib.counter(
    'skytpu_lb_breaker_recoveries_total',
    'Circuit-breaker recoveries (half-open trial succeeded -> '
    'closed) per replica.',
    labels=('replica',))


def breaker_threshold() -> int:
    return max(1, int(env_registry.get(
        env_registry.SKYTPU_LB_BREAKER_THRESHOLD, '3')))


def breaker_cooldown_s() -> float:
    return max(0.0, float(env_registry.get(
        env_registry.SKYTPU_LB_BREAKER_COOLDOWN_S, '2')))


class _StateDBClock(retry_lib.Clock):
    """Default clock: the injectable control-plane wall clock
    (statedb.set_wall_clock steers it in tests and the fleet
    harness), resolved per call rather than captured at import."""

    def now(self) -> float:
        return statedb.wall_now()

    def sleep(self, seconds: float) -> None:
        statedb.wall_clock().sleep(seconds)


class CircuitBreaker:
    """One replica's breaker. The LB consults :meth:`blocked` when it
    builds a pick-exclusion set, calls :meth:`acquire` for the URL it
    actually picked (this is what consumes the single half-open
    trial), and reports the attempt outcome via
    :meth:`record_success` / :meth:`record_failure`."""

    def __init__(self, replica: str,
                 threshold: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 clock: Optional[retry_lib.Clock] = None) -> None:
        self.replica = replica
        self.threshold = (breaker_threshold()
                          if threshold is None else max(1, threshold))
        self.cooldown_s = (breaker_cooldown_s()
                           if cooldown_s is None else cooldown_s)
        self._clock = clock or _StateDBClock()
        self.state = CLOSED
        self._soft_streak = 0
        self._opened_at: Optional[float] = None
        self._trial_inflight = False
        self.trips = 0
        self.recoveries = 0
        _M_STATE.set(STATE_VALUES[CLOSED], replica=replica)

    # ------------------------------------------------------- queries
    def blocked(self) -> bool:
        """True while the replica must not be picked: open with the
        cooldown still running, or half-open with its one trial
        already in flight. An open breaker whose cooldown elapsed is
        NOT blocked — the next pick becomes the half-open trial."""
        if self.state == CLOSED:
            return False
        if self.state == OPEN:
            assert self._opened_at is not None
            return (self._clock.now() - self._opened_at <
                    self.cooldown_s)
        return self._trial_inflight          # HALF_OPEN

    # ----------------------------------------------------- lifecycle
    def acquire(self) -> None:
        """The LB picked this replica. In CLOSED this is a no-op; an
        elapsed-cooldown OPEN transitions to HALF_OPEN and marks the
        single trial in flight (further picks are blocked until the
        trial resolves)."""
        if self.state == OPEN and not self.blocked():
            self._set_state(HALF_OPEN)
            self._trial_inflight = True
        elif self.state == HALF_OPEN and not self._trial_inflight:
            self._trial_inflight = True

    def record_success(self) -> None:
        self._soft_streak = 0
        if self.state == HALF_OPEN:
            self.recoveries += 1
            _M_RECOVERIES.inc(1, replica=self.replica)
            logger.info('Breaker for %s: half-open trial succeeded; '
                        'replica re-admitted.', self.replica)
        if self.state != CLOSED:
            self._set_state(CLOSED)
        self._trial_inflight = False

    def record_failure(self, hard: bool = False) -> None:
        """``hard`` = connect refused/reset (the replica never saw
        the request): trips immediately. Soft failures trip after
        ``threshold`` consecutive ones. Either failure kind re-opens
        a half-open breaker."""
        if self.state == HALF_OPEN:
            self._trial_inflight = False
            self._trip('half-open trial failed')
            return
        if hard:
            self._soft_streak = 0
            if self.state != OPEN:
                self._trip('connect failure')
            else:
                self._opened_at = self._clock.now()
            return
        self._soft_streak += 1
        if self.state == CLOSED and \
                self._soft_streak >= self.threshold:
            self._trip(f'{self._soft_streak} consecutive failures')

    def abandon_trial(self) -> None:
        """The attempt that consumed the half-open trial ended with
        NO verdict on the replica's health — a shed (capacity, not
        sickness), a client hangup, a cancelled hedge loser. Release
        the trial so the next pick re-probes; without this the
        breaker would wedge half-open-blocked forever (no outcome
        can ever be recorded for an ejected replica). No-op when a
        verdict already resolved the trial."""
        if self.state == HALF_OPEN and self._trial_inflight:
            self._trial_inflight = False

    def remove(self) -> None:
        """The replica left the fleet for good: retire its series."""
        _M_STATE.remove(replica=self.replica)

    # ------------------------------------------------------ internals
    def _trip(self, why: str) -> None:
        self._soft_streak = 0
        self._opened_at = self._clock.now()
        self.trips += 1
        _M_TRIPS.inc(1, replica=self.replica)
        self._set_state(OPEN)
        logger.warning('Breaker for %s tripped OPEN (%s); replica '
                       'ejected for %.1fs.', self.replica, why,
                       self.cooldown_s)

    def _set_state(self, state: str) -> None:
        self.state = state
        _M_STATE.set(STATE_VALUES[state], replica=self.replica)
