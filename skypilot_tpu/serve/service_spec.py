"""The ``service:`` section of a task YAML.

Re-design of reference ``sky/serve/service_spec.py:1-385``.

Example::

    service:
      readiness_probe:
        path: /health
        initial_delay_seconds: 60
      replica_policy:
        min_replicas: 1
        max_replicas: 4
        target_qps_per_replica: 2.5
      replica_port: 8000
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from skypilot_tpu import exceptions


@dataclasses.dataclass
class ServiceSpec:
    readiness_path: str = '/'
    initial_delay_seconds: int = 600
    readiness_timeout_seconds: int = 15
    min_replicas: int = 1
    max_replicas: Optional[int] = None
    target_qps_per_replica: Optional[float] = None
    replica_port: int = 8080
    # Hysteresis (reference autoscalers.py:431): consecutive decision
    # intervals before acting.
    upscale_delay_seconds: int = 300
    downscale_delay_seconds: int = 1200
    load_balancing_policy: str = 'least_load'
    # Spot policy (reference FallbackRequestRateAutoscaler,
    # autoscalers.py:546): serve from cheap spot replicas, with
    # `base_ondemand_fallback_replicas` always-on on-demand replicas,
    # and (if dynamic_ondemand_fallback) extra on-demand replicas
    # covering preempted spot capacity until spot recovers.
    use_spot: bool = False
    base_ondemand_fallback_replicas: int = 0
    dynamic_ondemand_fallback: bool = False

    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any]) -> 'ServiceSpec':
        if not isinstance(config, dict):
            raise exceptions.InvalidTaskError(
                f'service: must be a mapping, got {config!r}')
        probe = config.get('readiness_probe', {})
        if isinstance(probe, str):
            probe = {'path': probe}
        policy = config.get('replica_policy', {})
        if 'replicas' in config and policy:
            raise exceptions.InvalidTaskError(
                'Use either service.replicas or service.replica_policy, '
                'not both.')
        if 'replicas' in config:
            policy = {
                'min_replicas': config['replicas'],
                'max_replicas': config['replicas'],
            }
        spec = cls(
            readiness_path=probe.get('path', '/'),
            initial_delay_seconds=int(
                probe.get('initial_delay_seconds', 600)),
            readiness_timeout_seconds=int(
                probe.get('timeout_seconds', 15)),
            min_replicas=int(policy.get('min_replicas', 1)),
            max_replicas=(int(policy['max_replicas'])
                          if policy.get('max_replicas') is not None else
                          None),
            target_qps_per_replica=(
                float(policy['target_qps_per_replica'])
                if policy.get('target_qps_per_replica') is not None else
                None),
            replica_port=int(config.get('replica_port', 8080)),
            upscale_delay_seconds=int(
                policy.get('upscale_delay_seconds', 300)),
            downscale_delay_seconds=int(
                policy.get('downscale_delay_seconds', 1200)),
            load_balancing_policy=config.get('load_balancing_policy',
                                             'least_load'),
            use_spot=bool(policy.get('use_spot', False)),
            base_ondemand_fallback_replicas=int(
                policy.get('base_ondemand_fallback_replicas', 0)),
            dynamic_ondemand_fallback=bool(
                policy.get('dynamic_ondemand_fallback', False)),
        )
        spec.validate()
        return spec

    def validate(self) -> None:
        if self.min_replicas < 0:
            raise exceptions.InvalidTaskError('min_replicas must be >= 0')
        if (self.max_replicas is not None and
                self.max_replicas < self.min_replicas):
            raise exceptions.InvalidTaskError(
                'max_replicas must be >= min_replicas')
        if (self.target_qps_per_replica is not None and
                self.target_qps_per_replica <= 0):
            raise exceptions.InvalidTaskError(
                'target_qps_per_replica must be > 0')
        if (self.target_qps_per_replica is not None and
                self.max_replicas is None):
            raise exceptions.InvalidTaskError(
                'autoscaling (target_qps_per_replica) requires '
                'max_replicas')
        if self.base_ondemand_fallback_replicas < 0:
            raise exceptions.InvalidTaskError(
                'base_ondemand_fallback_replicas must be >= 0')
        if ((self.base_ondemand_fallback_replicas > 0 or
             self.dynamic_ondemand_fallback) and not self.use_spot):
            raise exceptions.InvalidTaskError(
                'on-demand fallback requires use_spot: true '
                '(fallback is the on-demand safety net under spot '
                'replicas)')

    def to_yaml_config(self) -> Dict[str, Any]:
        return {
            'readiness_probe': {
                'path': self.readiness_path,
                'initial_delay_seconds': self.initial_delay_seconds,
                'timeout_seconds': self.readiness_timeout_seconds,
            },
            'replica_policy': {
                'min_replicas': self.min_replicas,
                'max_replicas': self.max_replicas,
                'target_qps_per_replica': self.target_qps_per_replica,
                'upscale_delay_seconds': self.upscale_delay_seconds,
                'downscale_delay_seconds': self.downscale_delay_seconds,
                'use_spot': self.use_spot,
                'base_ondemand_fallback_replicas':
                    self.base_ondemand_fallback_replicas,
                'dynamic_ondemand_fallback':
                    self.dynamic_ondemand_fallback,
            },
            'replica_port': self.replica_port,
            'load_balancing_policy': self.load_balancing_policy,
        }
