"""The ``service:`` section of a task YAML.

Re-design of reference ``sky/serve/service_spec.py:1-385``.

Example::

    service:
      readiness_probe:
        path: /health
        initial_delay_seconds: 60
      replica_policy:
        min_replicas: 1
        max_replicas: 4
        target_qps_per_replica: 2.5
      replica_port: 8000
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.utils import qos as qos_lib


@dataclasses.dataclass
class ServiceSpec:
    readiness_path: str = '/'
    initial_delay_seconds: int = 600
    readiness_timeout_seconds: int = 15
    min_replicas: int = 1
    max_replicas: Optional[int] = None
    target_qps_per_replica: Optional[float] = None
    replica_port: int = 8080
    # Hysteresis (reference autoscalers.py:431): consecutive decision
    # intervals before acting.
    upscale_delay_seconds: int = 300
    downscale_delay_seconds: int = 1200
    load_balancing_policy: str = 'least_load'
    # Spot policy (reference FallbackRequestRateAutoscaler,
    # autoscalers.py:546): serve from cheap spot replicas, with
    # `base_ondemand_fallback_replicas` always-on on-demand replicas,
    # and (if dynamic_ondemand_fallback) extra on-demand replicas
    # covering preempted spot capacity until spot recovers.
    use_spot: bool = False
    base_ondemand_fallback_replicas: int = 0
    dynamic_ondemand_fallback: bool = False
    # Rate-aware over-provisioning (docs/spot_serving.md): how long a
    # replacement replica takes from launch to READY. At a non-zero
    # estimated preemption rate, the spot target carries headroom for
    # the losses statistically expected within one lead time, so the
    # fleet still meets demand while replacements provision.
    spot_recovery_lead_time_s: float = 300.0
    # SLO-driven scaling (docs/load_testing.md): latency objectives
    # the autoscaler holds by adding replicas — p99 TTFT / p99
    # inter-token latency (scraped from each replica's sliding-window
    # gauges) and the engine's estimated queue wait. Any of these set
    # selects the SLOAutoscaler; QPS-derived scaling still applies
    # underneath as the demand floor when target_qps_per_replica is
    # also set.
    target_ttft_p99_s: Optional[float] = None
    target_itl_p99_s: Optional[float] = None
    target_queue_wait_s: Optional[float] = None
    # Breach persistence before an SLO scale-up fires (and the
    # cooldown between consecutive SLO scale-ups). Deliberately much
    # shorter than upscale_delay_seconds: a latency regression is
    # user-visible NOW, while raw QPS growth tolerates minutes of
    # confirmation.
    slo_upscale_delay_seconds: int = 60
    # Per-class TTFT SLO targets (docs/qos.md): priority class ->
    # p99 TTFT seconds, scraped from each replica's
    # skytpu_engine_class_ttft_p99_seconds{class=...} gauge. Lets
    # the autoscaler hold 'interactive p99 TTFT <= 0.5s' while bulk
    # traffic runs at whatever latency capacity allows — an
    # aggregate-only target either over-scales for bulk or
    # under-protects interactive.
    class_target_ttft_p99_s: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    # Disaggregated prefill/decode (docs/disaggregation.md): a
    # separate pool of prefill-role replicas the LB's disagg router
    # hands prompts to (kv_prefill manifests + /kv/fetch exports);
    # min/max_replicas above then size the decode pool. 0/None keeps
    # the classic interleaved fleet. The SLO autoscaler scales the
    # prefill pool on TTFT breaches and the decode pool on ITL
    # breaches, independently.
    min_prefill_replicas: int = 0
    max_prefill_replicas: Optional[int] = None

    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any]) -> 'ServiceSpec':
        if not isinstance(config, dict):
            raise exceptions.InvalidTaskError(
                f'service: must be a mapping, got {config!r}')
        probe = config.get('readiness_probe', {})
        if isinstance(probe, str):
            probe = {'path': probe}
        policy = config.get('replica_policy', {})
        if 'replicas' in config and policy:
            raise exceptions.InvalidTaskError(
                'Use either service.replicas or service.replica_policy, '
                'not both.')
        if 'replicas' in config:
            policy = {
                'min_replicas': config['replicas'],
                'max_replicas': config['replicas'],
            }
        spec = cls(
            readiness_path=probe.get('path', '/'),
            initial_delay_seconds=int(
                probe.get('initial_delay_seconds', 600)),
            readiness_timeout_seconds=int(
                probe.get('timeout_seconds', 15)),
            min_replicas=int(policy.get('min_replicas', 1)),
            max_replicas=(int(policy['max_replicas'])
                          if policy.get('max_replicas') is not None else
                          None),
            target_qps_per_replica=(
                float(policy['target_qps_per_replica'])
                if policy.get('target_qps_per_replica') is not None else
                None),
            replica_port=int(config.get('replica_port', 8080)),
            upscale_delay_seconds=int(
                policy.get('upscale_delay_seconds', 300)),
            downscale_delay_seconds=int(
                policy.get('downscale_delay_seconds', 1200)),
            load_balancing_policy=config.get('load_balancing_policy',
                                             'least_load'),
            use_spot=bool(policy.get('use_spot', False)),
            base_ondemand_fallback_replicas=int(
                policy.get('base_ondemand_fallback_replicas', 0)),
            dynamic_ondemand_fallback=bool(
                policy.get('dynamic_ondemand_fallback', False)),
            spot_recovery_lead_time_s=float(
                policy.get('spot_recovery_lead_time_s', 300.0)),
            target_ttft_p99_s=(
                float(policy['target_ttft_p99_s'])
                if policy.get('target_ttft_p99_s') is not None else
                None),
            target_itl_p99_s=(
                float(policy['target_itl_p99_s'])
                if policy.get('target_itl_p99_s') is not None else
                None),
            target_queue_wait_s=(
                float(policy['target_queue_wait_s'])
                if policy.get('target_queue_wait_s') is not None else
                None),
            slo_upscale_delay_seconds=int(
                policy.get('slo_upscale_delay_seconds', 60)),
            class_target_ttft_p99_s={
                str(k): float(v)
                for k, v in (policy.get('class_target_ttft_p99_s')
                             or {}).items()},
            min_prefill_replicas=int(
                policy.get('min_prefill_replicas', 0)),
            max_prefill_replicas=(
                int(policy['max_prefill_replicas'])
                if policy.get('max_prefill_replicas') is not None
                else None),
        )
        spec.validate()
        return spec

    def slo_targets(self) -> Dict[str, float]:
        """The configured SLO objectives, keyed by signal name
        (``ttft_p99`` / ``itl_p99`` / ``est_wait``). Empty = no SLO
        scaling."""
        out = {}
        if self.target_ttft_p99_s is not None:
            out['ttft_p99'] = self.target_ttft_p99_s
        if self.target_itl_p99_s is not None:
            out['itl_p99'] = self.target_itl_p99_s
        if self.target_queue_wait_s is not None:
            out['est_wait'] = self.target_queue_wait_s
        return out

    def class_slo_targets(self) -> Dict[str, float]:
        """Per-class p99 TTFT objectives (priority class -> seconds;
        docs/qos.md). Empty = no per-class SLO scaling. Any entry
        makes the service an SLO-autoscaled one exactly like the
        aggregate targets do."""
        return dict(self.class_target_ttft_p99_s)

    def disaggregated(self) -> bool:
        """True when the service runs a prefill pool
        (docs/disaggregation.md): the replica manager then launches
        prefill-role replicas alongside the decode pool and the LB
        routes tagged requests prefill→manifest→decode."""
        return (self.min_prefill_replicas > 0 or
                (self.max_prefill_replicas or 0) > 0)

    def validate(self) -> None:
        if self.min_replicas < 0:
            raise exceptions.InvalidTaskError('min_replicas must be >= 0')
        if (self.max_replicas is not None and
                self.max_replicas < self.min_replicas):
            raise exceptions.InvalidTaskError(
                'max_replicas must be >= min_replicas')
        if (self.target_qps_per_replica is not None and
                self.target_qps_per_replica <= 0):
            raise exceptions.InvalidTaskError(
                'target_qps_per_replica must be > 0')
        if (self.target_qps_per_replica is not None and
                self.max_replicas is None):
            raise exceptions.InvalidTaskError(
                'autoscaling (target_qps_per_replica) requires '
                'max_replicas')
        for name in ('target_ttft_p99_s', 'target_itl_p99_s',
                     'target_queue_wait_s'):
            value = getattr(self, name)
            if value is not None and value <= 0:
                raise exceptions.InvalidTaskError(
                    f'{name} must be > 0')
        for cls, value in self.class_target_ttft_p99_s.items():
            if cls not in qos_lib.CLASS_RANK:
                raise exceptions.InvalidTaskError(
                    f'class_target_ttft_p99_s: unknown priority '
                    f'class {cls!r} (expected one of '
                    f'{qos_lib.PRIORITY_CLASSES})')
            if value <= 0:
                raise exceptions.InvalidTaskError(
                    f'class_target_ttft_p99_s[{cls}] must be > 0')
        any_slo = bool(self.slo_targets() or self.class_slo_targets())
        if any_slo and self.max_replicas is None:
            raise exceptions.InvalidTaskError(
                'SLO autoscaling (target_ttft_p99_s / '
                'target_itl_p99_s / target_queue_wait_s / '
                'class_target_ttft_p99_s) requires max_replicas')
        if (any_slo and self.min_replicas < 1 and
                self.target_qps_per_replica is None):
            # Latency-only SLO scaling gets every signal from ready
            # replicas' /metrics: at zero replicas there is nothing to
            # scrape, so the service could never scale up from zero.
            # A QPS target keeps scale-from-zero viable (LB-recorded
            # demand exists without replicas).
            raise exceptions.InvalidTaskError(
                'SLO-only autoscaling requires min_replicas >= 1: '
                'its signals come from replica /metrics, which do '
                'not exist at zero replicas (add '
                'target_qps_per_replica to allow scale-from-zero)')
        if self.slo_upscale_delay_seconds < 0:
            raise exceptions.InvalidTaskError(
                'slo_upscale_delay_seconds must be >= 0')
        if self.base_ondemand_fallback_replicas < 0:
            raise exceptions.InvalidTaskError(
                'base_ondemand_fallback_replicas must be >= 0')
        if ((self.base_ondemand_fallback_replicas > 0 or
             self.dynamic_ondemand_fallback) and not self.use_spot):
            raise exceptions.InvalidTaskError(
                'on-demand fallback requires use_spot: true '
                '(fallback is the on-demand safety net under spot '
                'replicas)')
        if self.spot_recovery_lead_time_s < 0:
            raise exceptions.InvalidTaskError(
                'spot_recovery_lead_time_s must be >= 0')
        if self.min_prefill_replicas < 0:
            raise exceptions.InvalidTaskError(
                'min_prefill_replicas must be >= 0')
        if (self.max_prefill_replicas is not None and
                self.max_prefill_replicas < self.min_prefill_replicas):
            raise exceptions.InvalidTaskError(
                'max_prefill_replicas must be >= '
                'min_prefill_replicas')
        if self.disaggregated() and self.min_replicas < 1:
            raise exceptions.InvalidTaskError(
                'a disaggregated service (min/max_prefill_replicas) '
                'requires min_replicas >= 1: the decode pool streams '
                'every response, so it can never be empty')

    def to_yaml_config(self) -> Dict[str, Any]:
        return {
            'readiness_probe': {
                'path': self.readiness_path,
                'initial_delay_seconds': self.initial_delay_seconds,
                'timeout_seconds': self.readiness_timeout_seconds,
            },
            'replica_policy': {
                'min_replicas': self.min_replicas,
                'max_replicas': self.max_replicas,
                'target_qps_per_replica': self.target_qps_per_replica,
                'upscale_delay_seconds': self.upscale_delay_seconds,
                'downscale_delay_seconds': self.downscale_delay_seconds,
                'target_ttft_p99_s': self.target_ttft_p99_s,
                'target_itl_p99_s': self.target_itl_p99_s,
                'target_queue_wait_s': self.target_queue_wait_s,
                'slo_upscale_delay_seconds':
                    self.slo_upscale_delay_seconds,
                'class_target_ttft_p99_s':
                    dict(self.class_target_ttft_p99_s),
                'use_spot': self.use_spot,
                'base_ondemand_fallback_replicas':
                    self.base_ondemand_fallback_replicas,
                'dynamic_ondemand_fallback':
                    self.dynamic_ondemand_fallback,
                'spot_recovery_lead_time_s':
                    self.spot_recovery_lead_time_s,
                'min_prefill_replicas': self.min_prefill_replicas,
                'max_prefill_replicas': self.max_prefill_replicas,
            },
            'replica_port': self.replica_port,
            'load_balancing_policy': self.load_balancing_policy,
        }
