"""Request-rate autoscaler with hysteresis.

Re-design of reference ``sky/serve/autoscalers.py:431``
(RequestRateAutoscaler): target replica count = ceil(recent QPS /
target_qps_per_replica), clamped to [min, max]; scale decisions only
fire after the signal persists for the upscale/downscale delay —
upscale reacts fast (minutes), downscale slowly (tens of minutes) so
bursts don't thrash TPU slices that take minutes to provision.
"""
from __future__ import annotations

import dataclasses
import math
import time
from collections import deque
from typing import Deque, Optional

from skypilot_tpu.serve.service_spec import ServiceSpec

_QPS_WINDOW_SECONDS = 60.0


@dataclasses.dataclass
class ScalingDecision:
    target_replicas: int


class FixedReplicaAutoscaler:
    """No target_qps: hold min_replicas."""

    def __init__(self, spec: ServiceSpec) -> None:
        self.spec = spec

    def record_request(self, now: Optional[float] = None) -> None:
        pass

    def evaluate(self, current_replicas: int,
                 now: Optional[float] = None) -> ScalingDecision:
        return ScalingDecision(self.spec.min_replicas)


class RequestRateAutoscaler:

    def __init__(self, spec: ServiceSpec) -> None:
        assert spec.target_qps_per_replica is not None
        self.spec = spec
        self._timestamps: Deque[float] = deque()
        # When the raw desire first diverged in the current direction.
        self._desire_since: Optional[float] = None
        self._desired: Optional[int] = None

    # ------------------------------------------------------------------
    def record_request(self, now: Optional[float] = None) -> None:
        self._timestamps.append(now if now is not None else time.time())

    def current_qps(self, now: Optional[float] = None) -> float:
        now = now if now is not None else time.time()
        cutoff = now - _QPS_WINDOW_SECONDS
        while self._timestamps and self._timestamps[0] < cutoff:
            self._timestamps.popleft()
        return len(self._timestamps) / _QPS_WINDOW_SECONDS

    def _raw_target(self, now: float) -> int:
        qps = self.current_qps(now)
        target = math.ceil(qps / self.spec.target_qps_per_replica)
        lo = self.spec.min_replicas
        hi = self.spec.max_replicas
        return max(lo, min(hi, target) if hi is not None else target)

    def evaluate(self, current_replicas: int,
                 now: Optional[float] = None) -> ScalingDecision:
        """Hysteresis: act only after the desire persists its delay."""
        now = now if now is not None else time.time()
        raw = self._raw_target(now)
        if raw == current_replicas:
            self._desire_since = None
            self._desired = None
            return ScalingDecision(current_replicas)
        if raw != self._desired:
            self._desired = raw
            self._desire_since = now
            return ScalingDecision(current_replicas)
        delay = (self.spec.upscale_delay_seconds
                 if raw > current_replicas else
                 self.spec.downscale_delay_seconds)
        if now - self._desire_since >= delay:
            self._desire_since = None
            self._desired = None
            return ScalingDecision(raw)
        return ScalingDecision(current_replicas)


def make_autoscaler(spec: ServiceSpec):
    if spec.target_qps_per_replica is None:
        return FixedReplicaAutoscaler(spec)
    return RequestRateAutoscaler(spec)
