"""Request-rate autoscaler with hysteresis.

Re-design of reference ``sky/serve/autoscalers.py:431``
(RequestRateAutoscaler): target replica count = ceil(recent QPS /
target_qps_per_replica), clamped to [min, max]; scale decisions only
fire after the signal persists for the upscale/downscale delay —
upscale reacts fast (minutes), downscale slowly (tens of minutes) so
bursts don't thrash TPU slices that take minutes to provision.
"""
from __future__ import annotations

import dataclasses
import math
import time
from collections import deque
from typing import Deque, Optional, Tuple

from skypilot_tpu import metrics as metrics_lib
from skypilot_tpu.serve.service_spec import ServiceSpec

_QPS_WINDOW_SECONDS = 60.0

# The scaling signal IS the scraped series (docs/metrics.md): every
# record_request increments this counter, and current_qps derives
# from its deltas — an operator graphing rate(skytpu_lb_requests_total)
# sees the exact number the autoscaler acts on.
_M_REQUESTS = metrics_lib.counter(
    'skytpu_lb_requests_total',
    'Requests observed by the service load balancer (the autoscaler '
    'QPS signal).',
    labels=('service',))


@dataclasses.dataclass
class ScalingDecision:
    target_replicas: int
    # Spot/on-demand split of the target. None = no split: every
    # replica uses the task's own resources as written.
    num_spot: Optional[int] = None
    num_ondemand: Optional[int] = None


class FixedReplicaAutoscaler:
    """No target_qps: hold min_replicas."""

    def __init__(self, spec: ServiceSpec,
                 service: str = 'default') -> None:
        self.spec = spec
        self._service = service

    def record_request(self, now: Optional[float] = None) -> None:
        # No scaling decision reads it, but the traffic series still
        # exists for dashboards.
        del now
        _M_REQUESTS.inc(1, service=self._service)

    def to_state(self) -> dict:
        return {}

    def restore(self, state: dict) -> None:
        pass

    def initial(self) -> ScalingDecision:
        return initial_decision(self.spec)

    def evaluate(self, current_replicas: int,
                 now: Optional[float] = None,
                 num_ready_spot: int = 0) -> ScalingDecision:
        return _with_spot_split(self.spec,
                                ScalingDecision(self.spec.min_replicas),
                                num_ready_spot)


def initial_decision(spec: ServiceSpec) -> ScalingDecision:
    """First scale-out at service start: min_replicas, spot split
    applied, no hysteresis."""
    return _with_spot_split(spec, ScalingDecision(spec.min_replicas),
                            num_ready_spot=0)


def _with_spot_split(spec: ServiceSpec, decision: ScalingDecision,
                     num_ready_spot: int) -> ScalingDecision:
    """Split a target into (spot, on-demand) per the spec's spot policy.

    Mirrors reference ``FallbackRequestRateAutoscaler``
    (sky/serve/autoscalers.py:546): the QPS-derived target is served by
    spot replicas; `base_ondemand_fallback_replicas` on-demand replicas
    are always on; with `dynamic_ondemand_fallback`, extra on-demand
    replicas cover whatever part of the spot target is not READY yet
    (spot stockout / preemption storm), draining again as spot
    recovers.
    """
    if not spec.use_spot:
        return decision
    target = decision.target_replicas
    ondemand = spec.base_ondemand_fallback_replicas
    if spec.dynamic_ondemand_fallback:
        ondemand += max(0, target - num_ready_spot)
    return ScalingDecision(target_replicas=target + ondemand,
                           num_spot=target, num_ondemand=ondemand)


class RequestRateAutoscaler:
    """QPS-derived scaling where the QPS signal comes from the
    SCRAPED request counter: ``record_request`` increments
    ``skytpu_lb_requests_total{service=...}`` and keeps a sliding
    window of (timestamp, cumulative-count) samples; ``current_qps``
    is the counter delta over the window — numerically identical to
    the old private-timestamp-deque computation (equivalence-tested),
    but now the dashboard and the scaling decision read one number."""

    def __init__(self, spec: ServiceSpec,
                 service: str = 'default') -> None:
        assert spec.target_qps_per_replica is not None
        self.spec = spec
        self._service = service
        # (timestamp, cumulative count) per recorded request, where
        # the cumulative count is the scraped counter plus a restore
        # offset; _window_base is the cumulative count at the window
        # start. The offset exists so restore() can rebuild the
        # window WITHOUT re-incrementing the counter: the restored
        # requests were already counted (by the previous process, or
        # by this process before a rolling-update rebuild) — replay
        # would show a phantom traffic spike on every scrape.
        self._samples: Deque[Tuple[float, float]] = deque()
        self._offset = 0.0
        self._window_base = _M_REQUESTS.value(service=service)
        # The autoscaler owns its target (reference autoscalers.py
        # target_num_replicas): the target is what capacity SHOULD be,
        # so a preemption that shrinks the live pool does not lower
        # the target — reconcile relaunches the lost replicas
        # immediately instead of waiting out upscale_delay.
        self._target = spec.min_replicas
        # When the raw desire first diverged in the current direction.
        self._desire_since: Optional[float] = None
        self._desired: Optional[int] = None

    def initial(self) -> ScalingDecision:
        return initial_decision(self.spec)

    # -------------------------------------------------- durability
    def to_state(self) -> dict:
        """Snapshot for serve_state persistence: the QPS window and
        hysteresis clocks survive a controller restart (reference
        sky/serve/autoscalers.py:431 persists LB request timestamps),
        so a restart under load does not forget demand and
        spuriously downscale."""
        return {
            'timestamps': [t for t, _ in self._samples],
            'target': self._target,
            'desired': self._desired,
            'desire_since': self._desire_since,
        }

    def restore(self, state: dict) -> None:
        now = time.time()
        cutoff = now - _QPS_WINDOW_SECONDS
        # Rebuild the window as synthetic cumulative samples on top
        # of the counter's CURRENT value — the restored requests are
        # window state, not new traffic, so the scraped counter is
        # not touched (no phantom rate() spike on controller restart
        # or rolling-update autoscaler rebuild). The offset keeps
        # later record_request() samples monotonically above the
        # replayed ones.
        base = _M_REQUESTS.value(service=self._service)
        kept = sorted(t for t in state.get('timestamps', ())
                      if t >= cutoff)
        self._samples = deque(
            (t, base + i + 1) for i, t in enumerate(kept))
        self._window_base = base
        self._offset = float(len(kept))
        self._target = max(self.spec.min_replicas,
                           int(state.get('target',
                                         self.spec.min_replicas)))
        if self.spec.max_replicas is not None:
            # A rolling update may have lowered max_replicas.
            self._target = min(self._target, self.spec.max_replicas)
        self._desired = state.get('desired')
        self._desire_since = state.get('desire_since')

    # ------------------------------------------------------------------
    def record_request(self, now: Optional[float] = None) -> None:
        t = now if now is not None else time.time()
        cum = _M_REQUESTS.inc(1, service=self._service) + self._offset
        self._samples.append((t, cum))

    def current_qps(self, now: Optional[float] = None) -> float:
        now = now if now is not None else time.time()
        cutoff = now - _QPS_WINDOW_SECONDS
        while self._samples and self._samples[0][0] < cutoff:
            self._window_base = self._samples.popleft()[1]
        latest = (self._samples[-1][1] if self._samples
                  else self._window_base)
        return (latest - self._window_base) / _QPS_WINDOW_SECONDS

    def _raw_target(self, now: float) -> int:
        qps = self.current_qps(now)
        target = math.ceil(qps / self.spec.target_qps_per_replica)
        lo = self.spec.min_replicas
        hi = self.spec.max_replicas
        return max(lo, min(hi, target) if hi is not None else target)

    def evaluate(self, current_replicas: Optional[int] = None,
                 now: Optional[float] = None,
                 num_ready_spot: int = 0) -> ScalingDecision:
        """Hysteresis: move the owned target only after the QPS-derived
        desire persists its up/downscale delay. `current_replicas` is
        accepted for signature compatibility but deliberately unused —
        targets track demand, not the (possibly preemption-shrunken)
        live pool.
        """
        now = now if now is not None else time.time()
        raw = self._raw_target(now)
        if raw == self._target:
            self._desire_since = None
            self._desired = None
        else:
            if raw != self._desired:
                self._desired = raw
                self._desire_since = now
            delay = (self.spec.upscale_delay_seconds
                     if raw > self._target else
                     self.spec.downscale_delay_seconds)
            if now - self._desire_since >= delay:
                self._desire_since = None
                self._desired = None
                self._target = raw
        return ScalingDecision(self._target)


class FallbackRequestRateAutoscaler(RequestRateAutoscaler):
    """QPS autoscaling on spot capacity with an on-demand safety net
    (reference sky/serve/autoscalers.py:546): the base target is
    served by spot replicas; on-demand covers the configured base plus
    (dynamically) whatever spot capacity is not READY."""

    def evaluate(self, current_replicas: Optional[int] = None,
                 now: Optional[float] = None,
                 num_ready_spot: int = 0) -> ScalingDecision:
        decision = super().evaluate(current_replicas, now)
        return _with_spot_split(self.spec, decision, num_ready_spot)


def make_autoscaler(spec: ServiceSpec, service: str = 'default'):
    if spec.target_qps_per_replica is None:
        return FixedReplicaAutoscaler(spec, service=service)
    if spec.use_spot:
        return FallbackRequestRateAutoscaler(spec, service=service)
    return RequestRateAutoscaler(spec, service=service)
