"""Request-rate + SLO autoscalers with hysteresis.

Re-design of reference ``sky/serve/autoscalers.py:431``
(RequestRateAutoscaler): target replica count = ceil(recent QPS /
target_qps_per_replica), clamped to [min, max]; scale decisions only
fire after the signal persists for the upscale/downscale delay —
upscale reacts fast (minutes), downscale slowly (tens of minutes) so
bursts don't thrash TPU slices that take minutes to provision.

:class:`SLOAutoscaler` layers latency objectives on top
(docs/load_testing.md): it scrapes each replica's sliding-window p99
TTFT/ITL gauges and the engine's ``skytpu_engine_est_wait_seconds``
queue-wait estimate from ``/metrics``, and scales UP when any signal
breaches its target for ``slo_upscale_delay_seconds`` — catching the
two failure shapes QPS-derived scaling is blind to: a latency
regression at flat request rate (a slow replica, a tick hang) and a
burst whose queue builds ticks before the 60 s QPS window moves.
"""
from __future__ import annotations

import dataclasses
import math
import urllib.error
import urllib.request
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from skypilot_tpu import metrics as metrics_lib
from skypilot_tpu.serve.service_spec import ServiceSpec
from skypilot_tpu.utils import env_registry
from skypilot_tpu.utils import statedb
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

_QPS_WINDOW_SECONDS = 60.0

# A replica sample older than this is ignored by breach detection: a
# replica that stopped answering scrapes must not pin the fleet to
# its last (possibly terrible) numbers forever.
_SLO_SAMPLE_TTL_SECONDS = 120.0

# (sample key, scraped gauge, ServiceSpec target attribute): the
# scraped series ARE the scaling signal, exactly like the QPS
# counter — an operator graphing the replica's p99 gauge sees the
# number the autoscaler acts on.
SLO_SIGNALS = (
    ('ttft_p99', 'skytpu_engine_ttft_p99_seconds',
     'target_ttft_p99_s'),
    ('itl_p99', 'skytpu_engine_itl_p99_seconds',
     'target_itl_p99_s'),
    ('est_wait', 'skytpu_engine_est_wait_seconds',
     'target_queue_wait_s'),
)

# Per-class TTFT signal (docs/qos.md): the engine exports one
# labeled series per priority class; parse_values() keys labeled
# samples as 'name{label="value"}', so the scrape lookup is an exact
# string match per configured class.
_CLASS_TTFT_METRIC = 'skytpu_engine_class_ttft_p99_seconds'


def _class_signal_key(cls: str) -> str:
    """Sample-dict key for one class's TTFT signal (namespaced so a
    class name can never collide with an aggregate signal key)."""
    return f'class_ttft:{cls}'

# The scaling signal IS the scraped series (docs/metrics.md): every
# record_request increments this counter, and current_qps derives
# from its deltas — an operator graphing rate(skytpu_lb_requests_total)
# sees the exact number the autoscaler acts on.
_M_REQUESTS = metrics_lib.counter(
    'skytpu_lb_requests_total',
    'Requests observed by the service load balancer (the autoscaler '
    'QPS signal).',
    labels=('service',))


@dataclasses.dataclass
class ScalingDecision:
    target_replicas: int
    # Spot/on-demand split of the target. None = no split: every
    # replica uses the task's own resources as written.
    num_spot: Optional[int] = None
    num_ondemand: Optional[int] = None
    # Disaggregated pool split (docs/disaggregation.md). None = not a
    # disaggregated service. When set, ``target_replicas`` (and the
    # spot split above) size the DECODE pool — ``num_prefill`` rides
    # on top as its own independently-scaled pool of prefill-role
    # replicas.
    num_prefill: Optional[int] = None
    num_decode: Optional[int] = None


class SpotPreemptionRateEstimator:
    """EWMA estimate of the spot preemption rate, in preemptions per
    spot-replica-hour (docs/spot_serving.md).

    Exposure-weighted: ``advance(now, num_ready_spot)`` — called once
    per autoscaler evaluation — decays both accumulators with the
    half-life ``SKYTPU_SPOT_RATE_HALFLIFE_S`` (default 1800 s) and
    integrates the elapsed spot-replica-hours of exposure;
    ``record_preemption()`` adds one event (fed by the replica
    manager on the FIRST evidence of each spot preemption — the
    notice when one was observed, else the kill). The rate is decayed
    events over decayed exposure, so one kill in a 10-replica fleet
    reads 10x lower than the same kill in a 1-replica fleet, and an
    old preemption storm fades on the half-life instead of haunting
    the headroom forever. Zero events (or zero exposure) estimates
    exactly 0.0 — the over-provisioning math then degenerates to the
    rate-blind split, bit for bit."""

    def __init__(self) -> None:
        self._events = 0.0
        self._exposure_h = 0.0
        self._last_at: Optional[float] = None

    @staticmethod
    def _halflife_s() -> float:
        raw = env_registry.get(
            env_registry.SKYTPU_SPOT_RATE_HALFLIFE_S, '1800')
        try:
            return max(1.0, float(raw))
        except ValueError:
            return 1800.0

    def advance(self, now: float, num_ready_spot: int) -> None:
        """Account exposure since the last call: ``num_ready_spot``
        replicas were preemptible for the elapsed interval."""
        if self._last_at is None:
            self._last_at = now
            return
        dt = now - self._last_at
        self._last_at = now
        if dt <= 0:
            return
        decay = 0.5 ** (dt / self._halflife_s())
        self._events *= decay
        self._exposure_h *= decay
        self._exposure_h += max(0, num_ready_spot) * dt / 3600.0

    def record_preemption(self) -> None:
        self._events += 1.0

    def rate_per_replica_hour(self) -> float:
        if self._exposure_h <= 0.0:
            return 0.0
        return self._events / self._exposure_h

    def expected_losses(self, num_spot: int,
                        lead_time_s: float) -> float:
        """Spot replicas statistically expected to be preempted out of
        ``num_spot`` within one recovery lead time."""
        return (self.rate_per_replica_hour() * max(0, num_spot) *
                max(0.0, lead_time_s) / 3600.0)

    # ------------------------------------------------- durability
    def to_state(self) -> dict:
        return {'events': self._events,
                'exposure_h': self._exposure_h,
                'last_at': self._last_at}

    def restore(self, state: dict) -> None:
        """Tolerant by construction: a missing/old-format dict leaves
        the estimator cold (rate 0), never raises."""
        try:
            self._events = max(0.0, float(state.get('events', 0.0)))
            self._exposure_h = max(
                0.0, float(state.get('exposure_h', 0.0)))
            last = state.get('last_at')
            self._last_at = None if last is None else float(last)
        except (AttributeError, TypeError, ValueError):
            self._events = 0.0
            self._exposure_h = 0.0
            self._last_at = None


class FixedReplicaAutoscaler:
    """No target_qps: hold min_replicas."""

    def __init__(self, spec: ServiceSpec,
                 service: str = 'default') -> None:
        self.spec = spec
        self._service = service
        self.spot_rate = SpotPreemptionRateEstimator()

    def record_request(self, now: Optional[float] = None) -> None:
        # No scaling decision reads it, but the traffic series still
        # exists for dashboards.
        del now
        _M_REQUESTS.inc(1, service=self._service)

    def record_preemption(self) -> None:
        """One spot replica was preempted (docs/spot_serving.md):
        feeds the EWMA rate behind the over-provisioning headroom."""
        self.spot_rate.record_preemption()

    def to_state(self) -> dict:
        return {'spot': self.spot_rate.to_state()}

    def restore(self, state: dict) -> None:
        self.spot_rate.restore(state.get('spot') or {})

    def initial(self) -> ScalingDecision:
        return initial_decision(self.spec)

    def evaluate(self, current_replicas: int,
                 now: Optional[float] = None,
                 num_ready_spot: int = 0) -> ScalingDecision:
        now = now if now is not None else statedb.wall_now()
        self.spot_rate.advance(now, num_ready_spot)
        return _with_spot_split(self.spec,
                                ScalingDecision(self.spec.min_replicas),
                                num_ready_spot,
                                estimator=self.spot_rate)


def initial_decision(spec: ServiceSpec) -> ScalingDecision:
    """First scale-out at service start: min_replicas, spot split
    applied, no hysteresis."""
    return _with_spot_split(spec, ScalingDecision(spec.min_replicas),
                            num_ready_spot=0)


def _with_spot_split(
        spec: ServiceSpec, decision: ScalingDecision,
        num_ready_spot: int,
        estimator: Optional[SpotPreemptionRateEstimator] = None
) -> ScalingDecision:
    """Split a target into (spot, on-demand) per the spec's spot policy.

    Mirrors reference ``FallbackRequestRateAutoscaler``
    (sky/serve/autoscalers.py:546): the QPS-derived target is served by
    spot replicas; `base_ondemand_fallback_replicas` on-demand replicas
    are always on; with `dynamic_ondemand_fallback`, extra on-demand
    replicas cover whatever part of the spot target is not READY yet
    (spot stockout / preemption storm), draining again as spot
    recovers.

    Rate-aware over-provisioning (docs/spot_serving.md): with an
    estimator, the spot target additionally carries
    ``ceil(rate * target * lead_time / 3600)`` headroom replicas —
    the losses statistically expected within one
    ``spot_recovery_lead_time_s`` at the EWMA preemption rate — so
    the fleet still meets the demand target while replacements
    provision, instead of starting each relaunch only after the kill.
    The dynamic fallback then covers whatever part of the *headroomed*
    spot plan is not READY, sizing the on-demand safety net
    proactively. At an estimated rate of zero the headroom is zero
    and the split is bit-identical to the rate-blind one.
    """
    if not spec.use_spot:
        return decision
    target = decision.target_replicas
    headroom = 0
    if estimator is not None and target > 0:
        headroom = max(0, math.ceil(estimator.expected_losses(
            target, spec.spot_recovery_lead_time_s) - 1e-9))
    spot = target + headroom
    ondemand = spec.base_ondemand_fallback_replicas
    if spec.dynamic_ondemand_fallback:
        ondemand += max(0, spot - num_ready_spot)
    return ScalingDecision(target_replicas=spot + ondemand,
                           num_spot=spot, num_ondemand=ondemand)


class RequestRateAutoscaler:
    """QPS-derived scaling where the QPS signal comes from the
    SCRAPED request counter: ``record_request`` increments
    ``skytpu_lb_requests_total{service=...}`` and keeps a sliding
    window of (timestamp, cumulative-count) samples; ``current_qps``
    is the counter delta over the window — numerically identical to
    the old private-timestamp-deque computation (equivalence-tested),
    but now the dashboard and the scaling decision read one number."""

    def __init__(self, spec: ServiceSpec,
                 service: str = 'default') -> None:
        # The SLOAutoscaler subclass may run latency-only (no QPS
        # target): the QPS path then holds min_replicas and only the
        # SLO path moves the target. Per-class TTFT targets count —
        # a class-only spec is a legitimate SLO-autoscaled service.
        assert (spec.target_qps_per_replica is not None or
                spec.slo_targets() or
                spec.class_slo_targets()), spec
        self.spec = spec
        self._service = service
        # (timestamp, cumulative count) per recorded request, where
        # the cumulative count is the scraped counter plus a restore
        # offset; _window_base is the cumulative count at the window
        # start. The offset exists so restore() can rebuild the
        # window WITHOUT re-incrementing the counter: the restored
        # requests were already counted (by the previous process, or
        # by this process before a rolling-update rebuild) — replay
        # would show a phantom traffic spike on every scrape.
        self._samples: Deque[Tuple[float, float]] = deque()
        self._offset = 0.0
        self._window_base = _M_REQUESTS.value(service=service)
        # The autoscaler owns its target (reference autoscalers.py
        # target_num_replicas): the target is what capacity SHOULD be,
        # so a preemption that shrinks the live pool does not lower
        # the target — reconcile relaunches the lost replicas
        # immediately instead of waiting out upscale_delay.
        self._target = spec.min_replicas
        # When the raw desire first diverged in the current direction.
        self._desire_since: Optional[float] = None
        self._desired: Optional[int] = None
        # Preemption-rate estimate behind the spot over-provisioning
        # headroom (docs/spot_serving.md) — idle unless a spot-aware
        # subclass advances it at evaluation time.
        self.spot_rate = SpotPreemptionRateEstimator()

    def initial(self) -> ScalingDecision:
        return initial_decision(self.spec)

    # -------------------------------------------------- durability
    def to_state(self) -> dict:
        """Snapshot for serve_state persistence: the QPS window and
        hysteresis clocks survive a controller restart (reference
        sky/serve/autoscalers.py:431 persists LB request timestamps),
        so a restart under load does not forget demand and
        spuriously downscale."""
        return {
            'timestamps': [t for t, _ in self._samples],
            'target': self._target,
            'desired': self._desired,
            'desire_since': self._desire_since,
            'spot': self.spot_rate.to_state(),
        }

    def restore(self, state: dict) -> None:
        now = statedb.wall_now()
        cutoff = now - _QPS_WINDOW_SECONDS
        # Rebuild the window as synthetic cumulative samples on top
        # of the counter's CURRENT value — the restored requests are
        # window state, not new traffic, so the scraped counter is
        # not touched (no phantom rate() spike on controller restart
        # or rolling-update autoscaler rebuild). The offset keeps
        # later record_request() samples monotonically above the
        # replayed ones.
        base = _M_REQUESTS.value(service=self._service)
        kept = sorted(t for t in state.get('timestamps', ())
                      if t >= cutoff)
        self._samples = deque(
            (t, base + i + 1) for i, t in enumerate(kept))
        self._window_base = base
        self._offset = float(len(kept))
        self._target = max(self.spec.min_replicas,
                           int(state.get('target',
                                         self.spec.min_replicas)))
        if self.spec.max_replicas is not None:
            # A rolling update may have lowered max_replicas.
            self._target = min(self._target, self.spec.max_replicas)
        self._desired = state.get('desired')
        self._desire_since = state.get('desire_since')
        # Old-format state (pre-spot) simply leaves the estimator
        # cold — rate 0, split unchanged.
        self.spot_rate.restore(state.get('spot') or {})

    def record_preemption(self) -> None:
        """One spot replica was preempted (docs/spot_serving.md):
        feeds the EWMA rate behind the over-provisioning headroom."""
        self.spot_rate.record_preemption()

    # ------------------------------------------------------------------
    def record_request(self, now: Optional[float] = None) -> None:
        t = now if now is not None else statedb.wall_now()
        cum = _M_REQUESTS.inc(1, service=self._service) + self._offset
        self._samples.append((t, cum))

    def current_qps(self, now: Optional[float] = None) -> float:
        now = now if now is not None else statedb.wall_now()
        cutoff = now - _QPS_WINDOW_SECONDS
        while self._samples and self._samples[0][0] < cutoff:
            self._window_base = self._samples.popleft()[1]
        latest = (self._samples[-1][1] if self._samples
                  else self._window_base)
        return (latest - self._window_base) / _QPS_WINDOW_SECONDS

    def _raw_target(self, now: float) -> int:
        lo = self.spec.min_replicas
        hi = self.spec.max_replicas
        if self.spec.target_qps_per_replica is None:
            # SLO-only scaling: the QPS path's desire is the floor,
            # so an SLO-raised target decays back once the breach
            # clears and the downscale delay passes.
            return lo
        qps = self.current_qps(now)
        target = math.ceil(qps / self.spec.target_qps_per_replica)
        return max(lo, min(hi, target) if hi is not None else target)

    def evaluate(self, current_replicas: Optional[int] = None,
                 now: Optional[float] = None,
                 num_ready_spot: int = 0) -> ScalingDecision:
        """Hysteresis: move the owned target only after the QPS-derived
        desire persists its up/downscale delay. `current_replicas` is
        accepted for signature compatibility but deliberately unused —
        targets track demand, not the (possibly preemption-shrunken)
        live pool.
        """
        now = now if now is not None else statedb.wall_now()
        raw = self._raw_target(now)
        if raw == self._target:
            self._desire_since = None
            self._desired = None
        else:
            if raw != self._desired:
                self._desired = raw
                self._desire_since = now
            delay = (self.spec.upscale_delay_seconds
                     if raw > self._target else
                     self.spec.downscale_delay_seconds)
            if now - self._desire_since >= delay:
                self._desire_since = None
                self._desired = None
                self._target = raw
        return ScalingDecision(self._target)


class SLOAutoscaler(RequestRateAutoscaler):
    """Scale on what users feel, not on how often they ask.

    Signals (see :data:`SLO_SIGNALS`) come from replica ``/metrics``
    scrapes: the engine's sliding-window p99 TTFT/ITL gauges and its
    ``estimate_wait_s`` queue-pressure gauge. A breach — any fresh
    sample over its target — that persists ``slo_upscale_delay_
    seconds`` raises the owned target proportionally to the worst
    breach ratio (clamped to one doubling per step), with the same
    delay as a cooldown so consecutive scale-ups step rather than
    run away. While breached, the QPS path's DOWNSCALE hysteresis is
    frozen: demand math must never shrink a fleet that is visibly
    missing its latency objectives. Recovery is the QPS path's job —
    once no signal breaches, its raw target (or min_replicas,
    latency-only) becomes the desire and the ordinary downscale
    delay walks the fleet back down.
    """

    def __init__(self, spec: ServiceSpec,
                 service: str = 'default') -> None:
        super().__init__(spec, service=service)
        # url -> {'at': ts, '<signal>': value}
        self._slo_samples: Dict[str, Dict[str, float]] = {}
        self._breach_since: Optional[float] = None
        self._last_slo_scale_at: Optional[float] = None
        # Disaggregated prefill pool (docs/disaggregation.md): its
        # own target with its own breach/cooldown clocks — TTFT
        # breaches scale prefill, ITL/queue-wait breaches scale
        # decode, independently.
        self._prefill_target = spec.min_prefill_replicas
        self._prefill_breach_since: Optional[float] = None
        self._last_prefill_scale_at: Optional[float] = None
        self._prefill_idle_since: Optional[float] = None

    # --------------------------------------------------- ingestion
    def observe_replica(self, url: str, values: Dict[str, float],
                        now: Optional[float] = None) -> None:
        """Record one replica's scraped gauge values (``values`` is a
        parse_values() dict, metric name -> value). Tests feed this
        directly; production goes through scrape_replicas()."""
        now = now if now is not None else statedb.wall_now()
        sample: Dict[str, float] = {'at': now}
        for key, metric, _ in SLO_SIGNALS:
            v = values.get(metric)
            if v is not None:
                sample[key] = float(v)
        for cls in self.spec.class_slo_targets():
            v = values.get(f'{_CLASS_TTFT_METRIC}{{class="{cls}"}}')
            if v is not None and float(v) > 0.0:
                # 0.0 is the gauge's "no observations yet" export —
                # a class with no traffic has no latency to judge.
                sample[_class_signal_key(cls)] = float(v)
        self._slo_samples[url] = sample

    def scrape_replicas(self, urls: List[str],
                        timeout: float = 2.0,
                        now: Optional[float] = None) -> None:
        """Best-effort scrape of every ready replica's ``/metrics``
        (called off the event loop by the controller). Scrapes run
        CONCURRENTLY so a pass is bounded by ~one timeout, not
        timeout * fleet — a few wedged replicas must not delay the
        very scale-up decision this loop exists to make. A replica
        that fails to answer keeps its previous sample until the TTL
        ages it out; replicas gone from ``urls`` are dropped."""
        import concurrent.futures

        def fetch(url: str) -> Optional[str]:
            try:
                with urllib.request.urlopen(
                        url.rstrip('/') + '/metrics',
                        timeout=timeout) as resp:
                    return resp.read().decode('utf-8', 'replace')
            except (urllib.error.URLError, OSError, ValueError) as e:
                logger.debug('SLO scrape of %s failed: %s', url, e)
                return None
        if urls:
            with concurrent.futures.ThreadPoolExecutor(
                    max_workers=min(16, len(urls))) as pool:
                for url, text in zip(urls, pool.map(fetch, urls)):
                    if text is not None:
                        self.observe_replica(
                            url, metrics_lib.parse_values(text),
                            now=now)
        keep = set(urls)
        self._slo_samples = {u: s
                             for u, s in self._slo_samples.items()
                             if u in keep}

    # ------------------------------------------------------ breach
    @staticmethod
    def _is_prefill_signal(key: str) -> bool:
        """TTFT-family signals (aggregate or per-class) are prefill
        pressure in a disaggregated service: time-to-first-token is
        dominated by prefill queueing and compute, while ITL and
        queue-wait are decode-side (docs/disaggregation.md)."""
        return key == 'ttft_p99' or key.startswith('class_ttft:')

    def _worst_breach(self, now: float, want=None
                      ) -> Optional[Tuple[float, str, str]]:
        """(ratio, signal, url) of the worst fresh signal relative to
        its target, or None with no usable samples. ratio > 1 means
        the objective is being missed. ``want`` optionally filters
        signal keys (the disaggregated pool split evaluates prefill
        and decode signals separately)."""
        targets = dict(self.spec.slo_targets())
        for cls, target in self.spec.class_slo_targets().items():
            targets[_class_signal_key(cls)] = target
        worst: Optional[Tuple[float, str, str]] = None
        for url, sample in self._slo_samples.items():
            if now - sample['at'] > _SLO_SAMPLE_TTL_SECONDS:
                continue
            for key, target in targets.items():
                if want is not None and not want(key):
                    continue
                value = sample.get(key)
                if value is None:
                    continue
                ratio = value / target
                if worst is None or ratio > worst[0]:
                    worst = (ratio, key, url)
        return worst

    # -------------------------------------------------- durability
    def to_state(self) -> dict:
        state = super().to_state()
        state['slo'] = {
            'breach_since': self._breach_since,
            'last_scale_at': self._last_slo_scale_at,
            'samples': {u: dict(s)
                        for u, s in self._slo_samples.items()},
            'prefill_target': self._prefill_target,
            'prefill_breach_since': self._prefill_breach_since,
            'prefill_last_scale_at': self._last_prefill_scale_at,
            'prefill_idle_since': self._prefill_idle_since,
        }
        return state

    def restore(self, state: dict) -> None:
        """Back-compat by construction: an old-format state dict
        (pre-SLO fields) restores the QPS window exactly as the base
        class does and leaves the SLO clocks cold — no error, no
        phantom breach. The converse also holds: the base class
        ignores the extra 'slo' key in a new-format dict."""
        super().restore(state)
        slo = state.get('slo') or {}
        self._breach_since = slo.get('breach_since')
        self._last_slo_scale_at = slo.get('last_scale_at')
        self._prefill_target = int(slo.get(
            'prefill_target', self.spec.min_prefill_replicas))
        self._prefill_breach_since = slo.get('prefill_breach_since')
        self._last_prefill_scale_at = slo.get('prefill_last_scale_at')
        self._prefill_idle_since = slo.get('prefill_idle_since')
        samples = slo.get('samples') or {}
        self._slo_samples = {
            str(u): {k: float(v) for k, v in s.items()}
            for u, s in samples.items()
            if isinstance(s, dict) and 'at' in s}

    # -------------------------------------------------- evaluation
    def _evaluate_prefill(self, now: float) -> int:
        """Prefill-pool target for a disaggregated service
        (docs/disaggregation.md): the SAME sustained-breach /
        proportional-step / cooldown shape as the aggregate path,
        run over the TTFT-family signals only and clamped to
        [min_prefill_replicas, max_prefill_replicas]. Quiet periods
        walk the pool back toward its floor one replica per
        downscale delay."""
        breach = self._worst_breach(now, want=self._is_prefill_signal)
        breached = breach is not None and breach[0] > 1.0
        if not breached:
            self._prefill_breach_since = None
            if self._prefill_target > self.spec.min_prefill_replicas:
                if self._prefill_idle_since is None:
                    self._prefill_idle_since = now
                elif (now - self._prefill_idle_since >=
                      self.spec.downscale_delay_seconds):
                    self._prefill_target -= 1
                    self._prefill_idle_since = now
            else:
                self._prefill_idle_since = None
            return self._prefill_target
        self._prefill_idle_since = None
        if self._prefill_breach_since is None:
            self._prefill_breach_since = now
        ratio, signal, url = breach
        delay = self.spec.slo_upscale_delay_seconds
        sustained = now - self._prefill_breach_since >= delay
        cooled = (self._last_prefill_scale_at is None or
                  now - self._last_prefill_scale_at >= delay)
        hi = self.spec.max_prefill_replicas
        if sustained and cooled and \
                (hi is None or self._prefill_target < hi):
            step = max(1, math.ceil(
                self._prefill_target * (min(ratio, 2.0) - 1.0)))
            new = self._prefill_target + step
            if hi is not None:
                new = min(new, hi)
            logger.info(
                'SLO prefill-pool scale-up %d -> %d: %s breached '
                '%.2fx at %s (sustained %.0fs).',
                self._prefill_target, new, signal, ratio, url,
                now - self._prefill_breach_since)
            self._prefill_target = new
            self._last_prefill_scale_at = now
        return self._prefill_target

    def evaluate(self, current_replicas: Optional[int] = None,
                 now: Optional[float] = None,
                 num_ready_spot: int = 0) -> ScalingDecision:
        now = now if now is not None else statedb.wall_now()
        disagg = self.spec.disaggregated()
        # In a disaggregated service the aggregate path owns only
        # the DECODE pool: TTFT-family breaches are routed to the
        # prefill pool below, so they neither grow the decode fleet
        # nor freeze its demand hysteresis. A classic service keeps
        # every signal on the one pool, bit for bit.
        want = ((lambda k: not self._is_prefill_signal(k))
                if disagg else None)
        breach = self._worst_breach(now, want=want)
        breached = breach is not None and breach[0] > 1.0
        if not breached:
            self._breach_since = None
            # Healthy: the QPS path owns the target (including the
            # slow decay of an SLO-raised target back to demand).
            decision = super().evaluate(current_replicas, now)
        else:
            # Freeze QPS hysteresis: a downscale desire built from
            # demand math must not fire while latency objectives are
            # being missed (the desire clock restarts clean after the
            # breach clears).
            self._desire_since = None
            self._desired = None
            # The QPS window still prunes while breached — breaches
            # happen under heavy traffic, exactly when an unpruned
            # sample deque (and the to_state() dump of it) would grow
            # without bound.
            self.current_qps(now)
            if self._breach_since is None:
                self._breach_since = now
            ratio, signal, url = breach
            delay = self.spec.slo_upscale_delay_seconds
            sustained = now - self._breach_since >= delay
            cooled = (self._last_slo_scale_at is None or
                      now - self._last_slo_scale_at >= delay)
            hi = self.spec.max_replicas
            if sustained and cooled and \
                    (hi is None or self._target < hi):
                # Proportional step, one doubling max: a 1.3x breach
                # adds ~30% capacity, a 10x breach doubles — enough
                # to move p99 fast without slamming max_replicas on
                # the first wobble.
                step = max(1, math.ceil(
                    self._target * (min(ratio, 2.0) - 1.0)))
                new = self._target + step
                if hi is not None:
                    new = min(new, hi)
                logger.info(
                    'SLO scale-up %d -> %d: %s breached %.2fx at %s '
                    '(sustained %.0fs).', self._target, new, signal,
                    ratio, url, now - self._breach_since)
                self._target = new
                self._last_slo_scale_at = now
            decision = ScalingDecision(self._target)
        self.spot_rate.advance(now, num_ready_spot)
        decision = _with_spot_split(self.spec, decision, num_ready_spot,
                                    estimator=self.spot_rate)
        if disagg:
            # Set AFTER the spot split — it may build a fresh
            # ScalingDecision and would drop the pool fields.
            decision.num_prefill = self._evaluate_prefill(now)
            decision.num_decode = decision.target_replicas
        return decision


class FallbackRequestRateAutoscaler(RequestRateAutoscaler):
    """QPS autoscaling on spot capacity with an on-demand safety net
    (reference sky/serve/autoscalers.py:546): the base target is
    served by spot replicas; on-demand covers the configured base plus
    (dynamically) whatever spot capacity is not READY."""

    def evaluate(self, current_replicas: Optional[int] = None,
                 now: Optional[float] = None,
                 num_ready_spot: int = 0) -> ScalingDecision:
        now = now if now is not None else statedb.wall_now()
        decision = super().evaluate(current_replicas, now)
        self.spot_rate.advance(now, num_ready_spot)
        return _with_spot_split(self.spec, decision, num_ready_spot,
                                estimator=self.spot_rate)


def make_autoscaler(spec: ServiceSpec, service: str = 'default'):
    if spec.slo_targets() or spec.class_slo_targets():
        # SLO targets win (aggregate or per-class): the SLOAutoscaler
        # keeps the QPS path as its demand floor (when configured)
        # and applies the spot split itself.
        return SLOAutoscaler(spec, service=service)
    if spec.target_qps_per_replica is None:
        return FixedReplicaAutoscaler(spec, service=service)
    if spec.use_spot:
        return FallbackRequestRateAutoscaler(spec, service=service)
    return RequestRateAutoscaler(spec, service=service)
