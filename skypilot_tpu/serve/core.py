"""Serve public API: up / down / status.

Re-design of reference ``sky/serve/server/core.py``: `up` records the
service and spawns the detached controller process that owns replicas
and the load balancer.
"""
from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import task as task_lib
from skypilot_tpu.serve import serve_state
from skypilot_tpu.utils import statedb
from skypilot_tpu.serve.replica_managers import ReplicaManager
from skypilot_tpu.serve.serve_state import ServiceStatus
from skypilot_tpu.serve.service_spec import ServiceSpec
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)


_CONTROLLER_START_TIMEOUT = 40.0


def _lb_reachable(port: int) -> bool:
    try:
        with socket.create_connection(('127.0.0.1', port), timeout=1):
            return True
    except OSError:
        return False


def _log_dir() -> str:
    return os.path.expanduser(
        os.environ.get('SKYTPU_SERVE_LOG_DIR', '~/.skytpu/serve'))


def up(task: task_lib.Task,
       service_name: Optional[str] = None,
       *,
       lb_port: Optional[int] = None,
       controller_loop_gap: Optional[float] = None) -> Dict[str, Any]:
    """Start a service; returns {'name', 'endpoint'}."""
    from skypilot_tpu import usage
    usage.record_event('serve.up')
    if task.service is None:
        raise exceptions.InvalidTaskError(
            'Task has no service: section.')
    spec: ServiceSpec = task.service
    name = service_name or task.name or 'service'
    if serve_state.get_service(name) is not None:
        raise exceptions.SkyTpuError(
            f'Service {name!r} already exists. `down` it first.')
    # The controller process binds the LB port itself (preferred port
    # via --lb-port, or OS-assigned) and writes the BOUND port back to
    # serve_state — the row stays 0 until then, so the poll below can
    # only ever see a controller-written port (no bind-probe-release
    # TOCTOU, and no mistaking a foreign listener for our LB).
    serve_state.add_service(
        name,
        spec_json=json.dumps(spec.to_yaml_config()),
        task_json=json.dumps(task.to_yaml_config()),
        lb_port=0)

    log_dir = _log_dir()
    os.makedirs(log_dir, exist_ok=True)
    log_path = os.path.join(log_dir, f'{name}.log')
    cmd = [
        sys.executable, '-u', '-m', 'skypilot_tpu.serve.controller', name
    ]
    if lb_port:
        cmd += ['--lb-port', str(lb_port)]
    if controller_loop_gap is not None:
        cmd += ['--loop-gap', str(controller_loop_gap)]
    env = dict(os.environ)
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    existing = env.get('PYTHONPATH', '')
    if repo_root not in existing.split(os.pathsep):
        env['PYTHONPATH'] = repo_root + (os.pathsep + existing
                                         if existing else '')
    with open(log_path, 'ab') as log_f:
        proc = subprocess.Popen(cmd, stdout=log_f,
                                stderr=subprocess.STDOUT,
                                start_new_session=True, env=env)
    serve_state.set_service_controller_pid(name, proc.pid)
    # Wait for the controller's LB to actually listen; surface startup
    # crashes here instead of handing back a dead endpoint.
    deadline = statedb.wall_now() + _CONTROLLER_START_TIMEOUT
    port = 0
    while statedb.wall_now() < deadline:
        if proc.poll() is not None:
            tail = ''
            try:
                with open(log_path, 'r', encoding='utf-8',
                          errors='replace') as f:
                    tail = ''.join(f.readlines()[-20:])
            except OSError:
                pass
            # The controller may have launched replicas before dying —
            # tear them down, or they leak untracked clusters.
            ReplicaManager(name, spec,
                           task.to_yaml_config()).terminate_all()
            serve_state.remove_service(name)
            raise exceptions.SkyTpuError(
                f'Serve controller for {name!r} exited at startup '
                f'(code {proc.returncode}). Log tail:\n{tail}')
        record = serve_state.get_service(name)
        port = (record or {}).get('lb_port') or 0
        if port and _lb_reachable(port):
            break
        # skytpu-lint: disable=STL002 — deadline-bounded readiness
        # poll (controller exit / LB reachable / timeout), not a
        # retried operation; the try above only reads the log tail.
        # Sleeps ride the same injectable clock as the deadline.
        statedb.wall_clock().sleep(0.2)
    else:
        logger.warning(
            'Load balancer for %s not reachable after %.0fs; '
            'returning anyway (check `serve status`).', name,
            _CONTROLLER_START_TIMEOUT)
    endpoint = f'http://127.0.0.1:{port}' if port else None
    logger.info('Service %s starting; endpoint %s (controller pid %d).',
                name, endpoint, proc.pid)
    return {'name': name, 'endpoint': endpoint}


def update(task: task_lib.Task, service_name: str) -> Dict[str, Any]:
    """Rolling update: register a new service version.

    The running controller notices the version bump on its next loop,
    launches new-version replicas, and drains old ones only after the
    new version's full target is READY (see ReplicaManager.reconcile).
    Returns {'name', 'version'}.
    """
    record = serve_state.get_service(service_name)
    if record is None:
        raise exceptions.SkyTpuError(
            f'Service {service_name!r} not found; use `up` first.')
    if task.service is None:
        raise exceptions.InvalidTaskError(
            'Task has no service: section.')
    spec: ServiceSpec = task.service
    version = serve_state.add_version(
        service_name,
        spec_json=json.dumps(spec.to_yaml_config()),
        task_json=json.dumps(task.to_yaml_config()))
    logger.info('Service %s updated to version %d.', service_name,
                version)
    return {'name': service_name, 'version': version}


def down(service_name: str, purge: bool = False) -> None:
    record = serve_state.get_service(service_name)
    if record is None:
        if purge:
            return
        raise exceptions.SkyTpuError(
            f'Service {service_name!r} not found.')
    serve_state.set_service_status(service_name,
                                   ServiceStatus.SHUTTING_DOWN)
    pid = record.get('controller_pid')
    if pid:
        try:
            os.kill(pid, signal.SIGTERM)
        except (OSError, ProcessLookupError):
            pass
    # Tear down replicas from here (controller may already be dead).
    spec = ServiceSpec.from_yaml_config(record['spec'])
    manager = ReplicaManager(service_name, spec, record['task'])
    manager.terminate_all()
    serve_state.remove_service(service_name)
    logger.info('Service %s torn down.', service_name)


def status(service_name: Optional[str] = None) -> List[Dict[str, Any]]:
    records = ([serve_state.get_service(service_name)]
               if service_name else serve_state.get_services())
    out = []
    for record in records:
        if record is None:
            continue
        replicas = serve_state.get_replicas(record['name'])
        out.append({
            'name': record['name'],
            'status': record['status'],
            'endpoint': (f'http://127.0.0.1:{record["lb_port"]}'
                         if record['lb_port'] else None),
            'version': record.get('current_version') or 1,
            'replicas': [{
                'replica_id': r['replica_id'],
                'status': r['status'],
                'url': r['url'],
                'version': r.get('version') or 1,
                'is_spot': bool(r.get('is_spot')),
            } for r in replicas],
        })
    return out
