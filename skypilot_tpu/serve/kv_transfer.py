"""KV-page transfer: prefix-cache pages as an addressable, movable
resource (docs/disaggregation.md).

The prefix cache (models/prefix_cache.py) already gives every KV page
a content address — a chain hash committing to the page's WHOLE token
prefix — and a fixed wire-friendly shape (quantized caches carry
their int8 planes plus bf16 scales as first-class fields). This
module is the missing half: a canonical byte encoding for a batch of
pages, a replica-side packer for the ``POST /kv/fetch`` surface, and
a client fetcher the disaggregated router and the KV-assisted resume
path share.

Wire format (version ``SKKV1``)::

    b"SKKV1\\n"
    <one JSON header line, sorted keys>
    <concatenated raw page payloads>

The header names the producer's page *signature* — page size plus
per-field dtype and block shape — and one record per page: its chain
hash (hex), payload length and a blake2b checksum. Decoding validates
magic, header, per-page checksums and the byte math; importing
replicas additionally compare the signature against their OWN pool's
(``PrefixCache.page_signature()``) and reject on any mismatch — a
fetched page either lands bit-exact in the local pool or not at all.
Because page keys are chain hashes, a transferred page means the same
thing on every replica running the same model: content addressing IS
the transfer protocol's correctness argument.

Failure semantics: every client entry point raises
:class:`KVFetchError` (transport, wire, signature — one exception
type), and callers degrade to interleaved re-prefill; a fetch can
slow a request down but never corrupt it. The ``serve.kv.fetch``
fault site is polled before each fetch so chaos plans can sever the
prefill→decode handoff deterministically (``connect_failure``) or
stall it (``hang``), with the usual cross-process receipts.

Knobs: ``SKYTPU_KV_FETCH_MAX_BYTES`` bounds a single response payload
(the replica packs whole pages until the budget is spent — absence of
a requested page in the response is the protocol's miss signal, never
an error) and ``SKYTPU_KV_FETCH_TIMEOUT_S`` bounds the client's wait.
"""
from __future__ import annotations

import hashlib
import io
import json
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from skypilot_tpu import metrics as metrics_lib
from skypilot_tpu.utils import env_registry
from skypilot_tpu.utils import fault_injection
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

MAGIC = b'SKKV1\n'

_M_FETCHES = metrics_lib.counter(
    'skytpu_kv_fetches_total',
    'KV page fetches issued against peer replicas, by outcome: ok, '
    'error (transport/wire/signature), injected (a serve.kv.fetch '
    'chaos spec fired).',
    labels=('outcome',))
_M_PAGES_SENT = metrics_lib.counter(
    'skytpu_kv_pages_sent_total',
    'Prefix-cache pages this replica packed into /kv/fetch responses '
    '(the prefill→decode transfer volume, in pages).')
_M_PAGES_FETCHED = metrics_lib.counter(
    'skytpu_kv_pages_fetched_total',
    'Prefix-cache pages fetched from peer replicas (decode-side '
    'arrivals; import into the pool is counted separately by '
    'skytpu_engine_prefix_pages_imported_total).')


class WireError(ValueError):
    """A byte stream that is not a valid SKKV1 payload."""


class KVFetchError(RuntimeError):
    """A KV fetch that produced no usable pages (transport, wire or
    signature failure). Callers fall back to interleaved re-prefill."""


def max_fetch_bytes() -> int:
    raw = env_registry.get(env_registry.SKYTPU_KV_FETCH_MAX_BYTES,
                           str(64 * 1024 * 1024))
    try:
        return max(0, int(raw))
    except ValueError:
        return 64 * 1024 * 1024


def fetch_timeout_s() -> float:
    raw = env_registry.get(env_registry.SKYTPU_KV_FETCH_TIMEOUT_S,
                           '10')
    try:
        return max(0.1, float(raw))
    except ValueError:
        return 10.0


def page_nbytes(sig: Dict[str, Any]) -> int:
    """Payload bytes of ONE page under ``sig`` (every page is the
    same fixed shape — the budget math needs no per-page probing)."""
    total = 0
    for f in sorted(sig['fields']):
        spec = sig['fields'][f]
        n = 1
        for d in spec['shape']:
            n *= int(d)
        total += n * np.dtype(spec['dtype']).itemsize
    return total


# ---------------------------------------------------------- encoding
def encode(sig: Dict[str, Any],
           pages: Sequence[Tuple[bytes, Dict[str, np.ndarray]]]
           ) -> bytes:
    """Canonical wire bytes for ``pages`` (``[(chain_hash, {field:
    array})]``) under signature ``sig``. Fields serialize in sorted
    name order; each page carries a blake2b checksum of its payload
    so truncation/corruption fails decode, not decode's caller."""
    order = sorted(sig['fields'])
    payload = io.BytesIO()
    recs: List[Dict[str, Any]] = []
    for h, blk in pages:
        start = payload.tell()
        digest = hashlib.blake2b(digest_size=16)
        for f in order:
            spec = sig['fields'][f]
            arr = np.ascontiguousarray(
                np.asarray(blk[f], dtype=np.dtype(spec['dtype'])))
            if list(arr.shape) != [int(d) for d in spec['shape']]:
                raise WireError(
                    f'page field {f!r} has shape {arr.shape}, '
                    f'signature says {spec["shape"]}')
            raw = arr.tobytes()
            digest.update(raw)
            payload.write(raw)
        recs.append({'hash': h.hex(),
                     'len': payload.tell() - start,
                     'sum': digest.hexdigest()})
    header = json.dumps({'sig': sig, 'fields': order, 'pages': recs},
                        sort_keys=True)
    return MAGIC + header.encode('utf-8') + b'\n' + payload.getvalue()


def decode(data: bytes) -> Tuple[Dict[str, Any],
                                 List[Tuple[bytes,
                                            Dict[str, np.ndarray]]]]:
    """Parse wire bytes back into ``(sig, [(chain_hash, {field:
    array})])``. Every malformation — bad magic, bad header, short
    payload, checksum mismatch — raises :class:`WireError`; a decoded
    page is byte-for-byte what the producer exported."""
    if not data.startswith(MAGIC):
        raise WireError('not an SKKV1 payload (bad magic)')
    nl = data.find(b'\n', len(MAGIC))
    if nl < 0:
        raise WireError('truncated SKKV1 header')
    try:
        header = json.loads(data[len(MAGIC):nl].decode('utf-8'))
        sig = header['sig']
        order = list(header['fields'])
        recs = list(header['pages'])
    except (ValueError, KeyError, TypeError) as e:
        raise WireError(f'malformed SKKV1 header: {e}') from e
    if sorted(sig.get('fields', {})) != sorted(order):
        raise WireError('SKKV1 field order disagrees with signature')
    field_specs = []
    for f in order:
        try:
            spec = sig['fields'][f]
            shape = tuple(int(d) for d in spec['shape'])
            dtype = np.dtype(spec['dtype'])
        except (KeyError, TypeError, ValueError) as e:
            raise WireError(f'malformed field spec for {f!r}: {e}') \
                from e
        field_specs.append((f, shape, dtype,
                            int(np.prod(shape)) * dtype.itemsize))
    page_len = sum(nb for _, _, _, nb in field_specs)
    out: List[Tuple[bytes, Dict[str, np.ndarray]]] = []
    off = nl + 1
    for rec in recs:
        try:
            h = bytes.fromhex(rec['hash'])
            declared = int(rec['len'])
            checksum = str(rec['sum'])
        except (KeyError, TypeError, ValueError) as e:
            raise WireError(f'malformed page record: {e}') from e
        if declared != page_len:
            raise WireError(
                f'page payload length {declared} != signature page '
                f'size {page_len}')
        raw = data[off:off + page_len]
        if len(raw) != page_len:
            raise WireError('truncated SKKV1 payload')
        if hashlib.blake2b(raw,
                           digest_size=16).hexdigest() != checksum:
            raise WireError(f'page {rec["hash"]} checksum mismatch')
        blk: Dict[str, np.ndarray] = {}
        f_off = 0
        for f, shape, dtype, nb in field_specs:
            blk[f] = np.frombuffer(
                raw[f_off:f_off + nb], dtype=dtype).reshape(shape)
            f_off += nb
        out.append((h, blk))
        off += page_len
    if off != len(data):
        raise WireError(
            f'{len(data) - off} trailing byte(s) after last page')
    return sig, out


# ------------------------------------------------------ replica side
def pack_pages(cache: Any, hashes_hex: Sequence[str],
               max_bytes: Optional[int] = None) -> bytes:
    """Build a ``/kv/fetch`` response body: export each requested
    page from the local pool, skipping hashes the pool no longer
    holds (absence IS the miss signal — the requester re-prefills
    those positions), packing whole pages until the byte budget is
    spent. Safe to call from HTTP threads: ``export_page`` validates
    the directory around its host copy and drops pages that move
    under it."""
    sig = cache.page_signature()
    budget = max_bytes if max_bytes is not None else max_fetch_bytes()
    per_page = page_nbytes(sig)
    pages: List[Tuple[bytes, Dict[str, np.ndarray]]] = []
    spent = 0
    for hx in hashes_hex:
        try:
            h = bytes.fromhex(str(hx))
        except ValueError:
            continue
        if spent + per_page > budget:
            break
        blk = cache.export_page(h)
        if blk is None:
            continue
        pages.append((h, blk))
        spent += per_page
    _M_PAGES_SENT.inc(len(pages))
    return encode(sig, pages)


# ------------------------------------------------------- client side
def fetch(url: str, hashes: Sequence[Any],
          timeout_s: Optional[float] = None,
          expect_sig: Optional[Dict[str, Any]] = None
          ) -> List[Tuple[bytes, Dict[str, np.ndarray]]]:
    """Fetch pages by chain hash from ``url``'s ``POST /kv/fetch``.

    Synchronous (urllib) by design: the decode replica calls it off
    its event loop via a thread, and the LB never calls it at all
    (transfer is replica-to-replica — the router only carries
    hashes). Polls ``serve.kv.fetch`` first: an armed
    ``connect_failure`` raises without touching the network (the
    chaos handle for a mid-handoff peer death) and a ``hang`` stalls
    ``params['seconds']`` before the request. Returns the pages the
    peer had; raises :class:`KVFetchError` on transport, wire or
    signature failure — the caller's cue to fall back to interleaved
    re-prefill.
    """
    spec = fault_injection.poll(
        'serve.kv.fetch',
        kinds=(fault_injection.FaultKind.CONNECT_FAILURE,
               fault_injection.FaultKind.HANG),
        url=url)
    if spec is not None:
        if spec.kind is fault_injection.FaultKind.HANG:
            time.sleep(float(spec.params.get('seconds', 1.0)))
        else:
            _M_FETCHES.inc(1, outcome='injected')
            raise KVFetchError(
                f'injected connect failure fetching KV from {url}')
    body = json.dumps({'hashes': [
        h.hex() if isinstance(h, bytes) else str(h)
        for h in hashes]}).encode('utf-8')
    req = urllib.request.Request(
        url.rstrip('/') + '/kv/fetch', data=body,
        headers={'Content-Type': 'application/json'})
    timeout = timeout_s if timeout_s is not None else fetch_timeout_s()
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            data = resp.read()
    except (urllib.error.URLError, OSError, ValueError) as e:
        _M_FETCHES.inc(1, outcome='error')
        raise KVFetchError(f'KV fetch from {url} failed: {e}') from e
    try:
        sig, pages = decode(data)
    except WireError as e:
        _M_FETCHES.inc(1, outcome='error')
        raise KVFetchError(
            f'KV fetch from {url}: bad payload: {e}') from e
    if expect_sig is not None and sig != expect_sig:
        _M_FETCHES.inc(1, outcome='error')
        raise KVFetchError(
            f'KV fetch from {url}: peer page signature {sig} does '
            f'not match local pool signature {expect_sig}')
    _M_FETCHES.inc(1, outcome='ok')
    _M_PAGES_FETCHED.inc(len(pages))
    return pages
