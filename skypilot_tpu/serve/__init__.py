"""Serving subsystem: replica autoscaling behind a load balancer.

Re-design of reference ``sky/serve/`` (SURVEY.md §2.7): a controller
process per service runs (a) a replica manager that launches/terminates
replica clusters through the normal launch path and probes their
readiness endpoints, (b) a request-rate autoscaler with hysteresis,
and (c) an HTTP load balancer (aiohttp) proxying to ready replicas.
JetStream/MaxText replicas on TPU slices are the flagship workload.
"""
from skypilot_tpu.serve.core import down, status, up
from skypilot_tpu.serve.service_spec import ServiceSpec

__all__ = ['up', 'down', 'status', 'ServiceSpec']
