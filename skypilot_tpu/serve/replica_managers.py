"""Replica manager: replica cluster lifecycle + readiness probing.

Re-design of reference ``sky/serve/replica_managers.py:59,563,782,1026``:
scale_up launches replica clusters (each a normal launch, possibly a
TPU pod slice) in background threads; a probe pass drives the
ReplicaStatus FSM from readiness-HTTP + cluster status, detecting
preemptions (cluster gone → PREEMPTED → replaced) and failures.
"""
from __future__ import annotations

import threading
import time
import traceback
from typing import Dict, List, Optional

import requests

from skypilot_tpu import exceptions
from skypilot_tpu import task as task_lib
from skypilot_tpu.backend import backend_utils
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve.serve_state import ReplicaStatus
from skypilot_tpu.serve.service_spec import ServiceSpec
from skypilot_tpu.utils import log as sky_logging
from skypilot_tpu.utils import status_lib

logger = sky_logging.init_logger(__name__)

# Local-cloud replicas share 127.0.0.1; give each a distinct port via
# this env var (recipes bind to it; real clouds also get it, set to
# the spec's replica_port, so the same recipe works everywhere).
SERVE_PORT_ENV = 'SKYTPU_SERVE_PORT'

# After this many failed replica launches the reconciler stops
# replacing (the task is broken, not the infra).
_MAX_FAILED_REPLICAS = 3


class ReplicaManager:

    def __init__(self, service_name: str, spec: ServiceSpec,
                 task_config: dict) -> None:
        self.service_name = service_name
        self.spec = spec
        self.task_config = task_config
        self._launch_threads: Dict[int, threading.Thread] = {}
        self._lock = threading.Lock()
        self._failed_probes: Dict[int, int] = {}

    # ------------------------------------------------------------------
    def _cluster_name(self, replica_id: int) -> str:
        return f'{self.service_name}-replica-{replica_id}'

    def _replica_port(self, replica_id: int) -> int:
        # Distinct per replica so local (same-IP) replicas never clash;
        # stable so recovery reuses the port.
        return self.spec.replica_port + replica_id

    def _make_task(self, replica_id: int) -> 'task_lib.Task':
        # A replica is a plain task: strip the service: section.
        config = {k: v for k, v in self.task_config.items()
                  if k != 'service'}
        task = task_lib.Task.from_yaml_config(config)
        envs = dict(task.envs or {})
        envs[SERVE_PORT_ENV] = str(self._replica_port(replica_id))
        task.update_envs(envs)
        return task

    # ------------------------------------------------------------------
    def scale_up(self, n: int = 1) -> None:
        for _ in range(n):
            replica_id = serve_state.next_replica_id(self.service_name)
            cluster = self._cluster_name(replica_id)
            serve_state.add_replica(self.service_name, replica_id,
                                    cluster)
            thread = threading.Thread(target=self._launch_replica,
                                      args=(replica_id, cluster),
                                      daemon=True)
            self._launch_threads[replica_id] = thread
            thread.start()

    def _launch_replica(self, replica_id: int, cluster: str) -> None:
        from skypilot_tpu import execution
        serve_state.set_replica_status(self.service_name, replica_id,
                                       ReplicaStatus.PROVISIONING)
        try:
            task = self._make_task(replica_id)
            execution.launch(task, cluster_name=cluster,
                             detach_run=True, stream_logs=False)
        except Exception:  # pylint: disable=broad-except
            logger.error('Replica %d launch failed:\n%s', replica_id,
                         traceback.format_exc())
            serve_state.set_replica_status(self.service_name, replica_id,
                                           ReplicaStatus.FAILED)
            return
        serve_state.set_replica_status(self.service_name, replica_id,
                                       ReplicaStatus.STARTING)

    # ------------------------------------------------------------------
    def scale_down(self, replica_ids: List[int]) -> None:
        for replica_id in replica_ids:
            serve_state.set_replica_status(self.service_name, replica_id,
                                           ReplicaStatus.SHUTTING_DOWN)
            thread = threading.Thread(target=self._terminate_replica,
                                      args=(replica_id,), daemon=True)
            thread.start()

    def _terminate_replica(self, replica_id: int) -> None:
        from skypilot_tpu import core
        try:
            core.down(self._cluster_name(replica_id))
        except exceptions.ClusterDoesNotExist:
            pass
        except Exception:  # pylint: disable=broad-except
            logger.warning('Replica %d teardown error:\n%s', replica_id,
                           traceback.format_exc())
        serve_state.set_replica_status(self.service_name, replica_id,
                                       ReplicaStatus.SHUTDOWN)

    def terminate_all(self) -> None:
        replicas = serve_state.get_replicas(self.service_name)
        ids = [
            r['replica_id'] for r in replicas
            if r['status'] not in (ReplicaStatus.SHUTDOWN,)
        ]
        threads = []
        for replica_id in ids:
            t = threading.Thread(target=self._terminate_replica,
                                 args=(replica_id,))
            t.start()
            threads.append(t)
        for t in threads:
            t.join()

    # ------------------------------------------------------------------
    def _replica_url(self, replica_id: int,
                     cluster: str) -> Optional[str]:
        record = backend_utils.refresh_cluster_record(cluster)
        if record is None or record.get('handle') is None:
            return None
        handle = record['handle']
        ips = handle.ip_list()
        if not ips:
            return None
        return f'http://{ips[0]}:{self._replica_port(replica_id)}'

    def _probe_ready(self, url: str) -> bool:
        try:
            resp = requests.get(
                url.rstrip('/') + self.spec.readiness_path,
                timeout=self.spec.readiness_timeout_seconds)
            return resp.status_code < 500
        except requests.RequestException:
            return False

    def probe_all(self) -> None:
        """One probe pass: drive the FSM for every live replica."""
        for replica in serve_state.get_replicas(self.service_name):
            rid = replica['replica_id']
            status = replica['status']
            if status in (ReplicaStatus.PENDING,
                          ReplicaStatus.PROVISIONING,
                          ReplicaStatus.SHUTTING_DOWN,
                          ReplicaStatus.SHUTDOWN, ReplicaStatus.FAILED):
                continue
            cluster = replica['cluster_name']
            try:
                record = backend_utils.refresh_cluster_record(
                    cluster, force_refresh=True)
            except Exception:  # pylint: disable=broad-except
                record = None
            if (record is None or
                    record['status'] != status_lib.ClusterStatus.UP):
                # Cluster died under us: preemption.
                logger.info('Replica %d cluster %s gone: PREEMPTED.',
                            rid, cluster)
                serve_state.set_replica_status(self.service_name, rid,
                                               ReplicaStatus.PREEMPTED)
                self._terminate_replica(rid)  # cleanup leftovers
                continue
            url = self._replica_url(rid, cluster)
            ready = url is not None and self._probe_ready(url)
            if ready:
                self._failed_probes[rid] = 0
                serve_state.set_replica_status(self.service_name, rid,
                                               ReplicaStatus.READY,
                                               url=url)
            elif status == ReplicaStatus.READY:
                self._failed_probes[rid] = (
                    self._failed_probes.get(rid, 0) + 1)
                # Transient blips tolerated; sustained failure demotes.
                if self._failed_probes[rid] >= 3:
                    serve_state.set_replica_status(
                        self.service_name, rid, ReplicaStatus.NOT_READY)
            elif status == ReplicaStatus.STARTING:
                launched_at = replica.get('launched_at') or 0
                if (time.time() - launched_at >
                        self.spec.initial_delay_seconds):
                    logger.warning(
                        'Replica %d never became ready within '
                        'initial_delay_seconds: FAILED.', rid)
                    serve_state.set_replica_status(
                        self.service_name, rid, ReplicaStatus.FAILED)
                    self._terminate_replica(rid)

    # ------------------------------------------------------------------
    def reconcile(self, target: int) -> None:
        """Converge live replica count toward `target`; replace
        preempted replicas."""
        replicas = serve_state.get_replicas(self.service_name)
        live = [
            r for r in replicas
            if r['status'] in (ReplicaStatus.PENDING,
                               ReplicaStatus.PROVISIONING,
                               ReplicaStatus.STARTING,
                               ReplicaStatus.READY,
                               ReplicaStatus.NOT_READY)
        ]
        preempted = [
            r for r in replicas
            if r['status'] == ReplicaStatus.PREEMPTED
        ]
        for r in preempted:
            serve_state.remove_replica(self.service_name,
                                       r['replica_id'])
        failed = sum(
            1 for r in replicas if r['status'] == ReplicaStatus.FAILED)
        if len(live) < target:
            # Replace missing replicas, but a string of FAILED
            # launches means the task itself is broken — stop burning
            # clusters (reference replica_managers marks the service
            # failed rather than relaunching forever).
            if failed > _MAX_FAILED_REPLICAS:
                logger.error(
                    'Service %s: %d failed replicas; halting scale-up.',
                    self.service_name, failed)
                return
            self.scale_up(target - len(live))
        elif len(live) > target:
            # Prefer shutting down not-ready, then newest.
            order = sorted(
                live,
                key=lambda r: (r['status'] == ReplicaStatus.READY,
                               -r['replica_id']))
            doomed = order[:len(live) - target]
            self.scale_down([r['replica_id'] for r in doomed])

    def ready_urls(self) -> List[str]:
        return [
            r['url'] for r in serve_state.get_replicas(self.service_name)
            if r['status'] == ReplicaStatus.READY and r['url']
        ]
