"""Replica manager: replica cluster lifecycle + readiness probing.

Re-design of reference ``sky/serve/replica_managers.py:59,563,782,1026``:
scale_up launches replica clusters (each a normal launch, possibly a
TPU pod slice) in background threads; a probe pass drives the
ReplicaStatus FSM from readiness-HTTP + cluster status, detecting
preemptions (cluster gone → PREEMPTED → replaced) and failures.
"""
from __future__ import annotations

import math
import threading
import traceback
from typing import Callable, Dict, List, Optional

import requests

from skypilot_tpu import exceptions
from skypilot_tpu import metrics as metrics_lib
from skypilot_tpu import task as task_lib
from skypilot_tpu import trace as trace_lib
from skypilot_tpu.backend import backend_utils
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve.serve_state import ReplicaStatus
from skypilot_tpu.serve.service_spec import ServiceSpec
from skypilot_tpu.utils import chain_hash
from skypilot_tpu.utils import env_registry
from skypilot_tpu.utils import statedb
from skypilot_tpu.utils import fault_injection
from skypilot_tpu.utils import lifecycle
from skypilot_tpu.utils import log as sky_logging
from skypilot_tpu.utils import retry as retry_lib
from skypilot_tpu.utils import status_lib

logger = sky_logging.init_logger(__name__)

# Local-cloud replicas share 127.0.0.1; give each a distinct port via
# this env var (recipes bind to it; real clouds also get it, set to
# the spec's replica_port, so the same recipe works everywhere).
SERVE_PORT_ENV = 'SKYTPU_SERVE_PORT'

# After this many failed replica launches the reconciler stops
# replacing (the task is broken, not the infra).
_MAX_FAILED_REPLICAS = 3

# A READY replica whose app dies (cluster still UP) is demoted to
# NOT_READY after this many consecutive failed probes...
_NOT_READY_THRESHOLD = 3
# ...and torn down + replaced once the streak reaches this (reference
# replica_managers.py _CONSECUTIVE_FAILURE_THRESHOLD_TIMEOUT).
_PROBE_FAILURE_TERMINATE_THRESHOLD = 10

# FAILED_* rows only count against the replacement cap while fresh; a
# crash-loop trips the cap within the window, but isolated failures
# spread over a long-lived service must not brick it. Old failed rows
# are garbage-collected.
_FAILED_ROW_TTL_SECONDS = 1800.0

# A probe request may never hang past this connect budget even when a
# spec asks for a long read timeout (a replica that won't even accept
# the TCP connection is down, not slow).
_PROBE_CONNECT_TIMEOUT_SECONDS = 5.0
_DEFAULT_PROBE_TIMEOUT_SECONDS = 15.0

_M_PROBE_FAILURES = metrics_lib.counter(
    'skytpu_serve_probe_failures_total',
    'Failed replica readiness probes (including injected faults).',
    labels=('replica',))

# Spot-preemption lifecycle (docs/spot_serving.md): one 'notice' per
# replica whose probe first answers 'preempting', one 'kill' per
# PREEMPTED transition (cluster gone). The notice->kill replay
# harness (loadgen/replay.py) and the LB's migration path share this
# family via the registry's get-or-create semantics.
_M_PREEMPTIONS = metrics_lib.counter(
    'skytpu_serve_preemptions_total',
    'Spot replica preemptions, by phase: notice (advance warning '
    'observed) and kill (the replica actually went away).',
    labels=('phase',))

_M_RECONCILED = metrics_lib.counter(
    'skytpu_serve_reconciled_intents_total',
    'Open scale-up/scale-down intent records replayed at controller '
    'startup, by outcome (adopt / roll_forward / roll_back / orphan).',
    labels=('action',))

# Peer cache warming (docs/affinity_routing.md): pages a newly
# provisioned replica (scale-up or spot replacement) pre-fetched from
# a warm donor's prefix pool before being marked READY.
_M_WARMED = metrics_lib.counter(
    'skytpu_serve_warmed_pages_total',
    'Prefix-cache pages pre-fetched into a new replica from a warm '
    'donor before the replica was marked READY (bounded by '
    'SKYTPU_WARM_MAX_PAGES; failures degrade to a cold start).')


def peer_warm(url: str, donor_url: str, hashes_hex: List[str],
              timeout_s: Optional[float] = None) -> int:
    """Tell the replica at ``url`` to pull the donor's pages: one
    POST /kv/warm carrying the donor URL and its hottest chain
    hashes (the recency-ordered /health digest list, already bounded
    by the caller's warm budget). Returns pages fetched; ANY failure
    returns 0 — warming is strictly best-effort and the caller marks
    the replica READY either way (docs/affinity_routing.md). Shared
    by the replica manager and the serve_affinity bench so both warm
    through the same wire path."""
    if not hashes_hex:
        return 0
    if timeout_s is None:
        timeout_s = float(env_registry.get(
            env_registry.SKYTPU_WARM_TIMEOUT_S, '15'))
    try:
        resp = requests.post(
            url.rstrip('/') + '/kv/warm',
            json={'donor': donor_url, 'hashes': list(hashes_hex)},
            timeout=(min(_PROBE_CONNECT_TIMEOUT_SECONDS, timeout_s),
                     timeout_s))
        if resp.status_code != 200:
            logger.info('Peer warm of %s from %s answered %d: '
                        'starting cold.', url, donor_url,
                        resp.status_code)
            return 0
        imported = int((resp.json() or {}).get('imported', 0))
    except (requests.RequestException, ValueError, TypeError):
        logger.info('Peer warm of %s from %s failed: starting cold.',
                    url, donor_url)
        return 0
    if imported > 0:
        _M_WARMED.inc(imported)
    return imported

# Replica-cluster teardown goes through the shared RetryPolicy: cloud
# teardown calls are flaky exactly when the cloud is having the bad
# day that killed the replica. ClusterDoesNotExist is success.
_TERMINATE_RETRY_POLICY = retry_lib.RetryPolicy(
    max_attempts=3,
    initial_backoff=1.0,
    max_backoff=10.0,
    jitter='full',
    retryable=lambda e: not isinstance(e, exceptions.ClusterDoesNotExist),
    site='serve.replica.terminate')


class ReplicaManager:

    def __init__(self, service_name: str, spec: ServiceSpec,
                 task_config: dict,
                 drain_fn: Optional[Callable[[str], None]] = None,
                 not_ready_threshold: int = _NOT_READY_THRESHOLD,
                 probe_failure_terminate_threshold: int = (
                     _PROBE_FAILURE_TERMINATE_THRESHOLD)) -> None:
        self.service_name = service_name
        self.spec = spec
        self.task_config = task_config
        self.not_ready_threshold = not_ready_threshold
        self.probe_failure_terminate_threshold = (
            probe_failure_terminate_threshold)
        # Blocking callable draining a replica URL at the LB before a
        # VOLUNTARY teardown (downscale / rolling update); involuntary
        # paths (preemption, failed probes) skip it — the replica is
        # already gone.
        self.drain_fn = drain_fn
        # One estimator event per spot preemption (docs/
        # spot_serving.md): called on the FIRST evidence — the notice
        # when one arrives, the PREEMPTED transition otherwise — so a
        # noticed-then-killed replica counts once, not twice. Set by
        # the controller to feed the autoscaler's rate estimator.
        self.on_preemption: Optional[Callable[[], None]] = None
        # Called with the replica URL on the FIRST 'preempting' probe
        # answer: the controller bridges this to the LB's
        # mark_preempting(), which migrates the replica's live
        # streams to survivors inside the notice window
        # (docs/spot_serving.md).
        self.on_preempt_notice: Optional[Callable[[str], None]] = None
        self._lock = threading.Lock()
        self._failed_probes: Dict[int, int] = {}
        # Latest parsed /health body per replica URL, stashed by
        # successful readiness probes: the prefix digests the
        # controller forwards to the LB's cache-aware policy on the
        # probe cadence, and the donor directory peer warming picks
        # from (docs/affinity_routing.md).
        self._probe_health: Dict[str, dict] = {}
        # Replica ids whose probe already answered 'preempting': the
        # notice metric/estimator event fires once per replica, and
        # the later PREEMPTED transition knows it was already counted.
        self._preempt_noticed: set = set()
        # Replica ids with a termination thread in flight (guards the
        # reconcile sweep from double-terminating what probe_all
        # already handed to a background thread).
        self._terminating: set = set()

    # ------------------------------------------------------------------
    def _cluster_name(self, replica_id: int) -> str:
        return f'{self.service_name}-replica-{replica_id}'

    def _replica_port(self, replica_id: int,
                      spec: Optional[ServiceSpec] = None) -> int:
        # Distinct per replica so local (same-IP) replicas never clash;
        # stable so recovery reuses the port.
        return (spec or self.spec).replica_port + replica_id

    def _version_config(self, version: int) -> dict:
        record = serve_state.get_version_spec(self.service_name, version)
        if record is not None:
            return record['task']
        return self.task_config

    def _version_spec(self, version: int) -> ServiceSpec:
        record = serve_state.get_version_spec(self.service_name, version)
        if record is not None:
            try:
                return ServiceSpec.from_yaml_config(record['spec'])
            except Exception as e:  # pylint: disable=broad-except
                logger.warning(
                    'Stored spec for %s v%d is unparsable (%s); '
                    'falling back to the in-memory spec.',
                    self.service_name, version, e)
        return self.spec

    def _make_task(self, replica_id: int, version: int,
                   is_spot: Optional[bool]) -> 'task_lib.Task':
        # A replica is a plain task: strip the service: section.
        config = {
            k: v for k, v in self._version_config(version).items()
            if k != 'service'
        }
        if is_spot is not None:
            # Spot policy overrides the task's own resources: the
            # autoscaler decides per replica which tier it runs on.
            resources = dict(config.get('resources') or {})
            resources['use_spot'] = bool(is_spot)
            config['resources'] = resources
        task = task_lib.Task.from_yaml_config(config)
        envs = dict(task.envs or {})
        envs[SERVE_PORT_ENV] = str(
            self._replica_port(replica_id, self._version_spec(version)))
        task.update_envs(envs)
        return task

    # ------------------------------------------------------------------
    def scale_up(self, n: int = 1, version: Optional[int] = None,
                 is_spot: Optional[bool] = None) -> None:
        if version is None:
            version = serve_state.get_current_version(self.service_name)
        for _ in range(n):
            replica_id = serve_state.next_replica_id(self.service_name)
            cluster = self._cluster_name(replica_id)
            # Row + scale-up intent land in ONE transaction: from here
            # until the launch thread's STARTING write, a controller
            # crash leaves an open intent that reconcile_on_start
            # resolves against cluster truth (adopt or roll back;
            # docs/crash_recovery.md).
            intent_id = serve_state.add_replica(
                self.service_name, replica_id, cluster, version=version,
                is_spot=bool(is_spot),
                intent_payload={
                    'service': self.service_name,
                    'replica_id': replica_id,
                    'cluster_name': cluster,
                })
            threading.Thread(
                target=self._launch_replica,
                args=(replica_id, cluster, version, is_spot, intent_id),
                daemon=True).start()

    def _launch_replica(self, replica_id: int, cluster: str,
                        version: int, is_spot: Optional[bool],
                        intent_id: Optional[int] = None) -> None:
        from skypilot_tpu import execution
        serve_state.set_replica_status(self.service_name, replica_id,
                                       ReplicaStatus.PROVISIONING)
        # One span per replica launch: runs on a fresh thread (no
        # inherited context), so this roots a launch trace whose
        # children are the backend/provision spans (docs/tracing.md).
        with trace_lib.span('serve.replica.launch', slow_ok=True,
                            service=self.service_name,
                            replica=replica_id, cluster=cluster):
            try:
                task = self._make_task(replica_id, version, is_spot)
                execution.launch(task, cluster_name=cluster,
                                 detach_run=True, stream_logs=False)
            except Exception:  # pylint: disable=broad-except
                logger.error('Replica %d launch failed:\n%s',
                             replica_id, traceback.format_exc())
                # Controlled failure: the operation concluded — settle
                # row and journal atomically.
                serve_state.set_replica_status(
                    self.service_name, replica_id,
                    ReplicaStatus.FAILED_PROVISION,
                    complete_intent=intent_id)
                return
        fault_injection.crashpoint('serve.scale_up.post_launch',
                                   service=self.service_name,
                                   replica_id=replica_id)
        serve_state.set_replica_status(self.service_name, replica_id,
                                       ReplicaStatus.STARTING,
                                       complete_intent=intent_id)

    # ------------------------------------------------------------------
    def scale_down(self, replica_ids: List[int]) -> None:
        records = {
            r['replica_id']: r
            for r in serve_state.get_replicas(self.service_name)
        }
        for replica_id in replica_ids:
            # SHUTTING_DOWN + the scale-down intent in one transaction:
            # the announcement IS the point of no return — a crash
            # anywhere in the drain/terminate below rolls FORWARD on
            # restart (reconcile re-runs the teardown, skipping the
            # drain; docs/crash_recovery.md).
            intent_id = serve_state.mark_shutting_down(
                self.service_name, replica_id, {
                    'service': self.service_name,
                    'replica_id': replica_id,
                    'cluster_name': self._cluster_name(replica_id),
                })
            url = (records.get(replica_id) or {}).get('url')

            def work(rid=replica_id, u=url, iid=intent_id):
                # Voluntary teardown is drain-then-kill
                # (docs/request_lifecycle.md): first the LB stops
                # routing and waits out in-flight proxied requests,
                # then the replica PROCESS drains (its own in-flight
                # work finishes or is cancelled under the drain
                # budget), and only then does the cluster go down.
                if u and self.drain_fn is not None:
                    try:
                        self.drain_fn(u)
                    except Exception:  # pylint: disable=broad-except
                        logger.warning(
                            'LB drain of %s failed:\n%s', u,
                            traceback.format_exc())
                if u:
                    self._drain_replica(u)
                    # Distinct crash window from pre_terminate below:
                    # the replica PROCESS has drained (in-flight work
                    # concluded) but the LB/url bookkeeping of this
                    # thread is gone with the crash.
                    fault_injection.crashpoint(
                        'serve.scale_down.post_drain',
                        service=self.service_name, replica_id=rid)
                fault_injection.crashpoint(
                    'serve.scale_down.pre_terminate',
                    service=self.service_name, replica_id=rid)
                self._terminate_replica(rid, complete_intent=iid)

            threading.Thread(target=work, daemon=True).start()

    def _drain_replica(self, url: str) -> None:
        """Ask the replica process to drain gracefully (POST /drain:
        /health flips to 'draining', in-flight requests finish or are
        cancelled under SKYTPU_DRAIN_TIMEOUT_SECONDS, the process
        exits), then wait — bounded — for it to finish before the
        hard cluster teardown. Best-effort: a replica that never
        exposed the endpoint (or is already gone) just falls through
        to the kill."""
        base = url.rstrip('/')
        budget = max(1.0, lifecycle.drain_timeout_s())
        try:
            resp = requests.post(
                base + '/drain',
                timeout=(_PROBE_CONNECT_TIMEOUT_SECONDS, 5))
            if resp.status_code >= 400:
                logger.info('Replica %s has no drain endpoint '
                            '(HTTP %d); proceeding to teardown.',
                            url, resp.status_code)
                return
            try:
                # The REPLICA's budget governs how long its drain may
                # take — its env may differ from this controller's.
                # Finite only: an inf budget (JSON round-trips
                # Infinity) would wedge this teardown thread forever.
                echoed = float((resp.json() or {}).get('budget_s'))
                if math.isfinite(echoed) and echoed >= 0:
                    budget = max(1.0, echoed)
            except (ValueError, TypeError):
                pass
        except requests.RequestException as e:
            logger.info('Replica drain request to %s failed (%s); '
                        'proceeding to teardown.', url, e)
            return
        deadline = statedb.wall_now() + budget + 5.0
        while statedb.wall_now() < deadline:
            try:
                health = requests.get(base + '/health', timeout=(2, 5))
            except requests.RequestException:
                return      # process exited: drain complete
            try:
                if (health.json() or {}).get('status') != 'draining':
                    return  # terminal (ok after abort, or dead)
            except ValueError:
                return
            # skytpu-lint: disable=STL002 — bounded drain-completion
            # poll, not a retry loop: nothing is re-attempted, the
            # loop only waits for the replica's own drain to finish.
            # Sleeps ride the same injectable clock as the deadline.
            statedb.wall_clock().sleep(0.25)
        logger.warning('Replica at %s still draining after the %.0fs '
                       'budget; proceeding to teardown.', url, budget)

    def _down_cluster(self, cluster: str) -> None:
        """Cloud-teardown seam (the synthetic fleet manager overrides
        this to reclaim from the synthetic cloud instead)."""
        from skypilot_tpu import core
        _TERMINATE_RETRY_POLICY.call(core.down, cluster)

    def _terminate_replica(
            self, replica_id: int,
            final_status: Optional[ReplicaStatus] = ReplicaStatus.SHUTDOWN,
            remove: bool = False,
            complete_intent: Optional[int] = None) -> None:
        try:
            with trace_lib.span('serve.replica.terminate',
                                slow_ok=True,
                                service=self.service_name,
                                replica=replica_id):
                self._down_cluster(self._cluster_name(replica_id))
        except exceptions.ClusterDoesNotExist:
            pass
        except Exception:  # pylint: disable=broad-except
            logger.warning('Replica %d teardown error:\n%s', replica_id,
                           traceback.format_exc())
        if remove:
            serve_state.remove_replica(self.service_name, replica_id,
                                       complete_intent=complete_intent)
        elif final_status is not None:
            serve_state.set_replica_status(self.service_name, replica_id,
                                           final_status,
                                           complete_intent=complete_intent)
        elif complete_intent is not None:
            serve_state.complete_intent(complete_intent)

    def _terminate_in_background(
            self, replica_id: int,
            final_status: Optional[ReplicaStatus] = ReplicaStatus.SHUTDOWN,
            remove: bool = False,
            complete_intent: Optional[int] = None) -> None:
        """Cluster teardown takes seconds-to-minutes; never block the
        probe loop on it (advisor finding: the synchronous PREEMPTED
        path stalled probing for the whole teardown)."""
        with self._lock:
            if replica_id in self._terminating:
                return
            self._terminating.add(replica_id)
            self._failed_probes.pop(replica_id, None)

        def work() -> None:
            try:
                self._terminate_replica(replica_id, final_status, remove,
                                        complete_intent=complete_intent)
            finally:
                with self._lock:
                    self._terminating.discard(replica_id)

        threading.Thread(target=work, daemon=True).start()

    # ------------------------------------------------------------------
    # Crash-only startup (docs/crash_recovery.md).

    def reconcile_on_start(self) -> Dict[str, int]:
        """Replay open scale-up/scale-down intents against cluster
        truth, then sweep orphans — the first thing a (re)started
        controller does, so a `kill -9` at any instruction of a
        scale operation leaves the service convergent:

        - open ``serve.scale_up`` + live cluster  -> **adopt** (mark
          STARTING; the probe loop takes it to READY — no relaunch,
          no duplicate cluster for the replica id);
        - open ``serve.scale_up`` + no/dead cluster -> **roll back**
          (drop the row, terminate leftovers; the autoscaler launches
          a fresh replica id);
        - open ``serve.scale_down``               -> **roll forward**
          (the announcement was the point of no return: terminate and
          drop the row; the drain is skipped — its requests died with
          the dead controller's LB anyway);
        - rows/clusters with no journal entry     -> **orphan** sweep
          (SHUTTING_DOWN rows re-enter teardown; replica-named
          clusters without a row are terminated).

        Returns action -> count (also exported via
        ``skytpu_serve_reconciled_intents_total``).
        """
        actions: Dict[str, int] = {}

        def count(action: str) -> None:
            actions[action] = actions.get(action, 0) + 1
            _M_RECONCILED.inc(1, action=action)

        rows = {r['replica_id']: r
                for r in serve_state.get_replicas(self.service_name)}
        journaled = set()
        for intent in serve_state.open_intents(self.service_name):
            payload = intent['payload']
            rid = payload.get('replica_id')
            cluster = payload.get('cluster_name')
            journaled.add(rid)
            if intent['kind'] == 'serve.scale_up':
                if self._cluster_is_up(cluster):
                    logger.info(
                        'Reconcile: adopting replica %s (cluster %s '
                        'launched by the previous controller).', rid,
                        cluster)
                    serve_state.set_replica_status(
                        self.service_name, rid, ReplicaStatus.STARTING,
                        complete_intent=intent['intent_id'])
                    count('adopt')
                else:
                    logger.info(
                        'Reconcile: rolling back half-launched replica '
                        '%s (cluster %s not up).', rid, cluster)
                    serve_state.remove_replica(
                        self.service_name, rid,
                        complete_intent=intent['intent_id'])
                    rows.pop(rid, None)
                    # A partially-provisioned cluster may still hold
                    # resources; the teardown is a no-op when nothing
                    # exists.
                    self._terminate_in_background(rid, final_status=None,
                                                  remove=False)
                    count('roll_back')
            elif intent['kind'] == 'serve.scale_down':
                logger.info(
                    'Reconcile: rolling forward scale-down of replica '
                    '%s.', rid)
                self._terminate_in_background(
                    rid, remove=True,
                    complete_intent=intent['intent_id'])
                count('roll_forward')
            else:
                logger.warning('Reconcile: unknown intent kind %r; '
                               'dropping.', intent['kind'])
                serve_state.complete_intent(intent['intent_id'])
                count('orphan')
        # Journal-less leftovers. SHUTTING_DOWN rows re-enter teardown;
        # PENDING/PROVISIONING rows without an intent can only be
        # pre-migration debris — their launch thread died with the old
        # process and nothing will ever advance them.
        for rid, row in list(rows.items()):
            if rid in journaled:
                continue
            if row['status'] is ReplicaStatus.SHUTTING_DOWN:
                logger.info('Reconcile: resuming teardown of replica '
                            '%d.', rid)
                self._terminate_in_background(rid, remove=True)
                count('roll_forward')
            elif row['status'] in (ReplicaStatus.PENDING,
                                   ReplicaStatus.PROVISIONING):
                if self._cluster_is_up(row['cluster_name']):
                    # The cluster made it up: adopt rather than waste.
                    logger.info(
                        'Reconcile: replica %d stuck %s with no '
                        'intent record but a live cluster; adopting.',
                        rid, row['status'].value)
                    serve_state.set_replica_status(
                        self.service_name, rid, ReplicaStatus.STARTING)
                    count('adopt')
                else:
                    logger.warning(
                        'Reconcile: replica %d stuck %s with no '
                        'intent record (orphan row); removing.', rid,
                        row['status'].value)
                    serve_state.remove_replica(self.service_name, rid)
                    self._terminate_in_background(rid, final_status=None,
                                                  remove=False)
                    count('orphan')
        # Orphan clusters: a cluster named like one of OUR replicas
        # with no row to account for it (e.g. a rolled-back row whose
        # teardown crashed) must not keep burning money.
        prefix = f'{self.service_name}-replica-'
        known = set(rows) | journaled
        for name in self._list_cluster_names():
            if not name.startswith(prefix):
                continue
            try:
                rid = int(name[len(prefix):])
            except ValueError:
                continue
            if rid in known:
                continue
            logger.warning(
                'Reconcile: orphan replica cluster %s (no replica '
                'row); terminating.', name)
            self._terminate_in_background(rid, final_status=None,
                                          remove=False)
            count('orphan')
        if actions:
            logger.info('Reconcile on start for %s: %s.',
                        self.service_name, actions)
        return actions

    def _list_cluster_names(self) -> List[str]:
        """All known cluster names — the orphan sweep's search space
        (seam: the synthetic fleet manager lists its cloud instead)."""
        from skypilot_tpu import global_user_state
        try:
            return [r.get('name') or ''
                    for r in global_user_state.get_clusters()]
        except Exception:  # pylint: disable=broad-except
            return []

    def _cluster_is_up(self, cluster: Optional[str]) -> bool:
        if not cluster:
            return False
        try:
            record = backend_utils.refresh_cluster_record(
                cluster, force_refresh=True)
        except Exception:  # pylint: disable=broad-except
            return False
        return (record is not None and
                record['status'] is status_lib.ClusterStatus.UP)

    def terminate_all(self) -> None:
        replicas = serve_state.get_replicas(self.service_name)
        ids = [
            r['replica_id'] for r in replicas
            if r['status'] not in (ReplicaStatus.SHUTDOWN,)
        ]
        threads = []
        for replica_id in ids:
            t = threading.Thread(target=self._terminate_replica,
                                 args=(replica_id,), daemon=False)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()

    # ------------------------------------------------------------------
    def _replica_url(self, replica_id: int, cluster: str,
                     spec: Optional[ServiceSpec] = None) -> Optional[str]:
        record = backend_utils.refresh_cluster_record(cluster)
        if record is None or record.get('handle') is None:
            return None
        handle = record['handle']
        ips = handle.ip_list()
        if not ips:
            return None
        return f'http://{ips[0]}:{self._replica_port(replica_id, spec)}'

    def _probe_ready(self, url: str, spec: ServiceSpec,
                     replica_id: Optional[int] = None) -> str:
        """One readiness probe with an explicit, always-bounded
        per-request timeout; returns 'ready', 'draining',
        'preempting' or 'down'. A single failed probe never declares
        a replica dead — probe_all counts consecutive failures
        against not_ready_threshold /
        probe_failure_terminate_threshold. A 'draining' answer (the
        replica got SIGTERM and is finishing its in-flight work,
        docs/request_lifecycle.md) and a 'preempting' answer (a spot
        reclaim notice arrived; the SIGKILL follows shortly,
        docs/spot_serving.md) are DELIBERATE states, not failures:
        the replica leaves the routable set immediately but is not
        counted toward the failed-probe terminate streak."""
        fault = fault_injection.poll('serve.replica.probe_ready',
                                     replica_id=replica_id, url=url)
        if fault is not None:
            _M_PROBE_FAILURES.inc(1, replica=url)
            return 'down'
        read_timeout = (_DEFAULT_PROBE_TIMEOUT_SECONDS
                        if spec.readiness_timeout_seconds is None
                        else spec.readiness_timeout_seconds)
        connect_timeout = min(_PROBE_CONNECT_TIMEOUT_SECONDS,
                              read_timeout)
        try:
            resp = requests.get(
                url.rstrip('/') + spec.readiness_path,
                timeout=(connect_timeout, read_timeout))
            if resp.status_code >= 500:
                try:
                    answered = (resp.json() or {}).get('status')
                    if answered in ('draining', 'preempting'):
                        return answered
                except ValueError:
                    pass
                _M_PROBE_FAILURES.inc(1, replica=url)
                return 'down'
            try:
                body = resp.json()
            except ValueError:
                body = None
            if isinstance(body, dict):
                self._note_health(url, body)
            return 'ready'
        except requests.RequestException:
            _M_PROBE_FAILURES.inc(1, replica=url)
            return 'down'

    def _note_health(self, url: str, body: dict) -> None:
        """Stash a ready probe's parsed /health body — the prefix
        digest source for affinity routing and peer warming. Guarded
        so a bare ``__new__``-built manager (unit-test idiom) can
        still run _probe_ready."""
        store = getattr(self, '_probe_health', None)
        if store is None:
            return
        with self._lock:
            store[url] = body

    def prefix_digests(self) -> Dict[str, Optional[dict]]:
        """Latest advertised /health prefix digest per replica URL
        (None for replicas without a prefix cache). The controller
        pushes this to the LB's cache-aware policy every probe cycle
        — probe cadence, never per-request HTTP
        (docs/affinity_routing.md)."""
        with self._lock:
            return {u: (b or {}).get('prefix')
                    for u, b in self._probe_health.items()}

    def _maybe_peer_warm(self, replica_id: int, url: str) -> None:
        """Peer cache warming at the STARTING->READY edge
        (docs/affinity_routing.md): before a newly provisioned
        replica becomes routable, pick the warmest READY donor from
        the stashed /health digests and have the new replica pull
        the donor's hottest pages (its recency-ordered digest list,
        truncated to the SKYTPU_WARM_MAX_PAGES budget) through
        /kv/warm -> /kv/fetch -> queue_kv_import. Strictly bounded
        and best-effort: any failure, a digest-less fleet, or an
        exhausted SKYTPU_WARM_TIMEOUT_S leaves the replica to start
        cold — readiness is delayed by at most the timeout, never
        blocked."""
        budget = max(0, int(env_registry.get(
            env_registry.SKYTPU_WARM_MAX_PAGES, '64')))
        if budget <= 0:
            return
        ready_urls = {
            r.get('url')
            for r in serve_state.get_replicas(self.service_name)
            if r['status'] is ReplicaStatus.READY and r.get('url')}
        ready_urls.discard(url)
        with self._lock:
            digests = {
                u: (self._probe_health.get(u) or {}).get('prefix')
                for u in ready_urls}
        donor: Optional[str] = None
        donor_hashes: List[str] = []
        # Warmest donor = most advertised pages; sorted for a
        # deterministic pick on ties.
        for u, d in sorted(digests.items()):
            if (not isinstance(d, dict) or
                    d.get('v') != chain_hash.SUMMARY_SCHEMA_VERSION):
                continue
            hx = d.get('hashes') or []
            if len(hx) > len(donor_hashes):
                donor, donor_hashes = u, hx
        if donor is None:
            return
        want = donor_hashes[:budget]
        with trace_lib.span('serve.peer_warm', replica=url,
                            donor=donor, requested=len(want)):
            imported = peer_warm(url, donor, want)
        if imported:
            logger.info(
                'Peer-warmed replica %d at %s with %d page(s) from '
                'donor %s before READY.', replica_id, url, imported,
                donor)

    def note_unreachable(self, url: str) -> None:
        """First-hand unreachability evidence from the data plane
        (docs/failover.md): the LB got a connection refused/reset on
        a PROXY attempt — the replica process is gone or wedged NOW.
        Demote the replica out of the routable set immediately
        instead of waiting for the probe cycle to notice, and feed
        the same consecutive-failure streak a failed probe would, so
        a dead-app replica still reaches the terminate threshold.
        Idempotent and cheap; called off the LB's event loop."""
        for replica in serve_state.get_replicas(self.service_name):
            if replica.get('url') != url:
                continue
            if replica['status'] not in (ReplicaStatus.READY,
                                         ReplicaStatus.NOT_READY):
                continue
            rid = replica['replica_id']
            with self._lock:
                self._failed_probes[rid] = (
                    self._failed_probes.get(rid, 0) + 1)
                streak = self._failed_probes[rid]
            _M_PROBE_FAILURES.inc(1, replica=url)
            if replica['status'] is ReplicaStatus.READY:
                logger.warning(
                    'Replica %d at %s unreachable on a proxy attempt '
                    '(streak %d): demoting to NOT_READY without '
                    'waiting for the probe cycle.', rid, url, streak)
                serve_state.set_replica_status(
                    self.service_name, rid, ReplicaStatus.NOT_READY)
            return

    def probe_all(self) -> None:
        """One probe pass: drive the FSM for every live replica."""
        spec_cache: Dict[int, ServiceSpec] = {}
        for replica in serve_state.get_replicas(self.service_name):
            rid = replica['replica_id']
            status = replica['status']
            version = replica.get('version') or 1
            if version not in spec_cache:
                spec_cache[version] = self._version_spec(version)
            spec = spec_cache[version]
            if status not in (ReplicaStatus.STARTING,
                              ReplicaStatus.READY,
                              ReplicaStatus.NOT_READY):
                continue
            cluster = replica['cluster_name']
            if not self._cluster_is_up(cluster):
                # Cluster died under us: preemption. Mark it (so
                # reconcile immediately launches a replacement) and
                # clean leftovers in the background; the cleanup
                # removes the row once the cluster is gone.
                logger.info('Replica %d cluster %s gone: PREEMPTED.',
                            rid, cluster)
                serve_state.set_replica_status(self.service_name, rid,
                                               ReplicaStatus.PREEMPTED)
                _M_PREEMPTIONS.inc(1, phase='kill')
                with self._lock:
                    noticed = rid in self._preempt_noticed
                    self._preempt_noticed.discard(rid)
                if (not noticed and replica.get('is_spot') and
                        self.on_preemption is not None):
                    # Killed without (observed) warning: this is the
                    # preemption's FIRST evidence, so the estimator
                    # event fires here instead of the notice path.
                    self.on_preemption()
                self._terminate_in_background(rid, remove=True)
                continue
            url = self._replica_url(rid, cluster, spec)
            probe = ('down' if url is None else
                     self._probe_ready(url, spec, replica_id=rid))
            if probe == 'ready':
                if status is ReplicaStatus.STARTING:
                    # First ready probe of a newly provisioned
                    # replica (scale-up or spot replacement): peer
                    # warming happens HERE, before the READY
                    # transition makes it routable — bounded by the
                    # warm budget/timeout, degrading to a cold start
                    # on any failure (docs/affinity_routing.md).
                    self._maybe_peer_warm(rid, url)
                with self._lock:
                    self._failed_probes[rid] = 0
                    # A notice the cloud walked back (capacity
                    # restored): a later notice is a NEW preemption.
                    self._preempt_noticed.discard(rid)
                serve_state.set_replica_status(self.service_name, rid,
                                               ReplicaStatus.READY,
                                               url=url)
            elif probe == 'draining':
                # Deliberate drain (SIGTERM'd replica finishing its
                # in-flight work): leave the routable set NOW — the
                # same exclusion a failed-probe demotion gets, but
                # without waiting out the not-ready threshold and
                # without feeding the terminate streak (the drain
                # path owns this replica's teardown).
                logger.info('Replica %d is draining: demoting to '
                            'NOT_READY.', rid)
                serve_state.set_replica_status(self.service_name, rid,
                                               ReplicaStatus.NOT_READY)
            elif probe == 'preempting':
                # Spot reclaim notice (docs/spot_serving.md): same
                # contract as draining — leave the routable set NOW,
                # never feed the terminate streak (the kill arrives on
                # the cloud's clock; terminating early would only
                # throw away the migration window). The notice
                # metric/estimator event fires once per replica.
                with self._lock:
                    first = rid not in self._preempt_noticed
                    self._preempt_noticed.add(rid)
                if first:
                    logger.info(
                        'Replica %d got a preemption notice: demoting '
                        'to NOT_READY until the kill lands.', rid)
                    _M_PREEMPTIONS.inc(1, phase='notice')
                    if (replica.get('is_spot') and
                            self.on_preemption is not None):
                        self.on_preemption()
                    if self.on_preempt_notice is not None and url:
                        self.on_preempt_notice(url)
                serve_state.set_replica_status(self.service_name, rid,
                                               ReplicaStatus.NOT_READY)
            elif status in (ReplicaStatus.READY,
                            ReplicaStatus.NOT_READY):
                with self._lock:
                    self._failed_probes[rid] = (
                        self._failed_probes.get(rid, 0) + 1)
                    streak = self._failed_probes[rid]
                if streak >= self.probe_failure_terminate_threshold:
                    # App is dead though the cluster is UP: tear the
                    # replica down so reconcile replaces it, instead
                    # of letting a broken replica hold a slot forever.
                    logger.warning(
                        'Replica %d failed %d consecutive probes: '
                        'terminating for replacement.', rid, streak)
                    serve_state.set_replica_status(
                        self.service_name, rid,
                        ReplicaStatus.FAILED_PROBING)
                    # Keep the row (counts toward the failure cap so a
                    # crash-looping app can't relaunch forever).
                    self._terminate_in_background(
                        rid, ReplicaStatus.FAILED_PROBING)
                elif streak >= self.not_ready_threshold:
                    # Transient blips tolerated; sustained demotes (LB
                    # stops routing to it).
                    serve_state.set_replica_status(
                        self.service_name, rid, ReplicaStatus.NOT_READY)
            elif status == ReplicaStatus.STARTING:
                # Budget counted from the STARTING transition
                # (post-provision), not submission: provisioning time
                # must not eat the app's warm-up allowance.
                starting_at = (replica.get('starting_at') or
                               replica.get('launched_at') or 0)
                if (statedb.wall_now() - starting_at >
                        spec.initial_delay_seconds):
                    logger.warning(
                        'Replica %d never became ready within '
                        'initial_delay_seconds: FAILED.', rid)
                    serve_state.set_replica_status(
                        self.service_name, rid,
                        ReplicaStatus.FAILED_INITIAL_DELAY)
                    self._terminate_in_background(
                        rid, ReplicaStatus.FAILED_INITIAL_DELAY)

    # ------------------------------------------------------------------
    _LIVE_STATUSES = (ReplicaStatus.PENDING, ReplicaStatus.PROVISIONING,
                      ReplicaStatus.STARTING, ReplicaStatus.READY,
                      ReplicaStatus.NOT_READY)

    def _scale_pool_to(self, pool: List[dict], want: int, version: int,
                       is_spot: Optional[bool]) -> None:
        if len(pool) < want:
            self.scale_up(want - len(pool), version=version,
                          is_spot=is_spot)
        elif len(pool) > want:
            # Prefer shutting down not-ready, then newest.
            order = sorted(
                pool,
                key=lambda r: (r['status'] == ReplicaStatus.READY,
                               -r['replica_id']))
            doomed = order[:len(pool) - want]
            self.scale_down([r['replica_id'] for r in doomed])

    def reconcile(self, decision) -> None:
        """Converge replicas toward the scaling decision: replace
        preempted replicas, roll old versions forward, and keep the
        spot/on-demand mix.

        Rolling update (reference sky/serve/autoscalers.py:215): new
        replicas always launch at current_version; old-version replicas
        keep serving until the new version's full target is READY, then
        drain all at once — an update that cannot come up never takes
        the service down.
        """
        from skypilot_tpu.serve import autoscalers
        if isinstance(decision, int):  # convenience for tests/callers
            decision = autoscalers.ScalingDecision(decision)
        target = decision.target_replicas
        current_version = serve_state.get_current_version(
            self.service_name)
        replicas = serve_state.get_replicas(self.service_name)
        live = [r for r in replicas if r['status'] in self._LIVE_STATUSES]
        # Fully-shutdown rows are done — garbage-collect them (replica
        # ids are a monotonic counter, so removal cannot cause a
        # cluster-name collision). PREEMPTED rows normally have a
        # cleanup thread in flight from probe_all; re-arm it here in
        # case a controller restart orphaned the row (the _terminating
        # guard makes this a no-op when one is already running).
        now = statedb.wall_now()
        for r in replicas:
            if r['status'] is ReplicaStatus.SHUTDOWN:
                serve_state.remove_replica(self.service_name,
                                           r['replica_id'])
            elif r['status'] is ReplicaStatus.PREEMPTED:
                self._terminate_in_background(r['replica_id'],
                                              remove=True)
            elif (r['status'].is_failed() and
                  now - (r.get('failed_at') or r['launched_at'] or 0)
                  > _FAILED_ROW_TTL_SECONDS):
                serve_state.remove_replica(self.service_name,
                                           r['replica_id'])
        # A string of FAILED launches means the task itself is broken —
        # stop burning clusters (reference replica_managers marks the
        # service failed rather than relaunching forever). Only recent
        # failures count, so isolated crashes over a long-lived service
        # cannot brick it.
        failed = sum(
            1 for r in replicas if r['status'].is_failed() and
            now - (r.get('failed_at') or r['launched_at'] or 0)
            <= _FAILED_ROW_TTL_SECONDS)
        halted = failed > _MAX_FAILED_REPLICAS
        if halted:
            logger.error(
                'Service %s: %d recently-failed replicas; halting '
                'scale-up.', self.service_name, failed)

        latest = [r for r in live
                  if (r.get('version') or 1) == current_version]
        old = [r for r in live
               if (r.get('version') or 1) != current_version]

        if not halted:
            if decision.num_spot is None:
                self._scale_pool_to(latest, target, current_version,
                                    is_spot=None)
            else:
                spot_pool = [r for r in latest if r.get('is_spot')]
                od_pool = [r for r in latest if not r.get('is_spot')]
                self._scale_pool_to(spot_pool, decision.num_spot,
                                    current_version, is_spot=True)
                self._scale_pool_to(od_pool, decision.num_ondemand,
                                    current_version, is_spot=False)

        if old:
            ready_latest = sum(1 for r in latest
                               if r['status'] is ReplicaStatus.READY)
            if ready_latest >= target:
                logger.info(
                    'Service %s: version %d fully READY (%d/%d); '
                    'draining %d old-version replicas.',
                    self.service_name, current_version, ready_latest,
                    target, len(old))
                self.scale_down([r['replica_id'] for r in old])

    def ready_urls(self) -> List[str]:
        return [
            r['url'] for r in serve_state.get_replicas(self.service_name)
            if r['status'] == ReplicaStatus.READY and r['url']
        ]

    def ready_replicas(self) -> List[dict]:
        """READY replicas with their routing-relevant attributes
        (url + is_spot): the controller hands this to the LB so
        hedge/resume target selection can prefer on-demand survivors
        over the next potential victim (docs/spot_serving.md)."""
        return [{
            'url': r['url'],
            'is_spot': bool(r.get('is_spot')),
        } for r in serve_state.get_replicas(self.service_name)
            if r['status'] == ReplicaStatus.READY and r['url']]
