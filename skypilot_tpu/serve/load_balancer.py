"""HTTP load balancer: aiohttp reverse proxy over ready replicas.

Re-design of reference ``sky/serve/load_balancer.py:22`` +
``load_balancing_policies.py:89,115`` (RoundRobinPolicy /
LeastLoadPolicy). Runs inside the service controller process; replica
URLs are pushed in by the replica manager, and every proxied request
is reported to the autoscaler as load signal.

Proxying is streaming end to end: response bodies are forwarded
chunk-by-chunk (SSE token streams from the engine front end reach the
client as they are produced, like the reference LB's streaming
passthrough), upstream connections come from one pooled
``ClientSession`` (per-request sessions pay TCP+TLS setup on every
proxied call), and a request whose replica cannot be reached — the
connection failed, so the replica never saw it — is transparently
retried on a different ready replica. Replica removal (rolling
update, downscale) can ``drain()`` a URL: stop picking it, then wait
for its in-flight requests to finish before teardown.
"""
from __future__ import annotations

import asyncio
import itertools
import threading
import time
from typing import Callable, List, Optional, Set

import aiohttp
from aiohttp import web

from skypilot_tpu import metrics as metrics_lib
from skypilot_tpu import trace as trace_lib
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

_HOP_HEADERS = {
    'connection', 'keep-alive', 'proxy-authenticate',
    'proxy-authorization', 'te', 'trailers', 'transfer-encoding',
    'upgrade', 'host',
}

# Per-replica serving signals (docs/metrics.md). The in-flight gauge
# is the SINGLE store of per-replica load: LeastLoadPolicy routes on
# it, drain() waits on it, and operators scrape it — no second
# private count that can disagree with the dashboard.
_M_INFLIGHT = metrics_lib.gauge(
    'skytpu_lb_replica_inflight',
    'Requests currently proxied to the replica.',
    labels=('replica',))
_M_LATENCY = metrics_lib.histogram(
    'skytpu_lb_replica_request_seconds',
    'End-to-end proxied request latency per replica.',
    labels=('replica',), buckets=metrics_lib.LATENCY_BUCKETS)
_M_ERRORS = metrics_lib.counter(
    'skytpu_lb_replica_errors_total',
    'Proxy failures per replica by kind (connect, disconnect, '
    'mid_stream, upstream).',
    labels=('replica', 'kind'))


class LoadBalancingPolicy:
    """Base: owns the replica URL set and the shared in-flight gauge
    lifecycle (series appear/disappear with replicas). ``pick`` must
    increment the gauge for the returned URL; ``done`` releases it."""

    def __init__(self) -> None:
        self._urls: List[str] = []

    def set_urls(self, urls: List[str]) -> None:
        for gone in set(self._urls) - set(urls):
            # Drop the series ONLY when idle: drain() waits on this
            # gauge, and a rotation (scale-down marks the replica
            # SHUTTING_DOWN before its in-flight requests finish)
            # must not zero the count out from under it — the old
            # private-dict implementation never pruned on set_urls
            # either. done() removes the straggler series once it
            # reaches zero.
            if not _M_INFLIGHT.has_series(replica=gone) or \
                    _M_INFLIGHT.value(replica=gone) <= 0:
                _M_INFLIGHT.remove(replica=gone)
        for url in urls:
            _M_INFLIGHT.touch(replica=url)
        self._on_set_urls(list(urls))
        self._urls = list(urls)

    def _on_set_urls(self, urls: List[str]) -> None:
        pass

    def pick(self, exclude: Optional[Set[str]] = None) -> Optional[str]:
        raise NotImplementedError

    def done(self, url: str) -> None:
        if url in self._urls:
            _M_INFLIGHT.dec(floor=0.0, replica=url)
        elif _M_INFLIGHT.has_series(replica=url):
            # Rotated out while in flight: release, and retire the
            # series once the last straggler finishes (drain() has
            # nothing left to wait on).
            if _M_INFLIGHT.dec(floor=0.0, replica=url) <= 0:
                _M_INFLIGHT.remove(replica=url)


class RoundRobinPolicy(LoadBalancingPolicy):

    def __init__(self) -> None:
        super().__init__()
        self._it = itertools.cycle([])

    def _on_set_urls(self, urls: List[str]) -> None:
        if urls != self._urls:
            self._it = itertools.cycle(urls)

    def pick(self, exclude: Optional[Set[str]] = None) -> Optional[str]:
        if not self._urls:
            return None
        for _ in range(len(self._urls)):
            url = next(self._it)
            if not exclude or url not in exclude:
                _M_INFLIGHT.inc(1, replica=url)
                return url
        return None


class LeastLoadPolicy(LoadBalancingPolicy):
    """Route to the replica with the fewest in-flight requests.

    The in-flight count IS the ``skytpu_lb_replica_inflight`` gauge:
    the policy routes on exactly the series operators scrape, instead
    of a private dict that could drift from the dashboard."""

    def __init__(self) -> None:
        super().__init__()
        self._lock = threading.Lock()

    def pick(self, exclude: Optional[Set[str]] = None) -> Optional[str]:
        with self._lock:
            candidates = [u for u in self._urls
                          if not exclude or u not in exclude]
            if not candidates:
                return None
            url = min(candidates,
                      key=lambda u: _M_INFLIGHT.value(replica=u))
            _M_INFLIGHT.inc(1, replica=url)
            return url


POLICIES = {
    'round_robin': RoundRobinPolicy,
    'least_load': LeastLoadPolicy,
}


class LoadBalancer:
    """aiohttp app proxying every request to a picked replica."""

    MAX_ATTEMPTS = 3

    def __init__(self, port: int, policy: str = 'least_load',
                 on_request: Optional[Callable[[], None]] = None) -> None:
        # port 0 = let the OS pick; the actual port is in `bound_port`
        # after start() (avoids probe-then-rebind TOCTOU races).
        self.port = port
        self.bound_port: Optional[int] = None
        self.policy: LoadBalancingPolicy = POLICIES[policy]()
        self.on_request = on_request
        self._runner: Optional[web.AppRunner] = None
        self._session: Optional[aiohttp.ClientSession] = None
        self._draining: Set[str] = set()

    def set_replica_urls(self, urls: List[str]) -> None:
        self.policy.set_urls(urls)
        self._draining &= set(urls)

    def inflight(self, url: str) -> int:
        # One store for in-flight load: the scraped gauge, maintained
        # by policy.pick()/done().
        return int(_M_INFLIGHT.value(replica=url))

    async def drain(self, url: str, timeout: float = 60.0) -> bool:
        """Stop routing new requests to ``url`` and wait for its
        in-flight ones to finish (rolling update / downscale: tear the
        replica down only after this returns). True = drained."""
        self._draining.add(url)
        deadline = time.time() + timeout
        while self.inflight(url) > 0:
            if time.time() > deadline:
                return False
            await asyncio.sleep(0.05)
        return True

    # ------------------------------------------------------------------
    async def _proxy(self, request: web.Request) -> web.StreamResponse:
        # One request span per proxied call, continuing the client's
        # trace when it sent a traceparent header (docs/tracing.md);
        # each replica attempt is a child span whose duration IS the
        # per-replica latency observation (single timing source), and
        # whose trace id rides on the histogram as an exemplar.
        ctx = trace_lib.context_from_headers(request.headers)
        with trace_lib.span('lb.request', parent=ctx,
                            method=request.method,
                            path=request.rel_url.path):
            return await self._proxy_attempts(request)

    async def _proxy_attempts(self, request: web.Request
                              ) -> web.StreamResponse:
        if self.on_request is not None:
            self.on_request()
        body = await request.read()
        tried: Set[str] = set()
        last_err: Optional[BaseException] = None
        trace_id = trace_lib.current_trace_id()
        for _ in range(self.MAX_ATTEMPTS):
            url = self.policy.pick(exclude=tried | self._draining)
            if url is None:
                break
            tried.add(url)
            sp = trace_lib.start_span('lb.proxy', replica=url)
            try:
                with trace_lib.activate(sp):
                    resp = await self._proxy_once(request, url, body)
                sp.finish(status=resp.status)
                _M_LATENCY.observe(sp.duration, exemplar=sp.exemplar,
                                   replica=url)
                return resp
            except aiohttp.ClientConnectorError as e:
                # TCP connect failed: the replica NEVER received the
                # request — safe to retry on another replica for any
                # method.
                sp.finish(error='connect')
                logger.warning('Replica %s unreachable (%s); retrying '
                               'on another replica (trace=%s)', url, e,
                               trace_id)
                _M_ERRORS.inc(1, replica=url, kind='connect')
                last_err = e
            except aiohttp.ClientConnectionError as e:
                # Connection dropped after the request was sent (e.g.
                # ServerDisconnectedError): the replica may have
                # started executing it. Retrying would double-execute
                # non-idempotent work, so only safe methods retry.
                sp.finish(error='disconnect')
                _M_ERRORS.inc(1, replica=url, kind='disconnect')
                if request.method not in ('GET', 'HEAD', 'OPTIONS'):
                    logger.warning('Replica %s dropped mid-request '
                                   '(%s); not retrying %s (trace=%s)',
                                   url, e, request.method, trace_id)
                    last_err = e
                    break
                logger.warning('Replica %s dropped %s (%s); retrying '
                               '(trace=%s)', url, request.method, e,
                               trace_id)
                last_err = e
            except _MidStreamError as e:
                # Bytes already reached the client: cannot retry.
                sp.finish(error='mid_stream')
                logger.warning('Replica %s died mid-response: %s '
                               '(trace=%s)', url, e.cause, trace_id)
                _M_ERRORS.inc(1, replica=url, kind='mid_stream')
                return e.response
            except (aiohttp.ClientError, asyncio.TimeoutError) as e:
                sp.finish(error='upstream')
                logger.warning('Proxy to %s failed: %s (trace=%s)',
                               url, e, trace_id)
                _M_ERRORS.inc(1, replica=url, kind='upstream')
                last_err = e
                if request.method not in ('GET', 'HEAD', 'OPTIONS'):
                    # Same double-execution risk as the dropped-
                    # connection branch: the replica may have run the
                    # request (e.g. 200 headers then a payload error).
                    break
            finally:
                # An exception outside the enumerated arms — notably
                # CancelledError when the client disconnects mid-
                # proxy — must still land the attempt in the trace:
                # aborted requests are exactly the ones worth
                # reading. finish() is idempotent for the arms above.
                if sp.end_time is None:
                    sp.finish(error='aborted')
                self.policy.done(url)
        if last_err is None:
            return web.Response(status=503,
                                text='No ready replicas.\n')
        return web.Response(status=502,
                            text=f'Replica unreachable: {last_err}\n')

    async def _proxy_once(self, request: web.Request, url: str,
                          body: bytes) -> web.StreamResponse:
        target = url.rstrip('/') + '/' + request.rel_url.path.lstrip('/')
        if request.rel_url.query_string:
            target += '?' + request.rel_url.query_string
        headers = {
            k: v for k, v in request.headers.items()
            if k.lower() not in _HOP_HEADERS
        }
        # Continue the trace into the replica: the active lb.proxy
        # span replaces any client-sent traceparent (the replica must
        # parent under THIS hop, not skip it). When tracing is off
        # this is {} and the client's own header passes through.
        tp = trace_lib.traceparent_headers()
        if tp:
            headers = {k: v for k, v in headers.items()
                       if k.lower() != trace_lib.TRACEPARENT_HEADER}
            headers.update(tp)
        assert self._session is not None, 'start() not called'
        async with self._session.request(request.method, target,
                                         headers=headers,
                                         data=body) as resp:
            out_headers = {
                k: v for k, v in resp.headers.items()
                if k.lower() not in _HOP_HEADERS and
                k.lower() != 'content-length'
            }
            out = web.StreamResponse(status=resp.status,
                                     headers=out_headers)
            started = False
            try:
                # Chunk-by-chunk passthrough: an SSE token stream (or
                # any long body) reaches the client as the replica
                # produces it, instead of buffering end-to-end.
                async for chunk in resp.content.iter_chunked(1 << 16):
                    if not started:
                        await out.prepare(request)
                        started = True
                    await out.write(chunk)
                if not started:
                    await out.prepare(request)
                await out.write_eof()
                return out
            except (aiohttp.ClientError, asyncio.TimeoutError) as e:
                if started:
                    # Headers/body already sent; surface the abort to
                    # the wrapper as non-retryable.
                    raise _MidStreamError(out, e) from e
                raise

    async def _handle_metrics(self, request: web.Request
                              ) -> web.Response:
        """The controller-side scrape point: this process's LB +
        autoscaler + replica-manager metrics (docs/metrics.md).
        Registered before the catch-all proxy route, so /metrics is
        served locally, not proxied. This process's registry only —
        spool merging is the API server's job (one merger per host,
        or multi-endpoint scrapes double-count the spool)."""
        text = metrics_lib.render_exposition()
        return web.Response(
            text=text, headers={'Content-Type': metrics_lib.CONTENT_TYPE})

    # ------------------------------------------------------------------
    async def start(self) -> None:
        app = web.Application()
        app.router.add_get('/metrics', self._handle_metrics)
        app.router.add_route('*', '/{tail:.*}', self._proxy)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        # One pooled upstream session: per-request sessions pay
        # connection setup on every proxied call (18% stack tax in the
        # r03 full-stack bench). No total timeout — long-lived SSE
        # streams are legitimate; sock_read bounds replica *silence*
        # instead, so a wedged replica still gets cut.
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=None, sock_connect=10,
                                          sock_read=300),
            connector=aiohttp.TCPConnector(limit=0,
                                           limit_per_host=0,
                                           keepalive_timeout=60))
        site = web.TCPSite(self._runner, '0.0.0.0', self.port)
        await site.start()
        sockets = site._server.sockets  # pylint: disable=protected-access
        self.bound_port = sockets[0].getsockname()[1]
        logger.info('Load balancer listening on :%d', self.bound_port)

    async def stop(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None
        if self._runner is not None:
            await self._runner.cleanup()


class _MidStreamError(Exception):
    """Upstream died after response bytes reached the client."""

    def __init__(self, response: web.StreamResponse,
                 cause: BaseException) -> None:
        super().__init__(str(cause))
        self.response = response
        self.cause = cause
