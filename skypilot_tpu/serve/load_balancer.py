"""HTTP load balancer: aiohttp reverse proxy over ready replicas.

Re-design of reference ``sky/serve/load_balancer.py:22`` +
``load_balancing_policies.py:89,115`` (RoundRobinPolicy /
LeastLoadPolicy). Runs inside the service controller process; replica
URLs are pushed in by the replica manager, and every proxied request
is reported to the autoscaler as load signal.
"""
from __future__ import annotations

import asyncio
import itertools
import threading
from typing import Callable, Dict, List, Optional

import aiohttp
from aiohttp import web

from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

_HOP_HEADERS = {
    'connection', 'keep-alive', 'proxy-authenticate',
    'proxy-authorization', 'te', 'trailers', 'transfer-encoding',
    'upgrade', 'host',
}


class LoadBalancingPolicy:

    def set_urls(self, urls: List[str]) -> None:
        raise NotImplementedError

    def pick(self) -> Optional[str]:
        raise NotImplementedError

    def done(self, url: str) -> None:
        pass


class RoundRobinPolicy(LoadBalancingPolicy):

    def __init__(self) -> None:
        self._urls: List[str] = []
        self._it = itertools.cycle([])

    def set_urls(self, urls: List[str]) -> None:
        if urls != self._urls:
            self._urls = list(urls)
            self._it = itertools.cycle(self._urls)

    def pick(self) -> Optional[str]:
        if not self._urls:
            return None
        return next(self._it)


class LeastLoadPolicy(LoadBalancingPolicy):
    """Route to the replica with the fewest in-flight requests."""

    def __init__(self) -> None:
        self._load: Dict[str, int] = {}
        self._lock = threading.Lock()

    def set_urls(self, urls: List[str]) -> None:
        with self._lock:
            for url in urls:
                self._load.setdefault(url, 0)
            for url in list(self._load):
                if url not in urls:
                    del self._load[url]

    def pick(self) -> Optional[str]:
        with self._lock:
            if not self._load:
                return None
            url = min(self._load, key=self._load.get)
            self._load[url] += 1
            return url

    def done(self, url: str) -> None:
        with self._lock:
            if url in self._load:
                self._load[url] = max(0, self._load[url] - 1)


POLICIES = {
    'round_robin': RoundRobinPolicy,
    'least_load': LeastLoadPolicy,
}


class LoadBalancer:
    """aiohttp app proxying every request to a picked replica."""

    def __init__(self, port: int, policy: str = 'least_load',
                 on_request: Optional[Callable[[], None]] = None) -> None:
        # port 0 = let the OS pick; the actual port is in `bound_port`
        # after start() (avoids probe-then-rebind TOCTOU races).
        self.port = port
        self.bound_port: Optional[int] = None
        self.policy: LoadBalancingPolicy = POLICIES[policy]()
        self.on_request = on_request
        self._runner: Optional[web.AppRunner] = None

    def set_replica_urls(self, urls: List[str]) -> None:
        self.policy.set_urls(urls)

    # ------------------------------------------------------------------
    async def _proxy(self, request: web.Request) -> web.StreamResponse:
        if self.on_request is not None:
            self.on_request()
        url = self.policy.pick()
        if url is None:
            return web.Response(status=503,
                                text='No ready replicas.\n')
        target = url.rstrip('/') + '/' + request.rel_url.path.lstrip('/')
        if request.rel_url.query_string:
            target += '?' + request.rel_url.query_string
        headers = {
            k: v for k, v in request.headers.items()
            if k.lower() not in _HOP_HEADERS
        }
        body = await request.read()
        try:
            timeout = aiohttp.ClientTimeout(total=300)
            async with aiohttp.ClientSession(timeout=timeout) as session:
                async with session.request(request.method, target,
                                           headers=headers,
                                           data=body) as resp:
                    payload = await resp.read()
                    out_headers = {
                        k: v for k, v in resp.headers.items()
                        if k.lower() not in _HOP_HEADERS and
                        k.lower() != 'content-length'
                    }
                    return web.Response(status=resp.status,
                                        body=payload,
                                        headers=out_headers)
        except aiohttp.ClientError as e:
            logger.warning('Proxy to %s failed: %s', url, e)
            return web.Response(status=502,
                                text=f'Replica unreachable: {e}\n')
        finally:
            self.policy.done(url)

    # ------------------------------------------------------------------
    async def start(self) -> None:
        app = web.Application()
        app.router.add_route('*', '/{tail:.*}', self._proxy)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, '0.0.0.0', self.port)
        await site.start()
        sockets = site._server.sockets  # pylint: disable=protected-access
        self.bound_port = sockets[0].getsockname()[1]
        logger.info('Load balancer listening on :%d', self.bound_port)

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
