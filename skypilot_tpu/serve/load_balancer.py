"""HTTP load balancer: aiohttp reverse proxy over ready replicas.

Re-design of reference ``sky/serve/load_balancer.py:22`` +
``load_balancing_policies.py:89,115`` (RoundRobinPolicy /
LeastLoadPolicy). Runs inside the service controller process; replica
URLs are pushed in by the replica manager, and every proxied request
is reported to the autoscaler as load signal.

Proxying is streaming end to end: response bodies are forwarded
chunk-by-chunk (SSE token streams from the engine front end reach the
client as they are produced, like the reference LB's streaming
passthrough), upstream connections come from one pooled
``ClientSession`` (per-request sessions pay TCP+TLS setup on every
proxied call), and a request whose replica cannot be reached — the
connection failed, so the replica never saw it — is transparently
retried on a different ready replica. Replica removal (rolling
update, downscale) can ``drain()`` a URL: stop picking it, then wait
for its in-flight requests to finish before teardown.

Request lifecycle (docs/request_lifecycle.md): a client's
``X-Request-Deadline`` remaining-budget header becomes an absolute
deadline at arrival; every proxy attempt re-stamps the budget still
left, a past-deadline request is answered 504 and never retried, and
a replica's 429/503 shed is retried on another replica — with the
last shed's Retry-After and reason forwarded when every candidate
sheds.

Replica-failure survivability (docs/failover.md): every replica has a
circuit breaker (serve/failover.py) fed by first-hand proxy evidence
— a connect-refused trips it immediately (and notifies the replica
manager, which would otherwise only learn from the next probe cycle),
consecutive soft failures trip it at a threshold, and a half-open
trial request re-admits a recovered replica. Streaming ``/generate``
requests additionally get TTFT *hedging* (zero bytes streamed after a
p95-TTFT-derived delay races a second replica; the loser is cancelled
by request id, so at most one token stream ever reaches the client)
and mid-stream *resumption* for greedy requests (a replica dying
mid-stream re-submits prompt + tokens-emitted-so-far to a healthy
replica and splices the bitwise-identical continuation into the
client's SSE stream — no duplicated, no dropped tokens).
"""
from __future__ import annotations

import asyncio
import hashlib
import itertools
import json
import threading
import time
import types
from typing import Any, Callable, Dict, List, Optional, Sequence, Set

import aiohttp
from aiohttp import web

from skypilot_tpu import metrics as metrics_lib
from skypilot_tpu import trace as trace_lib
from skypilot_tpu.serve import failover
from skypilot_tpu.utils import chain_hash
from skypilot_tpu.utils import env_registry
from skypilot_tpu.utils import fault_injection
from skypilot_tpu.utils import lifecycle
from skypilot_tpu.utils import qos as qos_lib
from skypilot_tpu.utils import statedb
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

_HOP_HEADERS = {
    'connection', 'keep-alive', 'proxy-authenticate',
    'proxy-authorization', 'te', 'trailers', 'transfer-encoding',
    'upgrade', 'host',
}

# Per-replica serving signals (docs/metrics.md). The in-flight gauge
# is the SINGLE store of per-replica load: LeastLoadPolicy routes on
# it, drain() waits on it, and operators scrape it — no second
# private count that can disagree with the dashboard.
_M_INFLIGHT = metrics_lib.gauge(
    'skytpu_lb_replica_inflight',
    'Requests currently proxied to the replica.',
    labels=('replica',))
_M_LATENCY = metrics_lib.histogram(
    'skytpu_lb_replica_request_seconds',
    'End-to-end proxied request latency per replica.',
    labels=('replica',), buckets=metrics_lib.LATENCY_BUCKETS)
_M_ERRORS = metrics_lib.counter(
    'skytpu_lb_replica_errors_total',
    'Proxy failures per replica by kind (connect, disconnect, '
    'mid_stream, upstream, shed).',
    labels=('replica', 'kind'))
_M_LATENCY_P99 = metrics_lib.gauge(
    'skytpu_lb_request_p99_seconds',
    'Sliding-window p99 of end-to-end proxied request latency across '
    'all replicas (SKYTPU_SLO_WINDOW_S, default 60 s). The '
    'LB-level latency signal dashboards and the SLO autoscaler read '
    'without a PromQL histogram_quantile over the cumulative '
    'per-replica histograms.')
_M_DEADLINE_REJECTS = metrics_lib.counter(
    'skytpu_lb_deadline_rejects_total',
    'Requests answered 504 at the LB because their deadline passed '
    'before (or between) proxy attempts — a past-deadline request '
    'is never retried (docs/request_lifecycle.md).')
# Failure-survivability counters (docs/failover.md).
_M_HEDGES = metrics_lib.counter(
    'skytpu_lb_hedges_total',
    'TTFT hedges launched for streaming /generate, by outcome: won '
    '(the hedge produced the first token and served the client), '
    'lost (the primary produced first; the hedge was cancelled), '
    'failed (the hedge itself errored or was shed before any first '
    'token).',
    labels=('outcome',))
_M_RESUMED = metrics_lib.counter(
    'skytpu_lb_resumed_streams_total',
    'Greedy SSE streams whose replica died mid-stream and whose '
    'continuation was successfully re-prefilled on a healthy '
    'replica and spliced into the client stream with no duplicated '
    'or dropped tokens (docs/failover.md).')
_M_RESUME_FAILURES = metrics_lib.counter(
    'skytpu_lb_resume_failures_total',
    'Mid-stream deaths the LB could NOT resume (non-greedy request, '
    'resumption disabled, no healthy replica, resume budget '
    'exhausted, or the resumed prompt exceeded the replica\'s '
    'max_prompt): the client saw a truncated stream.')
# Multi-tenant QoS (docs/qos.md): per-tenant in-flight load at the
# LB. Tenant ids are caller-controlled header values, so the series
# set is EXPLICITLY bounded — past max_series tenants fold into the
# registry's '_other' bucket on both write and read.
_M_TENANT_INFLIGHT = metrics_lib.gauge(
    'skytpu_lb_tenant_inflight',
    'Requests currently proxied on behalf of the tenant (X-Tenant-ID '
    'header; anonymous traffic is not counted). Bounded: past '
    'max_series tenants fold into _other.',
    labels=('tenant',), max_series=64)
# Spot-native serving (docs/spot_serving.md).
_M_MIGRATIONS = metrics_lib.counter(
    'skytpu_lb_migrations_total',
    'Live SSE streams the LB proactively migrated off a replica '
    'that received a spot-preemption notice, by trigger. Each '
    'migration closes the doomed upstream so the stream re-drives '
    'through the mid-stream resume path on a survivor BEFORE the '
    'kill lands — a noticed preemption costs zero client-visible '
    'errors (docs/spot_serving.md).',
    labels=('trigger',))
# Disaggregated prefill/decode (docs/disaggregation.md).
_M_DISAGG_HANDOFFS = metrics_lib.counter(
    'skytpu_lb_disagg_handoffs_total',
    'Streaming /generate requests routed through the disaggregated '
    'prefill→manifest→decode path: a prefill replica published the '
    "prompt's KV pages and answered a manifest, and the decode "
    'attempt carried kv_source so the decode replica pulls those '
    'pages instead of re-prefilling.')
_M_DISAGG_FALLBACKS = metrics_lib.counter(
    'skytpu_lb_disagg_fallbacks_total',
    'Disaggregated handoffs that degraded to the interleaved path, '
    'by reason: disabled (SKYTPU_DISAGG=0 with a prefill pool '
    'present), no_prefill (every prefill replica excluded — '
    'draining, preempting, breaker-open), prefill_error (the '
    'prefill POST failed or answered no manifest — e.g. the '
    "replica died mid-handoff). The decode side's own fetch "
    'failures are not counted here: they fall back inside the '
    'replica (skytpu_kv_fetches_total{outcome!="ok"}).',
    labels=('reason',))
_M_RESUME_KV = metrics_lib.counter(
    'skytpu_lb_resume_kv_reused_tokens_total',
    'Prompt tokens a resumed or migrated stream did NOT re-prefill '
    'because its resume target fetched the KV pages from the '
    "dying/doomed replica's cache (the X-KV-Reused-Tokens response "
    'header summed over resume attempts; docs/disaggregation.md).')
# Cache-aware routing (docs/affinity_routing.md). Hit, miss and
# override partition every prefix-scored pick (a pick whose request
# body carried tokens, under the prefix_affinity policy with
# SKYTPU_AFFINITY on): exactly one of the three increments per pick.
_M_AFFINITY_HITS = metrics_lib.counter(
    'skytpu_lb_affinity_hits_total',
    'Prefix-scored picks routed to a replica advertising a matching '
    'cached prefix (>=1 chain-hashed page) in its /health digest — '
    'the request lands where its prefill is already paid for.')
_M_AFFINITY_MISSES = metrics_lib.counter(
    'skytpu_lb_affinity_misses_total',
    'Prefix-scored picks where no usable replica advertised a match: '
    'routed by consistent hashing on the first prompt block (so the '
    'NEXT request with this prefix hits) or by least-load when the '
    'prompt has no full page / no digest is fresh.')
_M_AFFINITY_OVERRIDES = metrics_lib.counter(
    'skytpu_lb_affinity_overrides_total',
    'Prefix-scored picks whose affinity/consistent-hash target was '
    'rejected by the inflight imbalance guard '
    '(SKYTPU_AFFINITY_MAX_SKEW) and routed least-load instead — '
    'affinity never creates a hotspot deeper than the guard bound.')
_M_AFFINITY_TOKENS = metrics_lib.counter(
    'skytpu_lb_affinity_matched_tokens_total',
    'Prompt tokens covered by the matched prefix on affinity hits '
    '(matched pages x page size): rate() is the fleet prefill '
    'compute cache-aware routing steers onto already-warm caches.')


class LoadBalancingPolicy:
    """Base: owns the replica URL set and the shared in-flight gauge
    lifecycle (series appear/disappear with replicas). ``pick`` must
    increment the gauge for the returned URL; ``done`` releases it."""

    def __init__(self) -> None:
        self._urls: List[str] = []
        self._spot: Set[str] = set()

    def urls(self) -> List[str]:
        return list(self._urls)

    def set_spot_urls(self, spot_urls: Sequence[str]) -> None:
        """Which replicas run on spot capacity
        (docs/spot_serving.md): tie-break material for load-aware
        policies — spot may vanish on short notice, so on equal load
        an on-demand survivor is the stabler pick for new streams,
        hedges, and resume targets. Base policies ignore it."""
        self._spot = set(spot_urls)

    def set_urls(self, urls: List[str]) -> None:
        for gone in set(self._urls) - set(urls):
            # Drop the series ONLY when idle: drain() waits on this
            # gauge, and a rotation (scale-down marks the replica
            # SHUTTING_DOWN before its in-flight requests finish)
            # must not zero the count out from under it — the old
            # private-dict implementation never pruned on set_urls
            # either. done() removes the straggler series once it
            # reaches zero.
            if not _M_INFLIGHT.has_series(replica=gone) or \
                    _M_INFLIGHT.value(replica=gone) <= 0:
                _M_INFLIGHT.remove(replica=gone)
        for url in urls:
            _M_INFLIGHT.touch(replica=url)
        self._on_set_urls(list(urls))
        self._urls = list(urls)

    def _on_set_urls(self, urls: List[str]) -> None:
        pass

    def pick(self, exclude: Optional[Set[str]] = None,
             tokens: Optional[Sequence[int]] = None) -> Optional[str]:
        """``tokens`` is the parsed prompt when the caller has one
        (the SSE /generate path): cache-aware policies score it;
        base policies ignore it."""
        raise NotImplementedError

    def done(self, url: str) -> None:
        if url in self._urls:
            _M_INFLIGHT.dec(floor=0.0, replica=url)
        elif _M_INFLIGHT.has_series(replica=url):
            # Rotated out while in flight: release, and retire the
            # series once the last straggler finishes (drain() has
            # nothing left to wait on).
            if _M_INFLIGHT.dec(floor=0.0, replica=url) <= 0:
                _M_INFLIGHT.remove(replica=url)


class RoundRobinPolicy(LoadBalancingPolicy):

    def __init__(self) -> None:
        super().__init__()
        self._it = itertools.cycle([])

    def _on_set_urls(self, urls: List[str]) -> None:
        if urls != self._urls:
            self._it = itertools.cycle(urls)

    def pick(self, exclude: Optional[Set[str]] = None,
             tokens: Optional[Sequence[int]] = None) -> Optional[str]:
        del tokens
        if not self._urls:
            return None
        for _ in range(len(self._urls)):
            url = next(self._it)
            if not exclude or url not in exclude:
                _M_INFLIGHT.inc(1, replica=url)
                return url
        return None


class LeastLoadPolicy(LoadBalancingPolicy):
    """Route to the replica with the fewest in-flight requests.

    The in-flight count IS the ``skytpu_lb_replica_inflight`` gauge:
    the policy routes on exactly the series operators scrape, instead
    of a private dict that could drift from the dashboard."""

    def __init__(self) -> None:
        super().__init__()
        self._lock = threading.Lock()

    def pick(self, exclude: Optional[Set[str]] = None,
             tokens: Optional[Sequence[int]] = None) -> Optional[str]:
        del tokens
        with self._lock:
            candidates = [u for u in self._urls
                          if not exclude or u not in exclude]
            if not candidates:
                return None
            return self._pick_least_load_locked(candidates)

    def _pick_least_load_locked(self, candidates: List[str]) -> str:
        """The one least-load selection rule (callers hold _lock).
        Load first; on ties prefer on-demand over spot
        (docs/spot_serving.md): the spot replica may get a preemption
        notice any moment, and a stream started on an on-demand
        survivor never needs migrating. PrefixAffinityPolicy's
        fallback arm calls exactly this, so affinity-off/fallback
        routing is the tie-break-for-tie-break same as least_load."""
        url = min(candidates,
                  key=lambda u: (_M_INFLIGHT.value(replica=u),
                                 u in self._spot))
        _M_INFLIGHT.inc(1, replica=url)
        return url


class PrefixAffinityPolicy(LeastLoadPolicy):
    """Cache-aware routing (docs/affinity_routing.md): route to the
    replica already holding the longest cached prefix of the prompt.

    The policy keeps a TTL'd cache of per-replica /health prefix
    digests, pushed in on the replica manager's probe cadence
    (``update_summaries`` — never a per-request HTTP call) with a
    version-gated delta path: a digest whose directory ``version``
    is unchanged refreshes its staleness stamp without re-parsing
    the hash list. A pick with tokens chain-hashes the prompt's full
    pages (utils/chain_hash.py — the SAME bytes the engine's prefix
    pool keys on) and scores every candidate by longest matching
    advertised prefix:

    - best match > 0 pages -> affinity target (ties broken least-
      load, then on-demand-over-spot — the PR 16 tie-break);
    - no match but a fresh digest told us the page size ->
      consistent (rendezvous) hashing on the first prompt block, so
      a cold prefix lands on ONE deterministic replica and the next
      request with it hits;
    - no full page / no fresh digest / SKYTPU_AFFINITY=0 /
      tokens-less pick (opaque proxy, hedge) -> exactly
      least_load's selection.

    Any affinity or rendezvous target must pass the inflight
    imbalance guard: if its post-pick in-flight count would exceed
    ``max(SKYTPU_AFFINITY_MAX_SKEW * fleet_mean, SKYTPU_AFFINITY_-
    MAX_SKEW)`` the pick is overridden to least-load — affinity can
    never create a hotspot deeper than the guard bound. Exclusions
    (draining, preempting, breaker-open, prefill-role) are applied
    by the caller BEFORE scoring, so a doomed replica is never
    picked no matter how long a prefix it advertises."""

    def __init__(self) -> None:
        super().__init__()
        # url -> parsed digest: hashes (frozenset of hex), version,
        # pages, page, truncated, stamp (monotonic receipt time).
        self._summaries: Dict[str, Dict[str, Any]] = {}
        # Attrs of the latest scored pick, for the caller's
        # lb.affinity span (take_last_decision pops it).
        self._last_decision: Optional[Dict[str, Any]] = None

    # ----------------------------------------------------- knobs
    @staticmethod
    def _affinity_enabled() -> bool:
        return env_registry.get(env_registry.SKYTPU_AFFINITY,
                                '1') != '0'

    @staticmethod
    def _ttl_s() -> float:
        return float(env_registry.get(
            env_registry.SKYTPU_AFFINITY_TTL_S, '60'))

    @staticmethod
    def _max_skew() -> float:
        return max(1.0, float(env_registry.get(
            env_registry.SKYTPU_AFFINITY_MAX_SKEW, '2.0')))

    # ------------------------------------------- digest ingestion
    def update_summaries(
            self, summaries: Dict[str, Optional[Dict[str, Any]]]
    ) -> None:
        """Ingest per-replica /health prefix digests (probe cadence;
        any thread). A malformed/alien-schema digest is ignored; a
        replica absent from ``summaries`` keeps its previous digest
        until the TTL retires it (one missed probe must not blind
        affinity for a whole cycle)."""
        now = time.monotonic()
        with self._lock:
            for url, digest in summaries.items():
                if not isinstance(digest, dict):
                    continue
                if digest.get('v') != chain_hash.SUMMARY_SCHEMA_VERSION:
                    continue
                prev = self._summaries.get(url)
                if (prev is not None
                        and prev['version'] == digest.get('version')):
                    # Delta path: unchanged directory version means
                    # the hash list is byte-identical — refresh the
                    # staleness stamp only.
                    prev['stamp'] = now
                    continue
                try:
                    parsed = {
                        'hashes': frozenset(digest.get('hashes') or ()),
                        'version': digest.get('version'),
                        'pages': int(digest.get('pages', 0)),
                        'page': int(digest.get('page', 0)),
                        'truncated': bool(digest.get('truncated')),
                        'stamp': now,
                    }
                except (TypeError, ValueError):
                    continue
                if parsed['page'] < 1:
                    continue
                self._summaries[url] = parsed

    def _on_set_urls(self, urls: List[str]) -> None:
        with self._lock:
            for gone in set(self._summaries) - set(urls):
                self._summaries.pop(gone)

    def take_last_decision(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            d, self._last_decision = self._last_decision, None
            return d

    # ------------------------------------------------------ pick
    def pick(self, exclude: Optional[Set[str]] = None,
             tokens: Optional[Sequence[int]] = None) -> Optional[str]:
        with self._lock:
            candidates = [u for u in self._urls
                          if not exclude or u not in exclude]
            if not candidates:
                return None
            if tokens is None or not self._affinity_enabled():
                # Tokens-less (opaque proxy, hedge) or disabled:
                # exactly least_load, no affinity accounting.
                return self._pick_least_load_locked(candidates)
            return self._pick_scored_locked(candidates, tokens)

    def _pick_scored_locked(self, candidates: List[str],
                            tokens: Sequence[int]) -> str:
        now = time.monotonic()
        ttl = self._ttl_s()
        fresh = {u: s for u, s in self._summaries.items()
                 if u in set(candidates) and now - s['stamp'] <= ttl}
        # Chain hashes are page-size dependent; replicas advertise
        # their page in the digest, so a (never-expected) mixed-page
        # fleet still scores correctly — each page size hashes once.
        hashes_by_page: Dict[int, List[str]] = {}

        def _hashes(page: int) -> List[str]:
            if page not in hashes_by_page:
                hashes_by_page[page] = [
                    h.hex()
                    for h in chain_hash.page_hashes(tokens, page)]
            return hashes_by_page[page]

        scored: List[Any] = []
        for u in candidates:
            s = fresh.get(u)
            if s is None:
                continue
            n = chain_hash.match_len(_hashes(s['page']), s['hashes'])
            if n > 0:
                scored.append((u, n, n * s['page']))
        target = None
        mode = 'miss'
        matched_pages = 0
        matched_tokens = 0
        if scored:
            best = max(n for _, n, _ in scored)
            ties = [(u, t) for u, n, t in scored if n == best]
            target = min(
                ties,
                key=lambda ut: (_M_INFLIGHT.value(replica=ut[0]),
                                ut[0] in self._spot))[0]
            mode = 'hit'
            matched_pages = best
            matched_tokens = dict(ties)[target]
        elif fresh:
            # Cold prefix with live digests: consistent (rendezvous)
            # hash on the first prompt block, so equal prefixes stop
            # scattering. Keyed on the chain hash at the smallest
            # advertised page size (deterministic across LBs); a
            # prompt under one full page has nothing cacheable —
            # least-load is simply correct.
            page = min(s['page'] for s in fresh.values())
            first = _hashes(page)
            if first:
                key = bytes.fromhex(first[0])
                target = max(
                    fresh,
                    key=lambda u: hashlib.blake2b(
                        key + u.encode(), digest_size=8).digest())
                mode = 'rendezvous'
        overridden = False
        if target is not None:
            # Imbalance guard: mean is post-pick (this request
            # included), so one request on an idle fleet never
            # trips it.
            loads = {u: _M_INFLIGHT.value(replica=u)
                     for u in candidates}
            mean_after = (sum(loads.values()) + 1.0) / len(candidates)
            skew = self._max_skew()
            if loads[target] + 1.0 > max(skew * mean_after, skew):
                overridden = True
                target = None
        if target is not None:
            _M_INFLIGHT.inc(1, replica=target)
        else:
            target = self._pick_least_load_locked(candidates)
        if overridden:
            _M_AFFINITY_OVERRIDES.inc()
        elif mode == 'hit':
            _M_AFFINITY_HITS.inc()
            _M_AFFINITY_TOKENS.inc(matched_tokens)
        else:
            _M_AFFINITY_MISSES.inc()
        self._last_decision = {
            'replica': target,
            'mode': 'override' if overridden else mode,
            'matched_pages': matched_pages,
            'matched_tokens': matched_tokens,
        }
        return target


POLICIES = {
    'round_robin': RoundRobinPolicy,
    'least_load': LeastLoadPolicy,
    'prefix_affinity': PrefixAffinityPolicy,
}


class LoadBalancer:
    """aiohttp app proxying every request to a picked replica."""

    MAX_ATTEMPTS = 3

    def __init__(self, port: int, policy: str = 'least_load',
                 on_request: Optional[Callable[[], None]] = None,
                 on_replica_down: Optional[Callable[[str], None]] = None
                 ) -> None:
        # port 0 = let the OS pick; the actual port is in `bound_port`
        # after start() (avoids probe-then-rebind TOCTOU races).
        self.port = port
        self.bound_port: Optional[int] = None
        self.policy: LoadBalancingPolicy = POLICIES[policy]()
        self.on_request = on_request
        # Called (off the event loop) with a replica URL the moment a
        # proxy attempt proves it unreachable — the replica manager
        # demotes it without waiting for the next probe cycle
        # (docs/failover.md).
        self.on_replica_down = on_replica_down
        self._runner: Optional[web.AppRunner] = None
        self._session: Optional[aiohttp.ClientSession] = None
        self._draining: Set[str] = set()
        # Replicas that received a spot-preemption notice
        # (docs/spot_serving.md): excluded from every pick the moment
        # mark_preempting() runs, while their live streams migrate to
        # survivors ahead of the kill.
        self._preempting: Set[str] = set()
        # Prefill-role replicas (docs/disaggregation.md): a subset of
        # the fleet that ONLY takes the disagg router's kv_prefill
        # handoffs — excluded from every ordinary pick (new streams,
        # retries, hedges, resume targets) so decode traffic never
        # lands on them.
        self._prefill_urls: Set[str] = set()
        # Live SSE drivers, so mark_preempting() can find (and
        # migrate) the streams currently attached to a doomed
        # replica. Registered for the duration of driver.run().
        self._drivers: Set[Any] = set()
        # Per-replica circuit breakers (serve/failover.py): loop-
        # affine, fed by proxy outcomes, consulted at every pick.
        self._breakers: Dict[str, failover.CircuitBreaker] = {}
        # Sliding p99 window behind the cumulative per-replica
        # latency histograms (docs/load_testing.md): per-instance so
        # a rebuilt LB starts a fresh window, feeding the
        # skytpu_lb_request_p99_seconds gauge.
        window_s = float(env_registry.get(
            env_registry.SKYTPU_SLO_WINDOW_S, '60'))
        self._latency_window = metrics_lib.SlidingWindowPercentile(
            window_s)
        # Sliding TTFT window over streaming /generate (time from
        # attempt start to first token event): its p95 IS the hedge
        # delay once it has samples (docs/failover.md).
        self._ttft_window = metrics_lib.SlidingWindowPercentile(
            window_s)

    def set_replica_urls(self, urls: List[str],
                         spot_urls: Optional[Sequence[str]] = None,
                         prefill_urls: Optional[Sequence[str]] = None
                         ) -> None:
        for gone in set(self.policy.urls()) - set(urls):
            # The replica left the fleet (scale-down, terminate, or
            # manager demotion): retire its breaker — if it returns
            # via a READY probe it deserves a fresh closed breaker.
            b = self._breakers.pop(gone, None)
            if b is not None:
                b.remove()
        self.policy.set_urls(urls)
        # Spot-ness rides on every fleet push (docs/spot_serving.md):
        # None means "no spot info" — e.g. a bench/test LB fed plain
        # URL lists — and clears the tie-break set.
        self.policy.set_spot_urls(
            [u for u in (spot_urls or ()) if u in set(urls)])
        self._draining &= set(urls)
        # A preempting replica that left the fleet (the kill landed,
        # or the notice was walked back and it re-probed READY) sheds
        # its mark; re-notice re-marks it.
        self._preempting &= set(urls)
        # Prefill roles ride on every fleet push too
        # (docs/disaggregation.md): None/empty means no prefill pool
        # — the disagg router then falls back to interleaved.
        self._prefill_urls = {u for u in (prefill_urls or ())
                              if u in set(urls)}

    def inflight(self, url: str) -> int:
        # One store for in-flight load: the scraped gauge, maintained
        # by policy.pick()/done().
        return int(_M_INFLIGHT.value(replica=url))

    async def drain(self, url: str, timeout: float = 60.0) -> bool:
        """Stop routing new requests to ``url`` and wait for its
        in-flight ones to finish (rolling update / downscale: tear the
        replica down only after this returns). True = drained."""
        self._draining.add(url)
        deadline = statedb.wall_now() + timeout
        while self.inflight(url) > 0:
            if statedb.wall_now() > deadline:
                return False
            await asyncio.sleep(0.05)
        return True

    async def mark_preempting(self, url: str,
                              trigger: str = 'notice') -> int:
        """``url`` received a spot-preemption notice
        (docs/spot_serving.md): stop routing to it NOW and
        proactively migrate its live SSE streams to survivors.
        Migration closes each stream's doomed upstream, so the
        driver's pending read surfaces as a transport error and walks
        the ordinary mid-stream resume arm — on a replica the pick
        exclusion already keeps away from ``url``. Done BEFORE the
        kill lands, a noticed preemption costs zero client-visible
        errors. Returns the number of streams migrated."""
        self._preempting.add(url)
        migrating = [d for d in list(self._drivers)
                     if d.active_url() == url]
        with trace_lib.span('lb.migrate', replica=url,
                            trigger=trigger,
                            streams=len(migrating)):
            for d in migrating:
                _M_MIGRATIONS.inc(1, trigger=trigger)
                d.migrate()
        if migrating:
            logger.info(
                'Preemption notice for %s: migrating %d live '
                'stream(s) to survivors (trigger=%s).', url,
                len(migrating), trigger)
        return len(migrating)

    # ------------------------------------------------ breaker plumbing
    def _breaker(self, url: str) -> failover.CircuitBreaker:
        b = self._breakers.get(url)
        if b is None:
            b = failover.CircuitBreaker(url)
            self._breakers[url] = b
        return b

    def _blocked_urls(self) -> Set[str]:
        return {u for u, b in self._breakers.items() if b.blocked()}

    def _pick(self, exclude: Set[str],
              tokens: Optional[Sequence[int]] = None) -> Optional[str]:
        """Breaker-aware pick: open breakers are excluded; picking a
        cooled-down open breaker consumes its single half-open trial.
        Synchronous end to end, so two interleaved requests can never
        both claim the same trial. Preempting replicas
        (docs/spot_serving.md) are excluded HERE so every pick —
        opaque retry, SSE attempt, hedge, resume target — avoids a
        replica whose kill is seconds away; prefill-role replicas
        (docs/disaggregation.md) likewise, so decode traffic never
        lands on them. ``tokens`` (the parsed prompt, SSE path only)
        lets a cache-aware policy score the pick
        (docs/affinity_routing.md); the exclusions above are applied
        BEFORE scoring, so a breaker-open or preempting replica is
        never picked no matter what prefix it advertises."""
        url = self.policy.pick(exclude=exclude | self._blocked_urls()
                               | self._preempting
                               | self._prefill_urls,
                               tokens=tokens)
        if url is not None:
            self._breaker(url).acquire()
            take = getattr(self.policy, 'take_last_decision', None)
            if take is not None:
                decision = take()
                if decision is not None:
                    # Zero-duration marker span: the routing decision
                    # and its evidence, under the request's lb.request
                    # span (docs/tracing.md).
                    with trace_lib.span('lb.affinity', **decision):
                        pass
        return url

    def update_prefix_summaries(
            self, summaries: Dict[str, Optional[Dict[str, Any]]]
    ) -> None:
        """Push per-replica /health prefix digests into a cache-aware
        policy (docs/affinity_routing.md). Called by the controller on
        the replica manager's probe cadence — the LB itself NEVER
        makes an HTTP call to score a request. No-op for policies
        without affinity."""
        update = getattr(self.policy, 'update_summaries', None)
        if update is not None:
            update(summaries)

    def _pick_prefill(self) -> Optional[str]:
        """Least-loaded pick WITHIN the prefill pool
        (docs/disaggregation.md), honoring the same exclusions as
        _pick (draining, preempting, open breakers) and holding the
        same in-flight gauge — released via ``policy.done(url)`` like
        any pick. None when no prefill replica is usable: the disagg
        router's cue to fall back to interleaved."""
        cands = [u for u in self._prefill_urls
                 if u not in self._draining and
                 u not in self._preempting and
                 u not in self._blocked_urls()]
        if not cands:
            return None
        url = min(cands, key=lambda u: _M_INFLIGHT.value(replica=u))
        _M_INFLIGHT.inc(1, replica=url)
        self._breaker(url).acquire()
        return url

    def _note_success(self, url: str) -> None:
        self._breaker(url).record_success()

    def _note_neutral(self, url: str) -> None:
        """The attempt ended with no health verdict (shed, client
        hangup, cancelled hedge loser): release a consumed half-open
        trial so the breaker cannot wedge. No-op when the attempt
        already recorded success/failure."""
        b = self._breakers.get(url)
        if b is not None:
            b.abandon_trial()

    def _note_failure(self, url: str, *, hard: bool = False) -> None:
        """Feed the breaker; a hard failure (connect refused/reset —
        the replica never received the request) also notifies the
        replica manager so the ready set shrinks NOW instead of after
        the probe cycle."""
        self._breaker(url).record_failure(hard=hard)
        if hard and self.on_replica_down is not None:
            try:
                asyncio.get_running_loop().run_in_executor(
                    None, self.on_replica_down, url)
            except RuntimeError:
                self.on_replica_down(url)

    # --------------------------------------------------- hedge knobs
    @staticmethod
    def _hedge_enabled() -> bool:
        return env_registry.get(env_registry.SKYTPU_LB_HEDGE,
                                '1') == '1'

    @staticmethod
    def _resume_enabled() -> bool:
        return env_registry.get(env_registry.SKYTPU_LB_RESUME,
                                '1') == '1'

    @staticmethod
    def _resume_max() -> int:
        return max(0, int(env_registry.get(
            env_registry.SKYTPU_LB_RESUME_MAX, '3')))

    @staticmethod
    def _disagg_enabled() -> bool:
        return env_registry.get(env_registry.SKYTPU_DISAGG,
                                '1') == '1'

    @staticmethod
    def _resume_kv_enabled() -> bool:
        return env_registry.get(env_registry.SKYTPU_LB_RESUME_KV,
                                '1') == '1'

    def _hedge_delay_s(self) -> float:
        p95 = self._ttft_window.quantile(0.95)
        if p95 is None:
            return max(0.0, float(env_registry.get(
                env_registry.SKYTPU_LB_HEDGE_DELAY_S, '2')))
        return max(float(env_registry.get(
            env_registry.SKYTPU_LB_HEDGE_MIN_S, '0.05')), p95)

    # ------------------------------------------------------------------
    async def _proxy(self, request: web.Request) -> web.StreamResponse:
        # One request span per proxied call, continuing the client's
        # trace when it sent a traceparent header (docs/tracing.md);
        # each replica attempt is a child span whose duration IS the
        # per-replica latency observation (single timing source), and
        # whose trace id rides on the histogram as an exemplar.
        ctx = trace_lib.context_from_headers(request.headers)
        # Per-tenant in-flight gauge (docs/qos.md): best-effort — a
        # malformed tenant id is NOT rejected here (the replica owns
        # the 400), it is just not attributed.
        tenant = None
        try:
            tenant = qos_lib.validate_tenant(
                request.headers.get(qos_lib.TENANT_HEADER))
        except ValueError:
            pass
        if tenant is not None:
            _M_TENANT_INFLIGHT.inc(1, tenant=tenant)
        try:
            with trace_lib.span('lb.request', parent=ctx,
                                method=request.method,
                                path=request.rel_url.path):
                if (request.method == 'POST' and
                        request.rel_url.path.startswith('/cancel/')):
                    return await self._cancel_broadcast(request)
                if (request.method == 'POST' and
                        request.rel_url.path == '/generate'):
                    body = await request.read()
                    parsed = self._sse_generate_body(body)
                    if parsed is not None:
                        # Streaming generate: the SSE-aware path with
                        # TTFT hedging and mid-stream resumption
                        # (docs/failover.md).
                        return await self._proxy_generate_sse(
                            request, parsed)
                return await self._proxy_attempts(request)
        finally:
            if tenant is not None:
                _M_TENANT_INFLIGHT.dec(1, floor=0.0, tenant=tenant)

    @staticmethod
    def _sse_generate_body(body: bytes) -> Optional[Dict[str, Any]]:
        """The parsed /generate body IF it is a streaming request the
        SSE path can own (valid token list + max_new). Anything else
        returns None and flows through the opaque proxy — the replica
        is the authority on malformed bodies (400)."""
        try:
            parsed = json.loads(body)
        except (ValueError, UnicodeDecodeError):
            return None
        if not isinstance(parsed, dict) or not parsed.get('stream'):
            return None
        tokens = parsed.get('tokens')
        max_new = parsed.get('max_new', 64)
        if (not isinstance(tokens, list) or not tokens or
                not all(isinstance(t, int) and not isinstance(t, bool)
                        for t in tokens)):
            return None
        if (not isinstance(max_new, int) or isinstance(max_new, bool)
                or max_new < 1):
            return None
        return parsed

    async def _cancel_broadcast(self, request: web.Request
                                ) -> web.Response:
        """POST /cancel/<id> fans out to EVERY known replica —
        draining ones included. The LB routed the original /generate
        wherever it pleased, so a cancel-by-request-id cannot know
        which replica holds the request; round-robining it would let
        a wrong-replica 404 mask the right replica's 202
        (docs/request_lifecycle.md)."""
        # Draining AND preempting replicas still hold in-flight
        # requests, so the cancel broadcast must reach them too.
        urls = set(self.policy.urls()) | self._draining | self._preempting
        if not urls:
            return web.Response(status=503,
                                text='No ready replicas.\n')
        path = request.rel_url.path
        assert self._session is not None, 'start() not called'

        async def one(url: str):
            try:
                # Short per-call bound: one wedged replica must not
                # hold the whole broadcast (and the client's cancel)
                # hostage to the session's long sock_read.
                async with self._session.post(
                        url.rstrip('/') + path,
                        timeout=aiohttp.ClientTimeout(total=5)) as resp:
                    return (resp.status, await resp.read(),
                            resp.headers.get('Content-Type',
                                             'application/json'))
            except (aiohttp.ClientError, asyncio.TimeoutError):
                return None

        results = [r for r in await asyncio.gather(
            *(one(u) for u in sorted(urls))) if r is not None]
        # One replica accepting wins; otherwise surface any answer
        # (typically 404 unknown-id); only total unreachability 502s.
        chosen = next((r for r in results if r[0] == 202),
                      results[0] if results else None)
        if chosen is None:
            return web.Response(status=502,
                                text='No replica reachable.\n')
        return web.Response(status=chosen[0], body=chosen[1],
                            content_type=chosen[2].split(';')[0])

    async def _cancel_on(self, url: str, req_id: str) -> None:
        """Targeted best-effort cancel on ONE replica: the hedge
        loser's (or a dead primary's) in-flight request must not
        keep decoding tokens nobody will read. Request-id-keyed: the
        replica maps the id to its engine request, and its engine's
        DuplicateRequestError semantics mean the id identifies at
        most one in-flight request per replica."""
        if self._session is None:
            return
        try:
            async with self._session.post(
                    url.rstrip('/') + '/cancel/' + req_id,
                    timeout=aiohttp.ClientTimeout(total=5)) as resp:
                await resp.read()
        except (aiohttp.ClientError, asyncio.TimeoutError):
            pass

    # ------------------------------------------------ opaque proxying
    async def _proxy_attempts(self, request: web.Request
                              ) -> web.StreamResponse:
        if self.on_request is not None:
            self.on_request()
        body = await request.read()
        # End-to-end deadline (docs/request_lifecycle.md): the
        # client's remaining-budget header becomes an absolute
        # deadline HERE; every proxy attempt re-stamps the budget
        # still left, and a request whose deadline has passed is
        # answered 504 — never retried onto another replica.
        deadline = lifecycle.deadline_from_headers(request.headers)
        tried: Set[str] = set()
        last_err: Optional[BaseException] = None
        last_shed: Optional[_ReplicaShedError] = None
        # Set when an attempt failed AFTER the request reached a
        # replica that may have executed it: that ambiguity must
        # reach the client, never be masked by an earlier shed.
        may_have_executed = False
        trace_id = trace_lib.current_trace_id()
        for _ in range(self.MAX_ATTEMPTS):
            left = lifecycle.remaining(deadline)
            if left is not None and left <= 0:
                _M_DEADLINE_REJECTS.inc()
                logger.warning('Deadline passed before attempt '
                               '(trace=%s); answering 504.', trace_id)
                return web.json_response(
                    {'error': 'deadline exceeded before the request '
                              'could be served',
                     'reason': 'deadline_exceeded'}, status=504)
            url = self._pick(exclude=tried | self._draining)
            if url is None:
                break
            tried.add(url)
            sp = trace_lib.start_span('lb.proxy', replica=url,
                                      **({'budget_s': round(left, 3)}
                                         if left is not None else {}))
            try:
                with trace_lib.activate(sp):
                    resp = await self._proxy_once(request, url, body,
                                                  deadline)
                sp.finish(status=resp.status)
                _M_LATENCY.observe(sp.duration, exemplar=sp.exemplar,
                                   replica=url)
                self._latency_window.observe(sp.duration)
                p99 = self._latency_window.quantile(0.99)
                if p99 is not None:
                    _M_LATENCY_P99.set(p99)
                if resp.status >= 500:
                    # An upstream 5xx passes through (it is the
                    # replica's own verdict) but still counts against
                    # the breaker: a replica whose app 500s every
                    # request is sick, not busy.
                    self._note_failure(url)
                else:
                    self._note_success(url)
                return resp
            except _ReplicaShedError as e:
                # The replica REFUSED the request (429 queue-full /
                # deadline shed, 503 draining-or-warming) without
                # executing it: safe to try another replica for any
                # method. If every candidate sheds, the LAST shed
                # response — Retry-After and reason included — is
                # forwarded to the client instead of being swallowed.
                # A shed is a capacity verdict from a live replica:
                # it feeds neither breaker arm.
                sp.finish(status=e.status, error='shed')
                logger.info('Replica %s shed the request (%d, '
                            'reason=%s); trying another (trace=%s)',
                            url, e.status, e.reason, trace_id)
                _M_ERRORS.inc(1, replica=url, kind='shed')
                last_shed = e
            except aiohttp.ClientConnectorError as e:
                # TCP connect failed: the replica NEVER received the
                # request — safe to retry on another replica for any
                # method. Hard breaker trip + manager notification:
                # a replica that refuses TCP is down, not slow
                # (docs/failover.md).
                sp.finish(error='connect')
                logger.warning('Replica %s unreachable (%s); retrying '
                               'on another replica (trace=%s)', url, e,
                               trace_id)
                _M_ERRORS.inc(1, replica=url, kind='connect')
                self._note_failure(url, hard=True)
                last_err = e
            except aiohttp.ClientConnectionError as e:
                # Connection dropped after the request was sent (e.g.
                # ServerDisconnectedError): the replica may have
                # started executing it. Retrying would double-execute
                # non-idempotent work, so only safe methods retry.
                sp.finish(error='disconnect')
                _M_ERRORS.inc(1, replica=url, kind='disconnect')
                self._note_failure(url)
                if request.method not in ('GET', 'HEAD', 'OPTIONS'):
                    logger.warning('Replica %s dropped mid-request '
                                   '(%s); not retrying %s (trace=%s)',
                                   url, e, request.method, trace_id)
                    last_err = e
                    may_have_executed = True
                    break
                logger.warning('Replica %s dropped %s (%s); retrying '
                               '(trace=%s)', url, request.method, e,
                               trace_id)
                last_err = e
            except _MidStreamError as e:
                # Bytes already reached the client: cannot retry.
                sp.finish(error='mid_stream')
                logger.warning('Replica %s died mid-response: %s '
                               '(trace=%s)', url, e.cause, trace_id)
                _M_ERRORS.inc(1, replica=url, kind='mid_stream')
                self._note_failure(url)
                return e.response
            except (aiohttp.ClientError, asyncio.TimeoutError) as e:
                sp.finish(error='upstream')
                logger.warning('Proxy to %s failed: %s (trace=%s)',
                               url, e, trace_id)
                _M_ERRORS.inc(1, replica=url, kind='upstream')
                self._note_failure(url)
                last_err = e
                if request.method not in ('GET', 'HEAD', 'OPTIONS'):
                    # Same double-execution risk as the dropped-
                    # connection branch: the replica may have run the
                    # request (e.g. 200 headers then a payload error).
                    may_have_executed = True
                    break
            finally:
                # An exception outside the enumerated arms — notably
                # CancelledError when the client disconnects mid-
                # proxy — must still land the attempt in the trace:
                # aborted requests are exactly the ones worth
                # reading. finish() is idempotent for the arms above.
                if sp.end_time is None:
                    sp.finish(error='aborted')
                self.policy.done(url)
                # Verdict-less endings (shed, aborted) must release a
                # consumed half-open trial (no-op otherwise).
                self._note_neutral(url)
        if last_shed is not None and not may_have_executed:
            # Every candidate shed (or was unreachable without ever
            # receiving the request): surface the last replica's own
            # verdict (status, Retry-After, reason) so the client
            # backs off intelligently instead of seeing a generic
            # error with the hint stripped. A shed explicitly means
            # "refused WITHOUT executing, safe to resubmit" — so it
            # must never mask a later may-have-executed failure.
            return last_shed.client_response()
        if last_err is None:
            return web.Response(status=503,
                                text='No ready replicas.\n')
        return web.Response(status=502,
                            text=f'Replica unreachable: {last_err}\n')

    def _forward_headers(self, request: web.Request,
                         deadline: Optional[float],
                         drop: Sequence = ()) -> Dict[str, str]:
        """Headers for one upstream attempt: hop headers stripped,
        the active span's traceparent replacing any client-sent one
        (the replica must parent under THIS hop), and the budget
        STILL LEFT re-stamped (a retry after a slow failure hands the
        replica less than the original)."""
        headers = {
            k: v for k, v in request.headers.items()
            if k.lower() not in _HOP_HEADERS and
            k.lower() not in {d.lower() for d in drop}
        }
        tp = trace_lib.traceparent_headers()
        if tp:
            headers = {k: v for k, v in headers.items()
                       if k.lower() != trace_lib.TRACEPARENT_HEADER}
            headers.update(tp)
        budget = lifecycle.budget_headers(deadline)
        if budget:
            headers = {k: v for k, v in headers.items()
                       if k.lower() != lifecycle.DEADLINE_HEADER.lower()}
            headers.update(budget)
        return headers

    def _poll_connect_fault(self, url: str, path: str) -> None:
        """Chaos site lb.replica.connect (docs/fault_injection.md):
        act out a TCP connect failure on this proxy attempt — the
        deterministic way to drive the circuit breaker without
        killing a process."""
        spec = fault_injection.poll(
            'lb.replica.connect',
            kinds=(fault_injection.FaultKind.CONNECT_FAILURE,),
            replica=url, path=path)
        if spec is not None:
            raise _InjectedConnectError(
                f'[fault-injection] connect_failure at '
                f'lb.replica.connect ({url})')

    async def _proxy_once(self, request: web.Request, url: str,
                          body: bytes,
                          deadline: Optional[float] = None
                          ) -> web.StreamResponse:
        target = url.rstrip('/') + '/' + request.rel_url.path.lstrip('/')
        if request.rel_url.query_string:
            target += '?' + request.rel_url.query_string
        headers = self._forward_headers(request, deadline)
        self._poll_connect_fault(url, request.rel_url.path)
        assert self._session is not None, 'start() not called'
        # skytpu-lint: disable=STL012 — deliberate session-level
        # bound: the pooled session's ClientTimeout (sock_connect=10,
        # sock_read=300) governs every proxied call; a per-call total
        # would cut legitimate long-lived SSE streams.
        async with self._session.request(request.method, target,
                                         headers=headers,
                                         data=body) as resp:
            if (resp.status in (429, 503) and
                    request.rel_url.path != '/health'):
                # A shed, not a result: the replica refused without
                # executing (queue full, wont_make_deadline,
                # draining, warming). Raise so the attempt loop can
                # try a replica with capacity — and forward THIS
                # response's Retry-After/reason if none has any.
                raise _ReplicaShedError(
                    resp.status, await resp.read(),
                    dict(resp.headers))
            out_headers = {
                k: v for k, v in resp.headers.items()
                if k.lower() not in _HOP_HEADERS and
                k.lower() != 'content-length'
            }
            out = web.StreamResponse(status=resp.status,
                                     headers=out_headers)
            started = False
            disconnect = None
            try:
                # Chunk-by-chunk passthrough: an SSE token stream (or
                # any long body) reaches the client as the replica
                # produces it, instead of buffering end-to-end.
                async for chunk in resp.content.iter_chunked(1 << 16):
                    if not started:
                        await out.prepare(request)
                        started = True
                        # Chaos site (docs/fault_injection.md): act
                        # out the client hanging up mid-response.
                        # Polled only once a chunk really streamed —
                        # a shed or connect-failure attempt must not
                        # burn a one-shot disconnect spec without
                        # acting it out.
                        disconnect = fault_injection.poll(
                            'lb.client_disconnect',
                            kinds=(fault_injection.FaultKind
                                   .CLIENT_DISCONNECT,),
                            replica=url, path=request.rel_url.path)
                    await out.write(chunk)
                    if disconnect is not None:
                        resp.close()   # abort upstream: replica sees
                        raise _MidStreamError(  # the hangup
                            out, ConnectionResetError(
                                '[fault-injection] client '
                                'disconnect'))
                if not started:
                    await out.prepare(request)
                await out.write_eof()
                return out
            except (aiohttp.ClientError, asyncio.TimeoutError) as e:
                if started:
                    # Headers/body already sent; surface the abort to
                    # the wrapper as non-retryable.
                    raise _MidStreamError(out, e) from e
                raise

    # ------------------------------------- streaming /generate (SSE)
    async def _proxy_generate_sse(self, request: web.Request,
                                  parsed: Dict[str, Any]
                                  ) -> web.StreamResponse:
        """The failure-survivable path for streaming /generate
        (docs/failover.md). Parses the replica's SSE events instead of
        forwarding opaque chunks, which is what makes three things
        possible:

        - **TTFT hedging**: while ZERO tokens have streamed, a slow
          primary (no first event within the p95-TTFT-derived hedge
          delay) races a second replica; the first replica to produce
          a token serves the client, the loser is cancelled by
          request id. At most one token stream ever reaches the
          client.
        - **Mid-stream resumption** (greedy only): a replica dying
          mid-stream re-submits prompt + tokens-emitted-so-far to a
          healthy replica — greedy determinism (plus the prefix cache
          making the re-prefill cheap) yields a continuation bitwise
          equal to the uninterrupted stream, spliced in with no
          duplicated or dropped tokens. The final ``done`` event is
          rewritten to carry the FULL token list (and ``resumed`` /
          ``hedged`` markers for scoring).
        - **Breaker feeding** identical to the opaque path.
        """
        if self.on_request is not None:
            self.on_request()
        driver = _SSEGenerateDriver(self, request, parsed)
        # Registered so mark_preempting() can find (and migrate) the
        # streams attached to a noticed replica (docs/spot_serving.md).
        self._drivers.add(driver)
        try:
            return await driver.run()
        finally:
            self._drivers.discard(driver)

    async def _handle_metrics(self, request: web.Request
                              ) -> web.Response:
        """The controller-side scrape point: this process's LB +
        autoscaler + replica-manager metrics (docs/metrics.md).
        Registered before the catch-all proxy route, so /metrics is
        served locally, not proxied. This process's registry only —
        spool merging is the API server's job (one merger per host,
        or multi-endpoint scrapes double-count the spool)."""
        text = metrics_lib.render_exposition()
        return web.Response(
            text=text, headers={'Content-Type': metrics_lib.CONTENT_TYPE})

    # ------------------------------------------------------------------
    async def start(self) -> None:
        app = web.Application()
        app.router.add_get('/metrics', self._handle_metrics)
        app.router.add_route('*', '/{tail:.*}', self._proxy)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        # One pooled upstream session: per-request sessions pay
        # connection setup on every proxied call (18% stack tax in the
        # r03 full-stack bench). No total timeout — long-lived SSE
        # streams are legitimate; sock_read bounds replica *silence*
        # instead, so a wedged replica still gets cut.
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=None, sock_connect=10,
                                          sock_read=300),
            connector=aiohttp.TCPConnector(limit=0,
                                           limit_per_host=0,
                                           keepalive_timeout=60))
        site = web.TCPSite(self._runner, '0.0.0.0', self.port)
        await site.start()
        sockets = site._server.sockets  # pylint: disable=protected-access
        self.bound_port = sockets[0].getsockname()[1]
        logger.info('Load balancer listening on :%d', self.bound_port)

    async def stop(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None
        if self._runner is not None:
            await self._runner.cleanup()


class _SSEUpstream:
    """One upstream streaming /generate attempt: owns the pooled-
    session response and a line-wise SSE event parser."""

    def __init__(self, lb: LoadBalancer, url: str,
                 payload: Dict[str, Any],
                 headers: Dict[str, str]) -> None:
        self._lb = lb
        self.url = url
        self._payload = payload
        self._headers = headers
        self.resp: Optional[aiohttp.ClientResponse] = None
        # Loop-clock instant start() ran: TTFT observations measure
        # from the OWNING upstream's start, so a hedge winner's
        # sample is its own connect+first-token time, not the hedge
        # delay it waited behind (which would ratchet the p95-derived
        # delay upward on every win).
        self.started_at: Optional[float] = None
        self._buf = bytearray()

    async def start(self) -> aiohttp.ClientResponse:
        self.started_at = asyncio.get_event_loop().time()
        self._lb._poll_connect_fault(self.url, '/generate')  # pylint: disable=protected-access
        assert self._lb._session is not None, 'start() not called'  # pylint: disable=protected-access
        # skytpu-lint: disable=STL012 — same session-level bound as
        # _proxy_once: sock_connect/sock_read on the pooled session;
        # an SSE stream legitimately outlives any per-call total.
        self.resp = await self._lb._session.post(  # pylint: disable=protected-access
            self.url.rstrip('/') + '/generate', json=self._payload,
            headers=self._headers)
        return self.resp

    async def _readline(self) -> bytes:
        """Own line buffering instead of StreamReader.readline():
        aiohttp's readline raises ValueError past its 64 KiB line
        limit, and a done event's full token list routinely exceeds
        that. Upstream is an intra-stack replica, so the unbounded
        line buffer is trusted the same way the opaque proxy's
        passthrough was."""
        assert self.resp is not None
        while True:
            i = self._buf.find(b'\n')
            if i >= 0:
                line = bytes(self._buf[:i + 1])
                del self._buf[:i + 1]
                return line
            chunk = await self.resp.content.read(1 << 16)
            if not chunk:
                if self._buf:
                    line = bytes(self._buf)
                    self._buf.clear()
                    return line
                return b''
            self._buf.extend(chunk)

    async def next_event(self) -> Optional[Dict[str, Any]]:
        """The next parsed ``data:`` event, or None at a clean EOF.
        A torn line (replica died mid-write) surfaces as a payload
        error — the caller treats it like any mid-stream death."""
        while True:
            line = await self._readline()
            if not line:
                return None
            line = line.strip()
            if not line.startswith(b'data:'):
                continue
            try:
                event = json.loads(
                    line[len(b'data:'):].decode('utf-8', 'replace'))
            except ValueError as e:
                raise aiohttp.ClientPayloadError(
                    'malformed SSE event from replica') from e
            if isinstance(event, dict):
                return event

    def close(self) -> None:
        if self.resp is not None:
            self.resp.close()


class _SSEGenerateDriver:
    """State machine for ONE client streaming /generate request:
    attempt loop, hedge race, mid-stream resume, SSE splice.

    Invariants:

    - at most one upstream ever streams to the client (the hedge
      loser is cancelled by request id before any of its tokens are
      forwarded);
    - ``emitted`` is exactly the token sequence the client has seen,
      so a resume re-submits ``prompt + emitted`` and the rewritten
      ``done`` event carries ``emitted + continuation`` — no token is
      duplicated or dropped;
    - every picked URL is released (``policy.done``) exactly once,
      via the ``_held`` list.
    """

    def __init__(self, lb: LoadBalancer, request: web.Request,
                 parsed: Dict[str, Any]) -> None:
        self.lb = lb
        self.request = request
        self.parsed = parsed
        self.tokens: List[int] = list(parsed['tokens'])
        self.max_new: int = int(parsed.get('max_new', 64))
        temp = parsed.get('temperature')
        self.greedy = temp is None or temp == 0
        # The request id is the hedge/resume/cancel correlation key:
        # minted HERE if the client did not send one, and stamped on
        # every upstream attempt so a targeted /cancel on the loser
        # replica hits exactly this request.
        self.req_id = (request.headers.get(trace_lib.REQUEST_ID_HEADER)
                       or trace_lib.new_request_id())
        self.deadline = lifecycle.deadline_from_headers(request.headers)
        self.emitted: List[int] = []      # tokens the CLIENT has seen
        self.client: Optional[web.StreamResponse] = None
        self.tried: Set[str] = set()
        # Replicas that died MID-STREAM on this request: the only
        # hard exclusion for resume attempts. ``tried`` governs
        # pre-stream retries/hedges; a resume may legitimately
        # return to a replica that merely lost the hedge race (its
        # duplicate was cancelled).
        self.dead_urls: Set[str] = set()
        self._dup_retries = 0
        # Exception already breaker-noted inside the hedge race (the
        # primary's failure is noted at failure time, since the hedge
        # may win and swallow it): run()'s arm must not double-note.
        self._noted_exc: Optional[BaseException] = None
        self.resumes = 0
        self.hedged = False
        # KV-transfer source (docs/disaggregation.md): when set,
        # every upstream attempt carries kv_source=<url> so the
        # decode replica pulls the prompt's published pages from
        # that peer before prefilling. Set by the disagg phase-0
        # handoff (prefill peer) or by the KV-assisted resume arm
        # (the dying/doomed replica).
        self.kv_source: Optional[str] = None
        # Proactive migrations off preempting replicas
        # (docs/spot_serving.md): each one re-drives the stream
        # through the resume path, so ``migrated <= resumes`` once
        # the continuation lands.
        self.migrated = 0
        self._current_up: Optional[_SSEUpstream] = None
        self.last_shed: Optional[_ReplicaShedError] = None
        self.last_err: Optional[BaseException] = None
        self._disconnect_spec = None
        self._winner: Optional[_SSEUpstream] = None
        # URLs whose pick is currently held (inflight gauge): the
        # primary of the running attempt, plus a hedge while racing.
        self._held: List[str] = []
        self._active_url: Optional[str] = None
        self._loop = asyncio.get_event_loop()
        self._t0 = self._loop.time()
        self._trace_id = trace_lib.current_trace_id()

    # ------------------------------------------------------- helpers
    def _upstream(self, url: str) -> _SSEUpstream:
        payload = dict(self.parsed)
        payload['tokens'] = self.tokens + self.emitted
        payload['max_new'] = self.max_new - len(self.emitted)
        payload['stream'] = True
        payload.pop('disagg', None)
        if self.kv_source and self.kv_source != url:
            payload['kv_source'] = self.kv_source
        else:
            # Never ask a replica to fetch from itself, and never
            # forward a client-supplied kv_source the LB did not
            # establish.
            payload.pop('kv_source', None)
        headers = self.lb._forward_headers(  # pylint: disable=protected-access
            self.request, self.deadline,
            drop=('content-type', 'content-length'))
        headers[trace_lib.REQUEST_ID_HEADER] = self.req_id
        return _SSEUpstream(self.lb, url, payload, headers)

    def active_url(self) -> Optional[str]:
        """The replica URL the current attempt streams from (None
        between attempts) — mark_preempting()'s match key."""
        return self._active_url

    def migrate(self) -> None:
        """Proactively move this stream off its (preempting) replica
        (docs/spot_serving.md): close the live upstream so the
        pending read surfaces as a transport error and the ordinary
        mid-stream resume arm re-drives the stream on a survivor —
        the migration IS a resume, just triggered before the replica
        dies instead of after."""
        self.migrated += 1
        up = self._current_up
        if up is not None:
            up.close()

    def _release(self, url: str) -> None:
        if url in self._held:
            self._held.remove(url)
            self.lb.policy.done(url)
            # Verdict-less endings (shed, cancelled hedge loser,
            # client hangup) must release a consumed half-open trial
            # (no-op when success/failure already resolved it).
            self.lb._note_neutral(url)  # pylint: disable=protected-access

    def _classify(self, exc: BaseException) -> str:
        """Map an attempt exception onto the error-kind taxonomy
        (pure; no breaker side effects)."""
        if isinstance(exc, aiohttp.ClientConnectorError):
            return 'connect'
        if self.client is not None:
            return 'mid_stream'
        if isinstance(exc, aiohttp.ClientConnectionError):
            return 'disconnect'
        return 'upstream'

    def _note_kind(self, url: str, kind: str) -> None:
        """Feed the breaker + error counters exactly like the opaque
        path (a connect failure is the hard, notify-the-manager
        kind)."""
        self.lb._note_failure(url, hard=(kind == 'connect'))  # pylint: disable=protected-access
        _M_ERRORS.inc(1, replica=url, kind=kind)

    def _note_race_failure(self, url: str,
                           exc: Optional[BaseException]) -> None:
        """Breaker/error accounting for an upstream that failed
        INSIDE the hedge race (its exception may never surface to
        run()'s arms — e.g. the primary dies while the hedge wins,
        or the hedge itself is refused). Sheds and non-stream
        verdicts keep their opaque-path semantics: a shed feeds
        neither breaker arm, a 5xx verdict is a soft failure."""
        if exc is None or isinstance(exc, _ReplicaShedError):
            if exc is not None:
                _M_ERRORS.inc(1, replica=url, kind='shed')
            return
        if isinstance(exc, _NonStreamVerdict):
            if exc.status >= 500:
                self.lb._note_failure(url)  # pylint: disable=protected-access
            else:
                self.lb._note_success(url)  # pylint: disable=protected-access
            return
        self._note_kind(url, self._classify(exc))

    async def _write_event(self, payload: Dict[str, Any]) -> None:
        if self.client is None:
            self.client = web.StreamResponse(headers={
                'Content-Type': 'text/event-stream',
                'Cache-Control': 'no-cache',
                'X-Accel-Buffering': 'no',
                trace_lib.REQUEST_ID_HEADER: self.req_id,
            })
            await self.client.prepare(self.request)
        await self.client.write(
            f'data: {json.dumps(payload)}\n\n'.encode())

    async def _finish_stream(self) -> web.StreamResponse:
        assert self.client is not None
        try:
            await self.client.write_eof()
        except (ConnectionResetError, aiohttp.ClientError):
            pass
        return self.client

    def _synthesize_done(self) -> Dict[str, Any]:
        """A done event the LB composes itself — used when every
        budgeted token already reached the client but the replica
        died before its own done event could (nothing is left to
        resume; the stream IS complete)."""
        payload: Dict[str, Any] = {
            'done': True,
            'tokens': list(self.emitted),
            'latency_s': round(self._loop.time() - self._t0, 4),
            'status': lifecycle.FINISHED,
            'reason': None,
        }
        if self.resumes:
            payload['resumed'] = self.resumes
        if self.migrated:
            payload['migrated'] = self.migrated
        if self.hedged:
            payload['hedged'] = True
        return payload

    # ------------------------------------------- disagg phase 0
    async def _maybe_prefill_handoff(self) -> None:
        """Disaggregated phase 0 (docs/disaggregation.md): when a
        prefill pool exists, POST the prompt to a prefill replica as
        ``kv_prefill`` — it runs chunked prefill, publishes the
        prompt's KV pages, and answers a page manifest. On success,
        every decode attempt carries ``kv_source=<prefill url>`` so
        the decode replica pulls those pages instead of
        re-prefilling. EVERY failure — no usable prefill replica,
        transport error, non-manifest answer, SKYTPU_DISAGG=0, the
        client opting out with ``disagg: false`` — falls back to the
        ordinary interleaved path: disaggregation can slow a request
        down, never fail it."""
        if not self.lb._prefill_urls:  # pylint: disable=protected-access
            return
        if not self.parsed.get('disagg', True):
            return
        if not self.lb._disagg_enabled():  # pylint: disable=protected-access
            _M_DISAGG_FALLBACKS.inc(1, reason='disabled')
            return
        url = self.lb._pick_prefill()  # pylint: disable=protected-access
        if url is None:
            _M_DISAGG_FALLBACKS.inc(1, reason='no_prefill')
            return
        payload = dict(self.parsed)
        payload['tokens'] = list(self.tokens)
        payload['kv_prefill'] = True
        payload['stream'] = False
        payload['max_new'] = 1
        payload.pop('disagg', None)
        payload.pop('kv_source', None)
        headers = self.lb._forward_headers(  # pylint: disable=protected-access
            self.request, self.deadline,
            drop=('content-type', 'content-length'))
        # Distinct request id: the prefill half must not collide
        # with the decode stream's id in any replica's duplicate
        # detection (a mixed pool could see both).
        headers[trace_lib.REQUEST_ID_HEADER] = self.req_id + '.pf'
        sp = trace_lib.start_span('lb.disagg_prefill', replica=url,
                                  prompt_len=len(self.tokens))
        try:
            assert self.lb._session is not None, 'start() not called'  # pylint: disable=protected-access
            self.lb._poll_connect_fault(url, '/generate')  # pylint: disable=protected-access
            # skytpu-lint: disable=STL012 — session-level bound, same
            # rationale as _proxy_once: sock_read bounds replica
            # silence; a long prefill is legitimate work.
            async with self.lb._session.post(  # pylint: disable=protected-access
                    url.rstrip('/') + '/generate', json=payload,
                    headers=headers) as resp:
                body = await resp.read()
                if resp.status != 200:
                    raise _DisaggPrefillError(
                        f'prefill replica answered {resp.status}')
                manifest = json.loads(body)
                if not (isinstance(manifest, dict) and
                        manifest.get('manifest')):
                    raise _DisaggPrefillError(
                        'prefill replica answered no manifest')
            self.kv_source = url
            self.lb._note_success(url)  # pylint: disable=protected-access
            _M_DISAGG_HANDOFFS.inc()
            sp.finish(ok=True,
                      pages=len(manifest.get('hashes') or ()))
        except (aiohttp.ClientError, asyncio.TimeoutError,
                _DisaggPrefillError, ValueError) as e:
            # The mid-handoff death path: the prefill replica was
            # killed (or shed, or answered garbage) while the
            # handoff was in flight. Fall back to interleaved —
            # the request must survive, just without the handoff.
            sp.finish(ok=False, error=str(e)[:200])
            _M_DISAGG_FALLBACKS.inc(1, reason='prefill_error')
            if isinstance(e, aiohttp.ClientConnectorError):
                self.lb._note_failure(url, hard=True)  # pylint: disable=protected-access
            elif isinstance(e, (aiohttp.ClientError,
                                asyncio.TimeoutError)):
                self.lb._note_failure(url)  # pylint: disable=protected-access
            logger.warning(
                'Disagg prefill handoff to %s failed (%s); falling '
                'back to interleaved (trace=%s).', url, e,
                self._trace_id)
        finally:
            if sp.end_time is None:
                sp.finish(error='aborted')
            self.lb.policy.done(url)
            self.lb._note_neutral(url)  # pylint: disable=protected-access

    # ----------------------------------------------------------- run
    async def run(self) -> web.StreamResponse:
        attempts_left = self.lb.MAX_ATTEMPTS
        resume_budget = self.lb._resume_max()  # pylint: disable=protected-access
        await self._maybe_prefill_handoff()
        while attempts_left > 0:
            attempts_left -= 1
            left = lifecycle.remaining(self.deadline)
            if left is not None and left <= 0:
                if self.client is None:
                    _M_DEADLINE_REJECTS.inc()
                    logger.warning(
                        'Deadline passed before attempt (trace=%s); '
                        'answering 504.', self._trace_id)
                    return web.json_response(
                        {'error': 'deadline exceeded before the '
                                  'request could be served',
                         'reason': 'deadline_exceeded'}, status=504)
                # Mid-stream deadline: the replica's own expiry owns
                # this; ending truncated here is all the LB can do.
                break
            # Pre-stream attempts avoid every replica already tried;
            # a RESUME only needs to avoid the replicas that died
            # mid-stream on this request (a hedge loser whose
            # duplicate was cancelled is a perfectly good resume
            # target — with 2 replicas it is often the ONLY one).
            exclude = (self.dead_urls if self.client is not None
                       else self.tried)
            url = self.lb._pick(  # pylint: disable=protected-access
                exclude=exclude | self.lb._draining,  # pylint: disable=protected-access
                tokens=self.tokens)
            if url is None:
                break
            self.tried.add(url)
            self._held.append(url)
            self._active_url = url
            sp = trace_lib.start_span(
                'lb.proxy', replica=url, sse=True,
                **({'budget_s': round(left, 3)}
                   if left is not None else {}))
            up = self._upstream(url)
            self._current_up = up
            try:
                with trace_lib.activate(sp):
                    outcome = await self._drive_attempt(up, sp)
                sp.finish(status=200)
                win_url = self._active_url
                _M_LATENCY.observe(sp.duration, exemplar=sp.exemplar,
                                   replica=win_url)
                self.lb._latency_window.observe(sp.duration)  # pylint: disable=protected-access
                p99 = self.lb._latency_window.quantile(0.99)  # pylint: disable=protected-access
                if p99 is not None:
                    _M_LATENCY_P99.set(p99)
                return outcome
            except _NonStreamVerdict as v:
                sp.finish(status=v.status)
                if v.status >= 500:
                    self.lb._note_failure(self._active_url)  # pylint: disable=protected-access
                else:
                    self.lb._note_success(self._active_url)  # pylint: disable=protected-access
                if (v.status == 409 and self.client is not None and
                        self._dup_retries < 4):
                    # Resume raced the hedge loser's cancel: the
                    # duplicate id is still terminal-izing on that
                    # replica. A tick from now it is free — retry
                    # rather than truncate the client's stream.
                    self._dup_retries += 1
                    attempts_left = max(attempts_left, 1)
                    logger.info(
                        'Resume on %s hit duplicate_request (cancel '
                        'still applying); retrying (%d, trace=%s).',
                        self._active_url, self._dup_retries,
                        self._trace_id)
                    await asyncio.sleep(0.25)
                    continue
                if self.client is not None:
                    # A resumed attempt was refused (e.g. 400: the
                    # grown prompt exceeds the replica's max_prompt):
                    # the client already holds a partial stream —
                    # nothing to forward, end truncated.
                    _M_RESUME_FAILURES.inc()
                    logger.warning(
                        'Resume attempt on %s refused (HTTP %d); '
                        'ending truncated stream (trace=%s).',
                        self._active_url, v.status, self._trace_id)
                    return await self._finish_stream()
                return v.response
            except _ClientGone:
                # The LB-side client-disconnect chaos fired (or the
                # real client hung up): upstream already closed so
                # the replica cancels; end exactly like the opaque
                # path — truncated response, no retry, no resume.
                sp.finish(error='mid_stream')
                _M_ERRORS.inc(1, replica=self._active_url,
                              kind='mid_stream')
                assert self.client is not None
                return self.client
            except _ReplicaShedError as e:
                sp.finish(status=e.status, error='shed')
                logger.info('Replica %s shed the request (%d, '
                            'reason=%s); trying another (trace=%s)',
                            self._active_url, e.status, e.reason,
                            self._trace_id)
                _M_ERRORS.inc(1, replica=self._active_url,
                              kind='shed')
                self.last_shed = e
            except (aiohttp.ClientError, asyncio.TimeoutError) as e:
                fail_url = self._active_url
                kind = self._classify(e)
                # A proactive migration (mark_preempting closed the
                # upstream) is not a replica failure: the replica is
                # alive and healthy until the kill lands, so it must
                # feed neither the breaker nor the error counters
                # (docs/spot_serving.md).
                migrating = fail_url in self.lb._preempting  # pylint: disable=protected-access
                if e is not self._noted_exc and not migrating:
                    self._note_kind(fail_url, kind)
                sp.finish(error='migrate' if migrating else kind)
                self.last_err = e
                if self.client is not None:
                    self.dead_urls.add(fail_url)
                if self.client is None:
                    # ZERO bytes have streamed: a retry on another
                    # replica is safe — the request id keys a cancel
                    # to the possibly-started replica, and at most
                    # one stream ever reaches the client.
                    logger.warning(
                        'Replica %s failed pre-first-token (%s: %s); '
                        'retrying on another replica (trace=%s).',
                        fail_url, kind, e, self._trace_id)
                    asyncio.ensure_future(
                        self.lb._cancel_on(fail_url, self.req_id))  # pylint: disable=protected-access
                    continue
                # Bytes reached the client: resume (greedy) or end
                # truncated.
                if self.max_new - len(self.emitted) < 1:
                    # Every budgeted token is already with the
                    # client; only the done event died. The stream
                    # is complete — say so.
                    await self._write_event(self._synthesize_done())
                    return await self._finish_stream()
                can_resume = (self.greedy and
                              self.lb._resume_enabled() and  # pylint: disable=protected-access
                              self.resumes < resume_budget)
                if not can_resume:
                    _M_RESUME_FAILURES.inc()
                    logger.warning(
                        'Replica %s died mid-stream after %d tokens; '
                        'not resumable (greedy=%s budget=%d/%d) — '
                        'truncated (trace=%s).', fail_url,
                        len(self.emitted), self.greedy, self.resumes,
                        resume_budget, self._trace_id)
                    return await self._finish_stream()
                self.resumes += 1
                if (self.kv_source is None and
                        self.lb._resume_kv_enabled()):  # pylint: disable=protected-access
                    # KV-assisted resume (docs/disaggregation.md):
                    # point the resume attempt's kv_source at the
                    # failing replica. A migration's doomed replica
                    # is alive until the kill lands, so its published
                    # pages are fetchable; a hard-dead replica makes
                    # the fetch fail fast and the resume target
                    # re-prefills exactly as before. Never overrides
                    # a disagg prefill peer already in place.
                    self.kv_source = fail_url
                # One more attempt slot for the resume itself: the
                # resume budget (SKYTPU_LB_RESUME_MAX) is the real
                # bound, not the pre-stream attempt count.
                attempts_left = max(attempts_left, 1)
                logger.warning(
                    'Replica %s %s after %d/%d tokens; resuming on '
                    'another replica (trace=%s).', fail_url,
                    'is preempting — migrating stream' if migrating
                    else 'died mid-stream',
                    len(self.emitted), self.max_new, self._trace_id)
                continue
            finally:
                if sp.end_time is None:
                    sp.finish(error='aborted')
                for u in list(self._held):
                    self._release(u)
        # Out of candidates/attempts.
        if self.client is not None:
            _M_RESUME_FAILURES.inc()
            logger.warning(
                'Stream for request %s could not be resumed (no '
                'healthy candidate / attempts exhausted after %d '
                'tokens); ending truncated (trace=%s).', self.req_id,
                len(self.emitted), self._trace_id)
            return await self._finish_stream()
        if self.last_shed is not None:
            return self.last_shed.client_response()
        if self.last_err is None:
            return web.Response(status=503,
                                text='No ready replicas.\n')
        return web.Response(
            status=502,
            text=f'Replica unreachable: {self.last_err}\n')

    # ------------------------------------------------ attempt driving
    async def _drive_attempt(self, up: _SSEUpstream,
                             sp) -> web.StreamResponse:
        """Run one upstream attempt to client-stream completion.
        Raises _ReplicaShedError / _NonStreamVerdict / _ClientGone /
        aiohttp errors for run()'s arms; returns the finished client
        response on success."""
        attempt_started = self._loop.time()
        resume_sp = None
        if self.resumes:
            resume_sp = trace_lib.start_span(
                'lb.resume', to_replica=up.url,
                tokens_done=len(self.emitted), attempt=self.resumes)
        try:
            first_event = await self._first_event(up)
        except BaseException:
            if resume_sp is not None and resume_sp.end_time is None:
                # The resume target failed too: the span must still
                # land (with ok=False) rather than leak open.
                resume_sp.finish(ok=False)
            raise
        # The hedge race may have handed the stream to another
        # upstream.
        if self._winner is not None:
            up = self._winner
        self._active_url = up.url
        self._current_up = up
        # Hedge-delay signal: first-token latency of the upstream
        # that PRODUCED it, measured from its own start (a hedge
        # winner's sample must not embed the delay it waited behind).
        # Resume continuations skip the window — their prefix-cached
        # re-prefill TTFT is not an arrival-time sample.
        if not self.resumes:
            ttft = self._loop.time() - (up.started_at
                                        or attempt_started)
            self.lb._ttft_window.observe(ttft)  # pylint: disable=protected-access
        # KV-transfer savings receipt (docs/disaggregation.md): the
        # replica advertises how many prompt tokens its fetched pages
        # cover BEFORE the first byte, so the reading is attempt-
        # scoped and exact.
        kv_reused = 0
        if up.resp is not None:
            raw = up.resp.headers.get('X-KV-Reused-Tokens')
            if raw:
                try:
                    kv_reused = max(0, int(raw))
                except ValueError:
                    kv_reused = 0
        if kv_reused:
            sp.set_attr(kv_reused_tokens=kv_reused)
        if resume_sp is not None:
            # The resume span's duration IS the stream gap the client
            # saw between the dead replica's last token and the new
            # replica's first event.
            resume_sp.finish(ok=True, kv_reused_tokens=kv_reused)
            if kv_reused:
                _M_RESUME_KV.inc(kv_reused)
            _M_RESUMED.inc()
            logger.info('Stream resumed on %s after %d tokens '
                        '(trace=%s).', up.url, len(self.emitted),
                        self._trace_id)
        attempt_base = list(self.emitted)
        ev: Optional[Dict[str, Any]] = first_event
        try:
            return await self._forward_events(up, ev, attempt_base)
        except (asyncio.CancelledError, ConnectionResetError):
            # The real client hung up — aiohttp either cancels the
            # handler task or client.write() raises
            # ConnectionResetError (the same two modes serving_http's
            # stream handler documents). Abort upstream so the
            # replica sees the hangup and cancels its request, then
            # let the exception unwind (the opaque path propagates
            # client-side write failures the same way).
            up.close()
            raise

    async def _forward_events(self, up: _SSEUpstream,
                              ev: Optional[Dict[str, Any]],
                              attempt_base: List[int]
                              ) -> web.StreamResponse:
        while True:
            if ev is None:
                raise aiohttp.ServerDisconnectedError(
                    'stream ended without a done event')
            if ev.get('done'):
                payload = dict(ev)
                payload['tokens'] = (attempt_base +
                                     list(ev.get('tokens') or ()))
                if self.resumes:
                    payload['resumed'] = self.resumes
                if self.migrated:
                    # Resumes triggered by a preemption notice
                    # (docs/spot_serving.md) — lets the bench tell
                    # notice-migrated streams from reactive resumes.
                    payload['migrated'] = self.migrated
                if self.hedged:
                    payload['hedged'] = True
                await self._write_event(payload)
                self.lb._note_success(up.url)  # pylint: disable=protected-access
                return await self._finish_stream()
            if 'error' in ev:
                # Engine-side error event: forward verbatim and end —
                # exactly what the replica's own stream would do.
                await self._write_event(ev)
                return await self._finish_stream()
            toks = list(ev.get('tokens') or ())
            first_chunk = self.client is None
            await self._write_event({'tokens': toks})
            self.emitted.extend(toks)
            if first_chunk:
                # Chaos parity with the opaque path: the client-
                # disconnect site is polled once a chunk actually
                # streamed.
                self._disconnect_spec = fault_injection.poll(
                    'lb.client_disconnect',
                    kinds=(fault_injection.FaultKind
                           .CLIENT_DISCONNECT,),
                    replica=up.url, path='/generate')
            if self._disconnect_spec is not None:
                up.close()             # abort upstream: replica sees
                raise _ClientGone()    # the hangup and cancels
            ev = await up.next_event()

    async def _first_event(self, up: _SSEUpstream
                           ) -> Optional[Dict[str, Any]]:
        """Start ``up`` and wait for its first SSE event, hedging on
        a second replica when the primary streams nothing within the
        hedge delay. Sets self._winner to the upstream that owns the
        stream. Raises shed/verdict/transport errors from the
        primary when no hedge saves the attempt."""
        self._winner = None
        await self._start_checked(up)
        primary_task = asyncio.ensure_future(up.next_event())
        can_hedge = (not self.emitted and not self.hedged and
                     self.lb._hedge_enabled())  # pylint: disable=protected-access
        if can_hedge:
            delay = self.lb._hedge_delay_s()  # pylint: disable=protected-access
            try:
                ev = await asyncio.wait_for(
                    asyncio.shield(primary_task), timeout=delay)
                self._winner = up
                return ev
            except asyncio.TimeoutError:
                pass
            except BaseException:
                primary_task.cancel()
                up.close()
                raise
            return await self._hedge_race(up, primary_task, delay)
        try:
            ev = await primary_task
            self._winner = up
            return ev
        except BaseException:
            up.close()
            raise

    async def _start_checked(self, up: _SSEUpstream) -> None:
        """start() + status triage: sheds raise _ReplicaShedError,
        any other non-200 raises _NonStreamVerdict (passthrough)."""
        resp = await up.start()
        if resp.status in (429, 503):
            body = await resp.read()
            up.close()
            raise _ReplicaShedError(resp.status, body,
                                    dict(resp.headers))
        if resp.status != 200:
            body = await resp.read()
            headers = {
                k: v for k, v in resp.headers.items()
                if k.lower() not in _HOP_HEADERS and
                k.lower() != 'content-length'
            }
            up.close()
            raise _NonStreamVerdict(
                resp.status,
                web.Response(status=resp.status, body=body,
                             headers=headers))

    async def _hedge_race(self, primary: _SSEUpstream, primary_task,
                          delay: float) -> Optional[Dict[str, Any]]:
        """The primary streamed nothing within the hedge delay: race
        a second replica for the first token. Exactly one upstream
        wins and owns the client stream; the loser is closed AND its
        replica-side request cancelled by id."""
        hedge_url = self.lb._pick(  # pylint: disable=protected-access
            exclude=self.tried | self.lb._draining)  # pylint: disable=protected-access
        if hedge_url is None:
            # Nobody to hedge on: keep waiting on the primary alone.
            try:
                ev = await primary_task
                self._winner = primary
                return ev
            except BaseException:
                primary.close()
                raise
        self.tried.add(hedge_url)
        self._held.append(hedge_url)
        self.hedged = True
        hsp = trace_lib.start_span('lb.hedge', primary=primary.url,
                                   replica=hedge_url,
                                   delay_s=round(delay, 4))
        hedge = self._upstream(hedge_url)

        async def hedge_first():
            await self._start_checked(hedge)
            return await hedge.next_event()

        hedge_task = asyncio.ensure_future(hedge_first())
        arms = {primary_task: primary, hedge_task: hedge}
        pending = set(arms)
        hedge_alive = True
        primary_alive = True
        primary_exc: Optional[BaseException] = None
        try:
            while pending:
                done, pending = await asyncio.wait(
                    pending, return_when=asyncio.FIRST_COMPLETED)
                winner_task = next(
                    (t for t in done if t.exception() is None and
                     t.result() is not None), None)
                if winner_task is not None:
                    winner = arms[winner_task]
                    loser_task = (primary_task
                                  if winner_task is hedge_task
                                  else hedge_task)
                    loser = arms[loser_task]
                    loser_live = (primary_alive
                                  if loser is primary
                                  else hedge_alive)
                    # The loser may have landed in the SAME wait()
                    # batch: retrieve its outcome (else asyncio logs
                    # 'exception was never retrieved' and a dead
                    # replica's failure is never breaker-fed).
                    loser_exc: Optional[BaseException] = None
                    loser_streamed = False
                    if loser_live and loser_task.done():
                        loser_exc = loser_task.exception()
                        if loser_exc is None:
                            loser_streamed = (loser_task.result()
                                              is not None)
                            if not loser_streamed:
                                loser_exc = (
                                    aiohttp.ServerDisconnectedError(
                                        'stream ended without '
                                        'events'))
                    if winner_task is hedge_task:
                        outcome = 'won'
                    elif not hedge_alive:
                        # Hedge already failed in an earlier batch:
                        # counted 'failed' there.
                        outcome = None
                    elif loser_exc is not None:
                        outcome = 'failed'   # failed in THIS batch
                    else:
                        outcome = 'lost'
                    if outcome is not None:
                        _M_HEDGES.inc(1, outcome=outcome)
                    if hsp.end_time is None:
                        hsp.finish(outcome=outcome or 'failed')
                    if loser_live:
                        if not loser_task.done():
                            loser_task.cancel()
                        loser.close()
                        if loser_exc is not None:
                            self._note_race_failure(loser.url,
                                                    loser_exc)
                        else:
                            # Cancelled mid-flight (or it streamed an
                            # event nobody will forward): the loser
                            # replica may hold the request — cancel
                            # it so its slot frees now.
                            asyncio.ensure_future(
                                self.lb._cancel_on(loser.url,  # pylint: disable=protected-access
                                                   self.req_id))
                    self._release(loser.url)
                    self._winner = winner
                    logger.info(
                        'Hedge race for request %s: %s won '
                        '(primary=%s hedge=%s, trace=%s).',
                        self.req_id, outcome or 'primary', primary.url,
                        hedge_url, self._trace_id)
                    return winner_task.result()
                for t in done:
                    # This arm failed (error, shed, or EOF without an
                    # event): drop it from the race.
                    exc = t.exception()
                    if t is hedge_task:
                        hedge_alive = False
                        _M_HEDGES.inc(1, outcome='failed')
                        if hsp.end_time is None:
                            hsp.finish(outcome='failed')
                        hedge.close()
                        # A refused/dead hedge must feed the breaker
                        # too — its exception never reaches run()'s
                        # arms (the primary may still win).
                        self._note_race_failure(hedge_url, exc)
                        self._release(hedge_url)
                        logger.info(
                            'Hedge on %s failed (%s); primary still '
                            'pending (trace=%s).', hedge_url, exc,
                            self._trace_id)
                    else:
                        primary_alive = False
                        primary_exc = (
                            exc or aiohttp.ServerDisconnectedError(
                                'stream ended without events'))
                        primary.close()
                        # Note the primary NOW: if the hedge wins,
                        # this exception is swallowed and run() never
                        # sees it; if both fail, run() skips the
                        # double-note via _noted_exc.
                        self._note_race_failure(primary.url,
                                                primary_exc)
                        self._noted_exc = primary_exc
            # Both arms failed: surface the primary's failure so
            # run()'s retry/resume arms see the usual taxonomy.
            raise (primary_exc or
                   aiohttp.ServerDisconnectedError(
                       'hedge race produced no stream'))
        finally:
            if hsp.end_time is None:
                hsp.finish(outcome='aborted')


class _NonStreamVerdict(Exception):
    """The replica answered /generate with a non-200, non-shed
    response (400 bad request, 404, 409 duplicate id...): a final
    verdict to pass through, not an attempt failure."""

    def __init__(self, status: int, response: web.Response) -> None:
        super().__init__(f'replica verdict {status}')
        self.status = status
        self.response = response


class _DisaggPrefillError(Exception):
    """The disagg phase-0 prefill handoff produced no usable
    manifest (non-200, or a 200 without one): fall back to the
    interleaved path (docs/disaggregation.md)."""


class _ClientGone(Exception):
    """The client hung up mid-stream (or the lb.client_disconnect
    chaos site acted it out): end the attempt without retry/resume —
    there is nobody left to stream to."""


class _InjectedConnectError(aiohttp.ClientConnectorError):
    """A fault-injected TCP connect failure (site lb.replica.connect):
    walks the exact except arm a real ECONNREFUSED would."""

    def __init__(self, msg: str) -> None:  # pylint: disable=super-init-not-called
        self._conn_key = types.SimpleNamespace(host='fault-injection',
                                               port=0, ssl=None)
        self._os_error = ConnectionRefusedError(msg)
        self._msg = msg

    def __str__(self) -> str:
        return self._msg


class _MidStreamError(Exception):
    """Upstream died after response bytes reached the client."""

    def __init__(self, response: web.StreamResponse,
                 cause: BaseException) -> None:
        super().__init__(str(cause))
        self.response = response
        self.cause = cause


class _ReplicaShedError(Exception):
    """A replica answered 429/503 without executing the request
    (queue full, wont_make_deadline, draining, warming): the attempt
    loop may safely retry another replica, and must forward the shed
    verdict — Retry-After included — if every candidate sheds."""

    _FORWARD_HEADERS = ('retry-after', 'content-type', 'x-request-id')

    def __init__(self, status: int, body: bytes,
                 headers: Dict[str, str]) -> None:
        self.status = status
        self.body = body
        self.headers = headers
        self.reason = None
        try:
            self.reason = json.loads(body or b'{}').get('reason')
        except (ValueError, AttributeError):
            pass
        super().__init__(f'replica shed ({status}, '
                         f'reason={self.reason})')

    def client_response(self) -> web.Response:
        fwd = {k: v for k, v in self.headers.items()
               if k.lower() in self._FORWARD_HEADERS}
        return web.Response(status=self.status, body=self.body,
                            headers=fwd)
