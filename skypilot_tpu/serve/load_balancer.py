"""HTTP load balancer: aiohttp reverse proxy over ready replicas.

Re-design of reference ``sky/serve/load_balancer.py:22`` +
``load_balancing_policies.py:89,115`` (RoundRobinPolicy /
LeastLoadPolicy). Runs inside the service controller process; replica
URLs are pushed in by the replica manager, and every proxied request
is reported to the autoscaler as load signal.

Proxying is streaming end to end: response bodies are forwarded
chunk-by-chunk (SSE token streams from the engine front end reach the
client as they are produced, like the reference LB's streaming
passthrough), upstream connections come from one pooled
``ClientSession`` (per-request sessions pay TCP+TLS setup on every
proxied call), and a request whose replica cannot be reached — the
connection failed, so the replica never saw it — is transparently
retried on a different ready replica. Replica removal (rolling
update, downscale) can ``drain()`` a URL: stop picking it, then wait
for its in-flight requests to finish before teardown.

Request lifecycle (docs/request_lifecycle.md): a client's
``X-Request-Deadline`` remaining-budget header becomes an absolute
deadline at arrival; every proxy attempt re-stamps the budget still
left, a past-deadline request is answered 504 and never retried, and
a replica's 429/503 shed is retried on another replica — with the
last shed's Retry-After and reason forwarded when every candidate
sheds.
"""
from __future__ import annotations

import asyncio
import itertools
import json
import threading
from typing import Callable, Dict, List, Optional, Set

import aiohttp
from aiohttp import web

from skypilot_tpu import metrics as metrics_lib
from skypilot_tpu import trace as trace_lib
from skypilot_tpu.utils import env_registry
from skypilot_tpu.utils import fault_injection
from skypilot_tpu.utils import lifecycle
from skypilot_tpu.utils import statedb
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

_HOP_HEADERS = {
    'connection', 'keep-alive', 'proxy-authenticate',
    'proxy-authorization', 'te', 'trailers', 'transfer-encoding',
    'upgrade', 'host',
}

# Per-replica serving signals (docs/metrics.md). The in-flight gauge
# is the SINGLE store of per-replica load: LeastLoadPolicy routes on
# it, drain() waits on it, and operators scrape it — no second
# private count that can disagree with the dashboard.
_M_INFLIGHT = metrics_lib.gauge(
    'skytpu_lb_replica_inflight',
    'Requests currently proxied to the replica.',
    labels=('replica',))
_M_LATENCY = metrics_lib.histogram(
    'skytpu_lb_replica_request_seconds',
    'End-to-end proxied request latency per replica.',
    labels=('replica',), buckets=metrics_lib.LATENCY_BUCKETS)
_M_ERRORS = metrics_lib.counter(
    'skytpu_lb_replica_errors_total',
    'Proxy failures per replica by kind (connect, disconnect, '
    'mid_stream, upstream, shed).',
    labels=('replica', 'kind'))
_M_LATENCY_P99 = metrics_lib.gauge(
    'skytpu_lb_request_p99_seconds',
    'Sliding-window p99 of end-to-end proxied request latency across '
    'all replicas (SKYTPU_SLO_WINDOW_S, default 60 s). The '
    'LB-level latency signal dashboards and the SLO autoscaler read '
    'without a PromQL histogram_quantile over the cumulative '
    'per-replica histograms.')
_M_DEADLINE_REJECTS = metrics_lib.counter(
    'skytpu_lb_deadline_rejects_total',
    'Requests answered 504 at the LB because their deadline passed '
    'before (or between) proxy attempts — a past-deadline request '
    'is never retried (docs/request_lifecycle.md).')


class LoadBalancingPolicy:
    """Base: owns the replica URL set and the shared in-flight gauge
    lifecycle (series appear/disappear with replicas). ``pick`` must
    increment the gauge for the returned URL; ``done`` releases it."""

    def __init__(self) -> None:
        self._urls: List[str] = []

    def urls(self) -> List[str]:
        return list(self._urls)

    def set_urls(self, urls: List[str]) -> None:
        for gone in set(self._urls) - set(urls):
            # Drop the series ONLY when idle: drain() waits on this
            # gauge, and a rotation (scale-down marks the replica
            # SHUTTING_DOWN before its in-flight requests finish)
            # must not zero the count out from under it — the old
            # private-dict implementation never pruned on set_urls
            # either. done() removes the straggler series once it
            # reaches zero.
            if not _M_INFLIGHT.has_series(replica=gone) or \
                    _M_INFLIGHT.value(replica=gone) <= 0:
                _M_INFLIGHT.remove(replica=gone)
        for url in urls:
            _M_INFLIGHT.touch(replica=url)
        self._on_set_urls(list(urls))
        self._urls = list(urls)

    def _on_set_urls(self, urls: List[str]) -> None:
        pass

    def pick(self, exclude: Optional[Set[str]] = None) -> Optional[str]:
        raise NotImplementedError

    def done(self, url: str) -> None:
        if url in self._urls:
            _M_INFLIGHT.dec(floor=0.0, replica=url)
        elif _M_INFLIGHT.has_series(replica=url):
            # Rotated out while in flight: release, and retire the
            # series once the last straggler finishes (drain() has
            # nothing left to wait on).
            if _M_INFLIGHT.dec(floor=0.0, replica=url) <= 0:
                _M_INFLIGHT.remove(replica=url)


class RoundRobinPolicy(LoadBalancingPolicy):

    def __init__(self) -> None:
        super().__init__()
        self._it = itertools.cycle([])

    def _on_set_urls(self, urls: List[str]) -> None:
        if urls != self._urls:
            self._it = itertools.cycle(urls)

    def pick(self, exclude: Optional[Set[str]] = None) -> Optional[str]:
        if not self._urls:
            return None
        for _ in range(len(self._urls)):
            url = next(self._it)
            if not exclude or url not in exclude:
                _M_INFLIGHT.inc(1, replica=url)
                return url
        return None


class LeastLoadPolicy(LoadBalancingPolicy):
    """Route to the replica with the fewest in-flight requests.

    The in-flight count IS the ``skytpu_lb_replica_inflight`` gauge:
    the policy routes on exactly the series operators scrape, instead
    of a private dict that could drift from the dashboard."""

    def __init__(self) -> None:
        super().__init__()
        self._lock = threading.Lock()

    def pick(self, exclude: Optional[Set[str]] = None) -> Optional[str]:
        with self._lock:
            candidates = [u for u in self._urls
                          if not exclude or u not in exclude]
            if not candidates:
                return None
            url = min(candidates,
                      key=lambda u: _M_INFLIGHT.value(replica=u))
            _M_INFLIGHT.inc(1, replica=url)
            return url


POLICIES = {
    'round_robin': RoundRobinPolicy,
    'least_load': LeastLoadPolicy,
}


class LoadBalancer:
    """aiohttp app proxying every request to a picked replica."""

    MAX_ATTEMPTS = 3

    def __init__(self, port: int, policy: str = 'least_load',
                 on_request: Optional[Callable[[], None]] = None) -> None:
        # port 0 = let the OS pick; the actual port is in `bound_port`
        # after start() (avoids probe-then-rebind TOCTOU races).
        self.port = port
        self.bound_port: Optional[int] = None
        self.policy: LoadBalancingPolicy = POLICIES[policy]()
        self.on_request = on_request
        self._runner: Optional[web.AppRunner] = None
        self._session: Optional[aiohttp.ClientSession] = None
        self._draining: Set[str] = set()
        # Sliding p99 window behind the cumulative per-replica
        # latency histograms (docs/load_testing.md): per-instance so
        # a rebuilt LB starts a fresh window, feeding the
        # skytpu_lb_request_p99_seconds gauge.
        self._latency_window = metrics_lib.SlidingWindowPercentile(
            float(env_registry.get(env_registry.SKYTPU_SLO_WINDOW_S,
                                   '60')))

    def set_replica_urls(self, urls: List[str]) -> None:
        self.policy.set_urls(urls)
        self._draining &= set(urls)

    def inflight(self, url: str) -> int:
        # One store for in-flight load: the scraped gauge, maintained
        # by policy.pick()/done().
        return int(_M_INFLIGHT.value(replica=url))

    async def drain(self, url: str, timeout: float = 60.0) -> bool:
        """Stop routing new requests to ``url`` and wait for its
        in-flight ones to finish (rolling update / downscale: tear the
        replica down only after this returns). True = drained."""
        self._draining.add(url)
        deadline = statedb.wall_now() + timeout
        while self.inflight(url) > 0:
            if statedb.wall_now() > deadline:
                return False
            await asyncio.sleep(0.05)
        return True

    # ------------------------------------------------------------------
    async def _proxy(self, request: web.Request) -> web.StreamResponse:
        # One request span per proxied call, continuing the client's
        # trace when it sent a traceparent header (docs/tracing.md);
        # each replica attempt is a child span whose duration IS the
        # per-replica latency observation (single timing source), and
        # whose trace id rides on the histogram as an exemplar.
        ctx = trace_lib.context_from_headers(request.headers)
        with trace_lib.span('lb.request', parent=ctx,
                            method=request.method,
                            path=request.rel_url.path):
            if (request.method == 'POST' and
                    request.rel_url.path.startswith('/cancel/')):
                return await self._cancel_broadcast(request)
            return await self._proxy_attempts(request)

    async def _cancel_broadcast(self, request: web.Request
                                ) -> web.Response:
        """POST /cancel/<id> fans out to EVERY known replica —
        draining ones included. The LB routed the original /generate
        wherever it pleased, so a cancel-by-request-id cannot know
        which replica holds the request; round-robining it would let
        a wrong-replica 404 mask the right replica's 202
        (docs/request_lifecycle.md)."""
        urls = set(self.policy.urls()) | self._draining
        if not urls:
            return web.Response(status=503,
                                text='No ready replicas.\n')
        path = request.rel_url.path
        assert self._session is not None, 'start() not called'

        async def one(url: str):
            try:
                # Short per-call bound: one wedged replica must not
                # hold the whole broadcast (and the client's cancel)
                # hostage to the session's long sock_read.
                async with self._session.post(
                        url.rstrip('/') + path,
                        timeout=aiohttp.ClientTimeout(total=5)) as resp:
                    return (resp.status, await resp.read(),
                            resp.headers.get('Content-Type',
                                             'application/json'))
            except (aiohttp.ClientError, asyncio.TimeoutError):
                return None

        results = [r for r in await asyncio.gather(
            *(one(u) for u in sorted(urls))) if r is not None]
        # One replica accepting wins; otherwise surface any answer
        # (typically 404 unknown-id); only total unreachability 502s.
        chosen = next((r for r in results if r[0] == 202),
                      results[0] if results else None)
        if chosen is None:
            return web.Response(status=502,
                                text='No replica reachable.\n')
        return web.Response(status=chosen[0], body=chosen[1],
                            content_type=chosen[2].split(';')[0])

    async def _proxy_attempts(self, request: web.Request
                              ) -> web.StreamResponse:
        if self.on_request is not None:
            self.on_request()
        body = await request.read()
        # End-to-end deadline (docs/request_lifecycle.md): the
        # client's remaining-budget header becomes an absolute
        # deadline HERE; every proxy attempt re-stamps the budget
        # still left, and a request whose deadline has passed is
        # answered 504 — never retried onto another replica.
        deadline = lifecycle.deadline_from_headers(request.headers)
        tried: Set[str] = set()
        last_err: Optional[BaseException] = None
        last_shed: Optional[_ReplicaShedError] = None
        # Set when an attempt failed AFTER the request reached a
        # replica that may have executed it: that ambiguity must
        # reach the client, never be masked by an earlier shed.
        may_have_executed = False
        trace_id = trace_lib.current_trace_id()
        for _ in range(self.MAX_ATTEMPTS):
            left = lifecycle.remaining(deadline)
            if left is not None and left <= 0:
                _M_DEADLINE_REJECTS.inc()
                logger.warning('Deadline passed before attempt '
                               '(trace=%s); answering 504.', trace_id)
                return web.json_response(
                    {'error': 'deadline exceeded before the request '
                              'could be served',
                     'reason': 'deadline_exceeded'}, status=504)
            url = self.policy.pick(exclude=tried | self._draining)
            if url is None:
                break
            tried.add(url)
            sp = trace_lib.start_span('lb.proxy', replica=url,
                                      **({'budget_s': round(left, 3)}
                                         if left is not None else {}))
            try:
                with trace_lib.activate(sp):
                    resp = await self._proxy_once(request, url, body,
                                                  deadline)
                sp.finish(status=resp.status)
                _M_LATENCY.observe(sp.duration, exemplar=sp.exemplar,
                                   replica=url)
                self._latency_window.observe(sp.duration)
                p99 = self._latency_window.quantile(0.99)
                if p99 is not None:
                    _M_LATENCY_P99.set(p99)
                return resp
            except _ReplicaShedError as e:
                # The replica REFUSED the request (429 queue-full /
                # deadline shed, 503 draining-or-warming) without
                # executing it: safe to try another replica for any
                # method. If every candidate sheds, the LAST shed
                # response — Retry-After and reason included — is
                # forwarded to the client instead of being swallowed.
                sp.finish(status=e.status, error='shed')
                logger.info('Replica %s shed the request (%d, '
                            'reason=%s); trying another (trace=%s)',
                            url, e.status, e.reason, trace_id)
                _M_ERRORS.inc(1, replica=url, kind='shed')
                last_shed = e
            except aiohttp.ClientConnectorError as e:
                # TCP connect failed: the replica NEVER received the
                # request — safe to retry on another replica for any
                # method.
                sp.finish(error='connect')
                logger.warning('Replica %s unreachable (%s); retrying '
                               'on another replica (trace=%s)', url, e,
                               trace_id)
                _M_ERRORS.inc(1, replica=url, kind='connect')
                last_err = e
            except aiohttp.ClientConnectionError as e:
                # Connection dropped after the request was sent (e.g.
                # ServerDisconnectedError): the replica may have
                # started executing it. Retrying would double-execute
                # non-idempotent work, so only safe methods retry.
                sp.finish(error='disconnect')
                _M_ERRORS.inc(1, replica=url, kind='disconnect')
                if request.method not in ('GET', 'HEAD', 'OPTIONS'):
                    logger.warning('Replica %s dropped mid-request '
                                   '(%s); not retrying %s (trace=%s)',
                                   url, e, request.method, trace_id)
                    last_err = e
                    may_have_executed = True
                    break
                logger.warning('Replica %s dropped %s (%s); retrying '
                               '(trace=%s)', url, request.method, e,
                               trace_id)
                last_err = e
            except _MidStreamError as e:
                # Bytes already reached the client: cannot retry.
                sp.finish(error='mid_stream')
                logger.warning('Replica %s died mid-response: %s '
                               '(trace=%s)', url, e.cause, trace_id)
                _M_ERRORS.inc(1, replica=url, kind='mid_stream')
                return e.response
            except (aiohttp.ClientError, asyncio.TimeoutError) as e:
                sp.finish(error='upstream')
                logger.warning('Proxy to %s failed: %s (trace=%s)',
                               url, e, trace_id)
                _M_ERRORS.inc(1, replica=url, kind='upstream')
                last_err = e
                if request.method not in ('GET', 'HEAD', 'OPTIONS'):
                    # Same double-execution risk as the dropped-
                    # connection branch: the replica may have run the
                    # request (e.g. 200 headers then a payload error).
                    may_have_executed = True
                    break
            finally:
                # An exception outside the enumerated arms — notably
                # CancelledError when the client disconnects mid-
                # proxy — must still land the attempt in the trace:
                # aborted requests are exactly the ones worth
                # reading. finish() is idempotent for the arms above.
                if sp.end_time is None:
                    sp.finish(error='aborted')
                self.policy.done(url)
        if last_shed is not None and not may_have_executed:
            # Every candidate shed (or was unreachable without ever
            # receiving the request): surface the last replica's own
            # verdict (status, Retry-After, reason) so the client
            # backs off intelligently instead of seeing a generic
            # error with the hint stripped. A shed explicitly means
            # "refused WITHOUT executing, safe to resubmit" — so it
            # must never mask a later may-have-executed failure.
            return last_shed.client_response()
        if last_err is None:
            return web.Response(status=503,
                                text='No ready replicas.\n')
        return web.Response(status=502,
                            text=f'Replica unreachable: {last_err}\n')

    async def _proxy_once(self, request: web.Request, url: str,
                          body: bytes,
                          deadline: Optional[float] = None
                          ) -> web.StreamResponse:
        target = url.rstrip('/') + '/' + request.rel_url.path.lstrip('/')
        if request.rel_url.query_string:
            target += '?' + request.rel_url.query_string
        headers = {
            k: v for k, v in request.headers.items()
            if k.lower() not in _HOP_HEADERS
        }
        # Continue the trace into the replica: the active lb.proxy
        # span replaces any client-sent traceparent (the replica must
        # parent under THIS hop, not skip it). When tracing is off
        # this is {} and the client's own header passes through.
        tp = trace_lib.traceparent_headers()
        if tp:
            headers = {k: v for k, v in headers.items()
                       if k.lower() != trace_lib.TRACEPARENT_HEADER}
            headers.update(tp)
        # Stamp the budget STILL LEFT for this attempt (a retry after
        # a slow failure hands the replica less than the original):
        # the replica turns it back into an absolute local deadline.
        budget = lifecycle.budget_headers(deadline)
        if budget:
            headers = {k: v for k, v in headers.items()
                       if k.lower() != lifecycle.DEADLINE_HEADER.lower()}
            headers.update(budget)
        assert self._session is not None, 'start() not called'
        async with self._session.request(request.method, target,
                                         headers=headers,
                                         data=body) as resp:
            if (resp.status in (429, 503) and
                    request.rel_url.path != '/health'):
                # A shed, not a result: the replica refused without
                # executing (queue full, wont_make_deadline,
                # draining, warming). Raise so the attempt loop can
                # try a replica with capacity — and forward THIS
                # response's Retry-After/reason if none has any.
                raise _ReplicaShedError(
                    resp.status, await resp.read(),
                    dict(resp.headers))
            out_headers = {
                k: v for k, v in resp.headers.items()
                if k.lower() not in _HOP_HEADERS and
                k.lower() != 'content-length'
            }
            out = web.StreamResponse(status=resp.status,
                                     headers=out_headers)
            started = False
            disconnect = None
            try:
                # Chunk-by-chunk passthrough: an SSE token stream (or
                # any long body) reaches the client as the replica
                # produces it, instead of buffering end-to-end.
                async for chunk in resp.content.iter_chunked(1 << 16):
                    if not started:
                        await out.prepare(request)
                        started = True
                        # Chaos site (docs/fault_injection.md): act
                        # out the client hanging up mid-response.
                        # Polled only once a chunk really streamed —
                        # a shed or connect-failure attempt must not
                        # burn a one-shot disconnect spec without
                        # acting it out.
                        disconnect = fault_injection.poll(
                            'lb.client_disconnect',
                            kinds=(fault_injection.FaultKind
                                   .CLIENT_DISCONNECT,),
                            replica=url, path=request.rel_url.path)
                    await out.write(chunk)
                    if disconnect is not None:
                        resp.close()   # abort upstream: replica sees
                        raise _MidStreamError(  # the hangup
                            out, ConnectionResetError(
                                '[fault-injection] client '
                                'disconnect'))
                if not started:
                    await out.prepare(request)
                await out.write_eof()
                return out
            except (aiohttp.ClientError, asyncio.TimeoutError) as e:
                if started:
                    # Headers/body already sent; surface the abort to
                    # the wrapper as non-retryable.
                    raise _MidStreamError(out, e) from e
                raise

    async def _handle_metrics(self, request: web.Request
                              ) -> web.Response:
        """The controller-side scrape point: this process's LB +
        autoscaler + replica-manager metrics (docs/metrics.md).
        Registered before the catch-all proxy route, so /metrics is
        served locally, not proxied. This process's registry only —
        spool merging is the API server's job (one merger per host,
        or multi-endpoint scrapes double-count the spool)."""
        text = metrics_lib.render_exposition()
        return web.Response(
            text=text, headers={'Content-Type': metrics_lib.CONTENT_TYPE})

    # ------------------------------------------------------------------
    async def start(self) -> None:
        app = web.Application()
        app.router.add_get('/metrics', self._handle_metrics)
        app.router.add_route('*', '/{tail:.*}', self._proxy)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        # One pooled upstream session: per-request sessions pay
        # connection setup on every proxied call (18% stack tax in the
        # r03 full-stack bench). No total timeout — long-lived SSE
        # streams are legitimate; sock_read bounds replica *silence*
        # instead, so a wedged replica still gets cut.
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=None, sock_connect=10,
                                          sock_read=300),
            connector=aiohttp.TCPConnector(limit=0,
                                           limit_per_host=0,
                                           keepalive_timeout=60))
        site = web.TCPSite(self._runner, '0.0.0.0', self.port)
        await site.start()
        sockets = site._server.sockets  # pylint: disable=protected-access
        self.bound_port = sockets[0].getsockname()[1]
        logger.info('Load balancer listening on :%d', self.bound_port)

    async def stop(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None
        if self._runner is not None:
            await self._runner.cleanup()


class _MidStreamError(Exception):
    """Upstream died after response bytes reached the client."""

    def __init__(self, response: web.StreamResponse,
                 cause: BaseException) -> None:
        super().__init__(str(cause))
        self.response = response
        self.cause = cause


class _ReplicaShedError(Exception):
    """A replica answered 429/503 without executing the request
    (queue full, wont_make_deadline, draining, warming): the attempt
    loop may safely retry another replica, and must forward the shed
    verdict — Retry-After included — if every candidate sheds."""

    _FORWARD_HEADERS = ('retry-after', 'content-type', 'x-request-id')

    def __init__(self, status: int, body: bytes,
                 headers: Dict[str, str]) -> None:
        self.status = status
        self.body = body
        self.headers = headers
        self.reason = None
        try:
            self.reason = json.loads(body or b'{}').get('reason')
        except (ValueError, AttributeError):
            pass
        super().__init__(f'replica shed ({status}, '
                         f'reason={self.reason})')

    def client_response(self) -> web.Response:
        fwd = {k: v for k, v in self.headers.items()
               if k.lower() in self._FORWARD_HEADERS}
        return web.Response(status=self.status, body=self.body,
                            headers=fwd)
