"""Serve SQLite state: services + replicas.

Re-design of reference ``sky/serve/serve_state.py:40-57``.

Durability goes through :mod:`skypilot_tpu.utils.statedb`: replica
scale-up/scale-down are multi-step operations (row write -> cluster
launch/teardown -> row write) bracketed by intent records in the same
transactions as the row writes, so a controller killed mid-operation
is reconciled on restart (docs/crash_recovery.md).
"""
from __future__ import annotations

import json
import os
import sqlite3
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import statedb
from skypilot_tpu.utils.status_lib import ReplicaStatus, ServiceStatus

_DB_PATH_ENV = 'SKYTPU_SERVE_DB'
_DEFAULT_DB = '~/.skytpu/serve.db'


def _db_path() -> str:
    return os.path.expanduser(os.environ.get(_DB_PATH_ENV, _DEFAULT_DB))


def _init(conn: sqlite3.Connection) -> None:
    conn.execute("""
        CREATE TABLE IF NOT EXISTS services (
            name TEXT PRIMARY KEY,
            status TEXT,
            spec_json TEXT,
            task_json TEXT,
            controller_pid INTEGER,
            lb_port INTEGER,
            created_at REAL,
            next_replica_id INTEGER DEFAULT 0,
            current_version INTEGER DEFAULT 1
        )""")
    # Per-version task+spec so rolling updates can launch new-version
    # replicas while old-version replicas drain (reference
    # sky/serve/serve_state.py:40-57 version_specs).
    conn.execute("""
        CREATE TABLE IF NOT EXISTS version_specs (
            service_name TEXT,
            version INTEGER,
            spec_json TEXT,
            task_json TEXT,
            created_at REAL,
            PRIMARY KEY (service_name, version)
        )""")
    conn.execute("""
        CREATE TABLE IF NOT EXISTS replicas (
            service_name TEXT,
            replica_id INTEGER,
            cluster_name TEXT,
            status TEXT,
            url TEXT,
            launched_at REAL,
            starting_at REAL,
            failed_at REAL,
            version INTEGER DEFAULT 1,
            is_spot INTEGER DEFAULT 0,
            PRIMARY KEY (service_name, replica_id)
        )""")
    # Autoscaler durability (reference sky/serve/autoscalers.py:431
    # couples LB request timestamps into persisted state): the QPS
    # window + hysteresis clocks survive a controller restart, so a
    # restart under load does not forget demand and spuriously
    # downscale.
    conn.execute("""
        CREATE TABLE IF NOT EXISTS autoscaler_state (
            service_name TEXT PRIMARY KEY,
            state_json TEXT,
            updated_at REAL
        )""")
    # Migrate DBs created before these columns existed (CREATE TABLE IF
    # NOT EXISTS is a no-op on an old schema).
    for table, column, decl in (
        ('services', 'next_replica_id', 'INTEGER DEFAULT 0'),
        ('services', 'current_version', 'INTEGER DEFAULT 1'),
        ('replicas', 'starting_at', 'REAL'),
        ('replicas', 'failed_at', 'REAL'),
        ('replicas', 'version', 'INTEGER DEFAULT 1'),
        ('replicas', 'is_spot', 'INTEGER DEFAULT 0'),
    ):
        try:
            conn.execute(
                f'ALTER TABLE {table} ADD COLUMN {column} {decl}')
            if column == 'next_replica_id':
                # Seed the counter past any pre-migration replica ids.
                conn.execute("""
                    UPDATE services SET next_replica_id = COALESCE(
                        (SELECT MAX(replica_id) FROM replicas
                         WHERE replicas.service_name = services.name), 0)
                """)
        except sqlite3.OperationalError:
            pass  # already present


_DB = statedb.StateDB(_db_path, init_fn=_init, site='serve.state.write')


def db() -> statedb.StateDB:
    """The serve StateDB — the fleet layer builds its LeaseTable on
    it so service leases live next to the rows they guard."""
    return _DB


def controller_resource(service_name: str) -> str:
    """Lease resource name for ownership of one service's control
    loop (docs/control_plane.md)."""
    return f'serve.controller:{service_name}'


def register_controller_leases(names: List[str]) -> None:
    """Create (unowned) controller-lease rows for these services,
    gated on the service row still existing in the SAME transaction
    (same fence-resurrection hazard as
    ``jobs.state.register_controller_leases``)."""
    with _DB.transaction() as conn:
        for name in names:
            row = conn.execute('SELECT 1 FROM services WHERE name = ?',
                               (name,)).fetchone()
            if row is None:
                continue
            statedb.lease_register(conn, controller_resource(name))


# ------------------------------------------------------------- services


def add_service(name: str, spec_json: str, task_json: str,
                lb_port: int) -> None:
    with _DB.transaction() as conn:
        conn.execute(
            'INSERT OR REPLACE INTO services (name, status, spec_json, '
            'task_json, lb_port, created_at, current_version) '
            'VALUES (?,?,?,?,?,?,1)',
            (name, ServiceStatus.CONTROLLER_INIT.value, spec_json,
             task_json, lb_port, statedb.wall_now()))
        conn.execute(
            'INSERT OR REPLACE INTO version_specs (service_name, '
            'version, spec_json, task_json, created_at) '
            'VALUES (?,1,?,?,?)', (name, spec_json, task_json,
                                   statedb.wall_now()))


def add_version(name: str, spec_json: str, task_json: str) -> int:
    """Record a new service version; returns the new version number.

    The controller notices current_version changed on its next loop and
    rolls replicas forward (launch new, drain old once new are READY).
    """
    with _DB.transaction() as conn:
        row = conn.execute(
            'SELECT MAX(version) AS v FROM version_specs '
            'WHERE service_name = ?', (name,)).fetchone()
        version = int(row['v'] or 0) + 1
        conn.execute(
            'INSERT INTO version_specs (service_name, version, '
            'spec_json, task_json, created_at) VALUES (?,?,?,?,?)',
            (name, version, spec_json, task_json, statedb.wall_now()))
        # Keep the service row's spec/task mirroring the latest
        # version (what status/up readers see).
        conn.execute(
            'UPDATE services SET current_version = ?, spec_json = ?, '
            'task_json = ? WHERE name = ?',
            (version, spec_json, task_json, name))
    return version


def get_current_version(name: str) -> int:
    with _DB.reader() as conn:
        row = conn.execute(
            'SELECT current_version FROM services WHERE name = ?',
            (name,)).fetchone()
    return int(row['current_version']) if row else 1


def get_version_spec(name: str, version: int) -> Optional[Dict[str, Any]]:
    with _DB.reader() as conn:
        row = conn.execute(
            'SELECT * FROM version_specs WHERE service_name = ? AND '
            'version = ?', (name, version)).fetchone()
    if row is None:
        return None
    d = dict(row)
    d['spec'] = json.loads(d['spec_json'])
    d['task'] = json.loads(d['task_json'])
    return d


def set_service_status(name: str, status: ServiceStatus) -> None:
    with _DB.transaction() as conn:
        conn.execute('UPDATE services SET status = ? WHERE name = ?',
                     (status.value, name))


def set_service_status_unless(name: str, status: ServiceStatus,
                              unless: ServiceStatus) -> bool:
    """Conditional status write: one UPDATE, so a concurrent
    transition to ``unless`` (e.g. SHUTTING_DOWN from a teardown
    request) can never be clobbered by a stale read-modify-write.
    Returns True when the write applied."""
    with _DB.transaction() as conn:
        cur = conn.execute(
            'UPDATE services SET status = ? WHERE name = ? AND '
            'status != ?', (status.value, name, unless.value))
        return cur.rowcount == 1


def set_service_controller_pid(name: str, pid: int) -> None:
    """Record the controller process AND force-claim the service's
    controller lease in one transaction (same contract as
    ``jobs.state.set_controller_pid``: the spawned process IS the
    owner; the fence bump revokes any stale predecessor)."""
    with _DB.transaction() as conn:
        conn.execute(
            'UPDATE services SET controller_pid = ? WHERE name = ?',
            (pid, name))
        lease = statedb.lease_force_claim(conn,
                                          controller_resource(name),
                                          f'pid:{pid}',
                                          statedb.wall_now())
    statedb.record_lease_metric('claim', takeover=lease.takeover)


def set_service_lb_port(name: str, port: int) -> None:
    """The controller binds the LB port itself (port 0 = pick free) and
    records the bound port here; `up` polls for it (no bind-ahead
    TOCTOU)."""
    with _DB.transaction() as conn:
        conn.execute('UPDATE services SET lb_port = ? WHERE name = ?',
                     (port, name))


def get_service(name: str) -> Optional[Dict[str, Any]]:
    with _DB.reader() as conn:
        row = conn.execute('SELECT * FROM services WHERE name = ?',
                           (name,)).fetchone()
    if row is None:
        return None
    d = dict(row)
    d['status'] = ServiceStatus(d['status'])
    d['spec'] = json.loads(d['spec_json'])
    d['task'] = json.loads(d['task_json'])
    return d


def service_names() -> List[str]:
    """Lean name list (no spec/task JSON parsing) for the fleet
    worker's claim scans."""
    with _DB.reader() as conn:
        return [
            r['name']
            for r in conn.execute('SELECT name FROM services ORDER BY name')
        ]


def service_statuses() -> Dict[str, ServiceStatus]:
    """Lean ``name -> status`` map — the scale harness polls this
    every tick, so it must not pay get_service's spec/task JSON
    parsing per service."""
    with _DB.reader() as conn:
        return {
            r['name']: ServiceStatus(r['status'])
            for r in conn.execute('SELECT name, status FROM services')
        }


def get_services() -> List[Dict[str, Any]]:
    with _DB.reader() as conn:
        names = [
            r['name']
            for r in conn.execute('SELECT name FROM services ORDER BY name')
        ]
    return [get_service(n) for n in names]


def remove_service(name: str) -> None:
    with _DB.transaction() as conn:
        conn.execute('DELETE FROM services WHERE name = ?', (name,))
        conn.execute('DELETE FROM replicas WHERE service_name = ?',
                     (name,))
        conn.execute('DELETE FROM version_specs WHERE service_name = ?',
                     (name,))
        conn.execute(
            'DELETE FROM autoscaler_state WHERE service_name = ?',
            (name,))


def save_autoscaler_state(name: str, state: Dict[str, Any]) -> None:
    with _DB.transaction() as conn:
        conn.execute(
            'INSERT OR REPLACE INTO autoscaler_state '
            '(service_name, state_json, updated_at) VALUES (?, ?, ?)',
            (name, json.dumps(state), statedb.wall_now()))


def load_autoscaler_state(name: str) -> Optional[Dict[str, Any]]:
    with _DB.reader() as conn:
        row = conn.execute(
            'SELECT state_json FROM autoscaler_state '
            'WHERE service_name = ?', (name,)).fetchone()
    return json.loads(row['state_json']) if row else None


# ------------------------------------------------------------- replicas


def add_replica(service_name: str, replica_id: int, cluster_name: str,
                version: int = 1, is_spot: bool = False,
                intent_payload: Optional[Dict[str, Any]] = None
                ) -> Optional[int]:
    """Insert the replica row; when ``intent_payload`` is given, journal
    the scale-up intent in the SAME transaction (crash between row and
    journal is impossible) and return the intent id."""
    with _DB.transaction() as conn:
        conn.execute(
            'INSERT OR REPLACE INTO replicas (service_name, replica_id, '
            'cluster_name, status, launched_at, version, is_spot) '
            'VALUES (?,?,?,?,?,?,?)',
            (service_name, replica_id, cluster_name,
             ReplicaStatus.PENDING.value, statedb.wall_now(), version,
             int(is_spot)))
        if intent_payload is not None:
            return statedb.begin_intent(conn, 'serve.scale_up',
                                        intent_payload)
    return None


def set_replica_status(service_name: str, replica_id: int,
                       status: ReplicaStatus,
                       url: Optional[str] = None,
                       complete_intent: Optional[int] = None) -> None:
    # The readiness budget (initial_delay_seconds) is measured from the
    # STARTING transition — i.e. after provisioning — not from
    # submission (reference replica_managers.py:1105 counts from the
    # first probe after provision; cluster spin-up must not consume the
    # app's warm-up budget).
    sets = ['status = ?']
    args: list = [status.value]
    if status is ReplicaStatus.STARTING:
        sets.append('starting_at = ?')
        args.append(statedb.wall_now())
    if status.is_failed():
        # The replacement cap counts failures by WHEN they failed, not
        # when the replica launched (a replica dying after an hour of
        # service is a fresh failure).
        sets.append('failed_at = ?')
        args.append(statedb.wall_now())
    if url is not None:
        sets.append('url = ?')
        args.append(url)
    args += [service_name, replica_id]
    with _DB.transaction() as conn:
        conn.execute(
            f'UPDATE replicas SET {", ".join(sets)} '
            'WHERE service_name = ? AND replica_id = ?', args)
        if complete_intent is not None:
            statedb.complete_intent(conn, complete_intent)


def mark_shutting_down(service_name: str, replica_id: int,
                       intent_payload: Dict[str, Any]) -> int:
    """Scale-down announcement: SHUTTING_DOWN + the scale-down intent
    in one transaction. From here the operation only rolls FORWARD —
    a crash before the cluster teardown finishes is resumed by
    reconcile_on_start, never undone."""
    with _DB.transaction() as conn:
        conn.execute(
            'UPDATE replicas SET status = ? '
            'WHERE service_name = ? AND replica_id = ?',
            (ReplicaStatus.SHUTTING_DOWN.value, service_name,
             replica_id))
        return statedb.begin_intent(conn, 'serve.scale_down',
                                    intent_payload)


def get_replicas(service_name: str) -> List[Dict[str, Any]]:
    with _DB.reader() as conn:
        rows = conn.execute(
            'SELECT * FROM replicas WHERE service_name = ? '
            'ORDER BY replica_id', (service_name,)).fetchall()
    out = []
    for row in rows:
        d = dict(row)
        d['status'] = ReplicaStatus(d['status'])
        out.append(d)
    return out


def next_replica_id(service_name: str) -> int:
    # Monotonic counter in the service row (NOT max(replica_id):
    # terminated rows are garbage-collected, and a reused id would
    # collide with a cluster still being torn down asynchronously).
    with _DB.transaction() as conn:
        conn.execute(
            'UPDATE services SET next_replica_id = next_replica_id + 1 '
            'WHERE name = ?', (service_name,))
        row = conn.execute(
            'SELECT next_replica_id FROM services WHERE name = ?',
            (service_name,)).fetchone()
    return int(row['next_replica_id']) if row else 1


def remove_replica(service_name: str, replica_id: int,
                   complete_intent: Optional[int] = None) -> None:
    with _DB.transaction() as conn:
        conn.execute(
            'DELETE FROM replicas WHERE service_name = ? AND '
            'replica_id = ?', (service_name, replica_id))
        if complete_intent is not None:
            statedb.complete_intent(conn, complete_intent)


# ------------------------------------------------------ intent journal


def begin_intent(kind: str, payload: Dict[str, Any]) -> int:
    return _DB.begin_intent(kind, payload)


def complete_intent(intent_id: int) -> None:
    _DB.complete_intent(intent_id)


def open_intents(
        service_name: Optional[str] = None) -> List[Dict[str, Any]]:
    intents = _DB.open_intents('serve.*')
    if service_name is None:
        return intents
    return [i for i in intents
            if i['payload'].get('service') == service_name]
