"""Serve SQLite state: services + replicas.

Re-design of reference ``sky/serve/serve_state.py:40-57``.
"""
from __future__ import annotations

import json
import os
import pathlib
import sqlite3
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils.status_lib import ReplicaStatus, ServiceStatus

_DB_PATH_ENV = 'SKYTPU_SERVE_DB'
_DEFAULT_DB = '~/.skytpu/serve.db'


def _db_path() -> str:
    return os.path.expanduser(os.environ.get(_DB_PATH_ENV, _DEFAULT_DB))


def _conn() -> sqlite3.Connection:
    path = _db_path()
    pathlib.Path(path).parent.mkdir(parents=True, exist_ok=True)
    conn = sqlite3.connect(path, timeout=10)
    conn.row_factory = sqlite3.Row
    conn.execute('PRAGMA journal_mode=WAL')
    conn.execute("""
        CREATE TABLE IF NOT EXISTS services (
            name TEXT PRIMARY KEY,
            status TEXT,
            spec_json TEXT,
            task_json TEXT,
            controller_pid INTEGER,
            lb_port INTEGER,
            created_at REAL
        )""")
    conn.execute("""
        CREATE TABLE IF NOT EXISTS replicas (
            service_name TEXT,
            replica_id INTEGER,
            cluster_name TEXT,
            status TEXT,
            url TEXT,
            launched_at REAL,
            PRIMARY KEY (service_name, replica_id)
        )""")
    return conn


# ------------------------------------------------------------- services


def add_service(name: str, spec_json: str, task_json: str,
                lb_port: int) -> None:
    with _conn() as conn:
        conn.execute(
            'INSERT OR REPLACE INTO services (name, status, spec_json, '
            'task_json, lb_port, created_at) VALUES (?,?,?,?,?,?)',
            (name, ServiceStatus.CONTROLLER_INIT.value, spec_json,
             task_json, lb_port, time.time()))


def set_service_status(name: str, status: ServiceStatus) -> None:
    with _conn() as conn:
        conn.execute('UPDATE services SET status = ? WHERE name = ?',
                     (status.value, name))


def set_service_controller_pid(name: str, pid: int) -> None:
    with _conn() as conn:
        conn.execute(
            'UPDATE services SET controller_pid = ? WHERE name = ?',
            (pid, name))


def get_service(name: str) -> Optional[Dict[str, Any]]:
    with _conn() as conn:
        row = conn.execute('SELECT * FROM services WHERE name = ?',
                           (name,)).fetchone()
    if row is None:
        return None
    d = dict(row)
    d['status'] = ServiceStatus(d['status'])
    d['spec'] = json.loads(d['spec_json'])
    d['task'] = json.loads(d['task_json'])
    return d


def get_services() -> List[Dict[str, Any]]:
    with _conn() as conn:
        names = [
            r['name']
            for r in conn.execute('SELECT name FROM services ORDER BY name')
        ]
    return [get_service(n) for n in names]


def remove_service(name: str) -> None:
    with _conn() as conn:
        conn.execute('DELETE FROM services WHERE name = ?', (name,))
        conn.execute('DELETE FROM replicas WHERE service_name = ?',
                     (name,))


# ------------------------------------------------------------- replicas


def add_replica(service_name: str, replica_id: int,
                cluster_name: str) -> None:
    with _conn() as conn:
        conn.execute(
            'INSERT OR REPLACE INTO replicas (service_name, replica_id, '
            'cluster_name, status, launched_at) VALUES (?,?,?,?,?)',
            (service_name, replica_id, cluster_name,
             ReplicaStatus.PENDING.value, time.time()))


def set_replica_status(service_name: str, replica_id: int,
                       status: ReplicaStatus,
                       url: Optional[str] = None) -> None:
    with _conn() as conn:
        if url is not None:
            conn.execute(
                'UPDATE replicas SET status = ?, url = ? '
                'WHERE service_name = ? AND replica_id = ?',
                (status.value, url, service_name, replica_id))
        else:
            conn.execute(
                'UPDATE replicas SET status = ? '
                'WHERE service_name = ? AND replica_id = ?',
                (status.value, service_name, replica_id))


def get_replicas(service_name: str) -> List[Dict[str, Any]]:
    with _conn() as conn:
        rows = conn.execute(
            'SELECT * FROM replicas WHERE service_name = ? '
            'ORDER BY replica_id', (service_name,)).fetchall()
    out = []
    for row in rows:
        d = dict(row)
        d['status'] = ReplicaStatus(d['status'])
        out.append(d)
    return out


def next_replica_id(service_name: str) -> int:
    with _conn() as conn:
        row = conn.execute(
            'SELECT MAX(replica_id) AS m FROM replicas '
            'WHERE service_name = ?', (service_name,)).fetchone()
    return (row['m'] or 0) + 1


def remove_replica(service_name: str, replica_id: int) -> None:
    with _conn() as conn:
        conn.execute(
            'DELETE FROM replicas WHERE service_name = ? AND '
            'replica_id = ?', (service_name, replica_id))
