"""skypilot_tpu — a TPU-native sky orchestration framework.

Declare a Task (YAML or Python), let the optimizer pick the cheapest
feasible TPU slice / VM, provision it on GCP (or run it hermetically on
the Local cloud), gang-schedule the command across every TPU host with a
rank/IP/topology env contract feeding ``jax.distributed.initialize()``,
stream logs, and manage lifecycle: status reconciliation, autostop,
failover, managed spot recovery, storage mounts, and serving.

Re-design (not a port) of SkyPilot — see SURVEY.md for the mapping.
"""
__version__ = '0.1.0'

# Everything is lazy (reference sky/__init__.py:94-116 uses the same
# pattern): agent/driver subprocesses import subpackages of
# skypilot_tpu hundreds of times per session, and must not pay for
# optimizer/scipy/jsonschema imports they never use.
_LAZY_ATTRS = {
    'AdminPolicy': ('skypilot_tpu.admin_policy', 'AdminPolicy'),
    'Dag': ('skypilot_tpu.dag', 'Dag'),
    'SkyTpuError': ('skypilot_tpu.exceptions', 'SkyTpuError'),
    'Optimizer': ('skypilot_tpu.optimizer', 'Optimizer'),
    'OptimizeTarget': ('skypilot_tpu.optimizer', 'OptimizeTarget'),
    'Resources': ('skypilot_tpu.resources', 'Resources'),
    'Task': ('skypilot_tpu.task', 'Task'),
    'TpuSlice': ('skypilot_tpu.utils.tpu_utils', 'TpuSlice'),
    'parse_tpu': ('skypilot_tpu.utils.tpu_utils', 'parse'),
    'launch': ('skypilot_tpu.execution', 'launch'),
    'exec': ('skypilot_tpu.execution', 'exec_'),
    'status': ('skypilot_tpu.core', 'status'),
    'stop': ('skypilot_tpu.core', 'stop'),
    'start': ('skypilot_tpu.core', 'start'),
    'down': ('skypilot_tpu.core', 'down'),
    'autostop': ('skypilot_tpu.core', 'autostop'),
    'queue': ('skypilot_tpu.core', 'queue'),
    'cancel': ('skypilot_tpu.core', 'cancel'),
    'tail_logs': ('skypilot_tpu.core', 'tail_logs'),
    'job_status': ('skypilot_tpu.core', 'job_status'),
    'cost_report': ('skypilot_tpu.core', 'cost_report'),
    'Storage': ('skypilot_tpu.data.storage', 'Storage'),
}


def __getattr__(name):
    if name in _LAZY_ATTRS:
        import importlib
        module, attr = _LAZY_ATTRS[name]
        value = getattr(importlib.import_module(module), attr)
        globals()[name] = value  # cache
        return value
    raise AttributeError(f'module {__name__!r} has no attribute {name!r}')


def __dir__():
    return sorted(list(globals()) + list(_LAZY_ATTRS))


__all__ = list(_LAZY_ATTRS)
