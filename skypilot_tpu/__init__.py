"""skypilot_tpu — a TPU-native sky orchestration framework.

Declare a Task (YAML or Python), let the optimizer pick the cheapest
feasible TPU slice / VM, provision it on GCP (or run it hermetically on
the Local cloud), gang-schedule the command across every TPU host with a
rank/IP/topology env contract feeding ``jax.distributed.initialize()``,
stream logs, and manage lifecycle: status reconciliation, autostop,
failover, managed spot recovery, storage mounts, and serving.

Re-design (not a port) of SkyPilot — see SURVEY.md for the mapping.
"""
from skypilot_tpu.admin_policy import AdminPolicy
from skypilot_tpu.dag import Dag
from skypilot_tpu.exceptions import SkyTpuError
from skypilot_tpu.optimizer import Optimizer
from skypilot_tpu.optimizer import OptimizeTarget
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task
from skypilot_tpu.utils.tpu_utils import TpuSlice
from skypilot_tpu.utils.tpu_utils import parse as parse_tpu

__version__ = '0.1.0'


def __getattr__(name):
    """Lazy accessors for the heavier layers (execution, core ops).

    Keeps `import skypilot_tpu` fast and free of optional deps, like the
    reference's lazy import structure (sky/__init__.py:94-116).
    """
    _lazy = {
        'launch': ('skypilot_tpu.execution', 'launch'),
        'exec': ('skypilot_tpu.execution', 'exec_'),
        'status': ('skypilot_tpu.core', 'status'),
        'stop': ('skypilot_tpu.core', 'stop'),
        'start': ('skypilot_tpu.core', 'start'),
        'down': ('skypilot_tpu.core', 'down'),
        'autostop': ('skypilot_tpu.core', 'autostop'),
        'queue': ('skypilot_tpu.core', 'queue'),
        'cancel': ('skypilot_tpu.core', 'cancel'),
        'tail_logs': ('skypilot_tpu.core', 'tail_logs'),
        'job_status': ('skypilot_tpu.core', 'job_status'),
        'Storage': ('skypilot_tpu.data.storage', 'Storage'),
    }
    if name in _lazy:
        import importlib
        module, attr = _lazy[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(f'module {__name__!r} has no attribute {name!r}')


__all__ = [
    'AdminPolicy',
    'Dag',
    'Optimizer',
    'OptimizeTarget',
    'Resources',
    'SkyTpuError',
    'Task',
    'TpuSlice',
    'parse_tpu',
    'launch',
    'exec',
    'status',
    'stop',
    'start',
    'down',
    'autostop',
    'queue',
    'cancel',
    'tail_logs',
    'job_status',
    'Storage',
]
