"""Nebius — AI neocloud (REST/IAM).

Re-design of reference ``sky/clouds/nebius.py`` (~320 LoC) as a
RestNeocloud subclass: catalog-backed feasibility/pricing, token-
bearer REST provision plugin (``provision/nebius/``). Region-only
placement, stop/start supported, spot descoped, no TPUs (Nebius is a
GPU cloud).
"""
from __future__ import annotations

from skypilot_tpu.clouds import neocloud
from skypilot_tpu.utils import registry


@registry.CLOUD_REGISTRY.register(name='nebius')
class Nebius(neocloud.RestNeocloud):
    """Nebius (GPU VMs over REST, IAM-token auth)."""

    _REPR = 'Nebius'
    CATALOG_CLOUD = 'nebius'
    _PROVIDER = 'nebius'
    # Preset names continue '<platform>_<count>gpu-<vcpu>-<ram>': the
    # boundary after the accel prefix is '-' here, not '_'.
    _ACCEL_BOUNDARY = '-'
    _CREDENTIAL_HINT = ('Set NEBIUS_IAM_TOKEN or write '
                        '~/.nebius/credentials.json '
                        '(\'{"token": "<iam token>"}\').')

    @classmethod
    def _creds_api(cls):
        from skypilot_tpu.provision.nebius import api
        return api

    @staticmethod
    def _accel_prefix(name: str, count: int) -> str:
        """Catalog names are '<platform>_<count>gpu-<preset>', e.g.
        'gpu-h100-sxm_8gpu-128vcpu-1600gb': match on the platform
        carrying the GPU model plus the preset's leading count."""
        model = name.lower().replace('_', '-')
        return f'gpu-{model}_{count}gpu'
