"""GCP — the TPU cloud.

Re-design of reference ``sky/clouds/gcp.py``: TPU-VM pod slices are the
primary resource (not an accelerator bolt-on, cf. reference :473-497
where TPU handling is special-cased into deploy variables). Plain GCE
VMs are supported for CPU tasks and controllers.
"""
from __future__ import annotations

import os
import subprocess
import typing
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu import exceptions
from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.utils import registry

if typing.TYPE_CHECKING:
    from skypilot_tpu.resources import Resources

_CREDENTIAL_HINT = (
    'Run `gcloud auth application-default login` or set '
    'GOOGLE_APPLICATION_CREDENTIALS to a service-account key.')

DEFAULT_HOST_VM = 'n2-standard-8'


@registry.CLOUD_REGISTRY.register(name='gcp', default=True)
class GCP(cloud_lib.Cloud):
    """Google Cloud Platform with TPU pod slices first-class."""

    _REPR = 'GCP'
    MAX_CLUSTER_NAME_LEN_LIMIT = 35
    _EGRESS_PER_GB = 0.12  # premium-tier internet egress list price

    @classmethod
    def unsupported_features_for_resources(
        cls, resources: 'Resources'
    ) -> Dict[cloud_lib.CloudImplementationFeatures, str]:
        out: Dict[cloud_lib.CloudImplementationFeatures, str] = {}
        if resources.is_tpu and resources.tpu.is_pod:
            # Reference gcp.py:206-211: TPU pods cannot be stopped.
            out[cloud_lib.CloudImplementationFeatures.STOP] = (
                'TPU pod slices cannot be stopped, only terminated.')
            out[cloud_lib.CloudImplementationFeatures.AUTOSTOP] = (
                'TPU pod slices support autodown, not autostop.')
        return out

    # ------------------------------------------------------------------
    def regions_with_offering(
            self, resources: 'Resources') -> List[cloud_lib.Region]:
        regions: Dict[str, List[str]] = {}
        if resources.is_tpu:
            offerings = catalog.get_tpu_offerings(
                resources.tpu.name, resources.region, resources.zone)
            for o in offerings:
                regions.setdefault(o.region, []).append(o.zone)
        else:
            instance_type = (resources.instance_type or
                             catalog.get_default_instance_type(
                                 resources.cpus, resources.memory))
            if instance_type is None:
                return []
            for o in catalog.get_instance_offerings(
                    instance_type, resources.region, resources.zone):
                regions.setdefault(o.region, []).append(o.zone)
        return [
            cloud_lib.Region(name, sorted(set(zones)))
            for name, zones in sorted(regions.items())
        ]

    def get_feasible_launchable_resources(
            self, resources: 'Resources') -> List['Resources']:
        if resources.cloud is not None and not self.is_same_cloud(
                resources.cloud):
            return []
        if resources.is_tpu:
            offerings = catalog.get_tpu_offerings(
                resources.tpu.name, resources.region, resources.zone)
            if not offerings:
                return []
            return [resources.copy(cloud=self)]
        instance_type = resources.instance_type
        if instance_type is None and resources.accelerators:
            # Non-TPU accelerator (GPU) ask: select an a2/a3/g2-class
            # shape — falling through to the cheapest CPU shape would
            # launch the wrong machine.
            (name, count), = resources.accelerators.items()
            instance_type = catalog.get_instance_type_for_accelerator(
                name, count, cloud='gcp')
            if instance_type is None:
                return []
        if instance_type is None:
            instance_type = catalog.get_default_instance_type(
                resources.cpus, resources.memory)
            if instance_type is None:
                return []
        if not catalog.get_instance_offerings(
                instance_type, resources.region, resources.zone):
            return []
        return [resources.copy(cloud=self, instance_type=instance_type)]

    def hourly_price(self, resources: 'Resources') -> float:
        if resources.is_tpu:
            return catalog.get_tpu_hourly_cost(resources.tpu.name,
                                               resources.use_spot,
                                               resources.region,
                                               resources.zone)
        instance_type = resources.instance_type
        assert instance_type is not None, resources
        return catalog.get_hourly_cost(instance_type, resources.use_spot,
                                       resources.region, resources.zone)

    def validate_region_zone(self, region, zone):
        return catalog.validate_region_zone(region, zone)

    # ------------------------------------------------------------------
    def make_deploy_resources_variables(
            self, resources: 'Resources', cluster_name_on_cloud: str,
            region: str, zone: Optional[str]) -> Dict[str, Any]:
        """Variables consumed by provision/gcp (reference gcp.py:473-497)."""
        vars_: Dict[str, Any] = {
            'cluster_name_on_cloud': cluster_name_on_cloud,
            'region': region,
            'zone': zone,
            'use_spot': resources.use_spot,
            'disk_size': resources.disk_size,
            'labels': resources.labels or {},
            'ports': resources.ports or [],
        }
        if resources.is_tpu:
            tpu = resources.tpu
            args = resources.accelerator_args or {}
            vars_.update({
                'tpu_vm': True,
                'tpu_type': tpu.gcp_accelerator_type,
                'tpu_topology': tpu.topology,
                'num_hosts': tpu.num_hosts,
                'runtime_version': args.get('runtime_version',
                                            tpu.runtime_version),
                'network_tier': args.get('network_tier'),
            })
        else:
            vars_.update({
                'tpu_vm': False,
                'instance_type': resources.instance_type,
                # docker:<img> is a task container, not a VM source
                # image — the VM boots its default image and the
                # backend bootstraps the container on it.
                'image_id': (None
                             if resources.extract_docker_image()
                             else resources.image_id),
                'num_hosts': 1,
            })
        return vars_

    # ------------------------------------------------------------------
    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        try:
            import google.auth  # pylint: disable=import-outside-toplevel
            credentials, project = google.auth.default()
            del credentials
            if not project:
                return False, ('No default GCP project configured. ' +
                               _CREDENTIAL_HINT)
            return True, None
        except Exception as e:  # pylint: disable=broad-except
            return False, f'{e}. {_CREDENTIAL_HINT}'

    def get_project_id(self) -> str:
        import google.auth  # pylint: disable=import-outside-toplevel
        _, project = google.auth.default()
        if not project:
            raise exceptions.SkyTpuError(
                'No GCP project found. ' + _CREDENTIAL_HINT)
        return project

    def get_credential_file_mounts(self) -> Dict[str, str]:
        out = {}
        adc = os.path.expanduser(
            '~/.config/gcloud/application_default_credentials.json')
        if os.path.exists(adc):
            out['~/.config/gcloud/application_default_credentials.json'] = adc
        key = os.environ.get('GOOGLE_APPLICATION_CREDENTIALS')
        if key and os.path.exists(key):
            out['~/.gcp_key.json'] = key
        return out

    def get_user_identities(self) -> Optional[List[List[str]]]:
        try:
            proc = subprocess.run(
                'gcloud config list account --format "value(core.account)"',
                shell=True, capture_output=True, text=True, check=True,
                timeout=10)
            account = proc.stdout.strip()
            if account:
                return [[account]]
        except Exception:  # pylint: disable=broad-except
            pass
        return None
