"""Cloud plugins. Importing this package registers all built-in clouds."""
from skypilot_tpu.clouds.cloud import Cloud
from skypilot_tpu.clouds.cloud import CloudImplementationFeatures
from skypilot_tpu.clouds.cloud import Region
from skypilot_tpu.clouds.aws import AWS
from skypilot_tpu.clouds.azure import Azure
from skypilot_tpu.clouds.fluidstack import Fluidstack
from skypilot_tpu.clouds.gcp import GCP
from skypilot_tpu.clouds.kubernetes import Kubernetes
from skypilot_tpu.clouds.lambda_cloud import LambdaCloud
from skypilot_tpu.clouds.local import Local
from skypilot_tpu.clouds.nebius import Nebius
from skypilot_tpu.clouds.runpod import RunPod
from skypilot_tpu.clouds.vast import Vast

__all__ = [
    'AWS',
    'Azure',
    'Cloud',
    'CloudImplementationFeatures',
    'Region',
    'Fluidstack',
    'GCP',
    'Kubernetes',
    'LambdaCloud',
    'Local',
    'Nebius',
    'RunPod',
    'Vast',
]
