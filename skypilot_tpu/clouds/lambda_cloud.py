"""Lambda Cloud — long-tail GPU cloud (the reference's most-used
neocloud plugin).

Re-design of reference ``sky/clouds/lambda_cloud.py`` (303 LoC):
catalog-backed feasibility/pricing behind the standard seam, REST
provision plugin (``provision/lambda_cloud/``). Lambda has no
regions-with-zones (region only), no spot market, and no stop
operation — STOP/AUTOSTOP are declared unsupported so the optimizer
and autostop paths degrade cleanly. No TPUs here.
"""
from __future__ import annotations

import os
import typing
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.utils import registry

if typing.TYPE_CHECKING:
    from skypilot_tpu.resources import Resources

_CREDENTIAL_HINT = (
    'Set LAMBDA_API_KEY or write ~/.lambda_cloud/lambda_keys '
    "('api_key = <key>').")


@registry.CLOUD_REGISTRY.register(name='lambda',
                                  aliases=['lambda_cloud',
                                           'lambdacloud'])
class LambdaCloud(cloud_lib.Cloud):
    """Lambda Cloud (GPU VMs over REST)."""

    _REPR = 'Lambda'
    MAX_CLUSTER_NAME_LEN_LIMIT = 50

    @classmethod
    def canonical_name(cls) -> str:
        return 'lambda'

    def provider_name(self) -> str:
        # 'lambda' is a Python keyword: the provision module lives at
        # provision/lambda_cloud/.
        return 'lambda_cloud'

    @classmethod
    def unsupported_features_for_resources(
        cls, resources: 'Resources'
    ) -> Dict[cloud_lib.CloudImplementationFeatures, str]:
        del resources
        return {
            cloud_lib.CloudImplementationFeatures.STOP:
                'Lambda Cloud cannot stop instances, only terminate.',
            cloud_lib.CloudImplementationFeatures.AUTOSTOP:
                'Use autodown (no stop operation on Lambda Cloud).',
            cloud_lib.CloudImplementationFeatures.SPOT_INSTANCE:
                'Lambda Cloud has no spot market.',
        }

    # ------------------------------------------------------------------
    def regions_with_offering(
            self, resources: 'Resources') -> List[cloud_lib.Region]:
        if resources.is_tpu:
            return []
        instance_type = (resources.instance_type or
                         catalog.get_default_instance_type(
                             resources.cpus, resources.memory,
                             cloud='lambda'))
        if instance_type is None:
            return []
        regions = sorted({
            o.region
            for o in catalog.get_instance_offerings(
                instance_type, resources.region, None, cloud='lambda')
        })
        return [cloud_lib.Region(name) for name in regions]

    def zones_provision_loop(self, resources: 'Resources',
                             region: Optional[str] = None):
        for r in self.regions_with_offering(resources):
            if region is not None and r.name != region:
                continue
            yield (r.name, None)

    @staticmethod
    def _instance_type_for_accelerator(
            accelerators: dict) -> Optional[str]:
        """Map {'A100': 8}-style requests onto Lambda's gpu_<n>x_<gpu>
        instance-type names; None if no catalog type matches."""
        (name, count), = accelerators.items()
        prefix = f'gpu_{count}x_{name.lower()}'
        matches = sorted({
            o.instance_type
            for o in catalog.get_instance_offerings(None, None, None,
                                                    cloud='lambda')
            if o.instance_type.startswith(prefix)
        })
        return matches[0] if matches else None

    def get_feasible_launchable_resources(
            self, resources: 'Resources') -> List['Resources']:
        if resources.cloud is not None and not self.is_same_cloud(
                resources.cloud):
            return []
        if resources.is_tpu or resources.use_spot:
            return []
        instance_type = resources.instance_type
        if instance_type is None and resources.accelerators:
            # A GPU request must select GPU hardware — silently
            # satisfying it with the cheapest CPU box would launch
            # the wrong machine.
            instance_type = self._instance_type_for_accelerator(
                resources.accelerators)
            if instance_type is None:
                return []
        if instance_type is None:
            instance_type = catalog.get_default_instance_type(
                resources.cpus, resources.memory, cloud='lambda')
            if instance_type is None:
                return []
        if not catalog.get_instance_offerings(
                instance_type, resources.region, None, cloud='lambda'):
            return []
        return [resources.copy(cloud=self, instance_type=instance_type)]

    def hourly_price(self, resources: 'Resources') -> float:
        assert resources.instance_type is not None, resources
        return catalog.get_hourly_cost(resources.instance_type,
                                       resources.use_spot,
                                       resources.region, None,
                                       cloud='lambda')

    def validate_region_zone(self, region, zone):
        if zone is not None:
            raise ValueError('Lambda Cloud has regions, not zones.')
        return catalog.validate_region_zone(region, None)

    # ------------------------------------------------------------------
    def make_deploy_resources_variables(
            self, resources: 'Resources', cluster_name_on_cloud: str,
            region: str, zone: Optional[str]) -> Dict[str, Any]:
        return {
            'cluster_name_on_cloud': cluster_name_on_cloud,
            'region': region,
            'zone': None,
            'instance_type': resources.instance_type,
            'use_spot': False,
            'disk_size': resources.disk_size,
            'image_id': None,   # Lambda picks its own Ubuntu image
            'labels': resources.labels or {},
            'ports': resources.ports or [],
            'num_hosts': 1,
        }

    # ------------------------------------------------------------------
    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        from skypilot_tpu.provision.lambda_cloud import api
        if api.read_api_key():
            return True, None
        return False, 'No Lambda Cloud API key. ' + _CREDENTIAL_HINT

    def get_credential_file_mounts(self) -> Dict[str, str]:
        from skypilot_tpu.provision.lambda_cloud import api
        path = os.path.expanduser(api.CREDENTIALS_PATH)
        if os.path.exists(path):
            return {api.CREDENTIALS_PATH: path}
        return {}

    def get_user_identities(self) -> Optional[List[List[str]]]:
        from skypilot_tpu.provision.lambda_cloud import api
        key = api.read_api_key()
        # The key itself is the identity; report a stable digest, not
        # the secret.
        if key:
            import hashlib
            return [[hashlib.sha256(key.encode()).hexdigest()[:16]]]
        return None
