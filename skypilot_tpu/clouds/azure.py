"""Azure — the third metered VM cloud (controllers, CPU tasks,
cross-cloud arbitrage; the reference's second-largest cloud).

Re-design of reference ``sky/clouds/azure.py`` (708 LoC) scoped the
same way as the AWS plugin here: catalog-backed feasibility/pricing
so the optimizer genuinely arbitrates three clouds, plus the az-CLI
provision plugin behind the standard seam
(``provision/azure/instance.py``). No TPUs on Azure. Azure regions
have no user-facing zones in this catalog (placement inside a region
is Azure's allocator's job), so zone is always None.
"""
from __future__ import annotations

import os
import typing
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.utils import registry

if typing.TYPE_CHECKING:
    from skypilot_tpu.resources import Resources

_CREDENTIAL_HINT = (
    'Install the az CLI and run `az login` (or set a service '
    'principal via AZURE_CLIENT_ID/SECRET/TENANT).')


@registry.CLOUD_REGISTRY.register(name='azure')
class Azure(cloud_lib.Cloud):
    """Microsoft Azure (VMs via the az CLI)."""

    _REPR = 'Azure'
    # Azure resource names allow 64, but the VM name embeds
    # '-<idx>' and the group 'skytpu-' prefix.
    MAX_CLUSTER_NAME_LEN_LIMIT = 42

    # ------------------------------------------------------------------
    def regions_with_offering(
            self, resources: 'Resources') -> List[cloud_lib.Region]:
        if resources.is_tpu:
            return []
        instance_type = (resources.instance_type or
                         catalog.get_default_instance_type(
                             resources.cpus, resources.memory,
                             cloud='azure'))
        if instance_type is None:
            return []
        regions = sorted({
            o.region
            for o in catalog.get_instance_offerings(
                instance_type, resources.region, None, cloud='azure')
        })
        return [cloud_lib.Region(name) for name in regions]

    def zones_provision_loop(self, resources: 'Resources',
                             region: Optional[str] = None):
        # No zones: one candidate per region (Azure's allocator places
        # within the region).
        for r in self.regions_with_offering(resources):
            if region is not None and r.name != region:
                continue
            yield (r.name, None)

    def get_feasible_launchable_resources(
            self, resources: 'Resources') -> List['Resources']:
        if resources.cloud is not None and not self.is_same_cloud(
                resources.cloud):
            return []
        if resources.is_tpu:
            return []  # no TPUs on Azure
        instance_type = resources.instance_type
        if instance_type is None and resources.accelerators:
            # A GPU ask must select GPU hardware — falling through to
            # the cheapest CPU shape would launch the wrong machine.
            (name, count), = resources.accelerators.items()
            instance_type = catalog.get_instance_type_for_accelerator(
                name, count, cloud='azure')
            if instance_type is None:
                return []
        if instance_type is None:
            instance_type = catalog.get_default_instance_type(
                resources.cpus, resources.memory, cloud='azure')
            if instance_type is None:
                return []
        if not catalog.get_instance_offerings(
                instance_type, resources.region, None, cloud='azure'):
            return []
        return [resources.copy(cloud=self, instance_type=instance_type)]

    def hourly_price(self, resources: 'Resources') -> float:
        assert resources.instance_type is not None, resources
        return catalog.get_hourly_cost(resources.instance_type,
                                       resources.use_spot,
                                       resources.region, None,
                                       cloud='azure')

    def validate_region_zone(self, region, zone):
        if zone is not None:
            raise ValueError(
                'Azure placement is per-region; zones are not '
                'exposed (drop the zone).')
        return catalog.validate_region_zone(region, None)

    # ------------------------------------------------------------------
    def make_deploy_resources_variables(
            self, resources: 'Resources', cluster_name_on_cloud: str,
            region: str, zone: Optional[str]) -> Dict[str, Any]:
        return {
            'cluster_name_on_cloud': cluster_name_on_cloud,
            'region': region,
            'zone': None,
            'instance_type': resources.instance_type,
            'use_spot': resources.use_spot,
            'disk_size': resources.disk_size,
            # Marketplace image alias/URN; docker:<img> is a task
            # container (bootstrapped post-provision), not a VM image.
            'image_id': (None if resources.extract_docker_image() else
                         resources.image_id),
            'labels': resources.labels or {},
            'ports': resources.ports or [],
            'num_hosts': 1,
        }

    # ------------------------------------------------------------------
    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        from skypilot_tpu.provision.azure import api
        try:
            account = api.run_az(['account', 'show'], timeout=30)
        except FileNotFoundError:
            return False, 'az CLI not installed. ' + _CREDENTIAL_HINT
        except Exception as e:  # pylint: disable=broad-except
            return False, f'{e}. {_CREDENTIAL_HINT}'
        if not account or not account.get('id'):
            return False, ('az is not logged in. ' + _CREDENTIAL_HINT)
        return True, None

    def get_credential_file_mounts(self) -> Dict[str, str]:
        path = os.path.expanduser('~/.azure')
        if os.path.isdir(path):
            return {'~/.azure': path}
        return {}

    def get_user_identities(self) -> Optional[List[List[str]]]:
        from skypilot_tpu.provision.azure import api
        try:
            account = api.run_az(['account', 'show'], timeout=30)
            if account:
                return [[f"{account.get('user', {}).get('name', '')}"
                         f"@{account.get('id', '')}"]]
        except Exception:  # pylint: disable=broad-except
            pass
        return None
