"""Vast.ai — GPU marketplace cloud.

Re-design of reference ``sky/clouds/vast.py`` as a RestNeocloud
subclass. Vast is a spot-like MARKETPLACE: catalog prices are typical
market rates and the provision plugin rents from live offers
(an empty market surfaces as a stockout, driving failover).
Stop/start supported; 'regions' are coarse geolocations.
"""
from __future__ import annotations

from skypilot_tpu.clouds import neocloud
from skypilot_tpu.utils import registry


@registry.CLOUD_REGISTRY.register(name='vast', aliases=['vastai'])
class Vast(neocloud.RestNeocloud):
    """Vast.ai (GPU rentals from a marketplace over REST)."""

    _REPR = 'Vast'
    CATALOG_CLOUD = 'vast'
    _PROVIDER = 'vast'
    _CREDENTIAL_HINT = ('Set VAST_API_KEY or write the key to '
                        '~/.vast_api_key.')

    @classmethod
    def _creds_api(cls):
        from skypilot_tpu.provision.vast import api
        return api

    @staticmethod
    def _accel_prefix(name: str, count: int) -> str:
        # Catalog names look like '2x_RTX_4090'.
        return f'{count}x_{name}'
