"""AWS — the second metered VM cloud (controllers, CPU tasks,
cross-cloud arbitrage).

Re-design of reference ``sky/clouds/aws.py`` (1,181 LoC) scoped to
what a TPU-first framework needs from AWS: catalog-backed EC2
feasibility/pricing so the optimizer genuinely arbitrates clouds, and
an EC2 provision plugin behind the standard seam. No TPUs here — TPU
requests are never feasible on AWS — and no GPU catalog (out of
scope for this framework).
"""
from __future__ import annotations

import os
import typing
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.utils import registry

if typing.TYPE_CHECKING:
    from skypilot_tpu.resources import Resources

_CREDENTIAL_HINT = (
    'Install boto3 and configure credentials (`aws configure`, or '
    'AWS_ACCESS_KEY_ID/AWS_SECRET_ACCESS_KEY, or an instance role).')

DEFAULT_AMI_NAME = 'ubuntu-22.04'


@registry.CLOUD_REGISTRY.register(name='aws')
class AWS(cloud_lib.Cloud):
    """Amazon Web Services (EC2)."""

    _REPR = 'AWS'
    MAX_CLUSTER_NAME_LEN_LIMIT = 50

    # ------------------------------------------------------------------
    def regions_with_offering(
            self, resources: 'Resources') -> List[cloud_lib.Region]:
        if resources.is_tpu:
            return []
        instance_type = (resources.instance_type or
                         catalog.get_default_instance_type(
                             resources.cpus, resources.memory,
                             cloud='aws'))
        if instance_type is None:
            return []
        regions: Dict[str, List[str]] = {}
        for o in catalog.get_instance_offerings(
                instance_type, resources.region, resources.zone,
                cloud='aws'):
            regions.setdefault(o.region, []).append(o.zone)
        return [
            cloud_lib.Region(name, sorted(set(zones)))
            for name, zones in sorted(regions.items())
        ]

    def get_feasible_launchable_resources(
            self, resources: 'Resources') -> List['Resources']:
        if resources.cloud is not None and not self.is_same_cloud(
                resources.cloud):
            return []
        if resources.is_tpu:
            return []  # no TPUs on AWS
        instance_type = resources.instance_type
        if instance_type is None and resources.accelerators:
            # A GPU ask must select GPU hardware — falling through to
            # the cheapest CPU shape would launch the wrong machine.
            (name, count), = resources.accelerators.items()
            instance_type = catalog.get_instance_type_for_accelerator(
                name, count, cloud='aws')
            if instance_type is None:
                return []
        if instance_type is None:
            instance_type = catalog.get_default_instance_type(
                resources.cpus, resources.memory, cloud='aws')
            if instance_type is None:
                return []
        if not catalog.get_instance_offerings(
                instance_type, resources.region, resources.zone,
                cloud='aws'):
            return []
        return [resources.copy(cloud=self, instance_type=instance_type)]

    def hourly_price(self, resources: 'Resources') -> float:
        assert resources.instance_type is not None, resources
        return catalog.get_hourly_cost(resources.instance_type,
                                       resources.use_spot,
                                       resources.region, resources.zone,
                                       cloud='aws')

    def validate_region_zone(self, region, zone):
        return catalog.validate_region_zone(region, zone)

    # ------------------------------------------------------------------
    def make_deploy_resources_variables(
            self, resources: 'Resources', cluster_name_on_cloud: str,
            region: str, zone: Optional[str]) -> Dict[str, Any]:
        return {
            'cluster_name_on_cloud': cluster_name_on_cloud,
            'region': region,
            'zone': zone,
            'instance_type': resources.instance_type,
            'use_spot': resources.use_spot,
            'disk_size': resources.disk_size,
            # AMI id; None = default. docker:<img> is a task container
            # (bootstrapped post-provision), not an AMI.
            'image_id': (None if resources.extract_docker_image() else
                         resources.image_id),
            'labels': resources.labels or {},
            'ports': resources.ports or [],
            'num_hosts': 1,
        }

    # ------------------------------------------------------------------
    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        try:
            import boto3  # pylint: disable=import-outside-toplevel
        except ImportError:
            return False, 'boto3 is not installed. ' + _CREDENTIAL_HINT
        try:
            session = boto3.session.Session()
            if session.get_credentials() is None:
                return False, ('No AWS credentials found. ' +
                               _CREDENTIAL_HINT)
            return True, None
        except Exception as e:  # pylint: disable=broad-except
            return False, f'{e}. {_CREDENTIAL_HINT}'

    def get_credential_file_mounts(self) -> Dict[str, str]:
        out = {}
        for name in ('credentials', 'config'):
            path = os.path.expanduser(f'~/.aws/{name}')
            if os.path.exists(path):
                out[f'~/.aws/{name}'] = path
        return out

    def get_user_identities(self) -> Optional[List[List[str]]]:
        try:
            import boto3  # pylint: disable=import-outside-toplevel
            ident = boto3.client('sts').get_caller_identity()
            return [[ident['Arn']]]
        except Exception:  # pylint: disable=broad-except
            return None
