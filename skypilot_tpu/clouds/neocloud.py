"""Template base for REST neoclouds (Lambda-class GPU clouds).

The reference carries ten-plus near-identical ~300-LoC cloud modules
(``sky/clouds/fluidstack.py``, ``runpod.py``, ``nebius.py``, ...);
this base factors the shared shape — catalog-backed feasibility and
pricing, region-only placement, accelerator-to-instance-type mapping,
credential plumbing — so a concrete neocloud is ~50 declarative lines
(see clouds/runpod.py, fluidstack.py, nebius.py). This is the
"adding a cloud is mechanical" claim of docs/clouds.md, made literal.

Subclasses declare:
  - ``CATALOG_CLOUD``: key of data/<name>_catalog.csv
  - ``_PROVIDER``: provision module name (provision/<name>/)
  - ``_creds_api()``: module exposing read key + CREDENTIALS_PATH
  - ``_accel_prefix(name, count)``: catalog-name prefix for a GPU ask
  - ``unsupported_features_for_resources`` when the default (spot
    unsupported) is not right
"""
from __future__ import annotations

import os
import typing
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu.clouds import cloud as cloud_lib

if typing.TYPE_CHECKING:
    from skypilot_tpu.resources import Resources


class RestNeocloud(cloud_lib.Cloud):
    """Catalog-backed, region-only GPU cloud over a REST/GraphQL API."""

    CATALOG_CLOUD: str = ''
    _PROVIDER: str = ''
    _CREDENTIAL_HINT: str = ''
    MAX_CLUSTER_NAME_LEN_LIMIT = 50
    # Characters that may FOLLOW the accelerator prefix in a catalog
    # instance type for the ask to count as an exact token match.
    # 'Nx_NAME[_FORMFACTOR]' catalogs separate with '_' (so an 'A100'
    # ask matches '8x_A100_PCIE' but NOT '8x_A100-80GB_SECURE' — the
    # 80GB variant is a different, pricier SKU the user must name);
    # Nebius presets separate with '-' (see clouds/nebius.py).
    _ACCEL_BOUNDARY: str = '_'

    # ---- subclass seams ----------------------------------------------
    @classmethod
    def _creds_api(cls):
        """provision.<name>.api module (read_api_key/read_token +
        CREDENTIALS_PATH)."""
        raise NotImplementedError

    @staticmethod
    def _accel_prefix(name: str, count: int) -> str:
        """Catalog instance-type prefix for an accelerator request."""
        raise NotImplementedError

    @classmethod
    def _read_key(cls) -> Optional[str]:
        mod = cls._creds_api()
        reader = getattr(mod, 'read_api_key', None) or mod.read_token
        return reader()

    # ---- shared implementation ---------------------------------------
    @classmethod
    def canonical_name(cls) -> str:
        return cls.CATALOG_CLOUD

    def provider_name(self) -> str:
        return self._PROVIDER

    @classmethod
    def unsupported_features_for_resources(
        cls, resources: 'Resources'
    ) -> Dict[cloud_lib.CloudImplementationFeatures, str]:
        del resources
        return {
            cloud_lib.CloudImplementationFeatures.SPOT_INSTANCE:
                f'{cls._REPR} spot instances are not supported here.',
        }

    def regions_with_offering(
            self, resources: 'Resources') -> List[cloud_lib.Region]:
        if resources.is_tpu:
            return []
        instance_type = (resources.instance_type or
                         catalog.get_default_instance_type(
                             resources.cpus, resources.memory,
                             cloud=self.CATALOG_CLOUD))
        if instance_type is None:
            return []
        regions = sorted({
            o.region
            for o in catalog.get_instance_offerings(
                instance_type, resources.region, None,
                cloud=self.CATALOG_CLOUD)
        })
        return [cloud_lib.Region(name) for name in regions]

    def zones_provision_loop(self, resources: 'Resources',
                             region: Optional[str] = None):
        for r in self.regions_with_offering(resources):
            if region is not None and r.name != region:
                continue
            yield (r.name, None)

    def _accel_token_match(self, prefix: str,
                           instance_type: str) -> bool:
        """Exact-token match: the instance type is the prefix itself,
        or continues with a declared boundary character. A bare
        prefix-startswith would let an 'A100' ask silently select
        '1x_A100-80GB_SECURE' (a pricier SKU than the plain A100)."""
        it = instance_type.lower()
        if it == prefix:
            return True
        return (it.startswith(prefix) and
                it[len(prefix)] in self._ACCEL_BOUNDARY)

    def _instance_type_for_accelerator(
            self, accelerators: dict) -> Optional[str]:
        (name, count), = accelerators.items()
        prefix = self._accel_prefix(name, count).lower()
        matches = sorted({
            o.instance_type
            for o in catalog.get_instance_offerings(
                None, None, None, cloud=self.CATALOG_CLOUD)
            if self._accel_token_match(prefix, o.instance_type)
        })
        return matches[0] if matches else None

    def get_feasible_launchable_resources(
            self, resources: 'Resources') -> List['Resources']:
        if resources.cloud is not None and not self.is_same_cloud(
                resources.cloud):
            return []
        if resources.is_tpu:
            return []
        if resources.use_spot and (
                cloud_lib.CloudImplementationFeatures.SPOT_INSTANCE
                in self.unsupported_features_for_resources(resources)):
            return []
        instance_type = resources.instance_type
        if instance_type is None and resources.accelerators:
            # A GPU request must select GPU hardware — silently
            # satisfying it with the cheapest CPU box would launch
            # the wrong machine.
            instance_type = self._instance_type_for_accelerator(
                resources.accelerators)
            if instance_type is None:
                return []
        if instance_type is None:
            instance_type = catalog.get_default_instance_type(
                resources.cpus, resources.memory,
                cloud=self.CATALOG_CLOUD)
            if instance_type is None:
                return []
        if not catalog.get_instance_offerings(
                instance_type, resources.region, None,
                cloud=self.CATALOG_CLOUD):
            return []
        return [resources.copy(cloud=self, instance_type=instance_type)]

    def hourly_price(self, resources: 'Resources') -> float:
        assert resources.instance_type is not None, resources
        return catalog.get_hourly_cost(resources.instance_type,
                                       resources.use_spot,
                                       resources.region, None,
                                       cloud=self.CATALOG_CLOUD)

    def validate_region_zone(self, region, zone):
        if zone is not None:
            raise ValueError(
                f'{self._REPR} has regions, not zones.')
        return catalog.validate_region_zone(region, None)

    def make_deploy_resources_variables(
            self, resources: 'Resources', cluster_name_on_cloud: str,
            region: str, zone: Optional[str]) -> Dict[str, Any]:
        return {
            'cluster_name_on_cloud': cluster_name_on_cloud,
            'region': region,
            'zone': None,
            'instance_type': resources.instance_type,
            'use_spot': False,
            'disk_size': resources.disk_size,
            'image_id': None,
            'labels': resources.labels or {},
            'ports': resources.ports or [],
            'num_hosts': 1,
        }

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        if self._read_key():
            return True, None
        return (False,
                f'No {self._REPR} credentials. ' + self._CREDENTIAL_HINT)

    def get_credential_file_mounts(self) -> Dict[str, str]:
        mod = self._creds_api()
        path = os.path.expanduser(mod.CREDENTIALS_PATH)
        if os.path.exists(path):
            return {mod.CREDENTIALS_PATH: path}
        return {}

    def get_user_identities(self) -> Optional[List[List[str]]]:
        key = self._read_key()
        if key:
            import hashlib
            return [[hashlib.sha256(key.encode()).hexdigest()[:16]]]
        return None
