"""FluidStack — GPU neocloud (REST).

Re-design of reference ``sky/clouds/fluidstack.py`` (~260 LoC) as a
RestNeocloud subclass: catalog-backed feasibility/pricing, REST
provision plugin (``provision/fluidstack/``). Region-only placement,
stop/start supported, no spot market, no TPUs.
"""
from __future__ import annotations

from skypilot_tpu.clouds import neocloud
from skypilot_tpu.utils import registry


@registry.CLOUD_REGISTRY.register(name='fluidstack')
class Fluidstack(neocloud.RestNeocloud):
    """FluidStack (GPU VMs over REST)."""

    _REPR = 'FluidStack'
    CATALOG_CLOUD = 'fluidstack'
    _PROVIDER = 'fluidstack'
    _CREDENTIAL_HINT = ('Set FLUIDSTACK_API_KEY or write the key to '
                        '~/.fluidstack/api_key.')

    @classmethod
    def _creds_api(cls):
        from skypilot_tpu.provision.fluidstack import api
        return api

    @staticmethod
    def _accel_prefix(name: str, count: int) -> str:
        # Catalog names look like '8x_H100_SXM5'.
        return f'{count}x_{name}'
