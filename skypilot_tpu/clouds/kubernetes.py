"""Kubernetes — GKE TPU slices and generic pods behind kubeconfig.

Re-design of reference ``sky/clouds/kubernetes.py:796``: a kubeconfig
context is the unit of placement (modeled as the single "region");
TPU slices map onto GKE TPU podslice node pools via node selectors
(``cloud.google.com/gke-tpu-accelerator``/``-topology``), plain tasks
onto CPU pods. Kubernetes reports zero hourly cost (the cluster is
already paid for), so when enabled it wins cost optimization — same
behavior as the reference.
"""
from __future__ import annotations

import os
import typing
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.utils import registry

if typing.TYPE_CHECKING:
    from skypilot_tpu.resources import Resources

_CREDENTIAL_HINT = (
    'No usable kubeconfig. Point KUBECONFIG at (or create) a config '
    'with a current-context for your cluster.')


@registry.CLOUD_REGISTRY.register(name='kubernetes')
class Kubernetes(cloud_lib.Cloud):
    """Kubernetes (incl. GKE TPU podslice node pools)."""

    _REPR = 'Kubernetes'
    # DNS-1123 subdomain limit for pod names, minus our suffixes.
    MAX_CLUSTER_NAME_LEN_LIMIT = 40
    _EGRESS_PER_GB = 0.0   # cluster-internal by default

    @classmethod
    def unsupported_features_for_resources(
        cls, resources: 'Resources'
    ) -> Dict[cloud_lib.CloudImplementationFeatures, str]:
        del resources
        return {
            cloud_lib.CloudImplementationFeatures.STOP:
                'Pods cannot be stopped, only terminated.',
            cloud_lib.CloudImplementationFeatures.AUTOSTOP:
                'Use autodown: pods terminate, they do not stop.',
            cloud_lib.CloudImplementationFeatures.SPOT_INSTANCE:
                'Spot is a node-pool property in Kubernetes, not a '
                'per-pod request.',
        }

    # ------------------------------------------------------------------
    def regions_with_offering(
            self, resources: 'Resources') -> List[cloud_lib.Region]:
        del resources
        context = self._current_context()
        if context is None:
            return []
        # One "region" per kubeconfig context; placement within the
        # cluster is the scheduler's job (no zones).
        return [cloud_lib.Region(context)]

    def zones_provision_loop(self, resources: 'Resources',
                             region: Optional[str] = None):
        # Contexts have no zones — even for TPUs (the base class
        # iterates per-zone for TPU capacity; in-cluster placement is
        # the scheduler's job).
        for r in self.regions_with_offering(resources):
            if region is not None and r.name != region:
                continue
            yield (r.name, None)

    def get_feasible_launchable_resources(
            self, resources: 'Resources') -> List['Resources']:
        if resources.cloud is not None and not self.is_same_cloud(
                resources.cloud):
            return []
        if resources.use_spot:
            return []
        if resources.is_tpu:
            from skypilot_tpu.provision.kubernetes import instance
            gen = resources.tpu.generation
            if gen not in instance.GKE_TPU_ACCELERATORS:
                return []  # GKE has no podslice pools for this gen
            return [resources.copy(cloud=self)]
        # CPU pods: synthesize a launchable "<n>CPU--<m>GB" instance
        # type from the requested cpus/memory (reference
        # kubernetes_utils.KubernetesInstanceType) — candidates must
        # be launchable for the optimizer's cost sort.
        instance_type = resources.instance_type
        if instance_type is None:
            cpus = str(resources.cpus or '4+').rstrip('+')
            mem = str(resources.memory or
                      float(cpus) * 4).rstrip('+')
            instance_type = f'{cpus}CPU--{mem}GB'
        return [resources.copy(cloud=self,
                               instance_type=instance_type)]

    def hourly_price(self, resources: 'Resources') -> float:
        # The cluster is sunk cost (reference kubernetes.py prices
        # pods at 0) — enabling kubernetes makes the optimizer prefer
        # it over metered clouds.
        del resources
        return 0.0

    def validate_region_zone(self, region, zone):
        if zone is not None:
            raise ValueError('Kubernetes has contexts, not zones.')
        return region, zone

    # ------------------------------------------------------------------
    def make_deploy_resources_variables(
            self, resources: 'Resources', cluster_name_on_cloud: str,
            region: str, zone: Optional[str]) -> Dict[str, Any]:
        from skypilot_tpu.provision.kubernetes import instance
        vars_: Dict[str, Any] = {
            'cluster_name_on_cloud': cluster_name_on_cloud,
            'context': region,
            'region': region,
            'zone': None,
            # docker:<img> and a bare image mean the same thing on k8s:
            # the pod runs that image (no nested container).
            'image_id': (resources.extract_docker_image() or
                         resources.image_id),
            'cpus': resources.cpus,
            'memory': resources.memory,
            'labels': resources.labels or {},
        }
        if resources.is_tpu:
            tpu = resources.tpu
            vars_.update({
                'tpu_vm': True,
                'gke_accelerator':
                    instance.GKE_TPU_ACCELERATORS[tpu.generation],
                'tpu_topology': tpu.topology,
                'chips_per_host': tpu.chips_per_host,
                'num_hosts': tpu.num_hosts,
            })
        else:
            cpus, memory = None, None
            itype = resources.instance_type or ''
            if itype.endswith('GB') and 'CPU--' in itype:
                cpus, memory = itype[:-2].split('CPU--')
            vars_.update({'tpu_vm': False, 'num_hosts': 1,
                          'cpus': cpus, 'memory': memory})
        return vars_

    # ------------------------------------------------------------------
    @staticmethod
    def _current_context() -> Optional[str]:
        try:
            from skypilot_tpu.provision.kubernetes import api
            return api.load_kubeconfig().context_name
        except Exception:  # pylint: disable=broad-except
            return None

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        from skypilot_tpu.provision.kubernetes import api
        try:
            ctx = api.load_kubeconfig()
        except Exception as e:  # pylint: disable=broad-except
            return False, f'{e} {_CREDENTIAL_HINT}'
        if not ctx.server:
            return False, ('kubeconfig context has no cluster server. '
                           + _CREDENTIAL_HINT)
        return True, None

    def get_credential_file_mounts(self) -> Dict[str, str]:
        from skypilot_tpu.provision.kubernetes import api
        path = api.kubeconfig_path()
        if os.path.exists(path):
            return {'~/.kube/config': path}
        return {}

    def get_user_identities(self) -> Optional[List[List[str]]]:
        context = self._current_context()
        return [[context]] if context else None
