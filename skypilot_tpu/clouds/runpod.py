"""RunPod — GPU neocloud (pods over GraphQL).

Re-design of reference ``sky/clouds/runpod.py`` (~290 LoC) as a
~50-line RestNeocloud subclass (clouds/neocloud.py): catalog-backed
feasibility/pricing, GraphQL provision plugin (``provision/runpod/``).
RunPod has data centers (region only, no zones) and CAN stop pods —
STOP/AUTOSTOP work (unlike Lambda); the spot/bid market is descoped.
No TPUs.
"""
from __future__ import annotations

import typing

from skypilot_tpu.clouds import neocloud
from skypilot_tpu.utils import registry

if typing.TYPE_CHECKING:
    pass


@registry.CLOUD_REGISTRY.register(name='runpod')
class RunPod(neocloud.RestNeocloud):
    """RunPod (GPU pods over GraphQL)."""

    _REPR = 'RunPod'
    CATALOG_CLOUD = 'runpod'
    _PROVIDER = 'runpod'
    _CREDENTIAL_HINT = ('Set RUNPOD_API_KEY or write '
                        "~/.runpod/config.toml ('api_key = <key>').")

    @classmethod
    def _creds_api(cls):
        from skypilot_tpu.provision.runpod import api
        return api

    @staticmethod
    def _accel_prefix(name: str, count: int) -> str:
        # Catalog names look like '1x_A100-80GB_SECURE'.
        return f'{count}x_{name}'
