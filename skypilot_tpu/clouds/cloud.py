"""Cloud abstract base class.

Re-design of reference ``sky/clouds/cloud.py:117``: capability flags,
feasibility filtering, pricing, deploy variables, credential checks, and
region/zone enumeration for the failover provisioner. TPU-specific
quantities (slice topology, host count) flow through Resources, so cloud
plugins only translate them into provider API calls.
"""
from __future__ import annotations

import enum
import typing
from typing import Any, Dict, Iterator, List, Optional, Tuple

from skypilot_tpu import exceptions

if typing.TYPE_CHECKING:
    from skypilot_tpu.resources import Resources


class CloudImplementationFeatures(enum.Enum):
    """Features a cloud may or may not support (reference :29)."""
    STOP = 'stop'
    MULTI_NODE = 'multi_node'
    SPOT_INSTANCE = 'spot_instance'
    AUTOSTOP = 'autostop'
    STORAGE_MOUNTING = 'storage_mounting'
    OPEN_PORTS = 'open_ports'
    CUSTOM_DISK_TIER = 'custom_disk_tier'


class Region:

    def __init__(self, name: str, zones: Optional[List[str]] = None) -> None:
        self.name = name
        self.zones = zones or []

    def __repr__(self) -> str:
        return f'Region({self.name}, zones={self.zones})'


class Cloud:
    """Base class for cloud providers."""

    _REPR = 'Cloud'
    # Max cluster name length on this provider (None = unlimited).
    MAX_CLUSTER_NAME_LEN_LIMIT: Optional[int] = None

    # ------------------------------------------------------------------
    # Identity
    @classmethod
    def canonical_name(cls) -> str:
        return cls.__name__.lower()

    def provider_name(self) -> str:
        """Module name under skypilot_tpu/provision/ handling this cloud."""
        return self.canonical_name()

    def is_same_cloud(self, other: Optional['Cloud']) -> bool:
        return other is not None and self.canonical_name(
        ) == other.canonical_name()

    def __repr__(self) -> str:
        return self._REPR

    def __eq__(self, other) -> bool:
        return isinstance(other, Cloud) and self.is_same_cloud(other)

    def __hash__(self) -> int:
        return hash(self.canonical_name())

    # ------------------------------------------------------------------
    # Capabilities
    @classmethod
    def unsupported_features_for_resources(
        cls, resources: 'Resources'
    ) -> Dict[CloudImplementationFeatures, str]:
        """Map of unsupported feature -> reason, for these resources."""
        return {}

    @classmethod
    def check_features_are_supported(
            cls, resources: 'Resources',
            requested: set) -> None:
        unsupported = cls.unsupported_features_for_resources(resources)
        bad = {f: r for f, r in unsupported.items() if f in requested}
        if bad:
            raise exceptions.NotSupportedError(
                f'{cls._REPR} does not support: '
                + '; '.join(f'{f.value} ({r})' for f, r in bad.items()))

    # ------------------------------------------------------------------
    # Catalog / feasibility
    def regions_with_offering(self, resources: 'Resources') -> List[Region]:
        """Regions (with zones) that can host these resources."""
        raise NotImplementedError

    def zones_provision_loop(
            self, resources: 'Resources',
            region: Optional[str] = None
    ) -> Iterator[Tuple[str, Optional[str]]]:
        """Yield (region, zone) candidates in failover order.

        TPU capacity is zonal, so we yield per-zone for TPUs and spot,
        per-region otherwise (mirrors the reference's failover
        granularity, sky/optimizer.py:1140).
        """
        for r in self.regions_with_offering(resources):
            if region is not None and r.name != region:
                continue
            if resources.is_tpu or resources.use_spot:
                for zone in r.zones:
                    if resources.zone is not None and zone != resources.zone:
                        continue
                    yield (r.name, zone)
            else:
                yield (r.name, None)

    def get_feasible_launchable_resources(
            self, resources: 'Resources') -> List['Resources']:
        """Concretize a (possibly partial) spec into launchable candidates.

        Returns [] if infeasible on this cloud.
        """
        raise NotImplementedError

    def hourly_price(self, resources: 'Resources') -> float:
        raise NotImplementedError

    # $/GB leaving this cloud to the public internet / another cloud.
    # Reference carries this per cloud (sky/clouds/*.py get_egress_cost);
    # subclasses override. 0.09 is the common public-cloud list price.
    _EGRESS_PER_GB = 0.09

    def egress_cost(self, num_gigabytes: float) -> float:
        """Total $ to move ``num_gigabytes`` OUT of this cloud."""
        return self._EGRESS_PER_GB * max(0.0, num_gigabytes)

    def validate_region_zone(
            self, region: Optional[str],
            zone: Optional[str]) -> Tuple[Optional[str], Optional[str]]:
        raise NotImplementedError

    # ------------------------------------------------------------------
    # Provisioning support
    def make_deploy_resources_variables(
            self, resources: 'Resources', cluster_name_on_cloud: str,
            region: str, zone: Optional[str]) -> Dict[str, Any]:
        """Variables consumed by the provision plugin (reference :280)."""
        raise NotImplementedError

    # ------------------------------------------------------------------
    # Credentials
    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        """(ok, reason-if-not)."""
        raise NotImplementedError

    def get_credential_file_mounts(self) -> Dict[str, str]:
        """remote_path -> local_path credential files to ship to clusters."""
        return {}

    def get_user_identities(self) -> Optional[List[List[str]]]:
        """Active cloud identities, for multi-identity safety checks."""
        return None
