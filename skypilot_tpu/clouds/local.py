"""Local cloud — hermetic fake provider for tests and development.

The reference has no fake cluster layer and compensates with an
expensive real-cloud smoke-test matrix (SURVEY.md §4). Here the Local
cloud is a first-class plugin: "instances" are directories under
``~/.skytpu/local_cloud/<cluster>/`` plus real local processes, the
command runner executes directly via subprocess, and a simulated
"pod slice" exposes N hosts that are all localhost. This lets the full
launch → gang exec → status → autostop → teardown path (and preemption
recovery, via a fault-injection hook) run in CI with no cloud at all.
"""
from __future__ import annotations

import typing
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.utils import registry

if typing.TYPE_CHECKING:
    from skypilot_tpu.resources import Resources

_LOCAL_REGION = 'local'
_LOCAL_ZONE = 'local-a'
# Flat sim prices so the optimizer has something to rank.
_PRICE_PER_CPU_HOUR = 0.01


@registry.CLOUD_REGISTRY.register(name='local')
class Local(cloud_lib.Cloud):
    """Runs 'clusters' as processes on this machine."""

    _REPR = 'Local'
    _EGRESS_PER_GB = 0.0   # same machine; nothing leaves

    def regions_with_offering(
            self, resources: 'Resources') -> List[cloud_lib.Region]:
        if resources.region not in (None, _LOCAL_REGION):
            return []
        if resources.zone not in (None, _LOCAL_ZONE):
            return []
        return [cloud_lib.Region(_LOCAL_REGION, [_LOCAL_ZONE])]

    def get_feasible_launchable_resources(
            self, resources: 'Resources') -> List['Resources']:
        # Local is opt-in: only feasible when the spec names it, so a
        # real TPU request never "wins" by landing on the simulator.
        if resources.cloud is None or not self.is_same_cloud(resources.cloud):
            return []
        if resources.is_tpu:
            # Simulated slice: hosts become local processes. Feasible so
            # gang logic is testable hermetically.
            return [resources.copy(cloud=self)]
        if not self.regions_with_offering(resources):
            return []
        instance_type = resources.instance_type or 'local'
        return [resources.copy(cloud=self, instance_type=instance_type)]

    def hourly_price(self, resources: 'Resources') -> float:
        if resources.is_tpu:
            return 0.0
        return _PRICE_PER_CPU_HOUR * 8

    def validate_region_zone(self, region, zone):
        if region is not None and region != _LOCAL_REGION:
            from skypilot_tpu import exceptions
            raise exceptions.InvalidResourcesError(
                f'Local cloud has a single region {_LOCAL_REGION!r}.')
        if zone is not None and zone != _LOCAL_ZONE:
            from skypilot_tpu import exceptions
            raise exceptions.InvalidResourcesError(
                f'Local cloud has a single zone {_LOCAL_ZONE!r}.')
        return region, zone

    def make_deploy_resources_variables(
            self, resources: 'Resources', cluster_name_on_cloud: str,
            region: str, zone: Optional[str]) -> Dict[str, Any]:
        num_hosts = resources.tpu.num_hosts if resources.is_tpu else 1
        return {
            'cluster_name_on_cloud': cluster_name_on_cloud,
            'region': region,
            'zone': zone or _LOCAL_ZONE,
            'use_spot': resources.use_spot,
            'num_hosts': num_hosts,
            'tpu_vm': resources.is_tpu,
            'tpu_topology': (resources.tpu.topology
                             if resources.is_tpu else ''),
        }

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        return True, None
