"""Client-side persistent state (SQLite).

Re-design of reference ``sky/global_user_state.py``: the ``clusters``
table holds the pickled ResourceHandle, status, autostop settings; plus
``cluster_history`` and a ``config`` kv table. Connections and write
transactions go through :mod:`skypilot_tpu.utils.statedb` (WAL,
busy_timeout, synchronous=NORMAL, explicit transactions); a module
lock keeps the multi-process executor's threads serialized (reference
:40-52).

DB path: ``~/.skytpu/state.db`` (override: SKYTPU_STATE_DB for tests).
"""
from __future__ import annotations

import json
import os
import pickle
import sqlite3
import threading
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import log as sky_logging
from skypilot_tpu.utils import statedb
from skypilot_tpu.utils import status_lib

logger = sky_logging.init_logger(__name__)

_lock = threading.Lock()
_conn_local = threading.local()


def _db_path() -> str:
    return os.path.expanduser(
        os.environ.get('SKYTPU_STATE_DB', '~/.skytpu/state.db'))


def _conn() -> sqlite3.Connection:
    path = _db_path()
    cached = getattr(_conn_local, 'conn', None)
    if cached is not None and getattr(_conn_local, 'path', None) == path:
        return cached
    conn = statedb.connect(path, row_factory=False)
    _create_tables(conn)
    _conn_local.conn = conn
    _conn_local.path = path
    return conn


def _transaction():
    """One explicit write transaction on this thread's connection
    (statedb crashpoints + retry; see docs/crash_recovery.md)."""
    return statedb.transaction(_conn(), site='user.state.write')


def _create_tables(conn: sqlite3.Connection) -> None:
    conn.execute("""\
        CREATE TABLE IF NOT EXISTS clusters (
            name TEXT PRIMARY KEY,
            launched_at INTEGER,
            handle BLOB,
            last_use TEXT,
            status TEXT,
            autostop INTEGER DEFAULT -1,
            to_down INTEGER DEFAULT 0,
            owner TEXT DEFAULT NULL,
            cluster_hash TEXT DEFAULT NULL)""")
    conn.execute("""\
        CREATE TABLE IF NOT EXISTS cluster_history (
            cluster_hash TEXT PRIMARY KEY,
            name TEXT,
            num_nodes INTEGER,
            requested_resources BLOB,
            launched_resources BLOB,
            usage_intervals BLOB)""")
    conn.execute("""\
        CREATE TABLE IF NOT EXISTS config (
            key TEXT PRIMARY KEY, value TEXT)""")
    conn.execute("""\
        CREATE TABLE IF NOT EXISTS storage (
            name TEXT PRIMARY KEY,
            launched_at INTEGER,
            handle BLOB,
            last_use TEXT,
            status TEXT)""")


def _safe_unpickle(blob: Optional[bytes], what: str,
                   default: Any = None) -> Any:
    """Tolerate corrupt/truncated pickle blobs (a torn write from a
    crashed process, or a pre-WAL partial page): one bad row degrades
    to a warning + ``default`` instead of taking every ``list()`` /
    status call down with it."""
    if blob is None:
        return default
    try:
        return pickle.loads(blob)
    except Exception as e:  # pylint: disable=broad-except
        # Unpickling raises anything from UnpicklingError/EOFError to
        # AttributeError/ImportError depending on where the blob tore.
        logger.warning(
            '%s is corrupt or truncated (%s: %s); treating as missing.',
            what, type(e).__name__, e)
        return default


# ----------------------------------------------------------------------
# Clusters
def add_or_update_cluster(cluster_name: str,
                          cluster_handle: Any,
                          requested_resources: Optional[set] = None,
                          is_launch: bool = True,
                          ready: bool = False) -> None:
    status = (status_lib.ClusterStatus.UP
              if ready else status_lib.ClusterStatus.INIT)
    handle_blob = pickle.dumps(cluster_handle)
    cluster_hash = _get_hash_for_existing_cluster(
        cluster_name) or common_utils.generate_run_id(16)
    now = int(time.time())
    usage_intervals = _get_usage_intervals(cluster_hash)
    if is_launch and (not usage_intervals or
                      usage_intervals[-1][1] is not None):
        usage_intervals.append((now, None))
    with _lock, _transaction() as conn:
        conn.execute(
            """INSERT INTO clusters
               (name, launched_at, handle, last_use, status, autostop,
                to_down, owner, cluster_hash)
               VALUES (?,?,?,?,?,
                       COALESCE((SELECT autostop FROM clusters
                                 WHERE name=?), -1),
                       COALESCE((SELECT to_down FROM clusters
                                 WHERE name=?), 0),
                       NULL, ?)
               ON CONFLICT(name) DO UPDATE SET
                 launched_at=excluded.launched_at,
                 handle=excluded.handle,
                 last_use=excluded.last_use,
                 status=excluded.status,
                 cluster_hash=excluded.cluster_hash""",
            (cluster_name, now, handle_blob, _command_for_last_use(),
             status.value, cluster_name, cluster_name, cluster_hash))
        if requested_resources is not None:
            launched = getattr(cluster_handle, 'launched_resources', None)
            conn.execute(
                """INSERT INTO cluster_history
                   (cluster_hash, name, num_nodes, requested_resources,
                    launched_resources, usage_intervals)
                   VALUES (?,?,?,?,?,?)
                   ON CONFLICT(cluster_hash) DO UPDATE SET
                     num_nodes=excluded.num_nodes,
                     requested_resources=excluded.requested_resources,
                     launched_resources=excluded.launched_resources,
                     usage_intervals=excluded.usage_intervals""",
                (cluster_hash, cluster_name,
                 getattr(cluster_handle, 'launched_nodes', None),
                 pickle.dumps(requested_resources),
                 pickle.dumps(launched), pickle.dumps(usage_intervals)))
        else:
            conn.execute(
                'UPDATE cluster_history SET usage_intervals=? '
                'WHERE cluster_hash=?',
                (pickle.dumps(usage_intervals), cluster_hash))


def _command_for_last_use() -> str:
    import sys
    return ' '.join(sys.argv)[:200]


def update_cluster_status(cluster_name: str,
                          status: status_lib.ClusterStatus) -> None:
    with _lock:
        _conn().execute('UPDATE clusters SET status=? WHERE name=?',
                        (status.value, cluster_name))


def set_cluster_owner(cluster_name: str, owner: str) -> None:
    """Record the cloud identity that launched the cluster (comma-
    joined; compared on every refresh for multi-identity safety)."""
    with _lock:
        _conn().execute('UPDATE clusters SET owner=? WHERE name=?',
                        (owner, cluster_name))


def update_cluster_handle(cluster_name: str, cluster_handle: Any) -> None:
    with _lock:
        _conn().execute('UPDATE clusters SET handle=? WHERE name=?',
                        (pickle.dumps(cluster_handle), cluster_name))


def set_cluster_autostop_value(cluster_name: str, idle_minutes: int,
                               to_down: bool) -> None:
    with _lock:
        _conn().execute(
            'UPDATE clusters SET autostop=?, to_down=? WHERE name=?',
            (idle_minutes, int(to_down), cluster_name))


def remove_cluster(cluster_name: str, terminate: bool) -> None:
    cluster_hash = _get_hash_for_existing_cluster(cluster_name)
    now = int(time.time())
    # Close out the open usage interval (billing truth) in the SAME
    # transaction as the row removal: a crash between the two used to
    # leave a terminated cluster accruing usage forever.
    closed_intervals = None
    if cluster_hash is not None:
        usage_intervals = _get_usage_intervals(cluster_hash)
        if usage_intervals and usage_intervals[-1][1] is None:
            start, _ = usage_intervals.pop()
            usage_intervals.append((start, now))
            closed_intervals = usage_intervals
    with _lock, _transaction() as conn:
        if terminate:
            conn.execute('DELETE FROM clusters WHERE name=?',
                         (cluster_name,))
        else:
            conn.execute(
                'UPDATE clusters SET status=? WHERE name=?',
                (status_lib.ClusterStatus.STOPPED.value, cluster_name))
        if closed_intervals is not None:
            conn.execute(
                'UPDATE cluster_history SET usage_intervals=? '
                'WHERE cluster_hash=?',
                (pickle.dumps(closed_intervals), cluster_hash))


def get_cluster_from_name(
        cluster_name: Optional[str]) -> Optional[Dict[str, Any]]:
    rows = _query_clusters('WHERE name=?', (cluster_name,))
    return rows[0] if rows else None


def get_clusters() -> List[Dict[str, Any]]:
    return _query_clusters('', ())


def _query_clusters(where: str, params: tuple) -> List[Dict[str, Any]]:
    conn = _conn()
    cursor = conn.execute(
        f"""SELECT name, launched_at, handle, last_use, status, autostop,
                   to_down, owner, cluster_hash FROM clusters {where}
            ORDER BY launched_at DESC""", params)
    rows = []
    for (name, launched_at, handle, last_use, status, autostop, to_down,
         owner, cluster_hash) in cursor.fetchall():
        rows.append({
            'name': name,
            'launched_at': launched_at,
            'handle': _safe_unpickle(handle,
                                     f'Handle of cluster {name!r}'),
            'last_use': last_use,
            'status': status_lib.ClusterStatus(status),
            'autostop': autostop,
            'to_down': bool(to_down),
            'owner': owner,
            'cluster_hash': cluster_hash,
        })
    return rows


def _get_hash_for_existing_cluster(cluster_name: str) -> Optional[str]:
    conn = _conn()
    cursor = conn.execute('SELECT cluster_hash FROM clusters WHERE name=?',
                          (cluster_name,))
    row = cursor.fetchone()
    return row[0] if row else None


def _get_usage_intervals(cluster_hash: Optional[str]) -> list:
    if cluster_hash is None:
        return []
    conn = _conn()
    cursor = conn.execute(
        'SELECT usage_intervals FROM cluster_history WHERE cluster_hash=?',
        (cluster_hash,))
    row = cursor.fetchone()
    if row is None or row[0] is None:
        return []
    return _safe_unpickle(row[0],
                          f'Usage intervals of cluster {cluster_hash!r}',
                          default=[])


def get_cluster_history() -> List[Dict[str, Any]]:
    conn = _conn()
    cursor = conn.execute(
        """SELECT cluster_hash, name, num_nodes, requested_resources,
                  launched_resources, usage_intervals FROM cluster_history""")
    rows = []
    for (cluster_hash, name, num_nodes, requested, launched,
         usage_intervals) in cursor.fetchall():
        intervals = _safe_unpickle(
            usage_intervals, f'Usage intervals of {name!r}', default=[])
        duration = sum((end or int(time.time())) - start
                       for start, end in intervals)
        rows.append({
            'cluster_hash': cluster_hash,
            'name': name,
            'num_nodes': num_nodes,
            'requested_resources': _safe_unpickle(
                requested, f'Requested resources of {name!r}'),
            'launched_resources': _safe_unpickle(
                launched, f'Launched resources of {name!r}'),
            'usage_intervals': intervals,
            'duration': duration,
        })
    return rows


# ----------------------------------------------------------------------
# Storage records
def add_or_update_storage(storage_name: str, storage_handle: Any,
                          storage_status: str) -> None:
    with _lock:
        _conn().execute(
            """INSERT INTO storage (name, launched_at, handle, last_use,
                                    status)
               VALUES (?,?,?,?,?)
               ON CONFLICT(name) DO UPDATE SET handle=excluded.handle,
                 status=excluded.status, last_use=excluded.last_use""",
            (storage_name, int(time.time()), pickle.dumps(storage_handle),
             _command_for_last_use(), storage_status))


def remove_storage(storage_name: str) -> None:
    with _lock:
        _conn().execute('DELETE FROM storage WHERE name=?',
                        (storage_name,))


def get_storage() -> List[Dict[str, Any]]:
    conn = _conn()
    cursor = conn.execute(
        'SELECT name, launched_at, handle, last_use, status FROM storage')
    return [{
        'name': name,
        'launched_at': launched_at,
        'handle': _safe_unpickle(handle, f'Handle of storage {name!r}'),
        'last_use': last_use,
        'status': status,
    } for name, launched_at, handle, last_use, status in cursor.fetchall()]


def get_storage_from_name(name: str) -> Optional[Dict[str, Any]]:
    for row in get_storage():
        if row['name'] == name:
            return row
    return None


# ----------------------------------------------------------------------
# Generic config kv
def get_config_value(key: str) -> Optional[Any]:
    conn = _conn()
    cursor = conn.execute('SELECT value FROM config WHERE key=?', (key,))
    row = cursor.fetchone()
    return json.loads(row[0]) if row else None


def set_config_value(key: str, value: Any) -> None:
    with _lock:
        _conn().execute(
            """INSERT INTO config (key, value) VALUES (?,?)
               ON CONFLICT(key) DO UPDATE SET value=excluded.value""",
            (key, json.dumps(value)))
