"""SSH keypair management for cluster access.

Re-design of reference ``sky/authentication.py:1-514``: one framework
keypair (generated lazily), injected into instances via cloud metadata
(GCP 'ssh-keys' / TPU-VM metadata) so every provisioned host accepts
the client's SSH connections as the framework user.
"""
from __future__ import annotations

import os
import stat
import subprocess
from typing import Tuple

from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

DEFAULT_SSH_USER = 'skytpu'
_KEY_DIR = '~/.skytpu/keys'
PRIVATE_KEY_PATH = f'{_KEY_DIR}/skytpu.pem'
PUBLIC_KEY_PATH = f'{_KEY_DIR}/skytpu.pem.pub'


def _generate_with_cryptography(priv: str, pub: str) -> None:
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import ed25519
    key = ed25519.Ed25519PrivateKey.generate()
    priv_bytes = key.private_bytes(
        encoding=serialization.Encoding.PEM,
        format=serialization.PrivateFormat.OpenSSH,
        encryption_algorithm=serialization.NoEncryption())
    pub_bytes = key.public_key().public_bytes(
        encoding=serialization.Encoding.OpenSSH,
        format=serialization.PublicFormat.OpenSSH)
    with open(priv, 'wb') as f:
        f.write(priv_bytes)
    with open(pub, 'wb') as f:
        f.write(pub_bytes + b'\n')


def _derive_public_key(priv: str, pub: str) -> None:
    """Recreate the .pub from an existing private key (never overwrite
    the private key — it is already injected into running clusters)."""
    try:
        from cryptography.hazmat.primitives import serialization
        with open(priv, 'rb') as f:
            key = serialization.load_ssh_private_key(f.read(), None)
        pub_bytes = key.public_key().public_bytes(
            encoding=serialization.Encoding.OpenSSH,
            format=serialization.PublicFormat.OpenSSH)
        with open(pub, 'wb') as f:
            f.write(pub_bytes + b'\n')
    except ImportError:
        with open(pub, 'w', encoding='utf-8') as f:
            subprocess.run(['ssh-keygen', '-y', '-f', priv], check=True,
                           stdout=f)


def get_or_generate_keys() -> Tuple[str, str]:
    """Returns (private_key_path, public_key_path), generating once."""
    priv = os.path.expanduser(PRIVATE_KEY_PATH)
    pub = os.path.expanduser(PUBLIC_KEY_PATH)
    if os.path.exists(priv):
        if not os.path.exists(pub):
            _derive_public_key(priv, pub)
        return priv, pub
    os.makedirs(os.path.dirname(priv), exist_ok=True)
    try:
        _generate_with_cryptography(priv, pub)
    except ImportError:
        subprocess.run(
            ['ssh-keygen', '-t', 'ed25519', '-N', '', '-q', '-f', priv],
            check=True)
    os.chmod(priv, stat.S_IRUSR | stat.S_IWUSR)
    logger.info('Generated SSH keypair at %s', priv)
    return priv, pub


def public_key_openssh() -> str:
    _, pub = get_or_generate_keys()
    with open(pub, 'r', encoding='utf-8') as f:
        return f.read().strip()


def ssh_keys_metadata_value(user: str = DEFAULT_SSH_USER) -> str:
    """GCE/TPU 'ssh-keys' metadata entry: '<user>:<openssh pubkey>'."""
    return f'{user}:{public_key_openssh()}'
