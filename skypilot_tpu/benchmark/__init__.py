"""`skytpu bench` — run one task on N candidate TPU types, rank by
$/step (reference ``sky/benchmark/benchmark_utils.py`` +
``benchmark_state.py``)."""
from skypilot_tpu.benchmark.benchmark_utils import (collect_results,
                                                    down_benchmark,
                                                    launch_benchmark,
                                                    report)

__all__ = ['launch_benchmark', 'collect_results', 'report',
           'down_benchmark']
