"""Benchmark SQLite state: benchmarks + per-candidate results
(reference ``sky/benchmark/benchmark_state.py``)."""
from __future__ import annotations

import json
import os
import sqlite3
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import statedb

_DB_PATH_ENV = 'SKYTPU_BENCHMARK_DB'
_DEFAULT_DB = '~/.skytpu/benchmark.db'


def _conn() -> sqlite3.Connection:
    # statedb.connect: shared WAL/busy_timeout/autocommit recipe
    # (docs/crash_recovery.md).
    path = os.path.expanduser(
        os.environ.get(_DB_PATH_ENV, _DEFAULT_DB))
    conn = statedb.connect(path)
    conn.execute("""
        CREATE TABLE IF NOT EXISTS benchmarks (
            name TEXT PRIMARY KEY,
            task_json TEXT,
            created_at REAL
        )""")
    conn.execute("""
        CREATE TABLE IF NOT EXISTS candidates (
            benchmark TEXT,
            cluster_name TEXT,
            resources_repr TEXT,
            hourly_price REAL,
            job_id INTEGER,
            num_steps INTEGER,
            seconds_per_step REAL,
            cost_per_step REAL,
            total_steps INTEGER,
            eta_seconds REAL,
            total_cost REAL,
            status TEXT DEFAULT 'RUNNING',
            PRIMARY KEY (benchmark, cluster_name)
        )""")
    # Migrate pre-ETA databases in place.
    cols = {r[1] for r in conn.execute('PRAGMA table_info(candidates)')}
    for col, typ in (('total_steps', 'INTEGER'),
                     ('eta_seconds', 'REAL'), ('total_cost', 'REAL')):
        if col not in cols:
            conn.execute(
                f'ALTER TABLE candidates ADD COLUMN {col} {typ}')
    return conn


def add_benchmark(name: str, task_json: str) -> None:
    with _conn() as conn:
        conn.execute(
            'INSERT OR REPLACE INTO benchmarks (name, task_json, '
            'created_at) VALUES (?,?,?)',
            (name, task_json, time.time()))


def add_candidate(benchmark: str, cluster_name: str,
                  resources_repr: str, hourly_price: float,
                  job_id: Optional[int]) -> None:
    with _conn() as conn:
        conn.execute(
            'INSERT OR REPLACE INTO candidates (benchmark, '
            'cluster_name, resources_repr, hourly_price, job_id) '
            'VALUES (?,?,?,?,?)',
            (benchmark, cluster_name, resources_repr, hourly_price,
             job_id))


def update_candidate(benchmark: str, cluster_name: str,
                     **fields: Any) -> None:
    sets = ', '.join(f'{k} = ?' for k in fields)
    with _conn() as conn:
        conn.execute(
            f'UPDATE candidates SET {sets} WHERE benchmark = ? AND '
            'cluster_name = ?',
            list(fields.values()) + [benchmark, cluster_name])


def get_candidates(benchmark: str) -> List[Dict[str, Any]]:
    with _conn() as conn:
        return [dict(r) for r in conn.execute(
            'SELECT * FROM candidates WHERE benchmark = ? '
            'ORDER BY cluster_name', (benchmark,))]


def get_benchmarks() -> List[Dict[str, Any]]:
    with _conn() as conn:
        return [dict(r) for r in conn.execute(
            'SELECT * FROM benchmarks ORDER BY name')]


def remove_benchmark(name: str) -> None:
    with statedb.transaction(_conn(), site='benchmark.state.write') as conn:
        conn.execute('DELETE FROM benchmarks WHERE name = ?', (name,))
        conn.execute('DELETE FROM candidates WHERE benchmark = ?',
                     (name,))
