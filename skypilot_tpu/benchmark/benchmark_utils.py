"""Benchmark harness: launch one task on N candidate resources,
collect per-step timings from the in-task callback, rank by $/step.

Re-design of reference ``sky/benchmark/benchmark_utils.py``: the
reference pulls ``sky-callback`` summaries out of a shared bucket;
here the harness reads each candidate's ``summary.json`` straight off
the cluster head through its command runner — no bucket dependency,
and the whole loop runs hermetically on the local cloud. The natural
TPU use: `skytpu bench` one finetune recipe across v5e/v5p/v6e and
read off $/step before committing to a long run.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from skypilot_tpu import callbacks
from skypilot_tpu import execution
from skypilot_tpu import task as task_lib
from skypilot_tpu.benchmark import benchmark_state
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

_REMOTE_BENCH_DIR = '~/skytpu_bench'


def _cluster_name(benchmark: str, idx: int) -> str:
    return f'skytpu-bench-{benchmark}-{idx}'


def launch_benchmark(task: 'task_lib.Task',
                     candidates: List,
                     benchmark: str) -> List[str]:
    """Launch ``task`` once per candidate Resources. Returns cluster
    names (one per candidate, named skytpu-bench-<name>-<i>)."""
    import copy
    from concurrent.futures import ThreadPoolExecutor
    benchmark_state.add_benchmark(
        benchmark, json.dumps(task.to_yaml_config()))

    def launch_one(idx_resources):
        idx, resources = idx_resources
        cluster = _cluster_name(benchmark, idx)
        cand_task = copy.deepcopy(task)
        cand_task.set_resources(resources)
        envs = dict(cand_task.envs or {})
        envs[callbacks.ENV_DIR] = _REMOTE_BENCH_DIR
        cand_task.update_envs(envs)
        job_id, _ = execution.launch(cand_task, cluster_name=cluster,
                                     detach_run=True,
                                     stream_logs=False)
        try:
            price = resources.hourly_price()
        except Exception:  # pylint: disable=broad-except
            price = 0.0
        benchmark_state.add_candidate(benchmark, cluster,
                                      repr(resources), price, job_id)
        logger.info('Benchmark %s: candidate %d (%r) -> %s.',
                    benchmark, idx, resources, cluster)
        return cluster

    # Candidates provision concurrently — on real TPUs each launch is
    # minutes; serializing N of them would N-x the wall clock.
    with ThreadPoolExecutor(max_workers=len(candidates)) as pool:
        clusters = list(pool.map(launch_one,
                                 enumerate(candidates)))
    return clusters


def _read_summary(cluster: str) -> Optional[Dict[str, Any]]:
    from skypilot_tpu.backend import backend_utils
    from skypilot_tpu.utils import command_runner as runner_lib
    try:
        handle = backend_utils.check_cluster_available(cluster)
    except Exception:  # pylint: disable=broad-except
        return None
    runner = handle.head_runner()
    path = runner_lib.shell_path(
        f'{_REMOTE_BENCH_DIR}/{callbacks.SUMMARY}')
    rc, out, _ = runner.run(f'cat {path}', require_outputs=True)
    if rc != 0:
        return None
    try:
        return json.loads(out)
    except json.JSONDecodeError:
        return None


def collect_results(benchmark: str) -> List[Dict[str, Any]]:
    """Pull summaries off every candidate cluster and update state."""
    from skypilot_tpu import core
    rows = benchmark_state.get_candidates(benchmark)
    for row in rows:
        cluster = row['cluster_name']
        summary = _read_summary(cluster)
        if summary is None or summary.get('num_steps', 0) < 2:
            continue
        steps = summary['num_steps']
        span = summary['last_step'] - summary['first_step']
        sec_per_step = span / max(1, steps - 1)
        cost_per_step = row['hourly_price'] * sec_per_step / 3600.0
        # ETA / total-$ projection (reference benchmark report): when
        # the callback knows the run's total step count, project the
        # remaining wall time and the whole run's cost on this
        # candidate from the measured steady-state step time.
        total_steps = summary.get('total_steps')
        eta_seconds = total_cost = None
        if total_steps:
            eta_seconds = max(0, total_steps - steps) * sec_per_step
            total_cost = (row['hourly_price'] * total_steps *
                          sec_per_step / 3600.0)
        status = 'RUNNING'
        try:
            job_status = core.job_status(
                cluster, [row['job_id']])[row['job_id']]
            if job_status is not None and job_status.is_terminal():
                status = str(job_status.value)
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(
                'Could not refresh job status for benchmark '
                'candidate %s: %s', cluster, e)
        benchmark_state.update_candidate(
            benchmark, cluster, num_steps=steps,
            seconds_per_step=sec_per_step,
            cost_per_step=cost_per_step, total_steps=total_steps,
            eta_seconds=eta_seconds, total_cost=total_cost,
            status=status)
    return benchmark_state.get_candidates(benchmark)


def report(benchmark: str) -> List[Dict[str, Any]]:
    """Ranked candidates: cheapest $/step first (ties: fastest)."""
    rows = collect_results(benchmark)
    measured = [r for r in rows if r['seconds_per_step'] is not None]
    unmeasured = [r for r in rows if r['seconds_per_step'] is None]
    measured.sort(key=lambda r: (r['cost_per_step'],
                                 r['seconds_per_step']))
    return measured + unmeasured


def down_benchmark(benchmark: str) -> None:
    """Tear down every candidate cluster and forget the benchmark."""
    from skypilot_tpu import core
    for row in benchmark_state.get_candidates(benchmark):
        try:
            core.down(row['cluster_name'])
        except Exception as e:  # pylint: disable=broad-except
            # A cluster left running after `benchmark down` keeps
            # billing: surface it instead of silently moving on.
            logger.warning('Failed to tear down benchmark cluster '
                           '%s: %s', row['cluster_name'], e)
    benchmark_state.remove_benchmark(benchmark)
