"""The `skytpu` CLI — thin client over the SDK.

Re-design of reference ``sky/cli.py`` (launch/exec/status/stop/down/
autostop/queue/cancel/logs/jobs/serve/check/show-tpus click commands),
kept thin: every command submits through the client SDK and streams or
prints the result.

Run: ``python -m skypilot_tpu.client.cli <command>``.
"""
from __future__ import annotations

import json
import os
from typing import List, Optional

import click
import yaml

from skypilot_tpu.client import sdk
from skypilot_tpu.utils import rich_utils


def _load_task(entrypoint: str, **overrides):
    from skypilot_tpu import task as task_lib
    if os.path.exists(entrypoint):
        with open(entrypoint, 'r', encoding='utf-8') as f:
            config = yaml.safe_load(f) or {}
        task = task_lib.Task.from_yaml_config(config)
    else:
        # Bare command entrypoint: `skytpu launch -- echo hi`.
        task = task_lib.Task(run=entrypoint)
    if overrides.get('name'):
        task.name = overrides['name']
    return task


def _echo_table(rows: List[dict], columns: List[str]) -> None:
    if not rows:
        click.echo('(none)')
        return
    widths = {
        c: max(len(c), *(len(str(r.get(c, ''))) for r in rows))
        for c in columns
    }
    click.echo('  '.join(c.upper().ljust(widths[c]) for c in columns))
    for r in rows:
        click.echo('  '.join(
            str(r.get(c, '')).ljust(widths[c]) for c in columns))


@click.group()
def cli() -> None:
    """skytpu: TPU-native cloud orchestrator."""


# ------------------------------------------------------------- cluster


@cli.command()
@click.argument('entrypoint')
@click.option('--cluster', '-c', default=None, help='Cluster name.')
@click.option('--name', '-n', default=None, help='Task name.')
@click.option('--detach-run', '-d', is_flag=True, default=False)
@click.option('--idle-minutes-to-autostop', '-i', type=int, default=None)
@click.option('--down', is_flag=True, default=False,
              help='Autodown after the job finishes idle budget.')
@click.option('--retry-until-up', '-r', is_flag=True, default=False)
@click.option('--dryrun', is_flag=True, default=False)
def launch(entrypoint: str, cluster: Optional[str], name: Optional[str],
           detach_run: bool, idle_minutes_to_autostop: Optional[int],
           down: bool, retry_until_up: bool, dryrun: bool) -> None:
    """Launch a task YAML (provision + run)."""
    task = _load_task(entrypoint, name=name)
    request_id = sdk.launch(
        task, cluster_name=cluster, dryrun=dryrun,
        idle_minutes_to_autostop=idle_minutes_to_autostop, down=down,
        retry_until_up=retry_until_up)
    if detach_run:
        click.echo(f'request: {request_id}')
        return
    result = sdk.stream_and_get(request_id)
    if result and result.get('job_id') is not None:
        click.echo(f'Job {result["job_id"]} on cluster '
                   f'{result["cluster_name"]}.')


@cli.command('exec')
@click.argument('cluster')
@click.argument('entrypoint')
@click.option('--name', '-n', default=None)
def exec_cmd(cluster: str, entrypoint: str, name: Optional[str]) -> None:
    """Run a task on an existing cluster (skip provision/setup)."""
    task = _load_task(entrypoint, name=name)
    result = sdk.stream_and_get(sdk.exec_(task, cluster_name=cluster))
    if result:
        click.echo(f'Job {result["job_id"]} on {result["cluster_name"]}.')


@cli.command()
@click.option('--refresh', '-r', is_flag=True, default=False)
def status(refresh: bool) -> None:
    """Show clusters."""
    with rich_utils.client_status(
            'Refreshing cluster status from the cloud...'
            if refresh else 'Fetching cluster status...'):
        rows = sdk.get(sdk.status(refresh=refresh))
    _echo_table(rows, ['name', 'status', 'resources', 'autostop'])


@cli.command()
@click.argument('cluster')
def stop(cluster: str) -> None:
    with rich_utils.client_status(f'Stopping cluster {cluster}...'):
        sdk.get(sdk.stop(cluster))
    click.echo(f'Cluster {cluster} stopped.')


@cli.command()
@click.argument('cluster')
def start(cluster: str) -> None:
    with rich_utils.client_status(f'Starting cluster {cluster}...'):
        sdk.get(sdk.start(cluster))
    click.echo(f'Cluster {cluster} started.')


@cli.command()
@click.argument('cluster')
@click.option('--purge', is_flag=True, default=False)
def down(cluster: str, purge: bool) -> None:
    with rich_utils.client_status(f'Terminating cluster {cluster}...'):
        sdk.get(sdk.down(cluster, purge=purge))
    click.echo(f'Cluster {cluster} terminated.')


@cli.command()
@click.argument('cluster')
@click.option('--idle-minutes', '-i', type=int, required=True,
              help='-1 cancels autostop.')
@click.option('--down', 'down_', is_flag=True, default=False)
def autostop(cluster: str, idle_minutes: int, down_: bool) -> None:
    sdk.get(sdk.autostop(cluster, idle_minutes, down_))
    click.echo(f'Autostop set on {cluster}.')


@cli.command()
@click.argument('cluster')
def queue(cluster: str) -> None:
    """Show a cluster's job queue."""
    rows = sdk.get(sdk.queue(cluster))
    _echo_table(rows, ['job_id', 'name', 'status', 'submitted_at'])


@cli.command()
@click.argument('cluster')
@click.option('--job-ids', '-j', multiple=True, type=int)
@click.option('--all', 'all_jobs', is_flag=True, default=False)
def cancel(cluster: str, job_ids, all_jobs: bool) -> None:
    cancelled = sdk.get(
        sdk.cancel(cluster, list(job_ids) or None, all_jobs))
    click.echo(f'Cancelled: {cancelled}')


@cli.command()
@click.argument('cluster')
@click.option('--job-id', '-j', type=int, default=None)
@click.option('--sync-down', is_flag=True, default=False,
              help='Download the job log tree instead of tailing.')
@click.option('--local-dir', default='~/skytpu_logs',
              help='Destination for --sync-down.')
def logs(cluster: str, job_id: Optional[int], sync_down: bool,
         local_dir: str) -> None:
    """Tail a job's logs (in-process; logs need the live stream)."""
    from skypilot_tpu import core
    if sync_down:
        dst = core.sync_down_logs(cluster, job_id, local_dir)
        click.echo(dst)
        return
    core.tail_logs(cluster, job_id, follow=True)


@cli.command()
def check() -> None:
    """Check cloud credentials."""
    enabled = sdk.get(sdk.check())
    click.echo('Enabled clouds: ' + ', '.join(enabled))


@cli.command('cost-report')
def cost_report() -> None:
    """Accumulated cost per cluster from usage intervals."""
    # Through the SDK: the cluster history lives in the API server's
    # DB, which may be on another machine (team deployment).
    rows = sdk.get(sdk.cost_report())
    _echo_table([{
        'name': r['name'],
        'nodes': r['num_nodes'],
        'duration_h': round((r['duration'] or 0) / 3600.0, 2),
        'resources': r['resources'],
        'cost_usd': (round(r['cost'], 2) if r['cost'] is not None
                     else '-'),
    } for r in rows], ['name', 'nodes', 'duration_h', 'resources',
                       'cost_usd'])


@cli.command('show-tpus')
@click.option('--name-filter', default=None)
def show_tpus(name_filter: Optional[str]) -> None:
    """List TPU accelerator offerings (name, chips, hosts, price)."""
    from skypilot_tpu import catalog
    rows = []
    for name, offerings in sorted(
            catalog.list_accelerators(name_filter=name_filter).items()):
        for o in offerings:
            rows.append({
                'name': name,
                'chips': o.num_chips,
                'hosts': o.num_hosts,
                'topology': o.topology,
                'zone': o.zone,
                'price_hr': round(o.hourly_price(False), 2),
                'spot_hr': round(o.hourly_price(True), 2),
            })
    _echo_table(rows, ['name', 'chips', 'hosts', 'topology', 'zone',
                       'price_hr', 'spot_hr'])


# ------------------------------------------------------------- jobs


@cli.group()
def jobs() -> None:
    """Managed jobs with auto-recovery."""


@jobs.command('launch')
@click.argument('entrypoint')
@click.option('--name', '-n', default=None)
@click.option('--on-controller/--no-on-controller', default=None,
              help='Run the controller on the jobs controller cluster '
              '(survives this machine) instead of a local process.')
def jobs_launch(entrypoint: str, name: Optional[str],
                on_controller: Optional[bool]) -> None:
    task = _load_task(entrypoint, name=name)
    result = sdk.get(sdk.jobs_launch(task, name=name,
                                     on_controller=on_controller))
    click.echo(f'Managed job {result["managed_job_id"]} submitted.')


@jobs.command('queue')
def jobs_queue() -> None:
    rows = sdk.get(sdk.jobs_queue())
    _echo_table(rows, ['job_id', 'name', 'status', 'cluster_name',
                       'recovery_count'])


@jobs.command('cancel')
@click.option('--job-ids', '-j', multiple=True, type=int)
@click.option('--all', 'all_jobs', is_flag=True, default=False)
def jobs_cancel(job_ids, all_jobs: bool) -> None:
    result = sdk.get(sdk.jobs_cancel(list(job_ids) or None, all_jobs))
    click.echo(f'Cancelled: {result["cancelled"]}')


@jobs.command('dashboard')
@click.option('--port', type=int, default=46581)
def jobs_dashboard(port: int) -> None:
    """Serve the managed-jobs dashboard (reference sky/jobs/dashboard)."""
    click.echo(f'Dashboard on http://127.0.0.1:{port}')
    import subprocess
    import sys
    subprocess.run([sys.executable, '-m', 'skypilot_tpu.jobs.dashboard',
                    '--port', str(port)], check=False)


@jobs.command('logs')
@click.argument('job_id', type=int)
def jobs_logs(job_id: int) -> None:
    from skypilot_tpu.jobs import core as jobs_core
    jobs_core.tail_logs(job_id, follow=True)


# ------------------------------------------------------------- serve


@cli.group()
def bench() -> None:
    """Benchmark a task across candidate TPU types (reference
    `sky bench`)."""


@bench.command('launch')
@click.argument('entrypoint')
@click.option('--benchmark', '-b', required=True,
              help='Benchmark name.')
@click.option('--candidates', required=True,
              help='Comma-separated accelerator list, e.g. '
              '"tpu-v5e-8,tpu-v6e-8"; or "cloud:local" entries.')
def bench_launch(entrypoint: str, benchmark: str,
                 candidates: str) -> None:
    from skypilot_tpu import benchmark as bench_lib
    from skypilot_tpu import resources as resources_lib
    task = _load_task(entrypoint)
    res = []
    for cand in candidates.split(','):
        cand = cand.strip()
        if cand.startswith('cloud:'):
            res.append(resources_lib.Resources(cloud=cand[6:]))
        else:
            res.append(resources_lib.Resources(accelerators=cand))
    clusters = bench_lib.launch_benchmark(task, res, benchmark)
    click.echo(f'Benchmark {benchmark}: {len(clusters)} candidates '
               f'launched: {", ".join(clusters)}')


@bench.command('show')
@click.argument('benchmark')
def bench_show(benchmark: str) -> None:
    from skypilot_tpu import benchmark as bench_lib
    rows = bench_lib.report(benchmark)
    _echo_table([{
        'cluster': r['cluster_name'],
        'resources': r['resources_repr'],
        'steps': r['num_steps'] or '-',
        's/step': (round(r['seconds_per_step'], 4)
                   if r['seconds_per_step'] else '-'),
        '$/step': (round(r['cost_per_step'], 6)
                   if r['cost_per_step'] is not None else '-'),
        'status': r['status'],
    } for r in rows], ['cluster', 'resources', 'steps', 's/step',
                       '$/step', 'status'])


@bench.command('report')
@click.argument('benchmark')
def bench_report(benchmark: str) -> None:
    """Ranked candidate table with ETA and projected total cost
    (reference `sky bench show`'s richer report)."""
    from skypilot_tpu import benchmark as bench_lib

    def _dur(seconds):
        if seconds is None:
            return '-'
        seconds = int(seconds)
        if seconds >= 3600:
            return f'{seconds // 3600}h{(seconds % 3600) // 60:02d}m'
        if seconds >= 60:
            return f'{seconds // 60}m{seconds % 60:02d}s'
        return f'{seconds}s'

    rows = bench_lib.report(benchmark)
    _echo_table([{
        'cluster': r['cluster_name'],
        'resources': r['resources_repr'],
        '$/hr': round(r['hourly_price'], 2),
        'steps': (f"{r['num_steps']}/{r['total_steps']}"
                  if r.get('total_steps') else (r['num_steps'] or '-')),
        's/step': (round(r['seconds_per_step'], 4)
                   if r['seconds_per_step'] else '-'),
        '$/step': (round(r['cost_per_step'], 6)
                   if r['cost_per_step'] is not None else '-'),
        'eta': _dur(r.get('eta_seconds')),
        'total $': (round(r['total_cost'], 2)
                    if r.get('total_cost') is not None else '-'),
        'status': r['status'],
    } for r in rows], ['cluster', 'resources', '$/hr', 'steps',
                       's/step', '$/step', 'eta', 'total $', 'status'])


@bench.command('down')
@click.argument('benchmark')
def bench_down(benchmark: str) -> None:
    from skypilot_tpu import benchmark as bench_lib
    bench_lib.down_benchmark(benchmark)
    click.echo(f'Benchmark {benchmark} torn down.')


@cli.group()
def storage() -> None:
    """Named storage buckets (reference `sky storage`)."""


@storage.command('ls')
def storage_ls() -> None:
    from skypilot_tpu import global_user_state
    rows = global_user_state.get_storage()
    _echo_table([{
        'name': r['name'],
        'store': getattr(r.get('handle'), 'stores', None) and ','.join(
            s.value for s in r['handle'].stores) or '-',
        'status': r.get('status', '-'),
    } for r in rows], ['name', 'store', 'status'])


@storage.command('delete')
@click.argument('names', nargs=-1, required=True)
def storage_delete(names) -> None:
    from skypilot_tpu import global_user_state
    from skypilot_tpu.data import storage as storage_lib
    for name in names:
        record = global_user_state.get_storage_from_name(name)
        if record is None:
            click.echo(f'No storage named {name!r}.')
            continue
        handle = record.get('handle')
        if isinstance(handle, storage_lib.Storage):
            handle.delete()
        else:
            global_user_state.remove_storage(name)
        click.echo(f'Deleted storage {name!r}.')


@cli.group()
def serve() -> None:
    """Service serving with autoscaling."""


@serve.command('up')
@click.argument('entrypoint')
@click.option('--service-name', '-n', default=None)
def serve_up(entrypoint: str, service_name: Optional[str]) -> None:
    task = _load_task(entrypoint)
    result = sdk.get(sdk.serve_up(task, service_name))
    endpoint = result.get('endpoint')
    if endpoint:
        click.echo(f'Service {result["name"]} at {endpoint}.')
    else:
        click.echo(f'Service {result["name"]} starting; endpoint not '
                   'yet bound (check `serve status`).')


@serve.command('update')
@click.argument('service_name')
@click.argument('entrypoint')
def serve_update(service_name: str, entrypoint: str) -> None:
    task = _load_task(entrypoint)
    result = sdk.get(sdk.serve_update(task, service_name))
    click.echo(f'Service {result["name"]} rolling to version '
               f'{result["version"]}.')


@serve.command('down')
@click.argument('service_name')
@click.option('--purge', is_flag=True, default=False)
def serve_down(service_name: str, purge: bool) -> None:
    sdk.get(sdk.serve_down(service_name, purge))
    click.echo(f'Service {service_name} torn down.')


@serve.command('status')
@click.option('--service-name', '-n', default=None)
def serve_status(service_name: Optional[str]) -> None:
    for svc in sdk.get(sdk.serve_status(service_name)):
        click.echo(f'{svc["name"]}: {svc["status"]} at '
                   f'{svc["endpoint"] or "(endpoint not yet bound)"}')
        _echo_table(svc['replicas'], ['replica_id', 'status', 'url'])


# ------------------------------------------------------------- api


@cli.group()
def api() -> None:
    """API-server requests admin."""


@api.command('list')
def api_list() -> None:
    import requests as http
    url = sdk.ensure_server()
    rows = http.get(url + '/api/requests', timeout=10).json()['requests']
    _echo_table(rows, ['request_id', 'name', 'status'])


@api.command('cancel')
@click.argument('request_id')
def api_cancel(request_id: str) -> None:
    click.echo(json.dumps({'cancelled': sdk.api_cancel(request_id)}))


def main() -> None:
    cli()


if __name__ == '__main__':
    main()
