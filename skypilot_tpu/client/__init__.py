"""Thin client: SDK + CLI over the API server."""
