"""Client SDK: every call POSTs to the API server, returns a request
id; results come from get()/stream_and_get().

Re-design of reference ``sky/client/sdk.py:289-307`` + autostart
(``check_server_healthy_or_start``): if no server answers on the
configured endpoint, a local one is started detached, so the thin
client works out of the box.
"""
from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

import requests as http

from skypilot_tpu import exceptions
from skypilot_tpu import skypilot_config
from skypilot_tpu.server.server import DEFAULT_PORT
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

_SERVER_START_TIMEOUT = 30.0


def server_url() -> str:
    env = os.environ.get('SKYTPU_API_SERVER_ENDPOINT')
    if env:
        return env.rstrip('/')
    cfg = skypilot_config.get_nested(('api_server', 'endpoint'), None)
    if cfg:
        return str(cfg).rstrip('/')
    return f'http://127.0.0.1:{DEFAULT_PORT}'


# API versions this client can talk to. A server outside the range
# fails FAST with an actionable message instead of surfacing as
# mysterious 404s/shape errors mid-request (the failure mode the
# reference's backward_compatibility_tests.sh harness guards).
MIN_API_VERSION = 1
MAX_API_VERSION = 1


def _check_api_version(body: dict, url: str) -> None:
    version = body.get('api_version')
    if version is None:
        return   # pre-versioning server: let requests proceed
    if not MIN_API_VERSION <= version <= MAX_API_VERSION:
        raise exceptions.ApiVersionMismatchError(
            f'API server at {url} speaks version {version}; this '
            f'client supports {MIN_API_VERSION}..{MAX_API_VERSION}. '
            'Upgrade the older side (server: redeploy; client: pip '
            'install -U / git pull).')


def _healthy(url: str) -> bool:
    try:
        resp = http.get(url + '/api/health', timeout=2)
        if resp.status_code != 200:
            return False
        _check_api_version(resp.json(), url)
        return True
    except http.RequestException:
        return False


def ensure_server(url: Optional[str] = None) -> str:
    """Health-check; autostart a local server if it's the default."""
    url = url or server_url()
    if _healthy(url):
        return url
    if '127.0.0.1' not in url and 'localhost' not in url:
        raise exceptions.ApiServerConnectionError(url)
    port = int(url.rsplit(':', 1)[1])
    logger.info('Starting local API server on %s...', url)
    env = dict(os.environ)
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    existing = env.get('PYTHONPATH', '')
    if repo_root not in existing.split(os.pathsep):
        env['PYTHONPATH'] = repo_root + (os.pathsep + existing
                                         if existing else '')
    log_path = os.path.expanduser('~/.skytpu/api_server.log')
    os.makedirs(os.path.dirname(log_path), exist_ok=True)
    with open(log_path, 'ab') as log_f:
        subprocess.Popen(
            [sys.executable, '-u', '-m', 'skypilot_tpu.server.server',
             '--port', str(port)],
            stdout=log_f, stderr=subprocess.STDOUT,
            start_new_session=True, env=env)
    deadline = time.time() + _SERVER_START_TIMEOUT
    while time.time() < deadline:
        if _healthy(url):
            return url
        time.sleep(0.3)
    raise exceptions.ApiServerConnectionError(url)


# ------------------------------------------------------------------ rpc


def submit(op: str, body: Dict[str, Any]) -> str:
    url = ensure_server()
    resp = http.post(f'{url}/api/v1/{op.replace(".", "/")}', json=body,
                     timeout=30)
    resp.raise_for_status()
    return resp.json()['request_id']


def get(request_id: str, timeout: float = 3600) -> Any:
    """Block for the result; raise on failed requests."""
    url = ensure_server()
    resp = http.get(f'{url}/api/get',
                    params={'request_id': request_id,
                            'timeout': timeout},
                    timeout=timeout + 30)
    resp.raise_for_status()
    payload = resp.json()
    if payload.get('status') == 'FAILED':
        raise exceptions.SkyTpuError(
            f'Request {request_id} failed: {payload.get("error")}')
    if payload.get('status') == 'CANCELLED':
        raise exceptions.RequestCancelled(request_id)
    return payload.get('result')


def stream_and_get(request_id: str) -> Any:
    """Stream the request's log to stdout, then return its result."""
    url = ensure_server()
    with http.get(f'{url}/api/stream',
                  params={'request_id': request_id},
                  stream=True, timeout=None) as resp:
        resp.raise_for_status()
        for chunk in resp.iter_content(chunk_size=None):
            sys.stdout.write(chunk.decode('utf-8', errors='replace'))
            sys.stdout.flush()
    return get(request_id)


def api_cancel(request_id: str) -> bool:
    url = ensure_server()
    resp = http.post(f'{url}/api/cancel',
                     json={'request_id': request_id}, timeout=30)
    resp.raise_for_status()
    return resp.json()['cancelled']


# ------------------------------------------------------------ SDK calls


def _machine_id() -> Optional[str]:
    try:
        with open('/etc/machine-id', encoding='utf-8') as f:
            return f.read().strip() or None
    except OSError:
        return None


def _server_is_local() -> bool:
    """True when the API server shares this machine's filesystem.

    A loopback hostname is NOT proof (kubectl port-forward exposes a
    remote server on 127.0.0.1): compare machine ids via /api/health
    and fall back to uploading — the upload path is always correct,
    skipping it is only an optimization for the autostarted local
    server."""
    mine = _machine_id()
    if mine is None:
        return False
    try:
        resp = http.get(f'{ensure_server()}/api/health', timeout=5)
        return resp.json().get('machine_id') == mine
    except Exception:  # pylint: disable=broad-except
        return False


def upload_workdir(workdir: str) -> str:
    """Zip + upload a workdir; returns the server-side path
    (reference chunked upload, sky/server/server.py:312). The zip is
    spooled to disk past 32 MiB so huge workdirs don't live in RAM."""
    import tempfile
    import zipfile
    url = ensure_server()
    src = os.path.abspath(os.path.expanduser(workdir))
    with tempfile.SpooledTemporaryFile(
            max_size=32 * 1024 * 1024) as buf:
        with zipfile.ZipFile(buf, 'w', zipfile.ZIP_DEFLATED) as zf:
            for root, dirs, files in os.walk(src):
                dirs[:] = [d for d in dirs if d != '.git']
                for fname in files:
                    full = os.path.join(root, fname)
                    zf.write(full, os.path.relpath(full, src))
        buf.seek(0)
        resp = http.post(f'{url}/api/upload', data=buf, timeout=600)
    resp.raise_for_status()
    return resp.json()['path']


def _task_body(task, **extra) -> Dict[str, Any]:
    config = task.to_yaml_config()
    if config.get('workdir'):
        if _server_is_local():
            # Same filesystem: absolutize so the server does not
            # resolve a relative workdir against ITS cwd.
            config['workdir'] = os.path.abspath(
                os.path.expanduser(config['workdir']))
        else:
            config['workdir'] = upload_workdir(config['workdir'])
    return {'task': config, **extra}


def launch(task, cluster_name: Optional[str] = None, **kwargs) -> str:
    return submit('launch',
                  _task_body(task, cluster_name=cluster_name, **kwargs))


def exec_(task, cluster_name: str, **kwargs) -> str:
    return submit('exec',
                  _task_body(task, cluster_name=cluster_name, **kwargs))


def status(cluster_names: Optional[List[str]] = None,
           refresh: bool = False) -> str:
    return submit('status', {'cluster_names': cluster_names,
                             'refresh': refresh})


def stop(cluster_name: str) -> str:
    return submit('stop', {'cluster_name': cluster_name})


def start(cluster_name: str) -> str:
    return submit('start', {'cluster_name': cluster_name})


def down(cluster_name: str, purge: bool = False) -> str:
    return submit('down', {'cluster_name': cluster_name, 'purge': purge})


def autostop(cluster_name: str, idle_minutes: int,
             down_: bool = False) -> str:
    return submit('autostop', {'cluster_name': cluster_name,
                               'idle_minutes': idle_minutes,
                               'down': down_})


def queue(cluster_name: str) -> str:
    return submit('queue', {'cluster_name': cluster_name})


def cancel(cluster_name: str,
           job_ids: Optional[List[int]] = None,
           all_jobs: bool = False) -> str:
    return submit('cancel', {'cluster_name': cluster_name,
                             'job_ids': job_ids, 'all_jobs': all_jobs})


def cost_report() -> str:
    return submit('cost_report', {})


def check() -> str:
    return submit('check', {})


def jobs_launch(task, name: Optional[str] = None,
                on_controller: Optional[bool] = None) -> str:
    body = _task_body(task, name=name)
    if on_controller is not None:
        body['on_controller'] = on_controller
    return submit('jobs.launch', body)


def jobs_queue() -> str:
    return submit('jobs.queue', {})


def jobs_cancel(job_ids: Optional[List[int]] = None,
                all_jobs: bool = False) -> str:
    return submit('jobs.cancel', {'job_ids': job_ids, 'all': all_jobs})


def serve_up(task, service_name: Optional[str] = None) -> str:
    return submit('serve.up', _task_body(task,
                                         service_name=service_name))


def serve_update(task, service_name: str) -> str:
    return submit('serve.update', _task_body(task,
                                             service_name=service_name))


def serve_down(service_name: str, purge: bool = False) -> str:
    return submit('serve.down', {'service_name': service_name,
                                 'purge': purge})


def serve_status(service_name: Optional[str] = None) -> str:
    return submit('serve.status', {'service_name': service_name})
