"""Execution engine — the task lifecycle state machine.

Re-design of reference ``sky/execution.py`` (Stage :35, _execute :99,
launch :377, exec :557): OPTIMIZE -> PROVISION -> SYNC_WORKDIR ->
SYNC_FILE_MOUNTS -> SETUP -> PRE_EXEC -> EXEC -> (optional) DOWN.
`exec_` skips optimize/provision/setup and reuses the cluster handle —
the fast path for iterating on a running TPU slice.
"""
from __future__ import annotations

import enum
from typing import List, Optional, Tuple, Union

from skypilot_tpu import admin_policy
from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import optimizer as optimizer_lib
from skypilot_tpu import task as task_lib
from skypilot_tpu.backend import backend_utils
from skypilot_tpu.backend import gang_backend
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import log as sky_logging
from skypilot_tpu.utils import timeline

logger = sky_logging.init_logger(__name__)


class Stage(enum.Enum):
    OPTIMIZE = enum.auto()
    PROVISION = enum.auto()
    SYNC_WORKDIR = enum.auto()
    SYNC_FILE_MOUNTS = enum.auto()
    SETUP = enum.auto()
    PRE_EXEC = enum.auto()
    EXEC = enum.auto()
    DOWN = enum.auto()


def _to_dag(entrypoint: Union[task_lib.Task, dag_lib.Dag]) -> dag_lib.Dag:
    if isinstance(entrypoint, task_lib.Task):
        dag = dag_lib.Dag()
        dag.add(entrypoint)
        return dag
    return entrypoint


def _default_cluster_name() -> str:
    return f'skytpu-{common_utils.generate_run_id(4)}'


@timeline.event
def _execute(
    entrypoint: Union[task_lib.Task, dag_lib.Dag],
    *,
    cluster_name: Optional[str],
    stages: List[Stage],
    dryrun: bool = False,
    stream_logs: bool = True,
    optimize_target: optimizer_lib.OptimizeTarget = (
        optimizer_lib.OptimizeTarget.COST),
    detach_run: bool = False,
    idle_minutes_to_autostop: Optional[int] = None,
    down: bool = False,
    retry_until_up: bool = False,
    no_setup: bool = False,
    blocked_regions: Optional[List[str]] = None,
) -> Tuple[Optional[int], Optional[gang_backend.GangResourceHandle]]:
    """Returns (job_id, handle) of the last task executed."""
    dag = _to_dag(entrypoint)
    if len(dag.tasks) != 1:
        # Chain pipelines run through the managed-jobs controller (one
        # cluster per task), like the reference (sky/execution.py:99).
        raise exceptions.NotSupportedError(
            'launch()/exec() take a single-task dag; submit multi-task '
            'pipelines via `skytpu jobs launch`.')
    common_utils.check_cluster_name_is_valid(cluster_name)
    dag = admin_policy.apply(
        dag,
        admin_policy.RequestOptions(
            cluster_name=cluster_name,
            idle_minutes_to_autostop=idle_minutes_to_autostop,
            down=down,
            dryrun=dryrun))

    if Stage.OPTIMIZE in stages:
        needs_optimize = any(t.best_resources is None for t in dag.tasks)
        if needs_optimize:
            optimizer_lib.Optimizer.optimize(dag, minimize=optimize_target,
                                             quiet=not stream_logs)

    backend = gang_backend.GangBackend()
    job_id: Optional[int] = None
    handle: Optional[gang_backend.GangResourceHandle] = None
    name = cluster_name or _default_cluster_name()

    for task in dag.get_sorted_tasks():
        if Stage.PROVISION in stages:
            handle = backend.provision(task, task.best_resources,
                                       dryrun=dryrun,
                                       stream_logs=stream_logs,
                                       cluster_name=name,
                                       blocked_regions=blocked_regions,
                                       retry_until_up=retry_until_up)
        else:
            handle = backend_utils.check_cluster_available(name)
        if dryrun:
            logger.info('Dryrun finished.')
            return None, None
        assert handle is not None

        if Stage.SYNC_WORKDIR in stages and task.workdir is not None:
            backend.sync_workdir(handle, task.workdir)
        if Stage.SYNC_FILE_MOUNTS in stages:
            if task.file_mounts or task.storage_mounts:
                backend.sync_file_mounts(handle, task.file_mounts,
                                         task.storage_mounts)
        if no_setup:
            task.setup = None
        if Stage.PRE_EXEC in stages:
            # `down=True` without an idle budget means "autodown when
            # idle" (reference sky/execution.py maps it to autostop 0);
            # tearing down inline would race the detached job.
            if down and idle_minutes_to_autostop is None:
                idle_minutes_to_autostop = 0
            if idle_minutes_to_autostop is not None:
                backend.set_autostop(handle, idle_minutes_to_autostop,
                                     down=down)
        if Stage.EXEC in stages:
            job_id = backend.execute(handle, task, detach_run=detach_run)
    return job_id, handle


def launch(
    entrypoint: Union[task_lib.Task, dag_lib.Dag],
    cluster_name: Optional[str] = None,
    *,
    dryrun: bool = False,
    stream_logs: bool = True,
    optimize_target: optimizer_lib.OptimizeTarget = (
        optimizer_lib.OptimizeTarget.COST),
    detach_run: bool = True,
    idle_minutes_to_autostop: Optional[int] = None,
    down: bool = False,
    retry_until_up: bool = False,
    no_setup: bool = False,
    blocked_regions: Optional[List[str]] = None,
) -> Tuple[Optional[int], Optional[gang_backend.GangResourceHandle]]:
    """Provision (or reuse) a cluster and run the task on it."""
    from skypilot_tpu import usage
    task0 = (entrypoint.tasks[0]
             if isinstance(entrypoint, dag_lib.Dag) and entrypoint.tasks
             else entrypoint)
    res = next(iter(task0.resources)) if getattr(
        task0, 'resources', None) else None
    with usage.timed_event(
            'launch',
            cloud=(str(res.cloud)
                   if res is not None and res.cloud is not None
                   else None),
            accelerator=(res.tpu.name
                         if res is not None and res.is_tpu else None),
            num_nodes=getattr(task0, 'num_nodes', None),
            use_spot=res.use_spot if res is not None else None):
        return _execute(
            entrypoint,
            cluster_name=cluster_name,
            stages=[
                Stage.OPTIMIZE, Stage.PROVISION, Stage.SYNC_WORKDIR,
                Stage.SYNC_FILE_MOUNTS, Stage.SETUP, Stage.PRE_EXEC,
                Stage.EXEC, Stage.DOWN
            ],
            dryrun=dryrun,
            stream_logs=stream_logs,
            optimize_target=optimize_target,
            detach_run=detach_run,
            idle_minutes_to_autostop=idle_minutes_to_autostop,
            down=down,
            retry_until_up=retry_until_up,
            no_setup=no_setup,
            blocked_regions=blocked_regions,
        )


def exec_(  # pylint: disable=redefined-builtin
    entrypoint: Union[task_lib.Task, dag_lib.Dag],
    cluster_name: str,
    *,
    dryrun: bool = False,
    detach_run: bool = True,
) -> Tuple[Optional[int], Optional[gang_backend.GangResourceHandle]]:
    """Fast path: submit to an existing cluster, skipping provisioning
    and setup (reference sky/execution.py:557)."""
    dag = _to_dag(entrypoint)
    # Validate the cluster can serve the requested resources.
    handle = backend_utils.check_cluster_available(cluster_name)
    for task in dag.tasks:
        task.best_resources = handle.launched_resources
        for want in task.resources:
            if not want.less_demanding_than(handle.launched_resources):
                raise exceptions.ResourcesMismatchError(
                    f'Task requests {want!r}; cluster {cluster_name} has '
                    f'{handle.launched_resources!r}.')
    return _execute(
        dag,
        cluster_name=cluster_name,
        stages=[Stage.SYNC_WORKDIR, Stage.PRE_EXEC, Stage.EXEC],
        dryrun=dryrun,
        detach_run=detach_run,
        no_setup=True,
    )
